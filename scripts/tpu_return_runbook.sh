#!/bin/bash
# One-shot runbook for when the TPU tunnel recovers.  Probes first; on
# success runs the full measurement ladder and drops artifacts in
# /tmp/tpu_run/.  Round-5 ladder (updated after the 2-slot-bucket and
# K=128 retunes landed): dense-engine A/B, two-tier A/B, kernel
# ablate, 1M bench, 10M bench.  The pallas Mosaic verdict is CLOSED
# (BASELINE.md) - bench_pallas_small is only worth rerunning on a NEW
# jax/Mosaic version to re-test the gather lowering.
set -u
OUT=/tmp/tpu_run
mkdir -p "$OUT"

echo "== probe =="
if ! timeout 60 python -c "import jax, jax.numpy as jnp; print('TPU OK', jax.jit(lambda x: x+1)(jnp.ones((8,128))).sum())"; then
  echo "tunnel still down"; exit 1
fi

echo "== dense matmul A/B (hot-tier engine decision; crossover sweep) =="
timeout 900 python -c "
from emqx_tpu.ops.dense_match import bench_dense
for nf in (60, 130, 420):
    print(bench_dense(n_filters=nf))" > "$OUT/dense_ab.txt" 2>&1
tail -3 "$OUT/dense_ab.txt"

echo "== two-tier hot/cold A/B (anti-correlated workload) =="
timeout 1200 python -c "from emqx_tpu.ops.tiered import bench_tiered; print(bench_tiered())" \
  > "$OUT/tiered_ab.txt" 2>&1
tail -2 "$OUT/tiered_ab.txt"

echo "== kernel ablate (200k filters) =="
timeout 600 python scripts/kernel_scan_ablate.py > "$OUT/ablate.txt" 2>&1
tail -5 "$OUT/ablate.txt"

echo "== bench 1M (config 2) =="
timeout 1800 python bench.py --filters 1000000 --serve-seconds 8 \
  > "$OUT/bench_1m.json" 2> "$OUT/bench_1m.err"
tail -2 "$OUT/bench_1m.err"; head -c 400 "$OUT/bench_1m.json"; echo

echo "== bench 10M (config 3, north star) =="
timeout 3000 python bench.py \
  > "$OUT/bench_10m.json" 2> "$OUT/bench_10m.err"
tail -3 "$OUT/bench_10m.err"; head -c 400 "$OUT/bench_10m.json"; echo

echo "== done; archive to scripts/measured_bench_10m_r<N>_<date>.json"
echo "   (the round tag drives bench.py's tunnel-outage fallback pick)"
