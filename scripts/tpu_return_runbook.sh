#!/bin/bash
# One-shot runbook for when the TPU tunnel recovers.  Probes first; on
# success runs the full measurement ladder and drops artifacts in
# /tmp/tpu_run/.  Round-3 ladder: kernel ablate, pallas A/B, 1M bench,
# 10M bench (all through the flat-output pipelined serving path).
set -u
OUT=/tmp/tpu_run
mkdir -p "$OUT"

echo "== probe =="
if ! timeout 60 python -c "import jax, jax.numpy as jnp; print('TPU OK', jax.jit(lambda x: x+1)(jnp.ones((8,128))).sum())"; then
  echo "tunnel still down"; exit 1
fi

echo "== pallas small-table A/B (50k filters, VMEM-resident) =="
timeout 900 python -m emqx_tpu.ops.pallas_match > "$OUT/pallas_ab.txt" 2>&1
tail -2 "$OUT/pallas_ab.txt"

echo "== two-tier hot/cold A/B (200k filters, Zipf traffic) =="
timeout 1200 python -c "from emqx_tpu.ops.tiered import bench_tiered; print(bench_tiered())" \
  > "$OUT/tiered_ab.txt" 2>&1
tail -2 "$OUT/tiered_ab.txt"

echo "== kernel ablate (200k filters) =="
timeout 600 python scripts/kernel_scan_ablate.py > "$OUT/ablate.txt" 2>&1
tail -5 "$OUT/ablate.txt"

echo "== bench 1M (config 2) =="
timeout 1800 python bench.py --filters 1000000 --serve-seconds 8 \
  > "$OUT/bench_1m.json" 2> "$OUT/bench_1m.err"
tail -2 "$OUT/bench_1m.err"; head -c 400 "$OUT/bench_1m.json"; echo

echo "== bench 10M (config 3, north star) =="
timeout 3000 python bench.py \
  > "$OUT/bench_10m.json" 2> "$OUT/bench_10m.err"
tail -3 "$OUT/bench_10m.err"; head -c 400 "$OUT/bench_10m.json"; echo

echo "== done; update BASELINE.md + scripts/measured_bench_10m_*.json =="
