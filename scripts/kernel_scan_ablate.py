#!/usr/bin/env python
"""Pure-device ablation: run N chained kernel iterations inside ONE jit
(lax.scan, data dependence) so dispatch/tunnel cost amortizes away, and
ablate each component of the v2 walk at A=8.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def devtime(fn, args, N=16):
    f = jax.jit(fn)
    r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = f(*args)
        np.asarray(jax.tree_util.tree_leaves(r)[0])
        best = min(best, (time.perf_counter() - t0) / N)
    return best * 1e3


def make_looped(kernel_step, N=16):
    def looped(words, lens, is_sys, node, edge, seeds):
        def body(carry, _):
            w = jnp.bitwise_xor(words, carry)
            out = kernel_step(w, lens, is_sys, node, edge, seeds)
            return (carry + out[0][0]) % 2, out
        c, outs = jax.lax.scan(body, jnp.int32(0), None, length=N)
        return outs
    return looped


def variant(D, A, K, *, edges=True, node_g=True, per_step=True, final=True,
            seeds_n=2):
    from emqx_tpu.ops.match_kernel import _edge_lookup, _compact

    def run(words, lens, is_sys, node_tab, edge_tab, seeds):
        B = words.shape[0]
        active = jnp.zeros((B, 1), jnp.int32)
        accept_cols = []
        for t in range(D + 1):
            valid = active >= 0
            sa = jnp.maximum(active, 0)
            if node_g:
                node = node_tab[sa]
            else:
                node = jnp.stack([sa, sa, sa, sa], axis=-1)  # fake, no gather
            hacc = jnp.where(valid, node[..., 1], -1)
            if t == 0:
                hacc = jnp.where(is_sys[:, None], -1, hacc)
            at_end = (t == lens)[:, None]
            eacc = jnp.where(valid & at_end, node[..., 2], -1)
            accept_cols.append(jnp.concatenate([hacc, eacc], axis=1))
            if t == D:
                break
            w = jnp.broadcast_to(words[:, t][:, None], active.shape)
            if edges:
                lit = _edge_lookup(active, w, edge_tab, seeds)
            else:
                lit = jnp.where(w > 0, node[..., 0], -1)  # fake, no gather
            lit = jnp.where(valid, lit, -1)
            plus = jnp.where(valid, node[..., 0], -1)
            if t == 0:
                plus = jnp.where(is_sys[:, None], -1, plus)
            cand = jnp.concatenate([lit, plus], axis=1)
            cand = jnp.where((t < lens)[:, None], cand, -1)
            if cand.shape[1] <= A:
                active = cand
            elif per_step:
                active, _ = jax.lax.top_k(cand, A)
            else:
                active = cand[:, :A]  # fake, wrong semantics
        flat = jnp.concatenate(accept_cols, axis=1)
        n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
        if final:
            m = _compact(flat, K)
        else:
            m = flat[:, :K]
        return n, m

    return run


def main():
    from bench import build_workload
    from emqx_tpu.ops import compile_filters, encode_topics

    rng = np.random.default_rng(42)
    B, D = 8192, 8
    filters, topics = build_workload(rng, 200_000, B, D)
    t0 = time.perf_counter()
    table = compile_filters(filters, depth=D)
    print(f"compile {time.perf_counter()-t0:.1f}s states={table.n_states}")
    words, lens, is_sys = encode_topics(table, topics[:B], batch=B)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in table.device_arrays()])

    A = 8
    for name, kw in [
        ("full v2 A=8", {}),
        ("  -edge gathers", dict(edges=False)),
        ("  -node gathers", dict(node_g=False)),
        # ("  -per-step topk", dict(per_step=False)),
        # ("  -final compact", dict(final=False)),
        ("  bare (no gathers/compact)",
         dict(edges=False, node_g=False, per_step=False, final=False)),
    ]:
        fn = make_looped(variant(D, A, 32, **kw))
        ms = devtime(fn, args)
        print(f"{name:28s}: {ms:6.2f} ms/iter  {B/ms*1e3/1e6:.2f}M t/s")

    for A2 in ():
        fn = make_looped(variant(D, A2, 32))
        ms = devtime(fn, args)
        print(f"full v2 A={A2:<2d}                 : {ms:6.2f} ms/iter  "
              f"{B/ms*1e3/1e6:.2f}M t/s")


if __name__ == "__main__" and not os.environ.get("SWEEP"):
    main()


def batch_sweep():
    from bench import build_workload
    from emqx_tpu.ops import compile_filters, encode_topics
    rng = np.random.default_rng(42)
    D = 8
    filters, topics = build_workload(rng, 200_000, 65536, D)
    table = compile_filters(filters, depth=D)
    print(f"states={table.n_states}")
    arrs = [jnp.asarray(a) for a in table.device_arrays()]
    for B in (8192, 32768, 65536, 131072):
        tt = (topics * ((B // len(topics)) + 1))[:B]
        w, l, s = encode_topics(table, tt, batch=B)
        args = (jnp.asarray(w), jnp.asarray(l), jnp.asarray(s), *arrs)
        N = 8
        fn = make_looped(variant(D, 8, 32), N=N)
        ms = devtime(fn, args, N=N)
        print(f"B={B:6d} A=8 pure-device: {ms:7.2f} ms/iter  "
              f"{B/ms*1e3/1e6:.2f}M t/s")


if __name__ == "__main__" and os.environ.get("SWEEP"):
    batch_sweep()
