#!/usr/bin/env python
"""Project-invariant static analysis CLI (the dialyzer/xref analog).

Usage::

    python scripts/staticcheck.py                       # tree, all rules
    python scripts/staticcheck.py emqx_tpu/broker       # subtree
    python scripts/staticcheck.py --rule registry-drift --rule await-under-lock
    python scripts/staticcheck.py --changed             # git-diff scope
    python scripts/staticcheck.py --no-cache            # full cold scan
    python scripts/staticcheck.py --baseline write      # stamp waivers
    python scripts/staticcheck.py --format json

The two-pass whole-program analysis always builds the project symbol
graph over the full default path set (cross-module resolution needs
it); ``--changed`` narrows only which files' per-file findings are
(re)computed and reported — changed files from ``git diff`` plus their
reverse import-graph dependents, which the import graph makes sound.

Per-file results cache under ``.staticcheck_cache/`` keyed on
(path, mtime, size, content-hash) plus the rule/registry environment
and each file's transitive import closure; ``--no-cache`` bypasses.

Exit codes: 0 = clean (all findings waived by live waivers), 1 = new
findings (or expired waivers whose finding persists), 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from emqx_tpu.devtools.staticcheck import (  # noqa: E402
    Registries, analyze, get_rules, iter_py_files,
)
from emqx_tpu.devtools.staticcheck.cache import (  # noqa: E402
    AnalysisCache, environment_digest,
)
from emqx_tpu.devtools.staticcheck.report import (  # noqa: E402
    format_json, format_text,
)
from emqx_tpu.devtools.staticcheck.rules import ALL_RULES  # noqa: E402
from emqx_tpu.devtools.staticcheck.symbols import (  # noqa: E402
    module_name_for,
)
from emqx_tpu.devtools.staticcheck.waivers import (  # noqa: E402
    DEFAULT_EXPIRY_DAYS, WaiverFile,
)

DEFAULT_WAIVER_FILE = os.path.join(_REPO_ROOT, "staticcheck-waivers.json")
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".staticcheck_cache")

#: the tier-1 scan set: the package, plus the bench drivers that
#: consume metric/config names by literal (they have drifted before)
DEFAULT_SCAN_PATHS = ("emqx_tpu", "bench.py", "scripts/bench_e2e.py")

#: a change under the analysis itself (rules, ownership facts —
#: notably project.py INVARIANT_GROUPS/LOCKED_FIELDS edits) can
#: re-surface findings in ANY file; --changed then re-checks the full
#: tree instead of the import-graph dependents (which would miss
#: every file, since nothing imports the checker)
ANALYSIS_RELPATH_PREFIX = "emqx_tpu/devtools/staticcheck/"


def changed_targets(project, changed):
    """The ``--changed`` re-check set: the changed relpaths plus their
    reverse import-graph dependents — or None (re-check EVERYTHING)
    when the analysis/facts modules themselves changed."""
    if any(p.startswith(ANALYSIS_RELPATH_PREFIX) for p in changed):
        return None
    changed_mods = [module_name_for(p)[0] for p in changed]
    keep_mods = project.dependents_closure(changed_mods)
    return {
        s.relpath for s in project.modules.values()
        if s.module in keep_mods or s.relpath in changed
    }


def _default_paths(root: str):
    return [os.path.join(root, p) for p in DEFAULT_SCAN_PATHS]


def _changed_relpaths(root: str):
    """Repo-relative .py files touched per git (staged + unstaged +
    untracked)."""
    out = set()
    for args in (["diff", "--name-only", "HEAD"],
                 ["ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(
                ["git", "-C", root, *args],
                capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        out.update(l.strip() for l in r.stdout.splitlines() if l.strip())
    return {p for p in out if p.endswith(".py")}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="staticcheck.py",
        description="AST-based project-invariant checks for emqx_tpu",
    )
    parser.add_argument(
        "paths", nargs="*",
        default=None,
        help="files/directories to check (default: emqx_tpu/, bench.py, "
             "scripts/bench_e2e.py)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="NAME",
        help="run only this rule (repeatable); known: "
             + ", ".join(r.name for r in ALL_RULES),
    )
    parser.add_argument(
        "--waivers", default=DEFAULT_WAIVER_FILE, metavar="FILE",
        help="waiver file (default: staticcheck-waivers.json at repo "
             "root)",
    )
    parser.add_argument(
        "--baseline", choices=("write", "diff"), default="diff",
        help="'write' stamps current findings into the waiver file "
             "with a %d-day expiry; 'diff' (default) suppresses live "
             "waivers and fails on anything new" % DEFAULT_EXPIRY_DAYS,
    )
    parser.add_argument(
        "--expiry-days", type=int, default=DEFAULT_EXPIRY_DAYS,
        help="expiry horizon for --baseline write",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="only report findings for files in git diff (+ untracked) "
             "plus their reverse import-graph dependents",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="bypass the analysis cache (.staticcheck_cache/)",
    )
    parser.add_argument(
        "--jobs", type=int, default=os.cpu_count() or 1, metavar="N",
        help="process-pool width for the cold pass-1 parse (default: "
             "os.cpu_count(); 1 forces serial)",
    )
    parser.add_argument(
        "--cache-dir", default=DEFAULT_CACHE_DIR, metavar="DIR",
        help="analysis cache directory",
    )
    parser.add_argument(
        "--root", default=_REPO_ROOT, help=argparse.SUPPRESS,
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    root = os.path.abspath(args.root)
    paths = args.paths or _default_paths(root)
    for p in paths:
        if not os.path.exists(p):
            print(f"staticcheck: no such path: {p}", file=sys.stderr)
            return 2
    try:
        rules = get_rules(args.rules)
    except KeyError as e:
        print(f"staticcheck: {e.args[0]}", file=sys.stderr)
        return 2

    cache = None
    if not args.no_cache:
        registries = None
        if any(r.name == "registry-drift" for r in rules):
            try:
                registries = Registries.load()
            except Exception:
                registries = None
        env = environment_digest([r.name for r in rules], registries)
        cache = AnalysisCache(args.cache_dir, env)

    targets = None
    if args.changed:
        changed = _changed_relpaths(root)
        if changed is None:
            print("staticcheck: --changed needs a git checkout",
                  file=sys.stderr)
            return 2
        if not changed:
            print("0 finding(s) (clean); nothing changed per git")
            return 0
        # expand over the reverse import graph after pass 1 — done via
        # a pre-analysis to learn the graph, then the real run
        pre = analyze(paths, [], root=root, cache=cache, targets=set(),
                      jobs=args.jobs)
        targets = changed_targets(pre.project, changed)
        if targets is not None and not targets:
            print("0 finding(s) (clean); changed files outside the "
                  "scan set")
            return 0

    result = analyze(paths, rules, root=root, cache=cache,
                     targets=targets, prune_cache=not args.paths,
                     jobs=args.jobs)
    findings = result.findings

    if args.baseline == "write":
        wf = WaiverFile.baseline(findings, days=args.expiry_days)
        wf.save(args.waivers)
        print(f"wrote {len(wf.waivers)} waiver(s) to {args.waivers} "
              f"(expiring in {args.expiry_days} days)")
        return 0

    wf = WaiverFile.load(args.waivers)
    new, waived, expired, stale = wf.apply(findings)
    fmt = format_json if args.format == "json" else format_text
    print(fmt(new, waived, expired, stale,
              files_checked=len(result.files)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
