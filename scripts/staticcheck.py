#!/usr/bin/env python
"""Project-invariant static analysis CLI (the dialyzer/xref analog).

Usage::

    python scripts/staticcheck.py                       # tree, all rules
    python scripts/staticcheck.py emqx_tpu/broker       # subtree
    python scripts/staticcheck.py --rule registry-drift --rule await-under-lock
    python scripts/staticcheck.py --baseline write      # stamp waivers
    python scripts/staticcheck.py --format json

Exit codes: 0 = clean (all findings waived by live waivers), 1 = new
findings (or expired waivers whose finding persists), 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from emqx_tpu.devtools.staticcheck import (  # noqa: E402
    check_paths, get_rules, iter_py_files,
)
from emqx_tpu.devtools.staticcheck.report import (  # noqa: E402
    format_json, format_text,
)
from emqx_tpu.devtools.staticcheck.rules import ALL_RULES  # noqa: E402
from emqx_tpu.devtools.staticcheck.waivers import (  # noqa: E402
    DEFAULT_EXPIRY_DAYS, WaiverFile,
)

DEFAULT_WAIVER_FILE = os.path.join(_REPO_ROOT, "staticcheck-waivers.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="staticcheck.py",
        description="AST-based project-invariant checks for emqx_tpu",
    )
    parser.add_argument(
        "paths", nargs="*",
        default=None,
        help="files/directories to check (default: emqx_tpu/)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="NAME",
        help="run only this rule (repeatable); known: "
             + ", ".join(r.name for r in ALL_RULES),
    )
    parser.add_argument(
        "--waivers", default=DEFAULT_WAIVER_FILE, metavar="FILE",
        help="waiver file (default: staticcheck-waivers.json at repo "
             "root)",
    )
    parser.add_argument(
        "--baseline", choices=("write", "diff"), default="diff",
        help="'write' stamps current findings into the waiver file "
             "with a %d-day expiry; 'diff' (default) suppresses live "
             "waivers and fails on anything new" % DEFAULT_EXPIRY_DAYS,
    )
    parser.add_argument(
        "--expiry-days", type=int, default=DEFAULT_EXPIRY_DAYS,
        help="expiry horizon for --baseline write",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    paths = args.paths or [os.path.join(_REPO_ROOT, "emqx_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"staticcheck: no such path: {p}", file=sys.stderr)
            return 2
    try:
        rules = get_rules(args.rules)
    except KeyError as e:
        print(f"staticcheck: {e.args[0]}", file=sys.stderr)
        return 2

    files = list(iter_py_files(paths))
    findings = check_paths(files, rules, root=_REPO_ROOT)

    if args.baseline == "write":
        wf = WaiverFile.baseline(findings, days=args.expiry_days)
        wf.save(args.waivers)
        print(f"wrote {len(wf.waivers)} waiver(s) to {args.waivers} "
              f"(expiring in {args.expiry_days} days)")
        return 0

    wf = WaiverFile.load(args.waivers)
    new, waived, expired, stale = wf.apply(findings)
    fmt = format_json if args.format == "json" else format_text
    print(fmt(new, waived, expired, stale, files_checked=len(files)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
