#!/usr/bin/env python
"""Kernel component profiler: where do the 16 ms/batch go?

Times the full nfa_match against ablated variants (no top_k compaction,
no edge gather, no final top_k) at the round-2 bench shape, and sweeps
active_slots / batch.  Methodology mirrors bench.py: enqueue N calls,
force once, divide.
"""
import argparse
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, args, iters=20):
    r = fn(*args)
    jax.block_until_ready(r)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        rs = [fn(*args) for _ in range(iters)]
        jax.block_until_ready(rs[-1])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filters", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--depth", type=int, default=8)
    args = ap.parse_args()

    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_workload
    from emqx_tpu.ops import compile_filters, encode_topics
    from emqx_tpu.ops.compiler import BUCKET_SLOTS
    from emqx_tpu.ops.match_kernel import _bucket_hash, nfa_match

    rng = np.random.default_rng(42)
    filters, topics = build_workload(rng, args.filters, args.batch, args.depth)
    t0 = time.perf_counter()
    table = compile_filters(filters, depth=args.depth)
    print(f"compile {time.perf_counter()-t0:.1f}s states={table.n_states} "
          f"S={table.node_tab.shape[0]} Hb={table.edge_tab.shape[0]}")
    words, lens, is_sys = encode_topics(table, topics[: args.batch],
                                        batch=args.batch)
    dev_args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
                *[jnp.asarray(a) for a in table.device_arrays()])

    # full kernel at various active_slots
    for A in (8, 16, 32):
        ms = timeit(partial(nfa_match, active_slots=A, max_matches=32),
                    dev_args)
        print(f"full A={A:3d}: {ms:7.2f} ms  {args.batch/ms*1e3/1e6:.2f}M t/s")

    # ablations at A=16
    node_tab, edge_tab, seeds = dev_args[3:]
    B, D = words.shape
    A = 16

    @jax.jit
    def no_edges(words, lens, is_sys, node_tab, edge_tab, seeds):
        active = jnp.full((B, A), -1, jnp.int32).at[:, 0].set(0)
        accept_cols = []
        for t in range(D + 1):
            valid = active >= 0
            sa = jnp.maximum(active, 0)
            node = node_tab[sa]
            hacc = jnp.where(valid, node[..., 1], -1)
            at_end = (t == lens)[:, None]
            eacc = jnp.where(valid & at_end, node[..., 2], -1)
            accept_cols.append(jnp.concatenate([hacc, eacc], axis=1))
            if t == D:
                break
            lit = jnp.where(valid, node[..., 0], -1)  # fake: reuse plus
            plus = jnp.where(valid, node[..., 0], -1)
            cand = jnp.concatenate([lit, plus], axis=1)
            cand = jnp.where((t < lens)[:, None], cand, -1)
            active, _ = jax.lax.top_k(cand, A)
        flat = jnp.concatenate(accept_cols, axis=1)
        n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
        topk, _ = jax.lax.top_k(flat, 32)
        return topk, n

    print(f"no-edge-gather: {timeit(no_edges, dev_args):7.2f} ms")

    @jax.jit
    def no_topk(words, lens, is_sys, node_tab, edge_tab, seeds):
        active = jnp.full((B, A), -1, jnp.int32).at[:, 0].set(0)
        accept_cols = []
        for t in range(D + 1):
            valid = active >= 0
            sa = jnp.maximum(active, 0)
            node = node_tab[sa]
            hacc = jnp.where(valid, node[..., 1], -1)
            at_end = (t == lens)[:, None]
            eacc = jnp.where(valid & at_end, node[..., 2], -1)
            accept_cols.append(jnp.concatenate([hacc, eacc], axis=1))
            if t == D:
                break
            w = jnp.broadcast_to(words[:, t][:, None], (B, A))
            Hb = edge_tab.shape[0]
            mask = Hb - 1
            hits = []
            for k in range(2):
                b = _bucket_hash(active, w, seeds[k], mask)
                rows = edge_tab[b].reshape(B, A, BUCKET_SLOTS, 4)
                hit = (rows[..., 0] == active[..., None]) & (
                    rows[..., 1] == w[..., None])
                hits.append(jnp.max(jnp.where(hit, rows[..., 2], -1), axis=-1))
            lit = jnp.maximum(hits[0], hits[1])
            plus = jnp.where(valid, node[..., 0], -1)
            # NO top_k: just interleave lit/plus into A slots (wrong
            # semantics past A/2 actives, fine for timing)
            active = jnp.concatenate([lit[:, : A // 2], plus[:, : A // 2]],
                                     axis=1)
        flat = jnp.concatenate(accept_cols, axis=1)
        n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
        return flat, n

    print(f"no-topk (sum only): {timeit(no_topk, dev_args):7.2f} ms")

    # batch sweep at A=16
    for B2 in (2048, 4096, 8192, 16384, 32768):
        w2, l2, s2 = encode_topics(
            table, (topics * ((B2 // len(topics)) + 1))[:B2], batch=B2)
        a2 = (jnp.asarray(w2), jnp.asarray(l2), jnp.asarray(s2),
              node_tab, edge_tab, seeds)
        ms = timeit(partial(nfa_match, active_slots=16, max_matches=32), a2)
        print(f"batch={B2:6d}: {ms:7.2f} ms  {B2/ms*1e3/1e6:.2f}M t/s")


if __name__ == "__main__":
    main()
