#!/usr/bin/env python
"""Broker publish→deliver e2e A/B — per-message path vs fanout pipeline.

CPU-only (no device needed): measures the broker-side processing path
the fanout pipeline amortizes, on the telemetry-broadcast shape — twice:

* QoS1 publishers → wildcard **QoS0** subscribers (fire-and-forget
  delivery, the PR-1 number), and
* QoS1 publishers → wildcard **QoS1 windowed** subscribers with acks
  flowing (the acknowledged-delivery stack: batched inflight admission
  + ack/write coalescing, the PR-2 number) under ``"qos1"``.

Modes:

* ``--smoke``  — small N, ~15 s wall: the per-PR tracking numbers
  (wired as the ``slow``-marked ``tests/test_bench_e2e.py``).
* default      — the full A/B shapes ``bench.py`` reports under
  ``fanout_e2e`` / ``qos1_e2e``.

Prints one JSON object: per_message / pipeline sections plus the
delivered-msgs/s ``speedup`` (QoS0 fields at top level for
compatibility; the acknowledged A/B nests under ``"qos1"``).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(prog="bench_e2e")
    ap.add_argument("--smoke", action="store_true",
                    help="small-N CPU smoke (<60 s), for per-PR tracking")
    ap.add_argument("--duration", type=float, default=None,
                    help="override per-run duration (s)")
    args = ap.parse_args(argv)

    from bench import (
        _fanout_e2e_size, _qos1_e2e_size, bench_fanout_e2e, bench_qos1_e2e,
    )

    size = _fanout_e2e_size(args.smoke)
    qsize = _qos1_e2e_size(args.smoke)
    if args.duration is not None:
        size["duration"] = args.duration
        qsize["duration"] = args.duration
    out = bench_fanout_e2e(**size)
    out["qos1"] = bench_qos1_e2e(**qsize)
    print(json.dumps(out, indent=2))
    return out


if __name__ == "__main__":
    main()
