#!/usr/bin/env python
"""Broker publish→deliver e2e A/B — per-message path vs fanout pipeline.

CPU-only (no device needed): measures the broker-side processing path
the fanout pipeline amortizes, on the telemetry-broadcast shape — twice:

* QoS1 publishers → wildcard **QoS0** subscribers (fire-and-forget
  delivery, the PR-1 number), and
* QoS1 publishers → wildcard **QoS1 windowed** subscribers with acks
  flowing (the acknowledged-delivery stack: batched inflight admission
  + ack/write coalescing, the PR-2 number) under ``"qos1"``, and
* QoS2 publishers → wildcard **QoS2 windowed** subscribers running the
  full exactly-once exchange (ack-run ingest + batched QoS2 state
  machine, the PR-5 number) under ``"qos2"``.

Modes:

* ``--smoke``  — small N, ~15 s wall: the per-PR tracking numbers
  (wired as the ``slow``-marked ``tests/test_bench_e2e.py``).
* default      — the full A/B shapes ``bench.py`` reports under
  ``fanout_e2e`` / ``qos1_e2e``.

Prints one JSON object: per_message / pipeline sections plus the
delivered-msgs/s ``speedup`` (QoS0 fields at top level for
compatibility; the acknowledged A/B nests under ``"qos1"``).

``--chaos`` adds a ``"chaos"`` section: one kill-and-recover cycle per
delivery subsystem (fanout drain, cluster replication, bridge sink,
exhook channel) under the supervision tree, asserting QoS1 delivery
stays exactly-once through the wound — the CI-fast slice of
``tests/test_chaos_delivery.py``.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh_smoke(fn: str, n_filters: int) -> dict:
    """One bench.<fn> mesh row in ITS OWN subprocess with a virtual
    8-device CPU mesh (the conftest pattern).  Forcing 8 XLA host
    devices in THIS process would slow every single-chip row (8
    device threads on a 1-core box stall the table_lifecycle churn
    gates), so the mesh A/Bs are isolated instead."""
    import subprocess

    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8") \
            .strip()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import json, bench; print(json.dumps("
         f"bench.{fn}(n_filters={n_filters})))"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags},
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{fn} smoke failed: {proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def multichip_serve_smoke(n_filters: int) -> dict:
    return _mesh_smoke("bench_multichip_serve_smoke", n_filters)


def multichip_ep_smoke(n_filters: int) -> dict:
    return _mesh_smoke("bench_multichip_ep_smoke", n_filters)


def multichip_balance_smoke(n_filters: int) -> dict:
    return _mesh_smoke("bench_multichip_balance_smoke", n_filters)


def staticcheck_gate() -> dict:
    """Cold full-tree staticcheck as a CI gate row (ISSUE 19): runs
    ``scripts/staticcheck.py`` in a subprocess against a throwaway
    cache dir (so the row always measures the COLD cost, never a
    warm cache someone else left behind) and reports the exit code
    plus wall seconds.  ``gate_clean`` is the real invariant — the
    tree must scan clean with zero live waivers; ``gate_budget`` is
    the cold-scan ceiling (10 s here: the bench box is allowed to be
    slower than the ≤4 s dev-loop budget tests/test_staticcheck.py
    asserts, but a 10 s cold scan means the analysis went
    super-linear and the dev loop is next)."""
    import shutil
    import subprocess
    import tempfile
    import time

    cache_dir = tempfile.mkdtemp(prefix="staticcheck_bench_")
    try:
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "staticcheck.py"),
             "--cache-dir", cache_dir],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        cold_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    from emqx_tpu.devtools.staticcheck.rules import ALL_RULES

    tail = (proc.stdout or "").strip().splitlines()
    return {
        "exit_code": proc.returncode,
        "cold_s": round(cold_s, 3),
        "rules": len(ALL_RULES),
        "summary": tail[-1] if tail else "",
        "gate_clean": proc.returncode == 0,
        "gate_budget": cold_s <= 10.0,
    }


def chaos_smoke() -> dict:
    """One kill-and-recover cycle per subsystem; each section reports
    ok plus the evidence (restart counts, delivered totals)."""
    import asyncio as aio

    from emqx_tpu.broker import (
        Broker, FanoutPipeline, SubOpts, make_message,
    )
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.supervise import Supervisor

    def sup_of(m):
        return Supervisor(metrics=m, backoff_base=0.001,
                          backoff_max=0.01, jitter=0.0)

    async def settle(pred, timeout=8.0):
        deadline = aio.get_event_loop().time() + timeout
        while not pred() and aio.get_event_loop().time() < deadline:
            await aio.sleep(0.002)
        return pred()

    async def fanout_cycle():
        b = Broker()
        m = Metrics()
        sup = sup_of(m)
        sess, _ = b.open_session("sub", max_inflight=64)
        b.subscribe("sub", "t/#", SubOpts(qos=1))
        got, dups = [], [0]

        def on_deliver(cid, pubs):
            stack = list(pubs)
            while stack:
                p = stack.pop(0)
                got.append(p.msg.payload)
                if p.msg.dup:
                    dups[0] += 1
                if p.pid is not None:
                    _, more = sess.puback(p.pid)
                    stack.extend(more)

        b.on_deliver = on_deliver
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        n = 200
        killed = False
        for i in range(n):
            p.offer(make_message("pub", "t/x", b"%d" % i, qos=1))
            if i == n // 2:
                await aio.sleep(0.005)   # let the drain loop spin up
                killed = p._child.kill()
                await aio.sleep(0.003)   # ... and the restart land
        ok = await settle(lambda: len(got) >= n)
        delivered = len(got)
        exactly_once = sorted(int(x) for x in got) == list(range(n))
        restarts = m.get("broker.supervisor.restarts")
        await p.stop()
        await sup.stop()
        return {"ok": bool(ok and killed and exactly_once and not dups[0]
                           and restarts >= 1),
                "delivered": delivered, "duplicates": dups[0],
                "restarts": restarts}

    async def cluster_cycle():
        from emqx_tpu.client import Client
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        async def start(name, seeds=""):
            cfg = Config(file_text=(
                f'node.name = "{name}"\n'
                'listeners.tcp.default.bind = "127.0.0.1:0"\n'
                'cluster.enable = true\n'
                'cluster.listen = "127.0.0.1:0"\n'
                f'cluster.seeds = "{seeds}"\n'
                'cluster.heartbeat_interval = 200ms\n'
            ))
            cfg.put("tpu.enable", False)
            node = BrokerNode(cfg)
            await node.start()
            node.cluster.SYNC_INTERVAL = 0.02
            return node

        n1 = await start("chaos1@smoke")
        n2 = await start(
            "chaos2@smoke", seeds=f"127.0.0.1:{n1.cluster.listen_port}")
        try:
            peered = await settle(
                lambda: n2.cluster.name in n1.cluster.peers
                and n1.cluster.peers[n2.cluster.name].up)
            child = n1.supervisor.lookup("cluster.sync")
            killed = child is not None and child.kill()
            sub = Client(clientid="cs", port=n1.listeners.all()[0].port)
            await sub.connect()
            await sub.subscribe("chaos/+/x", qos=1)
            replicated = await settle(
                lambda: bool(n2.broker.router.match_routes("chaos/a/x")))
            pub = Client(clientid="cp", port=n2.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("chaos/a/x", b"hello", qos=1)
            got = await sub.recv(timeout=5)
            restarts = n1.observed.metrics.get("broker.supervisor.restarts")
            await sub.disconnect()
            await pub.disconnect()
            return {"ok": bool(peered and killed and replicated
                               and got.payload == b"hello"
                               and restarts >= 1),
                    "restarts": restarts}
        finally:
            await n2.stop()
            await n1.stop()

    async def bridge_cycle():
        from emqx_tpu.bridge.resource import BufferedWorker, Connector

        class Sink(Connector):
            def __init__(self):
                self.got = []

            async def send(self, items):
                self.got.extend(items)

        m = Metrics()
        sup = sup_of(m)
        sink = Sink()
        w = BufferedWorker(sink, name="chaos", batch_size=4,
                           retry_base=0.001, retry_max=0.01)
        w.supervisor = sup
        await w.start()
        items = [f"i{n}" for n in range(40)]
        for i, it in enumerate(items):
            w.enqueue(it)
            if i == 20:
                w._tasks[0].kill()
                await aio.sleep(0.002)
            await aio.sleep(0)
        ok = await settle(lambda: set(sink.got) >= set(items))
        restarts = m.get("broker.supervisor.restarts")
        await w.stop()
        await sup.stop()
        return {"ok": bool(ok and restarts >= 1),
                "delivered": len(set(sink.got)), "restarts": restarts}

    async def exhook_cycle():
        try:
            import types

            from emqx_tpu.exhook.manager import (
                ExHookManager, ServerSpec, _ServerState,
            )
        except ImportError:
            return {"skipped": "grpc unavailable"}

        class FakeStub:
            def __init__(self):
                self.calls = []

            def OnClientConnected(self, req):
                async def go():
                    self.calls.append(req)
                return go()

        b = Broker()
        m = Metrics()
        sup = sup_of(m)
        node = types.SimpleNamespace(broker=b, supervisor=sup,
                                     started_at=0.0)
        mgr = ExHookManager(node, [])
        st = _ServerState(spec=ServerSpec(name="s1", url="inproc"))
        st.stub = FakeStub()
        st.hooks = ["client.connected"]
        mgr.servers = [st]
        st.sender = sup.start_child("exhook.sender.s1",
                                    lambda: mgr._sender_loop(st))
        for i in range(3):
            st.queue.put_nowait(("OnClientConnected", i))
        await settle(lambda: len(st.stub.calls) == 3)
        st.sender.kill()
        for i in range(3, 6):
            st.queue.put_nowait(("OnClientConnected", i))
        ok = await settle(lambda: len(st.stub.calls) == 6)
        restarts = m.get("broker.supervisor.restarts")
        st.sender.cancel()
        await sup.stop()
        return {"ok": bool(ok and restarts >= 1),
                "notified": len(st.stub.calls), "restarts": restarts}

    async def match_cycle():
        """Serve-plane kill-and-recover (ISSUE 7): a clean prefetch+
        publish storm, the same storm with the match.batch loop killed
        mid-flight, a 10%-fault storm, then a breaker trip + recovery —
        delivery 1.0 throughout, waiters resolved without budget-length
        stalls, and the faulted storm's worst waiter within 2x the clean
        one (floored at 50 ms for tiny-denominator noise)."""
        import time as _time

        from emqx_tpu import faultinject as fi
        from emqx_tpu.broker.message import make_message
        from emqx_tpu.config import Config
        from emqx_tpu.faultinject import FaultInjector
        from emqx_tpu.node import BrokerNode

        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", True)
        cfg.put("tpu.mirror_refresh_interval", 0.01)
        cfg.put("tpu.bypass_rate", 0.0)
        cfg.put("match.deadline.enable", True)
        cfg.put("match.deadline_ms", 50.0)
        cfg.put("match.breaker.threshold", 3)
        cfg.put("match.breaker.probe_interval", 0.05)
        cfg.put("supervisor.backoff_base", 0.005)
        cfg.put("supervisor.backoff_max", 0.05)
        node = BrokerNode(cfg)
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            if ms is None:
                return {"skipped": "match service unavailable"}
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            await settle(lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                         timeout=60)

            async def storm(n, base, kill_at=None):
                child = node.supervisor.lookup("match.batch")
                waits = []
                for i in range(n):
                    topic = f"t/{base + i}/x"   # unique: every prefetch
                    t0 = _time.perf_counter()   # parks a real waiter
                    await ms.prefetch(topic)
                    waits.append(_time.perf_counter() - t0)
                    b.publish(make_message(
                        "pub", topic, b"%d" % (base + i)))
                    if kill_at is not None and i == kill_at:
                        child.kill()
                return waits

            n = 120
            clean = await storm(n, 0)
            killed = await storm(n, 1000, kill_at=40)
            fi.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise",
                 "prob": 0.1, "times": 0}], seed=11))
            wounded = await storm(n, 2000)
            fi.uninstall()
            # breaker trip + recovery
            fi.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise",
                 "times": 3}]))
            for i in range(3):
                await ms.prefetch(f"t/brk{i}/x")
            tripped = bool(ms._breaker_open) and \
                node.observed.alarms.is_active("match_degraded")
            for i in range(10):   # CPU path keeps serving while open
                topic = f"t/cpu{i}/x"
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"c%d" % i))
            recovered = await settle(lambda: not ms._breaker_open,
                                     timeout=15)
            alarm_cleared = not node.observed.alarms.is_active(
                "match_degraded")
            fi.uninstall()

            sent = 3 * n + 10
            delivered = len(got)
            restarts = node.observed.metrics.get(
                "broker.supervisor.restarts")
            waiter_bound = ms.prefetch_timeout_s * 0.9
            worst = max(clean + killed + wounded)
            p99_ratio = round(max(wounded) / max(max(clean), 1e-9), 2)
            p99_gate = max(wounded) <= max(2.0 * max(clean), 0.05)
            return {
                "ok": bool(delivered == sent and restarts >= 1
                           and tripped and recovered and alarm_cleared
                           and worst < waiter_bound and p99_gate),
                "delivered": delivered, "sent": sent,
                "delivery_ratio": round(delivered / max(1, sent), 4),
                "restarts": restarts,
                "breaker_tripped": tripped,
                "breaker_recovered": bool(recovered and alarm_cleared),
                "worst_waiter_ms": round(worst * 1e3, 1),
                "fault_vs_clean_worst_ratio": p99_ratio,
                "cpu_fallback": node.observed.metrics.get(
                    "broker.match.cpu_fallback"),
            }
        finally:
            fi.uninstall()
            await node.stop()

    async def segments_cycle():
        """Table-lifecycle chaos (ISSUE 9): kill the table.compact
        child mid-swap AND inject a table.swap fault (serving
        unaffected either way, the next cycle resumes), then corrupt
        the on-disk segment and cold-start a second node — checksum
        reject, full rebuild serves, delivery 1.0 throughout."""
        import tempfile

        from emqx_tpu import faultinject as fi
        from emqx_tpu.broker.message import make_message
        from emqx_tpu.config import Config
        from emqx_tpu.faultinject import FaultInjector
        from emqx_tpu.node import BrokerNode

        seg_dir = tempfile.mkdtemp(prefix="chaos_seg_")

        def make_cfg():
            cfg = Config(
                file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
            cfg.put("tpu.enable", True)
            cfg.put("tpu.mirror_refresh_interval", 0.01)
            cfg.put("tpu.bypass_rate", 0.0)
            cfg.put("tpu.table", "python")
            cfg.put("match.deadline.enable", True)
            cfg.put("match.deadline_ms", 100.0)
            cfg.put("match.segments.enable", True)
            cfg.put("match.segments.dir", seg_dir)
            cfg.put("match.segments.compact_interval", 0.1)
            cfg.put("match.segments.compact_min_mutations", 1)
            cfg.put("supervisor.backoff_base", 0.005)
            cfg.put("supervisor.backoff_max", 0.05)
            return cfg

        node = BrokerNode(make_cfg())
        await node.start()
        got = []
        try:
            b = node.broker
            ms = node.match_service
            if ms is None:
                return {"skipped": "match service unavailable"}
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            await settle(lambda: ms.ready, timeout=60)
            # injected swap fault: the cycle aborts atomically (no state
            # mutated) and the next interval compacts clean
            fi.install(FaultInjector([
                {"point": "table.swap", "action": "raise", "times": 1}]))
            sent = 0
            for i in range(60):
                topic = f"t/{i}/x"
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"%d" % i))
                sent += 1
            swapped = await settle(lambda: ms._table_gen >= 1, timeout=20)
            fi.uninstall()
            # kill the compact child mid-cycle: supervised restart
            child = node.supervisor.lookup("table.compact")
            killed = child is not None and child.kill()
            gen0 = ms._table_gen
            for i in range(60, 120):
                topic = f"t/{i}/x"
                # table mutations so the restarted compact child has
                # something to fold into the next segment
                b.subscribe("sub", f"chaos/{i}/+", SubOpts())
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"%d" % i))
                sent += 1
            resumed = await settle(
                lambda: ms._table_gen > gen0, timeout=20)
            restarts = node.observed.metrics.get(
                "broker.supervisor.restarts")
            compact_runs = node.observed.metrics.get(
                "tpu.table.compact_runs")
            seg_exists = os.path.exists(ms._segment_path)
            delivered = len(got)
        finally:
            fi.uninstall()
            await node.stop()
        # corrupt the segment: the next cold start must checksum-reject
        # it and serve from the full rebuild
        seg_path = os.path.join(seg_dir, "match_table.seg.npz")

        def flip_bytes():
            with open(seg_path, "r+b") as f:
                f.seek(256)
                f.write(b"\xff\xff\xff\xff")

        await aio.to_thread(flip_bytes)
        node2 = BrokerNode(make_cfg())
        await node2.start()
        got2 = []
        try:
            b2 = node2.broker
            ms2 = node2.match_service
            rejected = ms2 is not None and not ms2._segment_loaded
            b2.on_deliver = lambda cid, pubs: got2.extend(
                bytes(p.msg.payload) for p in pubs)
            b2.open_session("sub2")
            b2.subscribe("sub2", "t/#", SubOpts())
            await settle(lambda: ms2 is not None and ms2.ready,
                         timeout=60)
            for i in range(40):
                topic = f"t/r{i}/x"
                await ms2.prefetch(topic)
                b2.publish(make_message("pub", topic, b"r%d" % i))
            rebuilt_ok = await settle(lambda: len(got2) >= 40)
        finally:
            await node2.stop()
        return {
            "ok": bool(swapped and killed and resumed and seg_exists
                       and delivered == sent and rejected
                       and rebuilt_ok and restarts >= 1),
            "delivered": delivered, "sent": sent,
            "delivery_ratio": round(delivered / max(1, sent), 4),
            "restarts": restarts,
            "compact_runs": compact_runs,
            "swap_fault_recovered": swapped,
            "kill_resumed": resumed,
            "corrupt_segment_rejected": rejected,
            "rebuild_served": bool(rebuilt_ok),
        }

    async def pipeline_cycle():
        """Overlapped-serve-pipeline chaos (ISSUE 11): a clean storm,
        a storm with the match.readback child killed mid-flight, and a
        10%-injected match.readback fault storm — delivery 1.0
        throughout, waiters failing over to the CPU trie instead of
        stalling toward the prefetch timeout, supervised restart
        resumes the two-phase readback."""
        import time as _time

        from emqx_tpu import faultinject as fi
        from emqx_tpu.broker.message import make_message
        from emqx_tpu.config import Config
        from emqx_tpu.faultinject import FaultInjector
        from emqx_tpu.node import BrokerNode

        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", True)
        cfg.put("tpu.mirror_refresh_interval", 0.01)
        cfg.put("tpu.bypass_rate", 0.0)
        cfg.put("match.pipeline.enable", True)
        cfg.put("supervisor.backoff_base", 0.005)
        cfg.put("supervisor.backoff_max", 0.05)
        node = BrokerNode(cfg)
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            if ms is None:
                return {"skipped": "match service unavailable"}
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            await settle(lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                         timeout=60)

            async def storm(n, base, kill_at=None):
                child = node.supervisor.lookup("match.readback")
                waits = []
                for i in range(n):
                    topic = f"t/{base + i}/x"
                    t0 = _time.perf_counter()
                    await ms.prefetch(topic)
                    waits.append(_time.perf_counter() - t0)
                    b.publish(make_message(
                        "pub", topic, b"%d" % (base + i)))
                    if kill_at is not None and i == kill_at:
                        child.kill()
                return waits

            n = 100
            clean = await storm(n, 0)
            killed = await storm(n, 1000, kill_at=40)
            inj = fi.install(FaultInjector([
                {"point": "match.readback", "action": "raise",
                 "prob": 0.1, "times": 0}], seed=7))
            wounded = await storm(n, 2000)
            fi.uninstall()
            sent = 3 * n
            delivered = len(got)
            restarts = node.observed.metrics.get(
                "broker.supervisor.restarts")
            worst = max(clean + killed + wounded)
            rb_bytes = node.observed.metrics.get(
                "tpu.match.readback_bytes")
            return {
                "ok": bool(delivered == sent and restarts >= 1
                           and inj.fired.get("match.readback", 0) >= 1
                           and worst < ms.prefetch_timeout_s * 0.9
                           and rb_bytes > 0),
                "delivered": delivered, "sent": sent,
                "delivery_ratio": round(delivered / max(1, sent), 4),
                "restarts": restarts,
                "readback_faults": inj.fired.get("match.readback", 0),
                "worst_waiter_ms": round(worst * 1e3, 1),
                "readback_bytes": rb_bytes,
                "cpu_fallback": node.observed.metrics.get(
                    "broker.match.cpu_fallback"),
            }
        finally:
            fi.uninstall()
            await node.stop()

    async def admission_cycle():
        """Admission-plane chaos (ISSUE 14): an attacker is quarantined
        mid-storm, then the admission.score child is killed AND 10%
        admission.score faults are injected — every failure FAILS OPEN
        (standing decisions clear, admission_degraded raises, honest
        AND attacker traffic flows — never a new drop path), and the
        supervised restart resumes scoring, re-quarantines the
        attacker and clears the alarm."""
        from emqx_tpu import faultinject as fi
        from emqx_tpu.broker.message import make_message
        from emqx_tpu.config import Config
        from emqx_tpu.faultinject import FaultInjector
        from emqx_tpu.node import BrokerNode

        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", False)
        cfg.put("admission.enable", True)
        cfg.put("admission.tick", 0.02)
        cfg.put("admission.hold_ticks", 2)
        cfg.put("admission.decay_ticks", 1000)   # no decay mid-test
        # the synthetic storm drives BOTH clients at the same msgs/s;
        # only the attacker's topic-scan shape (fresh topic per
        # message) must trip, so the verdict rides the fan dimension
        cfg.put("admission.max_publish_rate", 1_000_000.0)
        cfg.put("admission.fan_window", 0.1)
        cfg.put("admission.max_topic_fan", 50.0)
        cfg.put("supervisor.backoff_base", 0.005)
        cfg.put("supervisor.backoff_max", 0.05)
        node = BrokerNode(cfg)
        await node.start()
        try:
            b = node.broker
            adm = node.admission
            alarms = node.observed.alarms
            sess, _ = b.open_session("sub", max_inflight=64)
            b.subscribe("sub", "t/#", SubOpts(qos=1))
            got = []

            def on_deliver(cid, pubs):
                stack = list(pubs)
                while stack:
                    p = stack.pop(0)
                    got.append(p.msg.payload)
                    if p.pid is not None:
                        _, more = sess.puback(p.pid)
                        stack.extend(more)

            b.on_deliver = on_deliver
            seq = [0]
            sent = [0]

            def storm(n_honest=40, atk_per=40):
                # drive the REAL ingest seams: publish notes + the
                # QoS0-shed enforcement path in Broker.publish
                for _ in range(n_honest):
                    i = seq[0]
                    seq[0] += 1
                    sent[0] += 1
                    adm.note_publish("honest", "t/h", 64)
                    b.publish(make_message("honest", "t/h", b"%d" % i,
                                           qos=1))
                for k in range(atk_per):
                    topic = f"scan/{seq[0]}/{k}"
                    adm.note_publish("attacker", topic, 64)
                    b.publish(make_message("attacker", topic, b"a",
                                           qos=0))

            # phase 1: attacker climbs to quarantine; honest stays clean
            for _ in range(60):
                storm()
                await aio.sleep(0.01)
                if "attacker" in adm._shed:
                    break
            quarantined = "attacker" in adm._shed
            honest_row = adm.explain("honest")
            honest_clean = bool(
                honest_row is not None and honest_row["level"] == 0
                and not node.banned.check(clientid="honest"))
            shed_before = adm.shed_count
            storm()
            attacker_shed = adm.shed_count > shed_before

            # phase 2: a PERSISTENT injected fault crashes every tick
            # (the restarted child dies again) + an explicit kill —
            # fail-open must hold the whole time: shed set empty,
            # alarm active, attacker traffic flowing unscreened
            fi.install(FaultInjector([
                {"point": "admission.score", "action": "raise",
                 "times": 0}]))
            child = node.supervisor.lookup("admission.score")
            killed = child is not None and child.kill()
            failed_open = await settle(
                lambda: adm.degraded
                and alarms.is_active("admission_degraded")
                and "attacker" not in adm._shed)
            shed_frozen = adm.shed_count
            storm()
            no_new_drop_path = adm.shed_count == shed_frozen

            # phase 3: lift the fault → supervised restart resumes
            # scoring, re-quarantines the attacker, clears the alarm
            fi.uninstall()
            give_up = aio.get_event_loop().time() + 10.0
            while "attacker" not in adm._shed \
                    and aio.get_event_loop().time() < give_up:
                storm()
                await aio.sleep(0.01)
            recovered = "attacker" in adm._shed
            alarm_cleared = await settle(
                lambda: not alarms.is_active("admission_degraded"))

            # phase 4: 10% injected admission.score faults mid-storm —
            # wounded ticks fail open + restart, honest delivery holds
            inj = fi.install(FaultInjector([
                {"point": "admission.score", "action": "raise",
                 "prob": 0.1, "times": 0}], seed=5))
            for _ in range(30):
                storm()
                await aio.sleep(0.01)
            fi.uninstall()
            faults = inj.fired.get("admission.score", 0)
            ok_drain = await settle(lambda: len(got) >= sent[0])
            restarts = node.observed.metrics.get(
                "broker.supervisor.restarts")
            fail_opens = node.observed.metrics.get(
                "broker.admission.fail_open")
            delivered = len(got)
            return {
                "ok": bool(quarantined and honest_clean
                           and attacker_shed and killed
                           and failed_open and no_new_drop_path
                           and recovered and alarm_cleared and ok_drain
                           and delivered == sent[0]
                           and restarts >= 1 and faults >= 1),
                "delivered": delivered, "sent": sent[0],
                "delivery_ratio": round(
                    delivered / max(1, sent[0]), 4),
                "restarts": restarts,
                "fail_opens": fail_opens,
                "score_faults": faults,
                "quarantined_then_shed": bool(quarantined
                                              and attacker_shed),
                "honest_never_flagged": honest_clean,
                "failed_open": bool(failed_open),
                "no_new_drop_path": bool(no_new_drop_path),
                "alarm_raised_and_cleared": bool(failed_open
                                                 and alarm_cleared),
                "requarantined_after_restart": bool(recovered),
            }
        finally:
            fi.uninstall()
            await node.stop()

    async def all_cycles():
        return {
            "fanout": await fanout_cycle(),
            "cluster": await cluster_cycle(),
            "bridge": await bridge_cycle(),
            "exhook": await exhook_cycle(),
            "match": await match_cycle(),
            "pipeline": await pipeline_cycle(),
            "segments": await segments_cycle(),
            "admission": await admission_cycle(),
            # degraded-mesh cycle (ISSUE 18): shard kill → degraded
            # serving → supervised rebuild (one injected crash =
            # restart evidence) → canary re-admit, delivery 1.0
            # throughout.  Needs an 8-device mesh, so it rides the
            # same subprocess isolation as the multichip A/Bs.
            "mesh": await aio.to_thread(
                _mesh_smoke, "bench_mesh_chaos_smoke", 96),
        }

    return aio.run(all_cycles())


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(prog="bench_e2e")
    ap.add_argument("--smoke", action="store_true",
                    help="small-N CPU smoke (<60 s), for per-PR tracking")
    ap.add_argument("--chaos", action="store_true",
                    help="add one kill-and-recover cycle per subsystem")
    ap.add_argument("--duration", type=float, default=None,
                    help="override per-run duration (s)")
    args = ap.parse_args(argv)

    from bench import (
        _adversarial_size, _config1_size, _config1_sweep_size,
        _fanout_e2e_size, _qos1_e2e_size, _qos2_e2e_size,
        _table_lifecycle_size, bench_adversarial, bench_config1,
        bench_config1_sweep, bench_fanout_e2e, bench_kernel_join_smoke,
        bench_qos1_e2e, bench_qos2_e2e, bench_serve_deadline_smoke,
        bench_serve_pipeline_smoke, bench_serve_roundtrip_smoke,
        bench_table_lifecycle,
    )

    size = _fanout_e2e_size(args.smoke)
    qsize = _qos1_e2e_size(args.smoke)
    q2size = _qos2_e2e_size(args.smoke)
    c1size = _config1_size(args.smoke)
    c1ssize = _config1_sweep_size(args.smoke)
    if args.duration is not None:
        size["duration"] = args.duration
        qsize["duration"] = args.duration
        q2size["duration"] = args.duration
        c1size["duration"] = args.duration
        c1ssize["duration"] = args.duration
    out = bench_fanout_e2e(**size)
    out["qos1"] = bench_qos1_e2e(**qsize)
    out["qos2"] = bench_qos2_e2e(**q2size)
    # connection-plane tracking numbers (PR 6): real-client config1
    # flag-off/flag-on A/B + the client-count sweep at constant load
    out["config1"] = bench_config1(**c1size)
    out["config1_sweep"] = bench_config1_sweep(**c1ssize)
    # deadline serve A/B (ISSUE 7): static vs deadline-mode continuous
    # batching at the same offered load, CPU-jax tiny scale — tracks
    # structure + delivery per PR; the real ratio comes from bench.py
    out["serve_deadline"] = bench_serve_deadline_smoke(
        seconds=(1.2 if args.smoke else 4.0))
    # overlapped serve pipeline A/B (ISSUE 11): serial round trips vs
    # the double-buffered chain with match-proportional two-phase
    # readback, same offered load; gates ride the JSON with the
    # host-dependent p99 bound (1-core hosts can't overlap stages)
    out["serve_pipeline"] = bench_serve_pipeline_smoke(
        seconds=(1.2 if args.smoke else 4.0))
    # one-round-trip serve A/B (ISSUE 17): chunked vs ragged readback
    # transfer shape at equal load — the ≤2-round-trip and bit-parity
    # gates are CI-asserted; the latency ratio is a tracking number
    # (loopback d2h has no RTT for the single transfer to win back)
    out["serve_roundtrip"] = bench_serve_roundtrip_smoke(
        seconds=(1.0 if args.smoke else 3.0))
    # streaming table lifecycle A/B (ISSUE 9): segment cold start vs
    # full rebuild + churn soak across live compaction swaps
    out["table_lifecycle"] = bench_table_lifecycle(
        **_table_lifecycle_size(args.smoke))
    # adversarial admission A/B (ISSUE 14): 5% attackers at 10x the
    # honest rate + a CONNECT storm — flag-on holds honest delivery 1.0
    # and p99 near clean while the ladder throttles/quarantines/bans
    # the attackers; flag-off records the brownout the gate prevents
    out["adversarial"] = bench_adversarial(**_adversarial_size(args.smoke))
    # kernel backend A/B (ISSUE 13): hash vs join vs auto at one serve
    # shape, short+deep mixes — the parity gate is CI-asserted, the
    # speedup ratios are tracking numbers for the r06 hardware round
    out["kernel_join"] = bench_kernel_join_smoke(
        n_filters=(2000 if args.smoke else 20000))
    # multichip serve A/B (ISSUE 15): the table sharded by topic-prefix
    # over the virtual 8-device CPU mesh vs the single-chip serve path
    # — parity / truncation-psum / shard-kill gates are CI-asserted;
    # the scaling ratio is a tracking number (8 host threads share one
    # CPU; bench.py's r06 hardware round owns the ≥6x claim).  Runs in
    # its own subprocess so the forced 8-device mesh cannot slow the
    # single-chip rows above.
    out["multichip_serve"] = multichip_serve_smoke(
        n_filters=(2000 if args.smoke else 20000))
    # prefix-EP routed vs replicated A/B (ISSUE 16): routed parity,
    # bucket-overflow fail-open, the per-shard width contract
    # (gate_shard_width_le_batch_over_tp) and routed-path shard-kill
    # failover are CI-asserted; the routed speedup is a tracking
    # number (host threads pay the all_to_all without the ICI win).
    out["multichip_ep"] = multichip_ep_smoke(
        n_filters=(2000 if args.smoke else 20000))
    # load-adaptive plane A/B (ISSUE 20): overflow-EWMA capacity grow
    # with zero dropped rows through the compile window, popularity
    # rebalance worst-shard width cut >= 1.5x on the skewed corpus,
    # post-remap routed parity, cold-start placement restore, and the
    # ep.rebalance fault no-op — all CI-asserted; the adaptive
    # speedup is a tracking number (host threads share one CPU).
    out["multichip_balance"] = multichip_balance_smoke(
        n_filters=(2000 if args.smoke else 20000))
    # stage-latency observatory parity (ISSUE 12): the serve sections'
    # p50/p99 now come from the product's histograms (observe/hist.py);
    # the legacy np.percentile extraction over the SAME post-warmup
    # samples must agree before the parallel lists stay deleted.  A
    # parity break here is a histogram-math bug, so the smoke fails
    # loudly instead of recording a gate nobody reads.
    for side in ("static", "deadline"):
        sec = out["serve_deadline"].get(side)
        if sec and "gate_hist_parity" in sec:
            assert sec["gate_hist_parity"], (
                "serve_deadline histogram/np.percentile parity broke",
                side, sec)
    for side in ("serial", "pipeline"):
        sec = out["serve_pipeline"].get(side)
        if sec and "gate_hist_parity" in sec:
            assert sec["gate_hist_parity"], (
                "serve_pipeline histogram/np.percentile parity broke",
                side, sec)
    # staticcheck gate row (ISSUE 19): the cold full-tree scan must
    # stay clean (exit 0, zero live waivers) and under the bench-box
    # cold budget — the per-PR smoke is where analysis regressions
    # (a rule gone quadratic, a new real finding) surface first
    out["staticcheck"] = staticcheck_gate()
    assert out["staticcheck"]["gate_clean"], (
        "staticcheck found new findings (or the CLI crashed)",
        out["staticcheck"])
    if args.chaos:
        out["chaos"] = chaos_smoke()
    print(json.dumps(out, indent=2))
    return out


if __name__ == "__main__":
    main()
