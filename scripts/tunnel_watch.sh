#!/bin/bash
# Poll the TPU tunnel with a REAL jit computation (jax.devices() can
# succeed while the data path is wedged); the moment it answers, run
# the full 10M bench and archive the artifact with a round tag so
# bench.py's outage fallback picks it up.  One-shot: exits after the
# first successful bench (or when $1 retries are exhausted).
set -u
TAG=${TAG:-r5e}
TRIES=${1:-120}                 # default: ~4 h at 2 min/poll
OUT=/tmp/tpu_run
mkdir -p "$OUT"
for i in $(seq 1 "$TRIES"); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
print('TPU OK', jax.jit(lambda x: x + 1)(jnp.ones((8, 128))).sum())" \
      >/dev/null 2>&1; then
    echo "[tunnel_watch] probe ok (try $i) $(date -u +%H:%M:%SZ); running bench"
    if timeout 3000 python bench.py \
        > "$OUT/bench_10m_${TAG}.json" 2> "$OUT/bench_10m_${TAG}.err" \
        && [ -s "$OUT/bench_10m_${TAG}.json" ] \
        && ! grep -q device_unreachable "$OUT/bench_10m_${TAG}.json"; then
      DATE=$(date -u +%Y%m%d)
      cp "$OUT/bench_10m_${TAG}.json" \
         "scripts/measured_bench_10m_${TAG}_${DATE}.json"
      echo "[tunnel_watch] archived scripts/measured_bench_10m_${TAG}_${DATE}.json"
      exit 0
    fi
    echo "[tunnel_watch] bench failed/unreachable mid-run; resuming polls"
  fi
  sleep 110
done
echo "[tunnel_watch] gave up after $TRIES tries"
exit 1
