#!/usr/bin/env python
"""Honest kernel profile: force with a device->host readback (the axon
block_until_ready returns early), and separate per-call dispatch cost
from device compute by looping the kernel inside ONE jit via lax.scan
with a data dependence between iterations.
"""
import argparse
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def force_time(fn, args, iters):
    """Enqueue iters calls back-to-back, force via readback of the last
    result; returns seconds/iter (bench.py methodology)."""
    r = fn(*args)
    np.asarray(r[0])  # warm + sync
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        rs = [fn(*args) for _ in range(iters)]
        np.asarray(rs[-1][0])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filters", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import build_workload
    from emqx_tpu.ops import compile_filters, encode_topics
    from emqx_tpu.ops.match_kernel import nfa_match

    rng = np.random.default_rng(42)
    filters, topics = build_workload(rng, args.filters, args.batch, args.depth)
    t0 = time.perf_counter()
    table = compile_filters(filters, depth=args.depth)
    print(f"compile {time.perf_counter()-t0:.1f}s states={table.n_states} "
          f"S={table.node_tab.shape[0]} Hb={table.edge_tab.shape[0]}")
    words, lens, is_sys = encode_topics(table, topics[: args.batch],
                                        batch=args.batch)
    arrs = [jnp.asarray(a) for a in table.device_arrays()]
    dev_args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
                *arrs)

    B = args.batch
    ms = force_time(
        lambda *a: nfa_match(*a, active_slots=16, max_matches=32).matches[
            None], dev_args, args.iters) * 1e3
    print(f"single-call A=16: {ms:7.2f} ms/batch  "
          f"{B/ms*1e3/1e6:.2f}M t/s")

    # device-side loop: N kernel runs inside one jit, chained so XLA
    # can't elide them; isolates device compute from dispatch/tunnel
    N = 16

    @jax.jit
    def looped(words, lens, is_sys, node, edge, seeds):
        def body(carry, _):
            w = jnp.bitwise_xor(words, carry)  # cheap data dependence
            r = nfa_match(w, lens, is_sys, node, edge, seeds,
                          active_slots=16, max_matches=32)
            return (carry + r.n_matches[0]) % 2, r.n_matches

        c, outs = jax.lax.scan(body, jnp.int32(0), None, length=N)
        return outs

    r = looped(*dev_args)
    np.asarray(r)
    t0 = time.perf_counter()
    r = looped(*dev_args)
    np.asarray(r)
    per = (time.perf_counter() - t0) / N * 1e3
    print(f"device-looped x{N}: {per:7.2f} ms/batch (pure device)  "
          f"{B/per*1e3/1e6:.2f}M t/s")

    for A in (4, 8, 32):
        ms = force_time(
            lambda *a: nfa_match(*a, active_slots=A, max_matches=32).matches[
                None], dev_args, args.iters) * 1e3
        print(f"single-call A={A:2d}: {ms:7.2f} ms/batch  "
              f"{B/ms*1e3/1e6:.2f}M t/s")

    for B2 in (16384, 32768):
        tt = (topics * ((B2 // len(topics)) + 1))[:B2]
        w2, l2, s2 = encode_topics(table, tt, batch=B2)
        a2 = (jnp.asarray(w2), jnp.asarray(l2), jnp.asarray(s2), *arrs)
        ms = force_time(
            lambda *a: nfa_match(*a, active_slots=16, max_matches=32).matches[
                None], a2, args.iters) * 1e3
        print(f"batch={B2:6d} A=16: {ms:7.2f} ms/batch  "
              f"{B2/ms*1e3/1e6:.2f}M t/s")


if __name__ == "__main__":
    main()
