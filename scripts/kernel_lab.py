#!/usr/bin/env python
"""Kernel layout lab: candidate nfa_match v2 designs, timed on the real
chip against the shipping kernel at the bench shape.

Variants (cumulative where it makes sense):
  base      — shipping nfa_match (2-choice cuckoo, per-step top_k)
  sh        — single-hash wide-bucket edge table (1 gather/step, 16 or 32
              slots/bucket, 0.5 load target)
  cc        — cumsum-compaction of the active set instead of top_k
  fc        — cumsum-compaction of the final accept list instead of top_k
  all       — sh + cc + fc
Sweeps A ∈ {8, 16} for the winners.
"""
import argparse
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def force_time(fn, args, iters=20):
    r = fn(*args)
    jax.tree_util.tree_map(np.asarray, r)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        rs = [fn(*args) for _ in range(iters)]
        np.asarray(jax.tree_util.tree_leaves(rs[-1])[0])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


# --- single-hash wide-bucket edge table --------------------------------------

def build_single_hash(edges, slots_per_bucket=16, seed=7, target_load=0.5):
    """Place (state, word, next) into a 1-choice table of wide buckets.
    Returns (tab (Hb, slots*4) int32, seed int32). Grows until no bucket
    overflows."""
    from emqx_tpu.ops.compiler import _bucket

    n = len(edges)
    Hb = _bucket(max(1, int(n / (slots_per_bucket * target_load))), 8)
    rng = np.random.default_rng(seed)
    arr = np.asarray(edges, np.int64)
    while True:
        sd = np.uint32(rng.integers(1, 2**31 - 1))
        mask = np.uint32(Hb - 1)
        with np.errstate(over="ignore"):
            h = (
                arr[:, 0].astype(np.uint32) * np.uint32(2654435761)
                + arr[:, 1].astype(np.uint32) * np.uint32(2246822519)
                + sd
            )
            h ^= h >> np.uint32(16)
            h *= np.uint32(3266489917)
            h ^= h >> np.uint32(13)
            b = (h & mask).astype(np.int64)
        order = np.argsort(b, kind="stable")
        bs = b[order]
        # rank within bucket
        uniq, start, counts = np.unique(bs, return_index=True, return_counts=True)
        if counts.max() > slots_per_bucket:
            Hb <<= 1
            continue
        rank = np.arange(len(bs)) - np.repeat(start, counts)
        tab = np.full((Hb, slots_per_bucket, 4), -1, np.int32)
        e = arr[order]
        tab[bs, rank, 0] = e[:, 0]
        tab[bs, rank, 1] = e[:, 1]
        tab[bs, rank, 2] = e[:, 2]
        return tab.reshape(Hb, slots_per_bucket * 4), np.int32(sd)


def sh_lookup(state, word, tab, seed, slots):
    Hb = tab.shape[0]
    mask = Hb - 1
    B, A = state.shape
    h = (
        state.astype(jnp.uint32) * jnp.uint32(2654435761)
        + word.astype(jnp.uint32) * jnp.uint32(2246822519)
        + seed.astype(jnp.uint32)
    )
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(3266489917)
    h = h ^ (h >> jnp.uint32(13))
    b = (h & jnp.uint32(mask)).astype(jnp.int32)
    rows = tab[b].reshape(B, A, slots, 4)
    hit = (rows[..., 0] == state[..., None]) & (rows[..., 1] == word[..., None])
    return jnp.max(jnp.where(hit, rows[..., 2], -1), axis=-1)


def compact_cc(cand, A):
    """Valids-first compaction — the shipping kernel's implementation."""
    from emqx_tpu.ops.match_kernel import _compact

    return _compact(cand, A)


def make_variant(D, use_sh, use_cc, use_fc, A, K, slots):
    from emqx_tpu.ops.match_kernel import _edge_lookup

    @jax.jit
    def run(words, lens, is_sys, node_tab, edge_tab, seeds):
        B = words.shape[0]
        active = jnp.full((B, A), -1, jnp.int32).at[:, 0].set(0)
        accept_cols = []
        for t in range(D + 1):
            valid = active >= 0
            sa = jnp.maximum(active, 0)
            node = node_tab[sa]
            hacc = jnp.where(valid, node[..., 1], -1)
            if t == 0:
                hacc = jnp.where(is_sys[:, None], -1, hacc)
            at_end = (t == lens)[:, None]
            eacc = jnp.where(valid & at_end, node[..., 2], -1)
            accept_cols.append(jnp.concatenate([hacc, eacc], axis=1))
            if t == D:
                break
            w = jnp.broadcast_to(words[:, t][:, None], active.shape)
            if use_sh:
                lit = sh_lookup(active, w, edge_tab, seeds, slots)
            else:
                lit = _edge_lookup(active, w, edge_tab, seeds)
            lit = jnp.where(valid, lit, -1)
            plus = jnp.where(valid, node[..., 0], -1)
            if t == 0:
                plus = jnp.where(is_sys[:, None], -1, plus)
            cand = jnp.concatenate([lit, plus], axis=1)
            cand = jnp.where((t < lens)[:, None], cand, -1)
            if use_cc:
                active = compact_cc(cand, A)
            else:
                active, _ = jax.lax.top_k(cand, A)
        flat = jnp.concatenate(accept_cols, axis=1)
        n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
        if use_fc:
            topk = compact_cc(flat, K)
        else:
            topk, _ = jax.lax.top_k(flat, K)
        return topk, n

    return run


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filters", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--depth", type=int, default=8)
    args = ap.parse_args()

    from bench import build_workload
    from emqx_tpu.ops import compile_filters, encode_topics
    from emqx_tpu.ops.match_kernel import nfa_match

    rng = np.random.default_rng(42)
    filters, topics = build_workload(rng, args.filters, args.batch, args.depth)
    t0 = time.perf_counter()
    table = compile_filters(filters, depth=args.depth)
    print(f"compile {time.perf_counter()-t0:.1f}s states={table.n_states} "
          f"S={table.node_tab.shape[0]} Hb={table.edge_tab.shape[0]}")
    words, lens, is_sys = encode_topics(table, topics[: args.batch],
                                        batch=args.batch)
    wla = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys))
    arrs = [jnp.asarray(a) for a in table.device_arrays()]

    # reference answer for parity
    ref = nfa_match(*wla, *arrs, active_slots=16, max_matches=32)
    ref_n = np.asarray(ref.n_matches)
    ref_sets = [set(r[r >= 0].tolist()) for r in np.asarray(ref.matches)]

    B = args.batch
    ms = force_time(
        lambda *a: nfa_match(*a, active_slots=16, max_matches=32).matches,
        (*wla, *arrs))
    print(f"base A=16           : {ms:7.2f} ms  {B/ms*1e3/1e6:.2f}M t/s")

    # single-hash tables
    from emqx_tpu.ops.compiler import BUCKET_SLOTS
    et = np.asarray(table.edge_tab).reshape(-1, 4)
    edges = [(int(a), int(b), int(c)) for a, b, c, _ in et[et[:, 0] >= 0]]
    sh_tabs = {}
    for slots in (8, 16, 32):
        t0 = time.perf_counter()
        tab, sd = build_single_hash(edges, slots)
        sh_tabs[slots] = (jnp.asarray(tab), jnp.asarray(sd))
        print(f"  sh build slots={slots}: Hb={tab.shape[0]} "
              f"load={len(edges)/(tab.shape[0]*slots):.2f} "
              f"{time.perf_counter()-t0:.1f}s")

    def check(out, name):
        topk, n = out
        n = np.asarray(n)
        m = np.asarray(topk)
        assert (n == ref_n).all(), f"{name}: n mismatch"
        for r in range(0, B, 97):
            got = set(m[r][m[r] >= 0].tolist())
            assert got == ref_sets[r], f"{name}: row {r} mismatch"

    for name, (use_sh, use_cc, use_fc, A, slots) in {
        "cc A=16"           : (False, True, False, 16, 0),
        "fc A=16"           : (False, False, True, 16, 0),
        "cc+fc A=16"        : (False, True, True, 16, 0),
        "sh16 A=16"         : (True, False, False, 16, 16),
        "sh16+cc+fc A=16"   : (True, True, True, 16, 16),
        "sh8+cc+fc A=16"    : (True, True, True, 16, 8),
        "sh32+cc+fc A=16"   : (True, True, True, 16, 32),
        "cc+fc A=8"         : (False, True, True, 8, 0),
        "sh16+cc+fc A=8"    : (True, True, True, 8, 16),
        "sh32+cc+fc A=8"    : (True, True, True, 8, 32),
    }.items():
        fn = make_variant(args.depth, use_sh, use_cc, use_fc, A, 32, slots)
        a = (*wla, arrs[0], *(sh_tabs[slots] if use_sh else (arrs[1], arrs[2])))
        out = fn(*a)
        if A >= 16:
            check(out, name)
        ms = force_time(fn, a)
        print(f"{name:20s}: {ms:7.2f} ms  {B/ms*1e3/1e6:.2f}M t/s")


if __name__ == "__main__":
    main()
