"""Where does the serving path lose throughput vs the raw pipelined
loop?  Measures, at --filters scale on the real chip:

  a) raw pipelined loop, pre-uploaded arrays (the bench 'tpu' number)
  b) encode+upload per iter, readback every iter, inflight=K
  c) like (b) but with encode in a worker thread (overlap host/device)

Run: python scripts/serve_path_lab.py [--filters 200000 --batch 8192]
"""

import argparse
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, ".")
from bench import _encode, build_table, build_workload  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filters", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--depth", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from emqx_tpu.ops.device_table import DeviceNfa

    rng = np.random.default_rng(7)
    filters, topics = build_workload(rng, args.filters, args.batch * 4,
                                     args.depth)
    table, kind, build_s = build_table(filters, args.depth)
    print(f"table {kind} build {build_s:.1f}s", flush=True)
    dev = DeviceNfa(table, active_slots=8, compact_output=True)
    names = topics[:args.batch]

    def enc():
        return _encode(table, names, args.depth, args.batch)

    w, l, s = enc()
    arrs = tuple(map(jnp.asarray, (w, l, s)))
    r = dev.match(*arrs)
    np.asarray(r.matches)  # warm

    # (a) raw pipelined, pre-uploaded
    t0 = time.perf_counter()
    rs = [dev.match(*arrs) for _ in range(args.iters)]
    np.asarray(rs[-1].matches)
    a = (time.perf_counter() - t0) / args.iters
    print(f"a) raw pipelined pre-uploaded : {a*1e3:7.2f} ms/batch "
          f"{args.batch/a:,.0f} t/s", flush=True)

    # (a2) same but read back EVERY iter (still enqueued ahead? no — sync)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        np.asarray(dev.match(*arrs).matches)
    a2 = (time.perf_counter() - t0) / args.iters
    print(f"a2) sync readback every iter  : {a2*1e3:7.2f} ms/batch "
          f"{args.batch/a2:,.0f} t/s", flush=True)

    # (b) encode+upload per iter, inflight K
    for k in (1, 3, 6):
        t0 = time.perf_counter()
        inflight = []
        for _ in range(args.iters):
            w, l, s = enc()
            inflight.append(dev.match(jnp.asarray(w), jnp.asarray(l),
                                      jnp.asarray(s)))
            if len(inflight) >= k:
                np.asarray(inflight.pop(0).matches)
        for r in inflight:
            np.asarray(r.matches)
        b = (time.perf_counter() - t0) / args.iters
        print(f"b) enc+upload, inflight={k}    : {b*1e3:7.2f} ms/batch "
              f"{args.batch/b:,.0f} t/s", flush=True)

    # (c) encode in a thread, double-buffered, inflight 3
    pool = ThreadPoolExecutor(2)
    t0 = time.perf_counter()
    inflight = []
    fut = pool.submit(enc)
    for _ in range(args.iters):
        w, l, s = fut.result()
        fut = pool.submit(enc)
        inflight.append(dev.match(jnp.asarray(w), jnp.asarray(l),
                                  jnp.asarray(s)))
        if len(inflight) >= 3:
            np.asarray(inflight.pop(0).matches)
    for r in inflight:
        np.asarray(r.matches)
    c = (time.perf_counter() - t0) / args.iters
    print(f"c) threaded encode, inflight=3: {c*1e3:7.2f} ms/batch "
          f"{args.batch/c:,.0f} t/s", flush=True)

    # component timings
    t0 = time.perf_counter()
    for _ in range(args.iters):
        enc()
    print(f"   encode alone              : "
          f"{(time.perf_counter()-t0)/args.iters*1e3:7.2f} ms", flush=True)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        jnp.asarray(w).block_until_ready()
        jnp.asarray(l).block_until_ready()
        jnp.asarray(s).block_until_ready()
    print(f"   upload alone              : "
          f"{(time.perf_counter()-t0)/args.iters*1e3:7.2f} ms", flush=True)
    m = np.asarray(rs[-1].matches)
    t0 = time.perf_counter()
    for r in [dev.match(*arrs) for _ in range(args.iters)]:
        pass
    t_enq = (time.perf_counter() - t0) / args.iters
    print(f"   enqueue alone             : {t_enq*1e3:7.2f} ms", flush=True)
    print(f"   matches bytes             : {m.nbytes}", flush=True)


if __name__ == "__main__":
    main()
