"""Channel FSM integration tests: full CONNECT/SUB/PUB round trips at the
packet level — the in-VM integration style of emqx CT suites (SURVEY.md
§4) without protocol mocks."""

import pytest

from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.channel import Channel
from emqx_tpu.mqtt import packet as P


def mk(broker=None, **kw):
    broker = broker or Broker()
    cm = ConnectionManager(broker)
    return broker, cm, Channel(broker, cm, **kw)


def connect(ch, clientid="c1", ver=4, **kw):
    return ch.handle_in(P.Connect(proto_ver=ver, clientid=clientid, **kw))


def sends(actions):
    return [a[1] for a in actions if a[0] == "send"]


def test_connect_connack():
    _, _, ch = mk()
    acts = connect(ch)
    (ack,) = sends(acts)
    assert ack.type == P.CONNACK and ack.reason_code == 0
    assert not ack.session_present
    assert ch.state == "connected"


def test_packet_before_connect_closes():
    _, _, ch = mk()
    acts = ch.handle_in(P.PingReq())
    assert acts[0][0] == "close"


def test_duplicate_connect_closes():
    _, _, ch = mk()
    connect(ch)
    assert ch.handle_in(P.Connect(clientid="c1"))[0][0] == "close"


def test_v5_assigned_clientid():
    _, _, ch = mk()
    (ack,) = sends(connect(ch, clientid="", ver=5))
    assert "Assigned-Client-Identifier" in ack.properties
    assert ch.clientid.startswith("emqx_tpu_")


def test_v3_empty_clientid_no_cleanstart_rejected():
    _, _, ch = mk()
    acts = connect(ch, clientid="", clean_start=False)
    ack = sends(acts)[0]
    assert ack.reason_code != 0
    assert acts[-1][0] == "close"


def test_auth_hook_rejects():
    b, _, ch = mk()
    b.hooks.add(
        "client.authenticate",
        lambda cid, u, pw, info, acc: (P.RC.BAD_USER_NAME_OR_PASSWORD
                                       if pw != b"secret" else acc),
    )
    acts = connect(ch, username="u", password=b"wrong")
    assert sends(acts)[0].reason_code == 4  # v3 bad credentials
    b2, _, ch2 = mk()
    b2.hooks.add(
        "client.authenticate",
        lambda cid, u, pw, info, acc: acc if pw == b"secret" else 0x86,
    )
    acts = connect(ch2, username="u", password=b"secret")
    assert sends(acts)[0].reason_code == 0


def test_subscribe_publish_roundtrip():
    b, cm, ch_sub = mk()
    ch_pub = Channel(b, cm)
    connect(ch_sub, "sub")
    connect(ch_pub, "pub")
    (suback,) = sends(
        ch_sub.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t/+", {"qos": 1})]))
    )
    assert suback.reason_codes == [1]
    acts = ch_pub.handle_in(
        P.Publish(topic="t/x", qos=1, packet_id=9, payload=b"hi")
    )
    (puback,) = sends(acts)
    assert puback.type == P.PUBACK and puback.packet_id == 9
    # delivery to subscriber goes through broker result → channel
    sess = b.sessions["sub"]
    assert len(sess.inflight) == 1


def test_qos2_inbound_exactly_once():
    b, cm, ch = mk()
    connect(ch, "c")
    deliveries = []
    b.hooks.add("message.publish", lambda m: deliveries.append(m) or m)
    pub = P.Publish(topic="t", qos=2, packet_id=5, payload=b"x")
    (rec,) = sends(ch.handle_in(pub))
    assert rec.type == P.PUBREC
    # duplicate PUBLISH same pid: PUBREC again but NOT re-published
    (rec2,) = sends(ch.handle_in(pub))
    assert rec2.type == P.PUBREC
    assert len(deliveries) == 1
    (comp,) = sends(ch.handle_in(P.PubAck(P.PUBREL, 5)))
    assert comp.type == P.PUBCOMP
    # after release the pid is fresh
    sends(ch.handle_in(pub))
    assert len(deliveries) == 2


def test_qos2_outbound_flow():
    b, cm, ch = mk()
    connect(ch, "s")
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 2})]))
    res = b.publish(
        __import__("emqx_tpu.broker", fromlist=["make_message"]).make_message(
            "pub", "t", b"x", qos=2
        )
    )
    (pub,) = ch.handle_deliver(res.publishes["s"])
    pub = pub[1] if isinstance(pub, tuple) else pub
    assert pub.qos == 2
    (rel,) = sends(ch.handle_in(P.PubAck(P.PUBREC, pub.packet_id)))
    assert rel.type == P.PUBREL
    assert sends(ch.handle_in(P.PubAck(P.PUBCOMP, pub.packet_id))) == []
    assert b.sessions["s"].inflight.is_empty()


def test_unsubscribe():
    b, cm, ch = mk()
    connect(ch, "c")
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("a", {"qos": 0})]))
    (unsuback,) = sends(
        ch.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["a", "nope"]))
    )
    assert unsuback.reason_codes == [0, 0x11]


def test_authz_hook_denies_subscribe_and_publish():
    b, cm, ch = mk()
    b.hooks.add(
        "client.authorize",
        lambda cid, action, topic, ctx, acc: (
            False if topic.startswith("secret") else acc
        ),
    )
    # 3.1.1 SUBACK only carries granted-QoS or 0x80 (spec §3.9.3)
    connect(ch, "c")
    (suback,) = sends(
        ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[
            ("secret/x", {"qos": 0}), ("open/x", {"qos": 0})]))
    )
    assert suback.reason_codes == [0x80, 0]
    (puback,) = sends(
        ch.handle_in(P.Publish(topic="secret/t", qos=1, packet_id=3))
    )
    assert puback.reason_code == P.RC.NOT_AUTHORIZED


def test_authz_deny_subscribe_v5_code():
    b, cm, ch = mk()
    b.hooks.add(
        "client.authorize",
        lambda cid, action, topic, ctx, acc: (
            False if topic.startswith("secret") else acc
        ),
    )
    connect(ch, "c", ver=5)
    (suback,) = sends(
        ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[
            ("secret/x", {"qos": 0}), ("open/x", {"qos": 0})]))
    )
    assert suback.reason_codes == [P.RC.NOT_AUTHORIZED, 0]


def test_invalid_topic_filter_in_subscribe():
    b, cm, ch = mk()
    connect(ch, "c")  # 3.1.1: failure is 0x80
    (suback,) = sends(
        ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("a/#/b", {"qos": 0})]))
    )
    assert suback.reason_codes == [0x80]
    b2, cm2, ch2 = mk()
    connect(ch2, "c", ver=5)
    (suback,) = sends(
        ch2.handle_in(P.Subscribe(packet_id=1, topic_filters=[("a/#/b", {"qos": 0})]))
    )
    assert suback.reason_codes == [P.RC.TOPIC_FILTER_INVALID]


def test_topic_alias_v5():
    b, cm, ch = mk()
    connect(ch, "c", ver=5)
    got = []
    b.hooks.add("message.publish", lambda m: got.append(m.topic) or m)
    ch.handle_in(P.Publish(topic="long/topic", payload=b"1",
                           properties={"Topic-Alias": 3}))
    ch.handle_in(P.Publish(topic="", payload=b"2",
                           properties={"Topic-Alias": 3}))
    assert got == ["long/topic", "long/topic"]
    acts = ch.handle_in(P.Publish(topic="", payload=b"3",
                                  properties={"Topic-Alias": 99}))
    assert acts[0][0] == "close"  # alias above maximum


def test_will_published_on_abnormal_close_only():
    b, cm, ch = mk()
    got = []
    b.hooks.add("message.publish", lambda m: got.append(m.topic) or m)
    connect(ch, "c", will=P.Will("will/t", b"gone"))
    ch2_actions = ch.handle_in(P.Disconnect())  # normal disconnect
    ch.handle_close("client disconnect")
    assert got == []  # will discarded
    # abnormal close fires the will
    b2, cm2, chx = mk()
    got2 = []
    b2.hooks.add("message.publish", lambda m: got2.append(m.topic) or m)
    chx.handle_in(P.Connect(clientid="c", will=P.Will("will/t", b"gone")))
    chx.handle_close("socket error")
    assert got2 == ["will/t"]


def test_disconnect_with_will_0x04():
    b, cm, ch = mk()
    got = []
    b.hooks.add("message.publish", lambda m: got.append(m.topic) or m)
    connect(ch, "c", ver=5, will=P.Will("w", b"x"))
    ch.handle_in(P.Disconnect(reason_code=0x04))
    ch.handle_close()
    assert got == ["w"]


def test_session_takeover():
    b, cm, ch1 = mk()
    connect(ch1, "dev1", clean_start=False)
    ch1.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    ch2 = Channel(b, cm)
    acts = ch2.handle_in(P.Connect(clientid="dev1", clean_start=False))
    takeovers = [a for a in acts if a[0] == "takeover"]
    assert takeovers and takeovers[0][1] is ch1
    ack = sends(acts)[0]
    assert ack.session_present
    # displaced channel: v3 gets plain close, no will
    old_acts = ch1.handle_takeover()
    assert old_acts[-1][0] == "close"
    # late close of displaced channel must not evict the new one
    ch1.handle_close("displaced")
    assert cm.lookup_channel("dev1") is ch2
    assert "t" in b.sessions["dev1"].subscriptions


def test_keepalive_timeout():
    b, cm, ch = mk()
    connect(ch, "c", keepalive=10)
    assert ch.check_keepalive(now=ch.last_rx + 14) == []
    acts = ch.check_keepalive(now=ch.last_rx + 16)
    assert acts and acts[0][0] == "close"


def test_keepalive_zero_never_times_out():
    b, cm, ch = mk()
    connect(ch, "c", keepalive=0)
    assert ch.check_keepalive(now=ch.last_rx + 1e9) == []


def test_retry_resends_dup():
    b, cm, ch = mk()
    connect(ch, "s")
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    from emqx_tpu.broker import make_message
    res = b.publish(make_message("p", "t", b"x", qos=1))
    ch.handle_deliver(res.publishes["s"])
    b.sessions["s"].retry_interval = 0.0
    (resend,) = sends(ch.retry_deliveries())
    assert resend.type == P.PUBLISH and resend.dup is True


def test_ping():
    b, cm, ch = mk()
    connect(ch, "c")
    (resp,) = sends(ch.handle_in(P.PingReq()))
    assert resp.type == P.PINGRESP


def test_late_close_of_displaced_channel_keeps_new_session():
    """A displaced channel closing late must not destroy the successor's
    live session (clean_start=True path)."""
    b, cm, ch1 = mk()
    connect(ch1, "dev", clean_start=True)
    ch2 = Channel(b, cm)
    ch2.handle_in(P.Connect(clientid="dev", clean_start=True))
    ch2.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 0})]))
    ch1.handle_takeover()
    ch1.handle_close("displaced")
    assert "dev" in b.sessions
    assert "t" in b.sessions["dev"].subscriptions
    assert cm.lookup_channel("dev") is ch2


def test_receive_maximum_zero_is_protocol_error():
    _, _, ch = mk()
    acts = connect(ch, "c", ver=5, properties={"Receive-Maximum": 0})
    assert sends(acts)[0].reason_code == P.RC.PROTOCOL_ERROR
    assert acts[-1][0] == "close"


def test_resume_renegotiates_receive_maximum():
    b, cm, ch1 = mk()
    connect(ch1, "c", ver=5, clean_start=False,
            properties={"Receive-Maximum": 32})
    assert b.sessions["c"].inflight.max_size == 32
    ch2 = Channel(b, cm)
    ch2.handle_in(P.Connect(proto_ver=5, clientid="c", clean_start=False,
                            properties={"Receive-Maximum": 1}))
    assert b.sessions["c"].inflight.max_size == 1


def test_takenover_hook_only_on_resume():
    b, cm, ch1 = mk()
    events = []
    b.hooks.add("session.takenover", lambda cid: events.append("takenover"))
    b.hooks.add("session.discarded", lambda cid: events.append("discarded"))
    connect(ch1, "c", clean_start=True)
    ch2 = Channel(b, cm)
    ch2.handle_in(P.Connect(clientid="c", clean_start=True))
    assert events == ["discarded"]
    ch3 = Channel(b, cm)
    ch3.handle_in(P.Connect(clientid="c", clean_start=False))
    assert events == ["discarded", "takenover"]


def test_retry_once_per_interval():
    b, cm, ch = mk()
    connect(ch, "s")
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    from emqx_tpu.broker import make_message
    res = b.publish(make_message("p", "t", b"x", qos=1))
    ch.handle_deliver(res.publishes["s"])
    sess = b.sessions["s"]
    sess.retry_interval = 10.0
    import time as _t
    now = _t.time()
    assert len(sess.retry(now + 11)) == 1
    assert sess.retry(now + 12) == []          # touched: not due again yet
    assert len(sess.retry(now + 22)) == 1      # due again a full interval later
