"""Batched-ingest stack (ack-run parse → batched QoS2 state machine →
coalesced replies): byte-parity against the per-packet path, parser
fast-path/partial-header behavior, batched session-state parity, and
the commit-after-flush retry semantics.

The contract under test: with ``broker.fanout.enable`` off nothing
changes at all; with it on, the wire output is byte-identical to the
per-packet path — only the write boundaries, the per-packet Python
work, and the session-call granularity change."""

import asyncio

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, Channel, ConnectionManager
from emqx_tpu.broker.session import Session
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.mqtt import frame as F
from emqx_tpu.mqtt import packet as P
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.transport.connection import Connection
from emqx_tpu.transport.proto_conn import MqttProtocol


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# parser: ack-run fast path + partial-header cache
# ---------------------------------------------------------------------------

def _expand(pkts):
    out = []
    for p in pkts:
        out.extend(p.expand() if type(p) is P.AckRun else [p])
    return out


def _mixed_stream():
    return b"".join([
        F.serialize(P.PubAck(P.PUBACK, 1)),
        F.serialize(P.PubAck(P.PUBACK, 2)),
        F.serialize(P.PubAck(P.PUBACK, 3)),
        F.serialize(P.Publish(qos=0, topic="t", payload=b"x")),
        F.serialize(P.PubAck(P.PUBREC, 4)),
        F.serialize(P.PubAck(P.PUBREC, 5)),
        F.serialize(P.PubAck(P.PUBREL, 6)),
        F.serialize(P.PubAck(P.PUBCOMP, 7)),
        F.serialize(P.PubAck(P.PUBCOMP, 8)),
        F.serialize(P.PingReq()),
        F.serialize(P.PubAck(P.PUBACK, 9)),
    ])


def test_parser_ack_runs_pack_contiguous_same_type_acks():
    data = _mixed_stream()
    fast = F.Parser(ack_runs=True).feed(data)
    # contiguous same-type acks pack; type changes and non-acks split
    runs = [p for p in fast if type(p) is P.AckRun]
    assert [(r.type, r.pids) for r in runs] == [
        (P.PUBACK, [1, 2, 3]), (P.PUBREC, [4, 5]), (P.PUBREL, [6]),
        (P.PUBCOMP, [7, 8]), (P.PUBACK, [9]),
    ]
    # expanded, the fast path equals the per-packet parse exactly
    assert _expand(fast) == F.Parser().feed(data)


def test_parser_ack_runs_equal_slow_path_at_every_split_boundary():
    data = _mixed_stream()
    want = F.Parser().feed(data)
    for cut in range(len(data) + 1):
        p = F.Parser(ack_runs=True)
        got = p.feed(data[:cut]) + p.feed(data[cut:])
        assert _expand(got) == want, cut


def test_parser_ack_runs_v5_reason_code_acks_fall_back_per_packet():
    # a v5 ack carrying rc/props has remaining length > 2: slow path
    data = (F.serialize(P.PubAck(P.PUBACK, 1, 0x10), ver=5)
            + F.serialize(P.PubAck(P.PUBACK, 2), ver=5)
            + F.serialize(P.PubAck(P.PUBACK, 3, 0x80), ver=5))
    p = F.Parser(proto_ver=5, ack_runs=True)
    got = p.feed(data)
    assert [type(x) for x in got] == [P.PubAck, P.AckRun, P.PubAck]
    assert _expand(got) == F.Parser(proto_ver=5).feed(data)


def test_parser_caches_decoded_header_across_partial_feeds():
    pkt = F.serialize(P.Publish(qos=0, topic="big", payload=b"z" * 100_000))
    p = F.Parser()
    assert p.feed(pkt[:1]) == []
    assert p._hdr is None                 # header itself not complete yet
    assert p.feed(pkt[1:10]) == []
    assert p._hdr is not None             # decoded once, cached
    cached = p._hdr
    mid = len(pkt) // 2
    assert p.feed(pkt[10:mid]) == []
    assert p._hdr == cached               # no re-decode while incomplete
    [out] = p.feed(pkt[mid:])
    assert p._hdr is None                 # consumed: cache invalidated
    assert out.payload == b"z" * 100_000


def test_parser_header_cache_cleared_by_ack_fast_path():
    # a partial ack primes the cache; the fast path must clear it when
    # it consumes the completed ack, or the NEXT packet parses with a
    # stale header
    p = F.Parser(ack_runs=True)
    ack = F.serialize(P.PubAck(P.PUBACK, 7))
    assert p.feed(ack[:2]) == []
    assert p._hdr == (2, 2)
    got = p.feed(ack[2:] + F.serialize(P.Publish(qos=0, topic="after",
                                                 payload=b"ok")))
    assert [type(x) for x in got] == [P.AckRun, P.Publish]
    assert got[1].topic == "after"


# ---------------------------------------------------------------------------
# session: batched QoS2 transitions == sequential ones
# ---------------------------------------------------------------------------

def _msg(payload=b"m", qos=1):
    from emqx_tpu.broker.message import make_message

    return make_message("pub", "t", payload, qos=qos)


def test_qos2_batch_transitions_match_sequential():
    a, b = Session("a", max_inflight=8), Session("b", max_inflight=8)
    for s in (a, b):
        out, _ = s.deliver([_msg(b"%d" % i, qos=2) for i in range(4)])
        assert [p.pid for p in out] == [1, 2, 3, 4]
        # backlog so the pubcomp refill cycle has work to admit
        s.mqueue.insert(_msg(b"q1", qos=2))
        s.mqueue.insert(_msg(b"q2", qos=2))
    seq = [a.pubrec(pid) for pid in (1, 2, 99, 2)]
    assert b.pubrec_batch([1, 2, 99, 2]) == seq == [True, True, False, False]
    seq_comp = [a.pubcomp(pid) for pid in (1, 99, 2)]
    known, more = b.pubcomp_batch([1, 99, 2])
    assert known == sum(1 for k, _ in seq_comp if k) == 2
    # sequential dequeues after each pubcomp; batch dequeues once — the
    # admitted refill set and pid sequence must match exactly
    seq_more = [p for _, ms in seq_comp for p in ms]
    assert [(p.pid, p.msg.payload) for p in more] == \
        [(p.pid, p.msg.payload) for p in seq_more]
    assert len(a.inflight) == len(b.inflight)


def test_inbound_pubrel_batch_matches_sequential():
    a, b = Session("a"), Session("b")
    for s in (a, b):
        for pid in (10, 11, 12):
            assert s.publish_qos2(pid, _msg(qos=2)) == "ok"
    seq = [a.pubrel_received(pid) for pid in (10, 99, 11, 10)]
    assert b.pubrel_received_batch([10, 99, 11, 10]) == seq
    assert set(a.awaiting_rel) == set(b.awaiting_rel) == {12}


# ---------------------------------------------------------------------------
# proto datapath: flag on/off byte parity (QoS2 + v5 error acks)
# ---------------------------------------------------------------------------

class _FakeTransport:
    def __init__(self):
        self.writes = []
        self.closed = False

    def write(self, data):
        self.writes.append(bytes(data))

    def close(self):
        self.closed = True

    def get_extra_info(self, key):
        return None

    def pause_reading(self):
        pass

    def resume_reading(self):
        pass


def _mk_proto(coalesce, max_inflight=2):
    b = Broker()
    cm = ConnectionManager(b)
    chan = Channel(b, cm, max_inflight=max_inflight)
    m = Metrics()
    b.metrics = m
    conn = MqttProtocol(chan, metrics=m, coalesce=coalesce)
    b.on_deliver = lambda cid, pubs: conn.deliver(pubs)
    t = _FakeTransport()
    conn.connection_made(t)
    return conn, t, m, b


def _qos2_echo_session(coalesce):
    """One client subscribes (QoS2) and publishes QoS2 to itself: the
    full outbound PUBREC/PUBREL/PUBCOMP machine and the inbound
    PUBREL release both run in ack bursts."""

    async def main():
        conn, t, m, b = _mk_proto(coalesce)
        conn.data_received(F.serialize(P.Connect(
            proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
        conn.data_received(F.serialize(P.Subscribe(
            packet_id=1, topic_filters=[("t", {"qos": 2})])))
        # 6 QoS2 publishes in ONE read: echoes 2 (window 2), queues 4
        conn.data_received(b"".join(
            F.serialize(P.Publish(qos=2, topic="t", packet_id=10 + i,
                                  payload=b"m%d" % i))
            for i in range(6)))
        # release our inbound publishes as one PUBREL burst → PUBCOMPs
        conn.data_received(b"".join(
            F.serialize(P.PubAck(P.PUBREL, 10 + i)) for i in range(6)))
        # drive the delivered QoS2 grants through their state machine
        # in bursts: PUBREC run → PUBREL replies; PUBCOMP run → window
        # refill publishes the next pair
        for pids in ((1, 2), (3, 4), (5, 6)):
            conn.data_received(b"".join(
                F.serialize(P.PubAck(P.PUBREC, pid)) for pid in pids))
            conn.data_received(b"".join(
                F.serialize(P.PubAck(P.PUBCOMP, pid)) for pid in pids))
        return conn, t, m

    return run(main())


def test_qos2_ack_stream_byte_identical_flag_on_vs_off():
    conn_b, t_b, m = _qos2_echo_session(coalesce=True)
    conn_p, t_p, _ = _qos2_echo_session(coalesce=False)
    assert b"".join(t_b.writes) == b"".join(t_p.writes)
    assert len(t_b.writes) < len(t_p.writes)
    assert m.get("broker.ack.run_parsed") >= 4    # PUBREL/PUBREC/PUBCOMP runs
    assert m.get("broker.qos2.batch") >= 3
    # both sessions fully drained: exactly-once completed for all 6 legs
    assert len(conn_b.channel.session.inflight) == 0
    assert conn_b.channel.session.awaiting_rel == {}


def _v5_unknown_ack_session(coalesce):
    async def main():
        conn, t, m, b = _mk_proto(coalesce)
        conn.data_received(F.serialize(P.Connect(
            proto_ver=5, clientid="c", clean_start=True, keepalive=0)))
        # pid-only v5 ack runs for pids nothing ever delivered: the
        # replies must carry rc 0x92, which in v5 changes the bytes —
        # the batch path has to reproduce the per-packet serializer
        conn.data_received(b"".join(
            F.serialize(P.PubAck(P.PUBREL, pid), ver=5)
            for pid in (60, 61, 62)))
        conn.data_received(b"".join(
            F.serialize(P.PubAck(P.PUBREC, pid), ver=5)
            for pid in (70, 71)))
        return t

    return run(main())


def test_v5_unknown_pid_ack_runs_byte_identical():
    t_b = _v5_unknown_ack_session(coalesce=True)
    t_p = _v5_unknown_ack_session(coalesce=False)
    joined = b"".join(t_b.writes)
    assert joined == b"".join(t_p.writes)
    # and the 0x92 reason actually hit the wire (v5 long-form acks)
    assert joined.count(bytes([P.RC.PACKET_ID_NOT_FOUND])) >= 5


# ---------------------------------------------------------------------------
# stream datapath parity (asyncio-streams Connection)
# ---------------------------------------------------------------------------

class _FakeStream:
    def __init__(self):
        self.inbox = asyncio.Queue()
        self.writes = []

    async def read(self, n):
        return await self.inbox.get()

    def write(self, data):
        self.writes.append(bytes(data))

    async def drain(self):
        pass

    def close(self):
        pass

    async def wait_closed(self):
        pass

    def peername(self):
        return ("fake", 0)


def _stream_session(coalesce):
    async def main():
        b = Broker()
        cm = ConnectionManager(b)
        chan = Channel(b, cm, max_inflight=2)
        s = _FakeStream()
        conn = Connection(s, chan, coalesce=coalesce)
        b.on_deliver = lambda cid, pubs: conn.deliver(pubs)
        task = asyncio.ensure_future(conn.run())
        s.inbox.put_nowait(F.serialize(P.Connect(
            proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
        s.inbox.put_nowait(F.serialize(P.Subscribe(
            packet_id=1, topic_filters=[("t", {"qos": 1})])))
        s.inbox.put_nowait(b"".join(
            F.serialize(P.Publish(qos=1, topic="t", packet_id=10 + i,
                                  payload=b"m%d" % i))
            for i in range(6)))
        await asyncio.sleep(0.05)
        for pids in ((1, 2), (3, 4), (5, 6)):
            s.inbox.put_nowait(b"".join(
                F.serialize(P.PubAck(P.PUBACK, pid)) for pid in pids))
            await asyncio.sleep(0.02)
        s.inbox.put_nowait(b"")   # EOF
        await task
        return s.writes

    return run(main())


def test_stream_connection_ack_runs_byte_identical():
    assert b"".join(_stream_session(True)) == \
        b"".join(_stream_session(False))


# ---------------------------------------------------------------------------
# retry: peek/commit split + template resend parity
# ---------------------------------------------------------------------------

def test_session_retry_peek_does_not_commit():
    import time as _t

    s = Session("c", max_inflight=8, retry_interval=10.0)
    now = _t.time()
    out, _ = s.deliver([_msg(b"a"), _msg(b"b")])
    entries = s.retry_peek(now + 11)
    assert sorted(pid for pid, _, _ in entries) == [p.pid for p in out]
    # nothing mutated: no DUP clone stored, age clock untouched
    for pid, _, _ in entries:
        assert s.inflight.lookup(pid)[1].dup is False
    assert len(s.retry_peek(now + 11)) == 2      # still due
    s.retry_commit(entries, now + 11)
    for pid, _, _ in entries:
        assert s.inflight.lookup(pid)[1].dup is True
    assert s.retry_peek(now + 12) == []          # touched at commit
    assert len(s.retry_peek(now + 21.5)) == 2    # due a full interval later


def test_session_retry_commit_skips_entries_acked_in_between():
    import time as _t

    s = Session("c", max_inflight=8, retry_interval=10.0)
    now = _t.time()
    out, _ = s.deliver([_msg(b"a"), _msg(b"b")])
    entries = s.retry_peek(now + 11)
    s.puback(out[0].pid)                         # acked mid-flush
    s.retry_commit(entries, now + 11)            # must not KeyError
    assert not s.inflight.contains(out[0].pid)
    assert s.inflight.lookup(out[1].pid)[1].dup is True


def _retry_harness(coalesce):
    conn, t, m, b = _mk_proto(coalesce, max_inflight=8)
    conn.data_received(F.serialize(P.Connect(
        proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
    conn.data_received(F.serialize(P.Subscribe(
        packet_id=1, topic_filters=[("t", {"qos": 1})])))
    conn.data_received(F.serialize(P.Publish(
        qos=1, topic="t", packet_id=10, payload=b"hello")))
    conn.channel.session.retry_interval = 0.0
    return conn, t


def test_retry_resend_bytes_template_path_matches_serializer():
    async def main():
        conn_b, t_b = _retry_harness(coalesce=True)
        conn_p, t_p = _retry_harness(coalesce=False)
        n_b, n_p = len(t_b.writes), len(t_p.writes)
        conn_b._tick()
        conn_p._tick()
        resend_b = b"".join(t_b.writes[n_b:])
        resend_p = b"".join(t_p.writes[n_p:])
        assert resend_b and resend_b == resend_p
        # the resend is the delivered PUBLISH with DUP set + same pid
        pkt = F.parse_one(resend_b)
        assert pkt.type == P.PUBLISH and pkt.dup is True
        assert pkt.payload == b"hello"
        # committed: stored message is now the DUP clone on both paths
        for conn in (conn_b, conn_p):
            (_pid, _ts, (kind, msg)), = conn.channel.session.inflight.items()
            assert kind == "publish" and msg.dup is True

    run(main())


def test_retry_does_not_commit_when_flush_raises():
    async def main():
        conn, t = _retry_harness(coalesce=True)
        inj = faultinject.install(FaultInjector([
            {"point": "transport.write", "action": "raise", "times": 1},
        ]))
        try:
            n0 = len(t.writes)
            conn._tick()                   # flush raises: commit skipped
            assert len(t.writes) == n0     # nothing reached the wire
            (_pid, _ts, (kind, msg)), = \
                conn.channel.session.inflight.items()
            assert msg.dup is False        # no clone burned
            assert inj.fired.get("transport.write") == 1
        finally:
            faultinject.uninstall()
        conn._tick()                       # next tick: resend + commit
        assert len(t.writes) > n0
        (_pid, _ts, (kind, msg)), = conn.channel.session.inflight.items()
        assert msg.dup is True

    run(main())


# ---------------------------------------------------------------------------
# publish-run ingest fast path (PR 6)
# ---------------------------------------------------------------------------

def _pub_stream():
    return b"".join([
        F.serialize(P.Publish(qos=1, topic="a/b", packet_id=1,
                              payload=b"x1")),
        F.serialize(P.Publish(qos=1, topic="a/b", packet_id=2,
                              payload=b"x2")),
        F.serialize(P.Publish(qos=1, topic="a/c", packet_id=3,
                              payload=b"x3")),
        F.serialize(P.Publish(qos=2, topic="a/b", packet_id=4,
                              payload=b"x4")),
        F.serialize(P.Publish(qos=2, topic="a/b", packet_id=5,
                              payload=b"x5")),
        F.serialize(P.Publish(qos=0, topic="a/b", payload=b"x6")),
        F.serialize(P.PubAck(P.PUBACK, 9)),
        F.serialize(P.Publish(qos=1, topic="a/d", packet_id=6,
                              payload=b"x7")),
    ])


def _expand_all(pkts):
    out = []
    for p in pkts:
        if type(p) in (P.AckRun, P.PublishRun):
            out.extend(p.expand())
        else:
            out.append(p)
    return out


def test_parser_publish_runs_pack_contiguous_same_qos():
    data = _pub_stream()
    fast = F.Parser(publish_runs=True).feed(data)
    runs = [p for p in fast if type(p) is P.PublishRun]
    # qos1×2 | qos1×1 (bare: run of one stays a packet) | qos2×2 …
    assert [(r.qos, [pp.packet_id for pp in r.pkts]) for r in runs] == [
        (1, [1, 2, 3]), (2, [4, 5]),
    ]
    assert _expand_all(fast) == F.Parser().feed(data)


def test_parser_publish_runs_equal_slow_path_at_every_split_boundary():
    data = _pub_stream()
    want = F.Parser().feed(data)
    for cut in range(len(data) + 1):
        p = F.Parser(publish_runs=True, ack_runs=True)
        got = p.feed(data[:cut]) + p.feed(data[cut:])
        assert _expand_all(got) == want, cut


def test_parser_publish_runs_off_by_default():
    data = _pub_stream()
    assert not any(type(p) is P.PublishRun
                   for p in F.Parser().feed(data))
    assert not any(type(p) is P.PublishRun
                   for p in F.Parser(ack_runs=True).feed(data))


def _pipeline_node(coalesce):
    """Broker + live fanout pipeline + proto conn — the publish-run
    fast path engages only when the pipeline guarantees acceptance."""
    from emqx_tpu.broker import FanoutPipeline

    conn, t, m, b = _mk_proto(coalesce, max_inflight=64)
    p = FanoutPipeline(b, metrics=m, window_s=0.0)
    return conn, t, m, b, p


def test_publish_run_burst_acks_match_per_packet_bytes():
    """Flag-on with a live pipeline: a QoS1 publish burst answers with
    one coalesced PUBACK burst whose bytes equal the per-packet acks,
    the run counts in broker.ingest.publish_runs, and every message is
    delivered by the pipeline."""
    async def main():
        conn, t, m, b, pipe = _pipeline_node(True)
        await pipe.start()
        b.fanout = pipe
        got = []
        sess, _ = b.open_session("watcher", max_inflight=64)
        from emqx_tpu.broker.session import SubOpts
        b.subscribe("watcher", "w/#", SubOpts())
        prev = b.on_deliver
        b.on_deliver = lambda cid, pubs: (
            got.extend(p.msg.payload for p in pubs)
            if cid == "watcher" else prev(cid, pubs))
        conn.data_received(F.serialize(P.Connect(
            proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
        t.writes.clear()
        conn.data_received(b"".join(
            F.serialize(P.Publish(qos=1, topic="w/t", packet_id=10 + i,
                                  payload=b"m%d" % i))
            for i in range(6)))
        # ONE write: the 6 PUBACKs, byte-identical to per-packet acks
        assert len(t.writes) == 1
        assert t.writes[0] == b"".join(
            F.serialize(P.PubAck(P.PUBACK, 10 + i)) for i in range(6))
        assert m.get("broker.ingest.publish_runs") == 1
        deadline = asyncio.get_event_loop().time() + 5
        while len(got) < 6 and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.005)
        assert got == [b"m%d" % i for i in range(6)]
        await pipe.stop()

    run(main())


def test_publish_run_qos2_state_matches_per_packet():
    """A QoS2 run drives publish_qos2 per packet and answers one PUBREC
    burst; the receiver's awaiting-rel table matches the per-packet
    path's."""
    async def main():
        conn, t, m, b, pipe = _pipeline_node(True)
        await pipe.start()
        b.fanout = pipe
        conn.data_received(F.serialize(P.Connect(
            proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
        t.writes.clear()
        conn.data_received(b"".join(
            F.serialize(P.Publish(qos=2, topic="z/t", packet_id=20 + i,
                                  payload=b"m%d" % i))
            for i in range(4)))
        assert t.writes[0] == b"".join(
            F.serialize(P.PubAck(P.PUBREC, 20 + i)) for i in range(4))
        assert sorted(conn.channel.session.awaiting_rel) == [
            20, 21, 22, 23]
        # duplicate pids in a later run do NOT re-publish (exactly-once)
        t.writes.clear()
        conn.data_received(b"".join(
            F.serialize(P.Publish(qos=2, topic="z/t", packet_id=20 + i,
                                  payload=b"dup" ))
            for i in range(2)))
        assert t.writes[0] == b"".join(
            F.serialize(P.PubAck(P.PUBREC, 20 + i)) for i in range(2))
        await pipe.stop()

    run(main())


def test_publish_run_bails_to_per_packet_without_pipeline():
    """No fanout pipeline: handle_publish_run consumes nothing (rest =
    the whole run) so the caller replays per-packet — already proven
    byte-identical by the _stream_session tests; here we pin the
    contract directly."""
    b = Broker()
    cm = ConnectionManager(b)
    chan = Channel(b, cm)
    chan.state = "connected"
    chan.clientid = "c"
    run_pkt = P.PublishRun(1, [
        P.Publish(qos=1, topic="t", packet_id=1, payload=b"a"),
        P.Publish(qos=1, topic="t", packet_id=2, payload=b"b"),
    ])
    reply, acts, rest = chan.handle_publish_run(run_pkt)
    assert reply == b"" and acts == [] and rest == run_pkt.pkts
