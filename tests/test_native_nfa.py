"""Native incremental NFA (nfa.cpp) vs the Python oracle
(IncrementalNfa): same mutation surface, kernel-compatible tables,
matching host answers, delta contract.  Skipped when the toolchain can't
build the .so (callers fall back to the Python path)."""

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops.incremental import IncrementalNfa

native = pytest.importorskip("emqx_tpu.native.nfa")
if not native.available():  # pragma: no cover
    pytest.skip("native nfa unavailable", allow_module_level=True)

from emqx_tpu.native.nfa import NativeNfa


def rand_filters(rng, n, words=24, depth=6):
    vocab = [f"w{i}" for i in range(words)]
    out = set()
    while len(out) < n:
        k = rng.integers(1, depth)
        ws = [("+" if rng.random() < 0.25 else vocab[rng.integers(words)])
              for _ in range(k)]
        if rng.random() < 0.3:
            ws.append("#")
        out.add("/".join(ws))
    return sorted(out)


def rand_topics(rng, n, words=24, depth=7):
    vocab = [f"w{i}" for i in range(words)]
    tops = ["/".join(vocab[rng.integers(words)]
                     for _ in range(rng.integers(1, depth)))
            for _ in range(n)]
    tops += ["$SYS/broker/x", "$share"]
    return tops


def filters_of(nfa, n_accepts_hint=100000):
    out = {}
    aid = 0
    misses = 0
    while misses < 64 and aid < n_accepts_hint:
        f = nfa.accept_get(aid)
        if f is None:
            misses += 1
        else:
            misses = 0
            out[aid] = f
        aid += 1
    return out


def test_add_remove_matches_oracle():
    rng = np.random.default_rng(11)
    filters = rand_filters(rng, 400)
    py = IncrementalNfa(depth=8)
    nt = NativeNfa(depth=8)
    for f in filters:
        assert py.add(f) == nt.add(f)
        assert not nt.add(f)  # dup detected
    assert nt.n_filters == py.n_filters == len(filters)
    assert nt.n_states == py.n_states

    topics = rand_topics(rng, 300)
    for t in topics:
        py_names = sorted(py.accept_filters[a] for a in py.match_host(t))
        nt_names = sorted(nt.accept_get(a) for a in nt.match_host(t))
        assert py_names == nt_names, t

    # remove half, re-check parity and pruning
    drop = filters[::2]
    for f in drop:
        assert py.remove(f) == nt.remove(f)
        assert not nt.remove(f)
    assert nt.n_filters == py.n_filters
    assert nt.n_states == py.n_states  # pruning agrees
    for t in topics:
        py_names = sorted(py.accept_filters[a] for a in py.match_host(t))
        nt_names = sorted(nt.accept_get(a) for a in nt.match_host(t))
        assert py_names == nt_names, t
    nt.close()


def test_tables_drive_the_kernel():
    """Kernel consumes native tables unchanged and answers match the
    topic oracle."""
    import jax.numpy as jnp

    from emqx_tpu.ops import encode_batch
    from emqx_tpu.ops.match_kernel import nfa_match

    rng = np.random.default_rng(5)
    filters = rand_filters(rng, 300)
    nt = NativeNfa(depth=8)
    assert nt.bulk_add(filters) == len(filters)
    node_tab, edge_tab, seeds = nt.tables()

    topics = rand_topics(rng, 200)
    w, l, s = encode_batch(nt, topics, batch=256)
    res = nfa_match(jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
                    jnp.asarray(node_tab), jnp.asarray(edge_tab),
                    jnp.asarray(seeds), active_slots=16, max_matches=64)
    m = np.asarray(res.matches)
    n = np.asarray(res.n_matches)
    for i, t in enumerate(topics):
        want = {f for f in filters if T.match(t, f)}
        got = {nt.accept_get(a) for a in m[i][: n[i]]}
        assert got == want, t
    nt.close()


def test_delta_contract_and_epoch_gating():
    nt = NativeNfa(depth=8, state_bucket=1024, edge_bucket=64)
    nt.bulk_add(["a/b", "a/+", "c/#"])
    d = nt.flush()
    assert d.epoch == nt.epoch
    # apply to shadow arrays == full tables
    node_tab, edge_tab, seeds = nt.tables()
    shadow_n = np.full_like(node_tab, -1)
    shadow_n[:, 3] = 0
    shadow_e = np.full_like(edge_tab, -1)
    if not d.resized:
        shadow_n[d.state_idx] = d.state_rows
        shadow_e[d.bucket_idx] = d.bucket_rows
        # dirty covers every live row after a fresh build
        assert (shadow_n == node_tab).all()
        assert (shadow_e == edge_tab).all()

    # incremental delta covers exactly the touched rows
    nt.add("a/x")
    d2 = nt.flush()
    assert not d2.resized and len(d2.state_idx) >= 1
    shadow_n[d2.state_idx] = d2.state_rows
    shadow_e[d2.bucket_idx] = d2.bucket_rows
    n2, e2, _ = nt.tables()
    assert (shadow_n == n2).all()
    assert (shadow_e == e2).all()

    # device-epoch gating: freed aid not reused until device acks
    nt.set_device_epoch(nt.epoch)
    aid = nt.aid_of("a/b")
    nt.remove("a/b")
    nt.add("z/z")                      # device hasn't acked the removal
    assert nt.aid_of("z/z") != aid
    nt.set_device_epoch(nt.epoch)
    nt.remove("z/z")
    freed_epoch_acked = nt.epoch
    nt.set_device_epoch(freed_epoch_acked + 1)
    nt.add("q/q")                      # now reuse is allowed
    reuses = nt.aid_reuses
    assert reuses >= 1
    nt.close()


def test_growth_resize_signals_reupload():
    nt = NativeNfa(depth=8, state_bucket=1024, edge_bucket=8)
    nt.flush()
    # enough distinct literal edges to force edge-table growth
    fl = [f"g{i}/h{i}" for i in range(400)]
    nt.bulk_add(fl)
    d = nt.flush()
    assert d.resized  # consumer must re-upload
    # tables still correct after growth
    assert sorted(nt.match_host("g7/h7")) == [nt.aid_of("g7/h7")]
    nt.close()


def test_bulk_matches_python_compiler_semantics():
    """Same filter set through compile_filters and NativeNfa gives the
    same answers (layouts may differ; behavior must not)."""
    from emqx_tpu.ops import compile_filters

    rng = np.random.default_rng(3)
    filters = rand_filters(rng, 250)
    table = compile_filters(filters, depth=8)
    nt = NativeNfa(depth=8)
    nt.bulk_add(filters)
    for t in rand_topics(rng, 150):
        want = {f for f in filters if T.match(t, f)}
        got = {nt.accept_get(a) for a in nt.match_host(t)}
        assert got == want
        # spot: aid_of round-trips
    for f in filters[:50]:
        assert nt.accept_get(nt.aid_of(f)) == f
    nt.close()


def test_invalid_filters_rejected_symmetrically():
    nt = NativeNfa(depth=4)
    with pytest.raises(ValueError):
        nt.add("a/#/b")          # '#' must be final
    with pytest.raises(ValueError):
        nt.add("a/b/c/d/e")      # deeper than table
    assert nt.n_filters == 0
    # bulk path skips invalid lines instead of truncate-inserting
    assert nt.bulk_add(["x/#/y", "ok/f"]) == 1
    assert nt.match_host("x/anything") == []
    nt.close()
