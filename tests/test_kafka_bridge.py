"""Kafka bridge against an in-test mock broker speaking the real wire
protocol (Metadata v1 / Produce v3, record batch v2 with CRC-32C
verification) — including rule-engine → bridge delivery through a live
node (emqx_bridge_kafka analog)."""

import asyncio
import struct

import pytest

from emqx_tpu.bridge.kafka import (
    KafkaClient, KafkaConnector, crc32c, parse_batches,
    parse_record_batch, record_batch, render_kafka,
)
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def test_crc32c_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_record_batch_roundtrip():
    records = [(b"k1", b"v1"), (None, b"v2"), (b"", b"long" * 100)]
    batch = record_batch(records, base_ts_ms=1234)
    got = parse_record_batch(batch)
    assert got == [(b"k1", b"v1"), (None, b"v2"), (b"", b"long" * 100)]
    # corrupt one payload byte -> crc check must fail
    bad = bytearray(batch)
    bad[-1] ^= 0xFF
    with pytest.raises(Exception):
        parse_record_batch(bytes(bad))


def _str(s):
    b = s.encode()
    return struct.pack("!h", len(b)) + b


class MockKafka:
    """Minimal broker: Metadata v1 + Produce v3; stores decoded records
    per (topic, partition) after verifying the batch CRC."""

    def __init__(self, topics=None, produce_errors=None):
        self.topics = topics or {"emqx": 2}     # name -> n_partitions
        self.records = {}                       # (topic, part) -> [(k,v)]
        self.produce_errors = list(produce_errors or [])
        self.requests = []
        self._conns = set()
        self.port = 0

    async def start(self):
        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                while True:
                    (ln,) = struct.unpack(
                        "!i", await reader.readexactly(4))
                    msg = await reader.readexactly(ln)
                    api, ver, corr = struct.unpack_from("!hhi", msg, 0)
                    (cl,) = struct.unpack_from("!h", msg, 8)
                    body = msg[10 + max(0, cl):]
                    self.requests.append(api)
                    if api == 3:                    # Metadata v1
                        out = [struct.pack("!i", 1),      # brokers
                               struct.pack("!i", 0), _str("127.0.0.1"),
                               struct.pack("!i", self.port),
                               struct.pack("!h", -1),     # rack null
                               struct.pack("!i", 0),      # controller
                               struct.pack("!i", len(self.topics))]
                        for name, nparts in self.topics.items():
                            out += [struct.pack("!h", 0), _str(name),
                                    b"\x00", struct.pack("!i", nparts)]
                            for p in range(nparts):
                                out += [struct.pack("!hii", 0, p, 0),
                                        struct.pack("!ii", 1, 0),
                                        struct.pack("!ii", 1, 0)]
                        payload = b"".join(out)
                    elif api == 2:                  # ListOffsets v1
                        off = 4                     # replica_id
                        off += 4                    # topic count (1)
                        (sl,) = struct.unpack_from("!h", body, off)
                        off += 2
                        topic = body[off:off + sl].decode()
                        off += sl + 4               # partition count (1)
                        part, ts = struct.unpack_from("!iq", body, off)
                        n = len(self.records.get((topic, part), []))
                        o = 0 if ts == -2 else n
                        payload = (struct.pack("!i", 1) + _str(topic)
                                   + struct.pack("!i", 1)
                                   + struct.pack("!ihqq", part, 0, -1, o))
                    elif api == 1:                  # Fetch v4
                        off = 4 + 4 + 4 + 4 + 1     # replica..isolation
                        off += 4                    # topic count (1)
                        (sl,) = struct.unpack_from("!h", body, off)
                        off += 2
                        topic = body[off:off + sl].decode()
                        off += sl + 4               # partition count (1)
                        part, fo, mb = struct.unpack_from("!iqi", body, off)
                        recs = self.records.get((topic, part), [])
                        chunk = recs[fo:fo + 50]
                        blob = (record_batch(chunk, base_offset=fo)
                                if chunk else b"")
                        payload = (struct.pack("!i", 0)      # throttle
                                   + struct.pack("!i", 1) + _str(topic)
                                   + struct.pack("!i", 1)
                                   + struct.pack("!ihqq", part, 0,
                                                 len(recs), len(recs))
                                   + struct.pack("!i", 0)    # aborted
                                   + struct.pack("!i", len(blob)) + blob)
                    elif api == 0:                  # Produce v3
                        off = 0
                        (tl,) = struct.unpack_from("!h", body, off)
                        off += 2 + max(0, tl)       # transactional_id
                        acks, tmo = struct.unpack_from("!hi", body, off)
                        off += 6
                        (nt,) = struct.unpack_from("!i", body, off)
                        off += 4
                        resp_topics = []
                        for _ in range(nt):
                            (sl,) = struct.unpack_from("!h", body, off)
                            off += 2
                            topic = body[off:off + sl].decode()
                            off += sl
                            (np_,) = struct.unpack_from("!i", body, off)
                            off += 4
                            parts = []
                            for _ in range(np_):
                                part, blen = struct.unpack_from(
                                    "!ii", body, off)
                                off += 8
                                batch = body[off:off + blen]
                                off += blen
                                err = (self.produce_errors.pop(0)
                                       if self.produce_errors else 0)
                                if not err:
                                    recs = parse_record_batch(batch)
                                    self.records.setdefault(
                                        (topic, part), []).extend(recs)
                                parts.append((part, err))
                            resp_topics.append((topic, parts))
                        out = [struct.pack("!i", len(resp_topics))]
                        for topic, parts in resp_topics:
                            out += [_str(topic),
                                    struct.pack("!i", len(parts))]
                            for part, err in parts:
                                out.append(struct.pack(
                                    "!ihqq", part, err,
                                    len(self.records.get(
                                        (topic, part), [])), -1))
                        out.append(struct.pack("!i", 0))  # throttle
                        if acks == 0:   # fire-and-forget: NO response
                            continue
                        payload = b"".join(out)
                    else:
                        return
                    resp = struct.pack("!i", corr) + payload
                    writer.write(struct.pack("!i", len(resp)) + resp)
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()

    def all_records(self, topic):
        out = []
        for (t, p), recs in sorted(self.records.items()):
            if t == topic:
                out.extend(recs)
        return out


def test_client_metadata_and_produce():
    async def main():
        mk = await MockKafka().start()
        c = KafkaClient(f"127.0.0.1:{mk.port}")
        assert await c.partitions("emqx") == 2
        base = await c.produce("emqx", 1, [(b"k", b"v"), (None, b"w")])
        assert base >= 0
        assert mk.records[("emqx", 1)] == [(b"k", b"v"), (None, b"w")]
        await c.close()
        await mk.stop()

    run(main())


def test_connector_partition_dispatch_and_retry():
    async def main():
        # first produce gets a retriable error (7 = REQUEST_TIMED_OUT)
        mk = await MockKafka(produce_errors=[7]).start()
        conn = KafkaConnector({"server": f"127.0.0.1:{mk.port}",
                               "topic": "emqx"}, name="k1")
        await conn.start()
        assert conn.n_partitions == 2
        from emqx_tpu.bridge.resource import BufferedWorker

        w = BufferedWorker(conn, name="k1", batch_size=8,
                           retry_base=0.01)
        await w.start()
        for i in range(4):
            w.enqueue({"key": b"same-key", "value": b"m%d" % i})
        for _ in range(400):
            if w.metrics["success"] >= 4:
                break
            await asyncio.sleep(0.01)
        assert w.metrics["success"] == 4
        assert w.metrics["retried"] >= 1
        # same key -> same partition, order preserved
        got = mk.all_records("emqx")
        assert [v for _, v in got] == [b"m0", b"m1", b"m2", b"m3"]
        parts = {p for (t, p) in mk.records}
        assert len(parts) == 1
        await w.stop()
        await mk.stop()

    run(main())


def test_render_kafka_templates():
    out = {"payload": b"xyz", "topic": "t/1"}
    cols = {"clientid": "c9"}
    item = render_kafka({}, out, cols)
    assert item == {"key": b"c9", "value": b"xyz"}
    item = render_kafka(
        {"key_template": "${topic}", "value_template": "p=${payload}"},
        out, cols)
    assert item == {"key": b"t/1", "value": b"p=xyz"}
    item = render_kafka({"partition": 3}, out, cols)
    assert item["partition"] == 3


def test_rule_to_kafka_through_live_node():
    async def main():
        mk = await MockKafka(topics={"iot-events": 1}).start()
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg)
        await node.start()
        try:
            await node.bridges.create("kafka", "mk", {
                "server": f"127.0.0.1:{mk.port}",
                "topic": "iot-events",
                "key_template": "${clientid}",
                "value_template": '{"t":"${topic}","p":"${payload}"}',
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rk", 'SELECT topic, payload, clientid FROM "ev/#"',
                actions=["kafka:mk"],
            )
            pub = Client(clientid="pub9",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/42", b"hello")
            br = node.bridges.get("kafka:mk")
            for _ in range(600):
                if br.worker.metrics["success"] >= 1:
                    break
                await asyncio.sleep(0.01)
            recs = mk.all_records("iot-events")
            assert recs, "nothing delivered"
            key, value = recs[0]
            assert key == b"pub9"
            assert value == b'{"t":"ev/42","p":"hello"}'
            await pub.disconnect()
        finally:
            await node.stop()
            await mk.stop()

    run(main())


def test_partial_partition_failure_no_duplicates():
    """Partition 0 acked, partition 1 fails retryably: the retry must
    re-produce ONLY partition 1 (SendError.remaining contract)."""
    async def main():
        mk = await MockKafka(produce_errors=[0, 7]).start()
        conn = KafkaConnector({"server": f"127.0.0.1:{mk.port}",
                               "topic": "emqx"}, name="k2")
        await conn.start()
        from emqx_tpu.bridge.resource import BufferedWorker

        w = BufferedWorker(conn, name="k2", batch_size=8,
                           retry_base=0.01)
        await w.start()
        for i in range(2):
            w.enqueue({"partition": 0, "value": b"p0-%d" % i})
        for i in range(2):
            w.enqueue({"partition": 1, "value": b"p1-%d" % i})
        for _ in range(400):
            if w.metrics["success"] >= 4:
                break
            await asyncio.sleep(0.01)
        assert w.metrics["success"] == 4
        assert mk.records[("emqx", 0)] == [
            (None, b"p0-0"), (None, b"p0-1")]      # exactly once
        assert mk.records[("emqx", 1)] == [
            (None, b"p1-0"), (None, b"p1-1")]
        await w.stop()
        await mk.stop()

    run(main())


def test_acks_zero_fire_and_forget():
    async def main():
        mk = await MockKafka().start()
        c = KafkaClient(f"127.0.0.1:{mk.port}")
        assert await c.produce("emqx", 0, [(None, b"f0")], acks=0) == -1
        # connection stays usable for the next (acked) request
        assert await c.produce("emqx", 0, [(None, b"f1")], acks=1) >= 0
        for _ in range(100):
            if len(mk.records.get(("emqx", 0), [])) >= 2:
                break
            await asyncio.sleep(0.01)
        assert [v for _, v in mk.records[("emqx", 0)]] == [b"f0", b"f1"]
        await c.close()
        await mk.stop()

    run(main())


def test_parse_batches_concatenated_and_partial():
    b1 = record_batch([(b"k0", b"v0"), (None, b"v1")], base_offset=10)
    b2 = record_batch([(b"k2", b"v2")], base_offset=12)
    recs, nxt, skipped = parse_batches(b1 + b2)
    assert recs == [(10, b"k0", b"v0"), (11, None, b"v1"),
                    (12, b"k2", b"v2")]
    assert nxt == 13 and skipped == 0
    # truncated tail batch is ignored
    recs, nxt, _ = parse_batches(b1 + b2[: len(b2) // 2])
    assert [o for o, _, _ in recs] == [10, 11] and nxt == 12


def test_parse_batches_skips_compressed_and_control():
    import struct as S

    b1 = record_batch([(None, b"plain")], base_offset=0)
    # forge a reserved-codec batch (gzip/snappy/lz4/zstd all decode
    # now): flip the attrs bits and re-CRC
    comp = bytearray(record_batch([(None, b"zzz")], base_offset=1))
    after = bytearray(comp[21:])
    S.pack_into("!h", after, 0, 6)                 # attrs: reserved codec
    S.pack_into("!I", comp, 17, crc32c(bytes(after)))
    comp[21:] = after
    recs, nxt, skipped = parse_batches(b1 + bytes(comp))
    assert [v for _, _, v in recs] == [b"plain"]
    assert nxt == 2 and skipped == 1               # advanced PAST the skip


def test_parse_batches_sparse_compaction_deltas():
    """Compacted batches keep per-record offset deltas; a dense
    enumerate() would loop forever re-fetching the same batch."""
    import struct as S

    # build a batch with record deltas [0, 5] and lastOffsetDelta 5
    raw = bytearray(record_batch([(None, b"a"), (None, b"b")],
                                 base_offset=100))
    from emqx_tpu.bridge.kafka import _record
    recs = _record(0, 0, None, b"a") + _record(5, 0, None, b"b")
    head = bytes(raw[21:21 + S.calcsize('!hiqqqhii')])
    after2 = bytearray(head + recs)
    S.pack_into("!i", after2, 2, 5)
    crc = crc32c(bytes(after2))
    body = S.pack("!iBI", -1, 2, crc) + bytes(after2)
    batch = S.pack("!qi", 100, len(body)) + body
    out, nxt, skipped = parse_batches(batch)
    assert [o for o, _, _ in out] == [100, 105]
    assert nxt == 106 and skipped == 0


def test_client_fetch_and_list_offsets():
    async def main():
        mk = await MockKafka().start()
        c = KafkaClient(f"127.0.0.1:{mk.port}")
        await c.produce("emqx", 0, [(None, b"a"), (None, b"b")])
        await c.produce("emqx", 0, [(None, b"c")])
        assert await c.list_offset("emqx", 0, -2) == 0   # earliest
        assert await c.list_offset("emqx", 0, -1) == 3   # latest
        recs, nxt = await c.fetch("emqx", 0, 1)
        assert [(o, v) for o, _, v in recs] == [(1, b"b"), (2, b"c")]
        assert nxt == 3
        recs, nxt = await c.fetch("emqx", 0, 3)
        assert recs == [] and nxt == 3
        await c.close()
        await mk.stop()

    run(main())


def test_kafka_ingress_republishes_into_broker():
    async def main():
        mk = await MockKafka(topics={"cmds": 1}).start()
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg)
        await node.start()
        try:
            await node.bridges.create("kafka", "in", {
                "server": f"127.0.0.1:{mk.port}",
                "topic": "cmds",
                "ingress": {
                    "start": "earliest",
                    "local_topic": "from-kafka/${topic}/${partition}",
                    "poll_interval": 0.05,
                },
            })
            sub = Client(clientid="s", port=node.listeners.all()[0].port)
            await sub.connect()
            await sub.subscribe("from-kafka/#", qos=0)
            # a remote producer writes into Kafka
            prod = KafkaClient(f"127.0.0.1:{mk.port}")
            await prod.produce("cmds", 0, [(b"dev1", b"reboot")])
            msg = await asyncio.wait_for(sub.messages.get(), 10)
            assert msg.topic == "from-kafka/cmds/0"
            assert msg.payload == b"reboot"
            br = node.bridges.get("kafka:in")
            assert br.connector.consumed == 1
            assert br.connector.offsets == {0: 1}
            await prod.close()
            await sub.disconnect()
        finally:
            await node.stop()
            await mk.stop()

    run(main())


def test_parse_batch_with_tombstone():
    """Null-value records (tombstones) must not corrupt the records
    that follow them in the same batch."""
    import struct as S

    # build manually: record with vlen=-1 then a normal record
    def raw_record(delta, key, value):
        from emqx_tpu.bridge.kafka import _varint
        body = (b"\x00" + _varint(0) + _varint(delta)
                + (_varint(-1) if key is None
                   else _varint(len(key)) + key)
                + (_varint(-1) if value is None
                   else _varint(len(value)) + value)
                + _varint(0))
        return _varint(len(body)) + body

    body_recs = raw_record(0, b"gone", None) + raw_record(1, b"k", b"v")
    head = S.pack("!hiqqqhii", 0, 1, 0, 0, -1, -1, -1, 2)
    after = head + body_recs
    batch = (S.pack("!qi", 5, 9 + len(after))
             + S.pack("!iBI", -1, 2, crc32c(after)) + after)
    out, nxt, skipped = parse_batches(batch)
    assert out == [(5, b"gone", b""), (6, b"k", b"v")]
    assert nxt == 7 and skipped == 0
