"""Pallas small-table fast path: parity vs nfa_match in interpret mode
(SURVEY.md §7.4 experiment; Mosaic lowering A/B'd on real hardware via
ops.pallas_match.bench_pallas_small)."""

import numpy as np
import jax.numpy as jnp
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops import compile_filters, encode_topics, nfa_match
from emqx_tpu.ops.pallas_match import (
    TILE_B, pallas_small_match, supports_table,
)

FILTERS = ["a/b/c", "a/+/c", "a/#", "#", "+", "+/b", "a/b", "b",
           "$SYS/#", "x//y", "+/+/+", "deep/1/2/3/4/5/6/#"]
TOPICS = (["a/b/c", "a/b", "a", "b", "x//y", "$SYS/broker",
           "deep/1/2/3/4/5/6/7", "nomatch/z", "a/q/c", "/"] * 26)[:256]


def test_pallas_parity_interpret():
    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    assert supports_table(*t.device_arrays()[:2])
    words, lens, is_sys = encode_topics(t, TOPICS, batch=256)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()])
    ref = nfa_match(*args, active_slots=8, compact_output=False)
    acc, aover = pallas_small_match(*args, depth=8, active_slots=8,
                                    interpret=True)
    ra, pa = np.asarray(ref.matches), np.asarray(acc)
    assert ra.shape == pa.shape
    # same accept-id multiset per row (slot layout is shared)
    assert (np.sort(np.where(ra < 0, -1, ra), axis=1)
            == np.sort(np.where(pa < 0, -1, pa), axis=1)).all()
    assert (np.asarray(ref.active_overflow) == np.asarray(aover)).all()
    # spot-check against the oracle too
    counts = np.asarray(ref.n_matches)
    for i, name in enumerate(TOPICS[:32]):
        want = {f for f in FILTERS if T.match(name, f)}
        got = {t.accept_filters[a] for a in pa[i] if a >= 0}
        assert got == want or counts[i] > len(got)


def test_pallas_flat_epilogue_parity_interpret():
    """The SHARED flat compaction epilogue rides the pallas walk too
    (ISSUE 11): pallas_small_match_flat produces the same dense flat
    buffer + packed row_meta as nfa_match(flat_cap=...), so both
    backends honor one two-phase readback contract."""
    from emqx_tpu.ops.match_kernel import decode_flat, decode_row_meta
    from emqx_tpu.ops.pallas_match import pallas_small_match_flat

    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS, batch=256)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()])
    K = 8
    cap = 8 * 256
    ref = nfa_match(*args, active_slots=8, max_matches=K, flat_cap=cap)
    got = pallas_small_match_flat(*args, depth=8, active_slots=8,
                                  max_matches=K, flat_cap=cap,
                                  interpret=True)
    np.testing.assert_array_equal(np.asarray(ref.n_matches),
                                  np.asarray(got.n_matches))
    np.testing.assert_array_equal(np.asarray(ref.row_meta),
                                  np.asarray(got.row_meta))
    # same per-row id SETS (slot order within a row may differ between
    # backends; the epilogue's compaction is order-preserving per input
    # layout, so compare decoded sets)
    n1 = np.asarray(ref.n_matches)
    rows_ref = decode_flat(np.asarray(ref.matches), n1, K)
    rows_got = decode_flat(np.asarray(got.matches),
                           np.asarray(got.n_matches), K)
    nk, sp = decode_row_meta(np.asarray(got.row_meta))
    for i in range(len(TOPICS)):
        if not sp[i]:
            assert set(rows_ref[i]) == set(rows_got[i]), i


def test_pallas_rejects_ragged_batch():
    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS[:100], batch=100)
    with pytest.raises(ValueError):
        pallas_small_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()],
            depth=8, interpret=True)
    assert TILE_B == 256


# ---------------------------------------------------------------------------
# fused join walk (ISSUE 17): the CSR join relation composed on-chip
# ---------------------------------------------------------------------------

JOIN_CORPUS = [
    "a/b/c", "a/+/c", "a/#", "+/b/#", "+/+/+", "#", "x/y",
    "$SYS/broker/clients/+", "$SYS/#", "queue/jobs/+",
    "d1/d2/d3/d4/d5/d6", "d1/d2/d3/d4/+/d6",
]
JOIN_TOPICS = [
    "a/b/c", "a/z/c", "a/b", "x/y", "q/w/e",
    "$SYS/broker/clients/c1", "$SYS/broker/uptime", "$delayed/x",
    "queue/jobs/7", "d1/d2/d3/d4/d5/d6", "d1/d2/d3/d4/zz/d6",
    "a", "", "a/b/c/d/e/f/g/h",
]


def _join_dev(filters, depth=8, active_slots=8, max_matches=16, **kw):
    from emqx_tpu.ops.device_table import DeviceNfa
    from emqx_tpu.ops.incremental import IncrementalNfa

    inc = IncrementalNfa(depth=depth, **kw)
    for f in filters:
        inc.add(f)
    dev = DeviceNfa(inc, active_slots=active_slots,
                    max_matches=max_matches)
    dev.enable_join()
    return inc, dev


def _assert_flat_parity(rj, rp, ctx=""):
    for f in ("matches", "n_matches", "active_overflow",
              "match_overflow", "row_meta"):
        a = np.asarray(getattr(rj, f))
        b = np.asarray(getattr(rp, f))
        assert np.array_equal(a, b), (ctx, f, a, b)


def test_pallas_join_parity_corpus_interpret():
    """Bit-parity gate: the fused Pallas join walk returns the SAME
    flat buffer, counts, packed row_meta, and fail-open flags as the
    lax join kernel over the full corpus suite — and both agree with
    the host oracle."""
    from emqx_tpu.ops import encode_batch
    from emqx_tpu.ops.match_kernel import decode_row_meta
    from emqx_tpu.ops.pallas_match import supports_join_table

    inc, dev = _join_dev(JOIN_CORPUS)
    assert supports_join_table(dev.arrays()[0], *dev._jarrs)
    enc = encode_batch(inc, JOIN_TOPICS, batch=16)
    cap = 8 * 16
    rj = dev.match(*enc, backend="join", flat_cap=cap)
    rp = dev.match(*enc, backend="join-pallas", flat_cap=cap)
    _assert_flat_parity(rj, rp, "corpus flat")
    nk, sp = decode_row_meta(np.asarray(rp.row_meta))
    flat = np.asarray(rp.matches)
    offs = np.cumsum(nk) - nk
    for i, t in enumerate(JOIN_TOPICS):
        if sp[i]:
            continue
        got = sorted(flat[offs[i]:offs[i] + nk[i]].tolist())
        assert got == sorted(inc.match_host(t)), (t, got)


def test_pallas_join_parity_overflow_rows_interpret():
    """Both spill kinds (active-set and match-count) flag the same
    rows bit-for-bit — the fail-open host re-run set is identical
    whichever join backend served."""
    from emqx_tpu.ops import encode_batch

    filters = ["+/+/#", "a/+/#", "+/3/#", "#"] \
        + [f"+/{i}/#" for i in range(6)]
    inc, dev = _join_dev(filters, active_slots=2, max_matches=2)
    enc = encode_batch(inc, ["a/3/x", "a/5/y/z", "q/1/w"], batch=4)
    rj = dev.match(*enc, backend="join", flat_cap=8)
    rp = dev.match(*enc, backend="join-pallas", flat_cap=8)
    _assert_flat_parity(rj, rp, "overflow flat")
    assert np.asarray(rj.active_overflow).sum() > 0
    assert np.asarray(rj.match_overflow).sum() > 0


def test_pallas_join_parity_dead_frontier_and_empty_batch():
    from emqx_tpu.ops import encode_batch

    inc, dev = _join_dev(["only/this"])
    enc = encode_batch(inc, ["zz/zz/zz", "$SYS/x"], batch=8)
    _assert_flat_parity(dev.match(*enc, backend="join", flat_cap=64),
                        dev.match(*enc, backend="join-pallas",
                                  flat_cap=64), "dead frontier")
    enc = encode_batch(inc, [], batch=8)
    _assert_flat_parity(dev.match(*enc, backend="join", flat_cap=64),
                        dev.match(*enc, backend="join-pallas",
                                  flat_cap=64), "empty batch")


def test_pallas_join_fallback_paths(monkeypatch):
    """join-pallas degrades without erroring: compact output falls to
    the lax join (the fused walk is flat-only), a non-tile-divisible
    batch falls to the lax join, and a table without the join relation
    falls to hash — spy-asserted (the Pallas entry never runs)."""
    from emqx_tpu.ops import encode_batch, pallas_match
    from emqx_tpu.ops.device_table import DeviceNfa
    from emqx_tpu.ops.incremental import IncrementalNfa

    def boom(*a, **kw):  # pragma: no cover - must never run
        raise AssertionError("pallas join ran on a fallback shape")

    inc, dev = _join_dev(JOIN_CORPUS)
    enc = encode_batch(inc, JOIN_TOPICS, batch=16)
    want = dev.match(*enc, backend="join")
    monkeypatch.setattr(pallas_match, "pallas_join_match_flat", boom)
    got = dev.match(*enc, backend="join-pallas")   # compact → lax join
    for f in ("matches", "n_matches", "active_overflow",
              "match_overflow"):
        assert np.array_equal(np.asarray(getattr(want, f)),
                              np.asarray(getattr(got, f))), f
    # batch not divisible by the 256-lane tile → lax join, same answer
    enc2 = encode_batch(inc, JOIN_TOPICS, batch=384)
    wf = dev.match(*enc2, backend="join", flat_cap=8 * 384)
    gf = dev.match(*enc2, backend="join-pallas", flat_cap=8 * 384)
    _assert_flat_parity(wf, gf, "non-tile batch")
    # no join relation → hash serves
    inc2 = IncrementalNfa(depth=8)
    inc2.add("a/+")
    dev2 = DeviceNfa(inc2, active_slots=8, max_matches=8)
    assert dev2._jarrs is None
    enc3 = encode_batch(inc2, ["a/k"], batch=8)
    r = dev2.match(*enc3, backend="join-pallas", flat_cap=64)
    np.testing.assert_array_equal(
        np.asarray(r.n_matches),
        np.asarray(dev2.match(*enc3, backend="hash",
                              flat_cap=64).n_matches))


def test_pallas_join_kernel_cache_backend():
    """The join-pallas backend is a first-class kernel-cache citizen:
    a cached dispatch compiles once, hits after, and returns the lax
    join's exact bits; lowering it without a flat cap is a contract
    error (flat-output only)."""
    import pytest as _pytest

    from emqx_tpu.ops import encode_batch
    from emqx_tpu.ops.kernel_cache import MatchKernelCache

    inc, dev = _join_dev(JOIN_CORPUS)
    kc = MatchKernelCache()
    dev.kernel_cache = kc
    enc = encode_batch(inc, JOIN_TOPICS, batch=16)
    cap = 8 * 16
    want = dev.match(*enc, backend="join", flat_cap=cap)
    rp = dev.match(*enc, backend="join-pallas", flat_cap=cap)
    _assert_flat_parity(want, rp, "cache first")
    compiles = kc.compiles
    rp2 = dev.match(*enc, backend="join-pallas", flat_cap=cap)
    _assert_flat_parity(want, rp2, "cache hit")
    assert kc.compiles == compiles    # pure hit, no recompile
    assert kc.hits >= 1
    s, hb, _d = inc.shape_key()
    with _pytest.raises(ValueError):
        kc._lower((16, 8, s, hb, 8, 16, True, 0, False,
                   "join-pallas", None))


def test_pallas_join_excluded_from_auto_prewarm_cross():
    """``auto`` prewarm crosses hash×join only — the Pallas family
    compiles on first explicit dispatch, never speculatively (VMEM
    budget gating is per-table, not per-shape)."""
    from emqx_tpu.ops.kernel_cache import MatchKernelCache

    kc = MatchKernelCache()
    assert "join-pallas" not in kc.auto_backends
