"""Pallas small-table fast path: parity vs nfa_match in interpret mode
(SURVEY.md §7.4 experiment; Mosaic lowering A/B'd on real hardware via
ops.pallas_match.bench_pallas_small)."""

import numpy as np
import jax.numpy as jnp
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops import compile_filters, encode_topics, nfa_match
from emqx_tpu.ops.pallas_match import (
    TILE_B, pallas_small_match, supports_table,
)

FILTERS = ["a/b/c", "a/+/c", "a/#", "#", "+", "+/b", "a/b", "b",
           "$SYS/#", "x//y", "+/+/+", "deep/1/2/3/4/5/6/#"]
TOPICS = (["a/b/c", "a/b", "a", "b", "x//y", "$SYS/broker",
           "deep/1/2/3/4/5/6/7", "nomatch/z", "a/q/c", "/"] * 26)[:256]


def test_pallas_parity_interpret():
    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    assert supports_table(*t.device_arrays()[:2])
    words, lens, is_sys = encode_topics(t, TOPICS, batch=256)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()])
    ref = nfa_match(*args, active_slots=8, compact_output=False)
    acc, aover = pallas_small_match(*args, depth=8, active_slots=8,
                                    interpret=True)
    ra, pa = np.asarray(ref.matches), np.asarray(acc)
    assert ra.shape == pa.shape
    # same accept-id multiset per row (slot layout is shared)
    assert (np.sort(np.where(ra < 0, -1, ra), axis=1)
            == np.sort(np.where(pa < 0, -1, pa), axis=1)).all()
    assert (np.asarray(ref.active_overflow) == np.asarray(aover)).all()
    # spot-check against the oracle too
    counts = np.asarray(ref.n_matches)
    for i, name in enumerate(TOPICS[:32]):
        want = {f for f in FILTERS if T.match(name, f)}
        got = {t.accept_filters[a] for a in pa[i] if a >= 0}
        assert got == want or counts[i] > len(got)


def test_pallas_flat_epilogue_parity_interpret():
    """The SHARED flat compaction epilogue rides the pallas walk too
    (ISSUE 11): pallas_small_match_flat produces the same dense flat
    buffer + packed row_meta as nfa_match(flat_cap=...), so both
    backends honor one two-phase readback contract."""
    from emqx_tpu.ops.match_kernel import decode_flat, decode_row_meta
    from emqx_tpu.ops.pallas_match import pallas_small_match_flat

    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS, batch=256)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()])
    K = 8
    cap = 8 * 256
    ref = nfa_match(*args, active_slots=8, max_matches=K, flat_cap=cap)
    got = pallas_small_match_flat(*args, depth=8, active_slots=8,
                                  max_matches=K, flat_cap=cap,
                                  interpret=True)
    np.testing.assert_array_equal(np.asarray(ref.n_matches),
                                  np.asarray(got.n_matches))
    np.testing.assert_array_equal(np.asarray(ref.row_meta),
                                  np.asarray(got.row_meta))
    # same per-row id SETS (slot order within a row may differ between
    # backends; the epilogue's compaction is order-preserving per input
    # layout, so compare decoded sets)
    n1 = np.asarray(ref.n_matches)
    rows_ref = decode_flat(np.asarray(ref.matches), n1, K)
    rows_got = decode_flat(np.asarray(got.matches),
                           np.asarray(got.n_matches), K)
    nk, sp = decode_row_meta(np.asarray(got.row_meta))
    for i in range(len(TOPICS)):
        if not sp[i]:
            assert set(rows_ref[i]) == set(rows_got[i]), i


def test_pallas_rejects_ragged_batch():
    t = compile_filters(FILTERS, depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS[:100], batch=100)
    with pytest.raises(ValueError):
        pallas_small_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()],
            depth=8, interpret=True)
    assert TILE_B == 256
