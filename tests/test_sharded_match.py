"""Sharded match pipeline on the virtual 8-device CPU mesh: DP/TP layouts
agree with the single-device reference (SURVEY.md §4 multi-node-analog)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from emqx_tpu.broker import FilterTrie
from emqx_tpu.ops import compile_filters, encode_topics, nfa_match
from emqx_tpu.parallel import (
    build_sharded_matcher,
    make_accept_bitmap,
    make_mesh,
    pick_shape,
)


FILTERS = ["a/+", "a/#", "+/b", "#", "x/y/z", "x/+/z", "$SYS/#"]
N_SUBS = 100


def subscribers_of(flt):
    # deterministic fake subscriber sets: filter index spreads over ids
    i = FILTERS.index(flt)
    return [(i * 13 + k * 7) % N_SUBS for k in range(i + 1)]


def _setup(batch=64):
    table = compile_filters(FILTERS, depth=8, state_bucket=8)
    rng = np.random.default_rng(7)
    names = [
        "/".join(rng.choice(["a", "b", "x", "y", "z"], size=rng.integers(1, 4)))
        for _ in range(batch)
    ]
    enc = encode_topics(table, names)
    return table, names, enc


def test_pick_shape():
    assert pick_shape(8) == {"dp": 2, "tp": 4}
    assert pick_shape(2) == {"dp": 1, "tp": 2}
    assert pick_shape(1) == {"dp": 1, "tp": 1}
    with pytest.raises(ValueError):
        pick_shape(6, tp=4)


def test_sharded_matches_unsharded():
    assert len(jax.devices()) == 8, "conftest must force 8 cpu devices"
    table, names, (words, lens, is_sys) = _setup(batch=64)
    bitmap = make_accept_bitmap(table, subscribers_of, N_SUBS, tp=4)
    mesh = make_mesh({"dp": 2, "tp": 4})
    step = build_sharded_matcher(mesh)
    args = (
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        jnp.asarray(bitmap),
    )
    res = step(*args)

    # reference: single-device match + host bitmap OR
    ref = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
    )
    m = np.asarray(ref.matches)
    ref_bm = np.zeros((64, bitmap.shape[1]), np.uint32)
    for r in range(64):
        for a in m[r][m[r] >= 0]:
            ref_bm[r] |= bitmap[a]
    np.testing.assert_array_equal(np.asarray(res.bitmap), ref_bm)
    popc = np.array([bin(int.from_bytes(row.tobytes(), "little")).count("1") for row in ref_bm])
    np.testing.assert_array_equal(np.asarray(res.n_subscribers), popc)
    np.testing.assert_array_equal(np.asarray(res.n_matches), np.asarray(ref.n_matches))
    assert int(np.sum(np.asarray(res.active_overflow))) == 0


def test_sharded_trie_parity():
    table, names, (words, lens, is_sys) = _setup(batch=32)
    tr = FilterTrie()
    for f in FILTERS:
        tr.insert(f)
    mesh = make_mesh({"dp": 4, "tp": 2})
    bitmap = make_accept_bitmap(table, subscribers_of, N_SUBS, tp=2)
    step = build_sharded_matcher(mesh)
    res = step(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        jnp.asarray(bitmap),
    )
    n = np.asarray(res.n_matches)
    for r, name in enumerate(names):
        assert n[r] == len(tr.match(name)), name


def test_accept_bitmap_padding():
    table = compile_filters(["a"], depth=4, state_bucket=8)
    bm = make_accept_bitmap(table, lambda f: [0, 31, 32, 99], 100, tp=4)
    assert bm.shape[1] % 4 == 0
    assert bm[0, 0] == (1 | (1 << 31))
    assert bm[0, 1] == 1
    assert bm[-1].sum() == 0  # invalid row is zeros


# ---------------------------------------------------------------------------
# config 4: on-device $share group selection (tp-sharded candidates)
# ---------------------------------------------------------------------------

def test_shared_group_selection_parity():
    import jax.numpy as jnp

    from emqx_tpu.parallel import (
        build_shared_selector, host_pick, make_group_masks, make_mesh,
    )

    rng = np.random.default_rng(9)
    n_subs, W, B, G = 4096, 128, 64, 8
    mesh = make_mesh({"dp": 2, "tp": 4})
    bitmap = rng.integers(0, 2**32, (B, W), dtype=np.uint32)
    groups = [rng.choice(n_subs, size=rng.integers(1, 200), replace=False)
              for _ in range(G - 1)]
    groups.append([])  # empty group -> -1
    masks = make_group_masks(groups, n_subs, W)
    sel_hash = rng.integers(0, 2**31 - 1, B).astype(np.int32)

    select = build_shared_selector(mesh)
    out = np.asarray(select(jnp.asarray(bitmap), jnp.asarray(masks),
                            jnp.asarray(sel_hash)))
    assert out.shape == (B, G)
    for b in range(B):
        for g in range(G):
            want = host_pick(bitmap[b], masks[g], int(sel_hash[b]))
            assert out[b, g] == want, (b, g, out[b, g], want)
    # empty group column is all -1
    assert (out[:, G - 1] == -1).all()


# ---------------------------------------------------------------------------
# config 5: ring-tiled accept-bitmap OR-reduction
# ---------------------------------------------------------------------------

def test_ring_fanout_parity():
    import jax.numpy as jnp

    from emqx_tpu.ops import compile_filters, encode_topics, nfa_match
    from emqx_tpu.parallel import (
        build_ring_fanout, make_accept_bitmap, make_mesh, shard_bitmap_rows,
    )

    rng = np.random.default_rng(4)
    words = [f"w{i}" for i in range(20)]
    filters = sorted({
        "/".join(
            ("+" if rng.random() < 0.25 else words[rng.integers(20)])
            for _ in range(rng.integers(1, 5))
        ) + ("/#" if rng.random() < 0.3 else "")
        for _ in range(300)
    })
    table = compile_filters(filters, depth=8)
    n_subs = 2048
    bitmap = make_accept_bitmap(
        table,
        lambda f: [(hash(f) + k * 13) % n_subs
                   for k in range(1 + hash(f) % 5)],
        n_subs,
    )
    topics = ["/".join(words[rng.integers(20)]
                       for _ in range(rng.integers(1, 6)))
              for _ in range(64)]
    w, l, s = encode_topics(table, topics, batch=64)
    args = (jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
            *[jnp.asarray(a) for a in table.device_arrays()])

    mesh = make_mesh({"dp": 2, "ring": 4})
    rows = shard_bitmap_rows(bitmap, 4)
    step = build_ring_fanout(mesh)
    got = np.asarray(step(*args, jnp.asarray(rows)))

    # dense single-device reference
    ref = nfa_match(*args)
    m = np.asarray(ref.matches)
    want = np.zeros((64, bitmap.shape[1]), np.uint32)
    for r in range(64):
        for aid in m[r][m[r] >= 0]:
            want[r] |= bitmap[aid]
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# shard-local compaction (ISSUE 11): dense id lists leave the mesh, not
# (B, W) bitmap tiles
# ---------------------------------------------------------------------------

def test_compact_bitmap_ids_unit():
    from emqx_tpu.parallel import compact_bitmap_ids

    rng = np.random.default_rng(21)
    bm = rng.integers(0, 2**32, (16, 4), dtype=np.uint32)
    ids, n, over = jax.jit(
        compact_bitmap_ids, static_argnums=(1,))(jnp.asarray(bm), 128)
    ids, n, over = np.asarray(ids), np.asarray(n), np.asarray(over)
    for r in range(16):
        want = [w * 32 + b for w in range(4) for b in range(32)
                if bm[r, w] >> b & 1]
        assert n[r] == len(want)
        assert ids[r, :n[r]].tolist() == want  # ascending, dense
        assert (ids[r, n[r]:] == -1).all()
        assert over[r] == 0
    # truncation: a cap below the densest row flags overflow and keeps
    # the surviving ascending prefix
    cap = int(n.max()) - 1
    ids2, n2, over2 = jax.jit(
        compact_bitmap_ids, static_argnums=(1,))(jnp.asarray(bm), cap)
    over2 = np.asarray(over2)
    assert over2[np.asarray(n2).argmax()] == 1
    dense = np.asarray(ids2)[np.asarray(n2).argmax()]
    assert (dense >= 0).sum() == cap


def test_compact_sharded_matcher_matches_bitmap_path():
    from emqx_tpu.parallel import (
        build_sharded_matcher_compact, decode_compact_rows,
    )

    table, names, (words, lens, is_sys) = _setup(batch=64)
    bitmap = make_accept_bitmap(table, subscribers_of, N_SUBS, tp=4)
    mesh = make_mesh({"dp": 2, "tp": 4})
    cap = 32
    step = build_sharded_matcher_compact(mesh, cap_row=cap)
    res = step(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        jnp.asarray(bitmap),
    )
    # what leaves the mesh is matches-proportional: tp·(cap+2) ints per
    # topic vs W words of bitmap tile — assert the dense decode agrees
    # with the single-device bitmap reference bit for bit
    ref = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
    )
    m = np.asarray(ref.matches)
    assert int(np.asarray(res.overflow).sum()) == 0
    rows = decode_compact_rows(
        np.asarray(res.ids), np.asarray(res.counts), cap)
    for r in range(64):
        want = set()
        for aid in m[r][m[r] >= 0]:
            for w in range(bitmap.shape[1]):
                v = int(bitmap[aid, w])
                want |= {w * 32 + b for b in range(32) if v >> b & 1}
        got = rows[r].tolist()
        assert sorted(got) == sorted(want), r
        # disjoint tp segments: concatenation needs no dedup
        assert len(got) == len(set(got))
    np.testing.assert_array_equal(
        np.asarray(res.n_matches), np.asarray(ref.n_matches))


def test_ring_fanout_compact_parity_and_truncation():
    from emqx_tpu.parallel import (
        build_ring_fanout, build_ring_fanout_compact, make_mesh,
        shard_bitmap_rows,
    )

    rng = np.random.default_rng(4)
    words = [f"w{i}" for i in range(20)]
    filters = sorted({
        "/".join(
            ("+" if rng.random() < 0.25 else words[rng.integers(20)])
            for _ in range(rng.integers(1, 5))
        ) + ("/#" if rng.random() < 0.3 else "")
        for _ in range(300)
    })
    table = compile_filters(filters, depth=8)
    n_subs = 2048
    bitmap = make_accept_bitmap(
        table,
        lambda f: [(hash(f) + k * 13) % n_subs
                   for k in range(1 + hash(f) % 5)],
        n_subs,
    )
    topics = ["/".join(words[rng.integers(20)]
                       for _ in range(rng.integers(1, 6)))
              for _ in range(64)]
    w, l, s = encode_topics(table, topics, batch=64)
    args = (jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
            *[jnp.asarray(a) for a in table.device_arrays()])
    mesh = make_mesh({"dp": 2, "ring": 4})
    rows = shard_bitmap_rows(bitmap, 4)

    ref = np.asarray(build_ring_fanout(mesh)(*args, jnp.asarray(rows)))
    # ample cap: the dense-id ring reduces to the SAME full bitmap
    # (dedup across ring shards included — OR semantics preserved)
    acc, trunc = build_ring_fanout_compact(mesh, cap_row=128)(
        *args, jnp.asarray(rows))
    np.testing.assert_array_equal(np.asarray(acc), ref)
    assert int(np.asarray(trunc).sum()) == 0
    # starving cap: truncation is FLAGGED (fail-open set), result rows
    # are a subset of the reference
    acc2, trunc2 = build_ring_fanout_compact(mesh, cap_row=1)(
        *args, jnp.asarray(rows))
    acc2, trunc2 = np.asarray(acc2), np.asarray(trunc2)
    assert int(trunc2.sum()) > 0
    assert ((acc2 & ~ref) == 0).all()   # never invents subscribers


# ---------------------------------------------------------------------------
# EP: prefix-partitioned tables + all-to-all routing (SURVEY §2.5)
# ---------------------------------------------------------------------------

def test_prefix_ep_all_to_all_parity():
    import jax.numpy as jnp

    from emqx_tpu import topic as T
    from emqx_tpu.parallel import (
        build_ep_matcher, build_partitions, make_mesh, owner_of,
    )
    from emqx_tpu.ops.encode import TopicEncoder

    rng = np.random.default_rng(17)
    words = [f"r{i}" for i in range(24)]
    filters = sorted({
        "/".join(
            (words[rng.integers(24)] if lvl > 0 or rng.random() > 0.15
             else "+")
            for lvl, _ in enumerate(range(rng.integers(1, 5)))
        ) + ("/#" if rng.random() < 0.3 else "")
        for _ in range(400)
    } | {"+/status", "#"})
    E = 4
    tabs = build_partitions(filters, E, depth=8)

    B = 64
    topics = ["/".join(words[rng.integers(24)]
                       for _ in range(rng.integers(1, 6)))
              for _ in range(B)]
    enc = TopicEncoder(tabs.vocab)
    w, l, s = enc.encode(topics, 8, batch=B)

    mesh = make_mesh({"ep": E})
    step = build_ep_matcher(mesh, capacity=B)  # ample: no overflow
    res = step(jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
               jnp.asarray(tabs.node_tabs), jnp.asarray(tabs.edge_tabs),
               jnp.asarray(tabs.seeds))
    m = np.asarray(res.matches)
    owners = np.asarray(res.owners)
    n = np.asarray(res.n_matches)
    assert (np.asarray(res.overflow) == 0).all()

    for i, t in enumerate(topics):
        own = owner_of(t, tabs.vocab, E)
        assert owners[i] == own
        got = {tabs.accept_filters[own][a] for a in m[i][: n[i]]}
        want = {f for f in filters if T.match(t, f)}
        assert got == want, (t, got ^ want)


def test_prefix_ep_overflow_flags_host_rerun():
    import jax.numpy as jnp

    from emqx_tpu.parallel import (
        build_ep_matcher, build_partitions, make_mesh,
    )
    from emqx_tpu.ops.encode import TopicEncoder

    filters = ["hot/a", "hot/+", "cold/b"]
    E = 2
    tabs = build_partitions(filters, E, depth=4)
    # every topic shares one root -> one owner bucket; capacity 2 with
    # 8 same-owner topics per source shard must overflow
    topics = ["hot/a"] * 16
    enc = TopicEncoder(tabs.vocab)
    w, l, s = enc.encode(topics, 4, batch=16)
    mesh = make_mesh({"ep": E})
    step = build_ep_matcher(mesh, capacity=2)
    res = step(jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
               jnp.asarray(tabs.node_tabs), jnp.asarray(tabs.edge_tabs),
               jnp.asarray(tabs.seeds))
    over = np.asarray(res.overflow)
    assert over.sum() == 16 - 2 * E  # C slots per (source, owner) pair
    # non-overflowed rows still answered correctly
    m = np.asarray(res.matches)
    n = np.asarray(res.n_matches)
    for i in range(16):
        if over[i]:
            continue
        own = int(np.asarray(res.owners)[i])
        got = {tabs.accept_filters[own][a] for a in m[i][: n[i]]}
        assert got == {"hot/a", "hot/+"}


def test_ulysses_reshard_roundtrip():
    """build_reshard flips row-sharded → column-sharded with bit-exact
    content; build_unreshard inverts it."""
    from emqx_tpu.parallel import build_reshard, build_unreshard
    from emqx_tpu.parallel.mesh import make_mesh as _mm

    mesh = _mm({"u": 8})
    rng = np.random.default_rng(3)
    x = rng.integers(0, 2**32, size=(64, 16), dtype=np.uint32)
    fwd = build_reshard(mesh)
    inv = build_unreshard(mesh)
    d = fwd(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(d), x)  # global value unchanged
    # sharding actually flipped: every out-shard spans all rows but
    # only a column slice
    assert all(s.data.shape[0] == 64 and s.data.shape[1] < 16
               for s in d.addressable_shards)
    col_widths = {s.data.shape[1] for s in d.addressable_shards}
    assert col_widths == {16 // 8}
    back = inv(d)
    np.testing.assert_array_equal(np.asarray(back), x)
    row_heights = {s.data.shape[0] for s in back.addressable_shards}
    assert row_heights == {64 // 8}


def test_ulysses_step_matches_reference():
    """Full ingest→match→reshard→dispatch step: the dispatch-layout
    bitmap equals the dense reference, per-subscriber delivery counts
    equal the host tally, and the output shardings are the dispatch
    layout (cols sharded over u)."""
    from emqx_tpu.parallel import build_ulysses_step
    from emqx_tpu.parallel.mesh import make_mesh as _mm

    table, names, (words, lens, is_sys) = _setup(batch=64)
    bitmap = make_accept_bitmap(table, subscribers_of, N_SUBS, tp=8)
    mesh = _mm({"u": 8})
    step = build_ulysses_step(mesh)
    res = step(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        jnp.asarray(bitmap),
    )
    ref = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
    )
    m = np.asarray(ref.matches)
    W = bitmap.shape[1]
    ref_bm = np.zeros((64, W), np.uint32)
    for r in range(64):
        for a in m[r][m[r] >= 0]:
            ref_bm[r] |= bitmap[a]
    np.testing.assert_array_equal(np.asarray(res.dispatch_bitmap), ref_bm)
    np.testing.assert_array_equal(np.asarray(res.n_matches),
                                  np.asarray(ref.n_matches))
    # per-subscriber deliveries = column bit tallies of the dense bitmap
    bits = (ref_bm[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    want = bits.astype(np.int32).sum(axis=0).reshape(-1)
    np.testing.assert_array_equal(np.asarray(res.sub_deliveries), want)
    # dispatch layout: every shard holds ALL 64 rows, a W/8 column slice
    shapes = {s.data.shape for s in res.dispatch_bitmap.addressable_shards}
    assert shapes == {(64, W // 8)}, shapes
    dshapes = {s.data.shape for s in res.sub_deliveries.addressable_shards}
    assert dshapes == {(W * 32 // 8,)}, dshapes
