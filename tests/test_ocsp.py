"""OCSP stapling cache against a mocked responder (VERDICT r4 item 8;
emqx_ocsp_cache analog).  A throwaway CA + server cert are generated
in-test; the responder is an injected fetch callable building real
RFC 6960 DER responses with the CA key."""

import asyncio
import datetime

import pytest

pytest.importorskip("cryptography")

from emqx_tpu.transport.ocsp import OcspCache, OcspError

from cryptography import x509
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.hazmat.primitives.serialization import Encoding
from cryptography.x509.oid import (
    AuthorityInformationAccessOID, NameOID,
)


def run(coro):
    return asyncio.run(coro)


def _name(cn):
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def make_pki(aia_url="http://ocsp.test/resp"):
    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca = (
        x509.CertificateBuilder()
        .subject_name(_name("test-ca")).issuer_name(_name("test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    srv_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    builder = (
        x509.CertificateBuilder()
        .subject_name(_name("broker.test")).issuer_name(_name("test-ca"))
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=30))
    )
    if aia_url:
        builder = builder.add_extension(
            x509.AuthorityInformationAccess([
                x509.AccessDescription(
                    AuthorityInformationAccessOID.OCSP,
                    x509.UniformResourceIdentifier(aia_url)),
            ]),
            critical=False,
        )
    srv = builder.sign(ca_key, hashes.SHA256())
    return ca, ca_key, srv, srv_key


def make_responder(ca, ca_key, srv, *, status="good",
                   next_update_s=3600.0, this_update_skew_s=0.0):
    """fetch(url, der_request) building real signed OCSP responses."""
    from cryptography.x509 import ocsp

    calls = []

    async def fetch(url, der_request):
        calls.append(url)
        req = ocsp.load_der_ocsp_request(der_request)
        assert req.serial_number == srv.serial_number
        now = datetime.datetime.now(datetime.timezone.utc)
        cert_status = {
            "good": ocsp.OCSPCertStatus.GOOD,
            "revoked": ocsp.OCSPCertStatus.REVOKED,
        }[status]
        builder = ocsp.OCSPResponseBuilder().add_response(
            cert=srv, issuer=ca, algorithm=hashes.SHA256(),
            cert_status=cert_status,
            this_update=now + datetime.timedelta(seconds=this_update_skew_s),
            next_update=now + datetime.timedelta(seconds=next_update_s),
            revocation_time=(now if status == "revoked" else None),
            revocation_reason=(
                x509.ReasonFlags.key_compromise
                if status == "revoked" else None),
        ).responder_id(ocsp.OCSPResponderEncoding.NAME, ca)
        resp = builder.sign(ca_key, hashes.SHA256())
        return resp.public_bytes(Encoding.DER)

    fetch.calls = calls
    return fetch


def pems(ca, srv):
    return (srv.public_bytes(Encoding.PEM), ca.public_bytes(Encoding.PEM))


def test_refresh_good_and_staple_served():
    ca, ca_key, srv, _k = make_pki()
    cert_pem, issuer_pem = pems(ca, srv)
    fetch = make_responder(ca, ca_key, srv)
    cache = OcspCache(cert_pem, issuer_pem, fetch=fetch)
    # responder URL came from the certificate's AIA extension
    assert cache.responder_url == "http://ocsp.test/resp"
    status = run(cache.refresh())
    assert status == "good"
    assert cache.current() is not None
    info = cache.info()
    assert info["stapled"] and info["status"] == "good"
    assert info["refreshes"] == 1 and fetch.calls == ["http://ocsp.test/resp"]


def test_revoked_status_surfaces():
    ca, ca_key, srv, _k = make_pki()
    cache = OcspCache(*pems(ca, srv),
                      fetch=make_responder(ca, ca_key, srv, status="revoked"))
    assert run(cache.refresh()) == "revoked"
    # the revoked response IS the staple (clients must see the proof)
    assert cache.current() is not None


def test_expired_staple_not_served():
    import time

    ca, ca_key, srv, _k = make_pki()
    cache = OcspCache(*pems(ca, srv),
                      fetch=make_responder(ca, ca_key, srv,
                                           next_update_s=3600))
    run(cache.refresh())
    assert cache.current() is not None
    cache._next_update = time.time() - 1    # the staple just expired
    assert cache.current() is None          # expired: unstapled fail-open


def test_refresh_sleep_tracks_next_update():
    """A short-lived response pulls the next refresh AHEAD of expiry
    (review finding: a 10-minute window must not wait out a 1-hour
    interval unstapled)."""
    import time

    ca, ca_key, srv, _k = make_pki()
    cache = OcspCache(*pems(ca, srv),
                      refresh_interval_s=3600.0,
                      fetch=make_responder(ca, ca_key, srv,
                                           next_update_s=600))
    run(cache.refresh())
    sleep = cache._next_sleep()
    # ~ (600 - margin 60); definitely nowhere near 3600
    assert 400 < sleep < 600
    # and the floor holds for pathologically short windows
    cache._next_update = time.time() + 5
    assert cache._next_sleep() == cache.MIN_SLEEP_S


def test_failures_counted_once():
    ca, ca_key, srv, _k = make_pki()

    async def broken(url, der):
        raise OSError("nope")

    cache = OcspCache(*pems(ca, srv), fetch=broken)
    with pytest.raises(OSError):
        run(cache.refresh())
    assert cache.failures == 1
    cache2 = OcspCache(*pems(ca, srv),
                       fetch=make_responder(ca, ca_key, srv,
                                            this_update_skew_s=900))
    with pytest.raises(OcspError):
        run(cache2.refresh())
    assert cache2.failures == 1


def test_responder_failure_keeps_last_good_response():
    ca, ca_key, srv, _k = make_pki()
    good = make_responder(ca, ca_key, srv)

    async def flaky(url, der):
        if flaky.fail:
            raise OSError("responder unreachable")
        return await good(url, der)

    flaky.fail = False
    cache = OcspCache(*pems(ca, srv), fetch=flaky)
    run(cache.refresh())
    staple = cache.current()
    assert staple is not None
    flaky.fail = True
    with pytest.raises(OSError):
        run(cache.refresh())
    assert cache.current() == staple        # stale-while-refresh


def test_future_dated_response_rejected():
    ca, ca_key, srv, _k = make_pki()
    cache = OcspCache(
        *pems(ca, srv),
        fetch=make_responder(ca, ca_key, srv, this_update_skew_s=900))
    with pytest.raises(OcspError):
        run(cache.refresh())
    assert cache.current() is None


def test_no_aia_and_no_override_is_an_error():
    ca, ca_key, srv, _k = make_pki(aia_url=None)
    cache = OcspCache(*pems(ca, srv),
                      fetch=make_responder(ca, ca_key, srv))
    assert cache.responder_url is None
    with pytest.raises(OcspError):
        run(cache.refresh())


def test_node_wires_ocsp_cache(tmp_path):
    """listeners.ssl.default.ocsp.enable builds the cache from the
    configured cert pair and exposes the health surface."""
    from cryptography.hazmat.primitives.serialization import (
        NoEncryption, PrivateFormat,
    )
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    ca, ca_key, srv, srv_key = make_pki()
    (tmp_path / "srv.pem").write_bytes(srv.public_bytes(Encoding.PEM))
    (tmp_path / "srv.key").write_bytes(srv_key.private_bytes(
        Encoding.PEM, PrivateFormat.TraditionalOpenSSL, NoEncryption()))
    (tmp_path / "ca.pem").write_bytes(ca.public_bytes(Encoding.PEM))

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'listeners.ssl.default.enable = true\n'
            'listeners.ssl.default.bind = "127.0.0.1:0"\n'
            f'listeners.ssl.default.certfile = "{tmp_path}/srv.pem"\n'
            f'listeners.ssl.default.keyfile = "{tmp_path}/srv.key"\n'
            f'listeners.ssl.default.cacertfile = "{tmp_path}/ca.pem"\n'
            'listeners.ssl.default.ocsp.enable = true\n'
            'listeners.ssl.default.ocsp.responder_url = '
            '"http://127.0.0.1:1/ocsp"\n'
            'listeners.ssl.default.ocsp.refresh_interval = 3600s\n'
        ))
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.ocsp_cache is not None
            info = node.ocsp_cache.info()
            assert info["responder_url"] == "http://127.0.0.1:1/ocsp"
            # swap in the mocked responder and refresh through the cache
            node.ocsp_cache._fetch = make_responder(ca, ca_key, srv)
            assert await node.ocsp_cache.refresh() == "good"
            assert node.ocsp_cache.current() is not None
        finally:
            await node.stop()
            assert node.ocsp_cache is None

    run(main())


def test_wrong_serial_rejected():
    """A response for a DIFFERENT certificate must not install."""
    ca, ca_key, srv, _k = make_pki()
    _ca2, _k2, other, _ok = make_pki()
    cache = OcspCache(*pems(ca, srv),
                      fetch=make_responder(ca, ca_key, other))
    with pytest.raises(Exception):
        run(cache.refresh())
    assert cache.current() is None


def test_forged_signature_rejected():
    """A response signed by someone other than the issuer must not
    install (OCSP rides plain HTTP)."""
    ca, ca_key, srv, _k = make_pki()
    mitm_ca, mitm_key, _s, _mk = make_pki()

    from cryptography.x509 import ocsp as _o

    async def forged(url, der_request):
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = _o.OCSPResponseBuilder().add_response(
            cert=srv, issuer=ca, algorithm=hashes.SHA256(),
            cert_status=_o.OCSPCertStatus.GOOD,
            this_update=now, next_update=now + datetime.timedelta(hours=1),
            revocation_time=None, revocation_reason=None,
        ).responder_id(_o.OCSPResponderEncoding.NAME, mitm_ca)
        return builder.sign(mitm_key, hashes.SHA256()).public_bytes(
            Encoding.DER)

    cache = OcspCache(*pems(ca, srv), fetch=forged)
    with pytest.raises(OcspError):
        run(cache.refresh())
    assert cache.current() is None and cache.failures == 1
