"""Zstandard decoder (`native/zstd.cpp` via `native/zstd.py`) +
pure-Python compressing encoder, cross-validated against SYSTEM
libzstd in both directions — the Kafka codec-4 fetch path must accept
whatever a real (Java/librdkafka) producer emits, and real consumers
must accept our frames."""

import ctypes
import ctypes.util
import os
import random
import struct

import pytest

from emqx_tpu.native import zstd

# ZSTD_CCtx_setParameter enums (public zstd.h ABI)
_C_LEVEL = 100
_C_WINDOWLOG = 101
_C_CONTENTSIZE = 200
_C_CHECKSUM = 201

_SYS = None


def _syszstd():
    global _SYS
    if _SYS is None:
        path = ctypes.util.find_library("zstd") or "libzstd.so.1"
        try:
            lib = ctypes.CDLL(path)
            lib.ZSTD_compress.restype = ctypes.c_size_t
            lib.ZSTD_decompress.restype = ctypes.c_size_t
            lib.ZSTD_compressBound.restype = ctypes.c_size_t
            lib.ZSTD_isError.restype = ctypes.c_uint
            lib.ZSTD_createCCtx.restype = ctypes.c_void_p
            lib.ZSTD_freeCCtx.restype = ctypes.c_size_t
            lib.ZSTD_freeCCtx.argtypes = [ctypes.c_void_p]
            lib.ZSTD_CCtx_setParameter.restype = ctypes.c_size_t
            lib.ZSTD_CCtx_setParameter.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib.ZSTD_compress2.restype = ctypes.c_size_t
            lib.ZSTD_compress2.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t]
            _SYS = lib
        except OSError:
            _SYS = False
    return _SYS or None


def _ref_compress(data: bytes, level: int = 3,
                  checksum: bool = False) -> bytes:
    lib = _syszstd()
    cap = lib.ZSTD_compressBound(len(data))
    dst = ctypes.create_string_buffer(max(64, cap))
    if checksum:
        cctx = lib.ZSTD_createCCtx()
        assert cctx
        try:
            lib.ZSTD_CCtx_setParameter(cctx, _C_LEVEL, level)
            lib.ZSTD_CCtx_setParameter(cctx, _C_CHECKSUM, 1)
            n = lib.ZSTD_compress2(cctx, dst, cap, data, len(data))
        finally:
            lib.ZSTD_freeCCtx(cctx)
    else:
        n = lib.ZSTD_compress(dst, cap, data, len(data), level)
    assert not lib.ZSTD_isError(n)
    return dst.raw[:n]


def _ref_decompress(frame: bytes, want: int) -> bytes:
    lib = _syszstd()
    dst = ctypes.create_string_buffer(max(1, want))
    n = lib.ZSTD_decompress(dst, want, frame, len(frame))
    assert not lib.ZSTD_isError(n), "reference zstd rejected our frame"
    return dst.raw[:n]


def _cases():
    random.seed(4013)
    blob = os.urandom(256)
    return [
        b"",
        b"q",
        b"abc",
        b"hello world " * 3,
        b"\x00" * 300_000,                       # RLE blocks + rep offsets
        b"ab" * 40_000,                          # tight matches
        os.urandom(5000),                        # incompressible: raw lits
        bytes(random.randrange(6) for _ in range(120_000)),
        b"the quick brown fox jumps over the lazy dog " * 400,
        b'{"topic":"t/1","qos":1,"payload":"' + blob.hex().encode()
        + b'"}' * 100,
        bytes(random.choice(blob) for _ in range(70_000)),
        (b"x" * 131_072) + b"tail-after-block-boundary" + os.urandom(64),
    ]


def test_store_mode_roundtrip_own_decoder():
    if not zstd.available():
        pytest.skip("no native toolchain")
    for d in _cases():
        assert zstd.decompress_frame(zstd.compress_frame(d)) == d


def test_store_mode_fcs_boundaries():
    # the frame-content-size field changes width at these sizes
    if not zstd.available():
        pytest.skip("no native toolchain")
    for n in (0, 1, 255, 256, 65791, 65792, 131072, 131073):
        d = os.urandom(n)
        f = zstd.compress_frame(d)
        assert zstd.decompress_frame(f) == d


def test_reference_encodings_decode():
    """Every libzstd level exercises different block shapes: fast
    levels lean on raw/RLE literals, high levels on 4-stream Huffman +
    described FSE tables."""
    if _syszstd() is None or not zstd.available():
        pytest.skip("system libzstd or toolchain unavailable")
    for level in (1, 3, 9, 19, 22):
        for d in _cases():
            frame = _ref_compress(d, level)
            assert zstd.decompress_frame(frame) == d, \
                f"level {level}, {len(d)} bytes"


def test_reference_checksum_frames_verify():
    if _syszstd() is None or not zstd.available():
        pytest.skip("system libzstd or toolchain unavailable")
    d = b"checksummed payload " * 2000
    frame = _ref_compress(d, 3, checksum=True)
    assert zstd.decompress_frame(frame) == d
    # flip one payload bit: the xxh64 content checksum must catch it
    # (pick a byte past the frame header)
    bad = bytearray(frame)
    bad[len(bad) // 2] ^= 0x01
    with pytest.raises(ValueError):
        zstd.decompress_frame(bytes(bad))


def test_our_frames_decode_with_reference():
    if _syszstd() is None:
        pytest.skip("system libzstd unavailable")
    for d in _cases():
        frame = zstd.compress_frame(d)
        assert _ref_decompress(frame, max(1, len(d))) == d, \
            f"reference zstd rejected our store-mode frame ({len(d)}B)"


def test_multi_frame_and_skippable():
    if _syszstd() is None or not zstd.available():
        pytest.skip("system libzstd or toolchain unavailable")
    a, b = b"first frame " * 100, os.urandom(2000)
    skippable = struct.pack("<II", 0x184D2A50, 5) + b"meta!"
    stream = _ref_compress(a, 3) + skippable + _ref_compress(b, 19)
    assert zstd.decompress_frame(stream) == a + b


def test_corrupt_and_unsupported_frames():
    if not zstd.available():
        pytest.skip("no native toolchain")
    good = _ref_compress(b"corruption target " * 500, 3) \
        if _syszstd() else zstd.compress_frame(b"corruption target " * 500)
    with pytest.raises(ValueError):
        zstd.decompress_frame(b"\x00\x11\x22\x33garbage")
    with pytest.raises(ValueError):
        zstd.decompress_frame(good[:-4])             # truncated
    # a frame declaring a dictionary ID is unsupported, not corrupt-
    # crash: magic + FHD(dictFlag=1) + window + dictid + empty block
    dict_frame = struct.pack("<I", 0xFD2FB528) + bytes([0x01, 0x38, 7]) \
        + b"\x01\x00\x00"
    with pytest.raises(ValueError, match="dict"):
        zstd.decompress_frame(dict_frame)
    # corrupt-bit sweep over a small frame must never crash or hang
    frame = bytearray(good[:200] if len(good) > 200 else good)
    for i in range(len(frame)):
        bad = bytes(frame[:i]) + bytes([frame[i] ^ 0xA5]) \
            + bytes(frame[i + 1:])
        try:
            zstd.decompress_frame(bad)
        except ValueError:
            pass


def test_kafka_batch_zstd_roundtrip():
    from emqx_tpu.bridge.kafka import parse_batches, record_batch
    if not zstd.available():
        pytest.skip("no native toolchain")
    msgs = [(b"k%d" % i, b"payload-%d" % i * 20) for i in range(50)]
    batch = record_batch(msgs, compression="zstd")
    out, next_off, skipped = parse_batches(batch)
    assert skipped == 0
    assert [(k, v) for _, k, v in out] == msgs
    assert next_off == 50


def test_kafka_batch_java_producer_shape():
    """A batch whose records section was compressed by REAL libzstd
    (what a Java/librdkafka producer emits) must ingest whole."""
    from emqx_tpu.bridge import kafka as kf
    if _syszstd() is None or not zstd.available():
        pytest.skip("system libzstd or toolchain unavailable")
    msgs = [(None, b'{"n":%d}' % i) for i in range(200)]
    recs = b"".join(
        kf._record(i, 0, k, v) for i, (k, v) in enumerate(msgs))
    comp = _ref_compress(recs, 3)
    n = len(msgs)
    after_crc = struct.pack("!hiqqqhii", 4, n - 1, 17, 17, -1, -1, -1,
                            n) + comp
    body = struct.pack("!iBI", -1, 2, kf.crc32c(after_crc)) + after_crc
    batch = struct.pack("!qi", 0, len(body)) + body
    out, next_off, skipped = kf.parse_batches(batch)
    assert skipped == 0 and next_off == n
    assert [(k, v) for _, k, v in out] == msgs


def test_store_mode_fallback_without_native_decoder(monkeypatch):
    """On a toolchain-less host the bridge's OWN zstd production must
    still round-trip — and since round 5 the pure-Python fallback
    decodes FOREIGN (libzstd) frames too: Huffman literals incl.
    treeless reuse, every sequence-table mode, repeat offsets and
    cross-block matches."""
    monkeypatch.setattr(zstd, "_lib", None)
    monkeypatch.setattr(zstd, "_loaded", True)
    assert not zstd.available()
    for d in (b"", b"own production " * 999, os.urandom(200_000)):
        assert zstd.decompress_frame(zstd.compress_frame(d)) == d
    if _syszstd() is not None:
        # hex text at level 19: Huffman literal blocks; a big templated
        # payload at 19: multi-block with treeless/repeat/window use
        for payload in (os.urandom(30_000).hex().encode(),
                        b'{"a":%d,"b":"x"},' % 5 * 20000):
            real = _ref_compress(payload, 19)
            assert zstd.decompress_frame(real) == payload
    # and the kafka fetch path decodes, never stalls
    from emqx_tpu.bridge.kafka import parse_batches, record_batch
    batch = record_batch([(b"k", b"v" * 50)], compression="zstd")
    out, nxt, skipped = parse_batches(batch)
    assert skipped == 0 and [v for _, _, v in out] == [b"v" * 50]


def test_fallback_truncated_header_is_valueerror(monkeypatch):
    """A frame cut right after the magic must raise ValueError (the
    class kafka.py maps to KafkaError), never IndexError."""
    monkeypatch.setattr(zstd, "_lib", None)
    monkeypatch.setattr(zstd, "_loaded", True)
    for frag in (b"\x28\xb5\x2f\xfd", b"\x28\xb5\x2f\xfd\x20",
                 b"\x50\x2a\x4d\x18\x05\x00"):
        with pytest.raises(ValueError):
            zstd.decompress_frame(frag)


def test_compressing_encoder_tri_decoder_and_ratio():
    """The predefined-FSE encoder's output must be accepted by all
    three decoders (libzstd, our C++, the Python fallback) and
    actually compress compressible payloads."""
    if not zstd.available():
        pytest.skip("no native toolchain")
    json_like = b'{"topic":"t/%d","qos":1,"payload":"sensor"},' * 2000
    frame = zstd.compress_frame(json_like)
    assert len(frame) < len(json_like) // 10          # real ratio
    assert zstd.decompress_frame(frame) == json_like  # our C++
    if _syszstd() is not None:                        # reference
        assert _ref_decompress(frame, len(json_like)) == json_like
    assert zstd._py_store_decompress(frame) == json_like  # fallback


def test_compressing_encoder_roundtrip_fuzz():
    """Structured fuzz across sizes/alphabets: encoder output decodes
    identically via the native decoder AND the Python fallback."""
    if not zstd.available():
        pytest.skip("no native toolchain")
    random.seed(8878)
    for trial in range(40):
        size = random.choice((0, 1, 3, 17, 400, 5000, 140_000))
        alpha = random.choice((1, 4, 64, 256))
        d = bytes(random.randrange(alpha) for _ in range(size))
        f = zstd.compress_frame(d)
        assert zstd.decompress_frame(f) == d, (trial, size, alpha)
        assert zstd._py_store_decompress(f) == d, (trial, size, alpha)


def _craft_sequence_block(seqs, literals=b""):
    """Hand-assemble a compressed block from hostile (ll, ml, off)
    tuples using the encoder's own FSE machinery, bypassing its
    legitimate-input invariants."""
    ln = len(literals)
    lhead = bytes([((ln & 0x0F) << 4) | 0x0C, (ln >> 4) & 0xFF, ln >> 12])
    nseq = len(seqs)
    shead = (bytes([nseq]) if nseq < 128
             else bytes([128 + (nseq >> 8), nseq & 0xFF])) + b"\x00"
    ll = zstd._FseEnc(zstd._LL_NORM, 6)
    of = zstd._FseEnc(zstd._OF_NORM, 5)
    ml = zstd._FseEnc(zstd._ML_NORM, 6)
    w = zstd._BitWriter()
    for i in range(nseq - 1, -1, -1):
        ll_len, m_len, offset = seqs[i]
        lc = zstd._ll_code(ll_len)
        oc = (offset + 3).bit_length() - 1
        mc = zstd._ml_code(m_len)
        if i == nseq - 1:
            ll.start(lc), of.start(oc), ml.start(mc)
        else:
            w.push(*of.prev(oc))
            w.push(*ml.prev(mc))
            w.push(*ll.prev(lc))
        w.push(ll_len - zstd._LL_BASE[lc], zstd._LL_BITS[lc])
        w.push(m_len - zstd._ML_BASE[mc], zstd._ML_BITS[mc])
        w.push((offset + 3) - (1 << oc), oc)
    w.push(ml.state, 6)
    w.push(of.state, 5)
    w.push(ll.state, 6)
    body = lhead + literals + shead + w.finish()
    bh = (len(body) << 3) | 0x04 | 1              # compressed, last
    return (struct.pack("<I", 0xFD2FB528) + b"\x00\x38"
            + struct.pack("<I", bh)[:3] + body)


def test_fallback_rejects_decompression_bomb(monkeypatch):
    """A crafted predefined-FSE frame regenerating ~128 KB per ~3
    input bytes must be rejected INSIDE the decode loop (block-maximum
    cap), not after gigabytes of output (review finding)."""
    import time as _time
    monkeypatch.setattr(zstd, "_lib", None)
    monkeypatch.setattr(zstd, "_loaded", True)
    bomb = _craft_sequence_block(
        [(1, 100_000, 1)] * 400, literals=b"A" * 400)
    t0 = _time.monotonic()
    with pytest.raises(ValueError, match="maximum"):
        zstd.decompress_frame(bomb)
    assert _time.monotonic() - t0 < 1.0           # rejected early
    # and the native decoder also bounds it
    if zstd.load_library("zstd") is not None:
        monkeypatch.setattr(zstd, "_loaded", False)
        monkeypatch.setattr(zstd, "_lib", None)
        with pytest.raises(ValueError):
            zstd.decompress_frame(bomb)


def test_sequence_dense_block_linear_time(monkeypatch):
    """A 128 KB block of repeated 4-byte words produces ~28k
    sequences; encode + toolchain-less decode must stay linear
    (the review found quadratic whole-int bitstream handling at
    ~0.4 s / ~1.4 s for this exact input)."""
    import time as _time
    data = (b"abcd" * 32768)[:131_072] + b"tail"
    t0 = _time.monotonic()
    frame = zstd.compress_frame(data)
    enc_s = _time.monotonic() - t0
    monkeypatch.setattr(zstd, "_lib", None)
    monkeypatch.setattr(zstd, "_loaded", True)
    t0 = _time.monotonic()
    assert zstd.decompress_frame(frame) == data
    dec_s = _time.monotonic() - t0
    assert enc_s + dec_s < 1.5, (enc_s, dec_s)


# ---- Huffman literal encoding (round-5 ratio work) -------------------------


def _first_block_literal_type(frame: bytes) -> int:
    """Literals-section type (0 raw / 1 RLE / 2 Huffman) of the first
    block of a single-segment frame our encoder produced."""
    fhd = frame[4]
    assert fhd & 0x20                       # single-segment, our shape
    off = 5 + (1 if fhd >> 6 == 0 else (2, 2, 4, 8)[fhd >> 6])
    bh = int.from_bytes(frame[off:off + 3], "little")
    assert (bh >> 1) & 3 == 2, "expected a compressed block"
    return frame[off + 3] & 3


def test_huffman_literals_tri_decoder():
    """Literal-heavy payload with no LZ77 matches: before round 5 this
    emitted a raw block (ratio 1.0); now the literals section itself
    Huffman-compresses, and all three decoders accept it."""
    random.seed(11)
    data = bytes(random.choice(b"abcdefgh") for _ in range(6000))
    frame = zstd.compress_frame(data)
    assert len(frame) < len(data) // 2      # ~3 bits/symbol vs 8
    assert _first_block_literal_type(frame) == 2
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_huffman_stream_layouts():
    """Every header layout the encoder can emit: 1-stream (<=1023
    literals), 4-stream 14-bit sizes, 4-stream 18-bit sizes."""
    random.seed(12)
    for size in (200, 1023, 1024, 8000, 16384, 120_000):
        data = bytes(random.choice(b"ACGTacgt-") for _ in range(size))
        frame = zstd.compress_frame(data)
        assert len(frame) < len(data)
        assert zstd._py_store_decompress(frame) == data, size
        if _syszstd() is not None:
            assert _ref_decompress(frame, len(data)) == data, size


def test_huffman_high_bytes_fall_back():
    """Bytes above 128 exceed the direct weight description's symbol
    range; the encoder must fall back (raw literals) rather than emit
    a tree it cannot describe."""
    random.seed(13)
    data = bytes(random.choice(b"\xf0\xf1\xf2\xf3") for _ in range(4096))
    frame = zstd.compress_frame(data)       # compresses via LZ77 only
    assert zstd._py_store_decompress(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_rle_literal_section():
    """A single repeated byte ships as an RLE literals section (2-18
    bytes total), not 500 raw literal bytes."""
    data = b"z" * 500
    frame = zstd.compress_frame(data)
    assert len(frame) < 32
    assert zstd._py_store_decompress(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_huffman_literals_with_sequences():
    """Huffman literals and predefined-FSE sequences in the same
    block: mixed compressible text with repeats."""
    random.seed(14)
    words = [bytes(random.choice(b"etaoin shrdlu") for _ in range(9))
             for _ in range(40)]
    data = b" ".join(random.choice(words) for _ in range(4000))
    frame = zstd.compress_frame(data)
    assert len(frame) < len(data) // 3
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


# ---- described FSE tables + FSE-compressed Huffman weights (round 5) -------


def test_described_sequence_tables_tri_decoder():
    """Blocks whose code statistics diverge from the predefined
    distributions ship fitted FSE-described tables; all three
    decoders must accept them and the result must be smaller than the
    predefined coding of the same sequences."""
    # many sequences with a very skewed (single-ish) shape
    data = (b"abcdefgh" * 3 + b"XY") * 3000
    frame = zstd.compress_frame(data)
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_fse_weight_description_lifts_high_byte_cap():
    """Literals with bytes above 128 (binary payloads) used to fall
    back to raw; the FSE-compressed weight description lets Huffman
    engage — ~2.5x on skewed high-byte data."""
    random.seed(31)
    data = bytes(random.choice(b"\xf0\xf1\xf2\xf3\xf4\xf5\xf6\xf7" * 3
                               + b"\xff") for _ in range(8000))
    frame = zstd.compress_frame(data)
    assert len(frame) < len(data) // 2
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_rle_sequence_table_mode():
    """A stream where every sequence shares one code (uniform offsets
    and lengths) uses the 1-byte RLE table mode."""
    data = b"0123456789abcdef" * 4000      # perfectly periodic
    frame = zstd.compress_frame(data)
    assert len(frame) < 64
    assert zstd._py_store_decompress(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_tri_decoder_fuzz_described_modes():
    """Alphabet shapes chosen to exercise every table mode and weight
    description across all three decoders."""
    if _syszstd() is None or not zstd.available():
        pytest.skip("system libzstd or toolchain unavailable")
    random.seed(8879)
    for trial in range(60):
        size = random.choice((31, 400, 1023, 1024, 5000, 70000))
        alpha = random.choice((2, 8, 129, 200, 256))
        base = 256 - alpha if alpha < 256 else 0
        d = bytes(base + random.randrange(alpha) for _ in range(size))
        f = zstd.compress_frame(d)
        assert _ref_decompress(f, len(d)) == d, (trial, size, alpha)
        assert zstd.decompress_frame(f) == d, (trial, size, alpha)
        assert zstd._py_store_decompress(f) == d, (trial, size, alpha)


def test_repeat_offsets_tri_decoder():
    """Templated records (same match stride, nonzero literal gaps) hit
    the repeat-offset codes; all three decoders agree and the frame
    beats the no-repeat encoding era (~3 KB for this corpus)."""
    data = b"".join(b'{"id":%04d,"status":"OK","fw":"2.1.9"}\n' % i
                    for i in range(4000))
    frame = zstd.compress_frame(data)
    assert len(frame) < 3000
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_fallback_decodes_foreign_frames_fully(monkeypatch):
    """The pure-Python fallback covers the full non-dictionary format:
    foreign libzstd frames at every level — multi-block with treeless
    literals, Repeat_Mode tables and cross-block window matches —
    decode without the native module."""
    if _syszstd() is None:
        pytest.skip("system libzstd unavailable")
    monkeypatch.setattr(zstd, "_lib", None)
    monkeypatch.setattr(zstd, "_loaded", True)
    random.seed(77)                     # reproducible corpora
    corpora = [
        random.randbytes(30_000).hex().encode(),
        b'{"a":%d,"b":"x"},' % 5 * 20000,         # ~320 KB, 3 blocks
        (b"the quick brown fox. " * 9000),
        random.randbytes(5000) + b"A" * 200_000 + random.randbytes(5000),
    ]
    for d in corpora:
        for level in (1, 6, 19):
            assert zstd.decompress_frame(_ref_compress(d, level)) == d


def test_cross_block_window_matches_on_encode():
    """The encoder's LZ77 table persists across a frame's blocks: a
    200 KB payload repeated immediately after itself compresses ~2:1
    (the second copy is one long window match), where the per-block
    era emitted it raw."""
    random.seed(99)
    unique = random.randbytes(200_000)
    data = unique + unique
    frame = zstd.compress_frame(data)
    assert len(frame) < len(data) * 0.55
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_repeat_mode_tables_emitted_and_accepted():
    """Multi-block frames with per-block-similar code statistics reuse
    the previous block's described tables via Repeat_Mode (zero
    description bytes); libzstd and both in-repo decoders accept."""
    random.seed(8)
    data = b"".join(
        b'{"k":"%s","n":%d}' % (
            bytes(random.choice(b"abcdefgh") for _ in range(6)),
            random.randrange(10 ** 6))
        for _ in range(14000))                    # ~348 KB, 3 blocks
    frame = zstd.compress_frame(data)
    assert zstd._py_store_decompress(frame) == data
    if zstd.available():
        assert zstd.decompress_frame(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_lz_window_history_survives_high_entropy_prefix():
    """The table cap exceeds the window's max distinct-4-gram count,
    so a duplicate of a large unique prefix WITHIN the window always
    matches — eviction never silently discards in-window history
    (review finding)."""
    random.seed(2)
    prefix = random.randbytes(400_000)
    data = prefix + prefix[:200_000]
    frame = zstd.compress_frame(data)
    assert len(frame) < len(data) * 0.75
    assert zstd._py_store_decompress(frame) == data
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(data)) == data


def test_treeless_literals_and_rle_blocks_emitted():
    """The last two encode-side constructs: a stable literal
    distribution across blocks ships later sections TREELESS (type 3,
    zero tree bytes), and an all-one-byte block ships as the RLE
    block type (4 bytes total).  All decoders accept."""
    random.seed(44)
    stable = bytes(random.choice(b"etaoinshrdlucmfwyp,. ")
                   for _ in range(400_000))
    frame = zstd.compress_frame(stable)
    # scan literal section types across blocks
    pos = 4
    fhd = frame[pos]
    pos += 1 + (1, 2, 4, 8)[fhd >> 6]
    ltypes = []
    while True:
        bh = int.from_bytes(frame[pos:pos + 3], "little")
        pos += 3
        last, btype, bsize = bh & 1, (bh >> 1) & 3, bh >> 3
        if btype == 2:
            ltypes.append(frame[pos] & 3)
        pos += bsize if btype != 1 else 1
        if last:
            break
    assert 3 in ltypes, ltypes              # treeless reuse happened
    assert zstd._py_store_decompress(frame) == stable
    if _syszstd() is not None:
        assert _ref_decompress(frame, len(stable)) == stable
    rle = b"\x07" * 300_000
    f2 = zstd.compress_frame(rle)
    assert len(f2) < 32                     # RLE block type, not huffman
    assert zstd._py_store_decompress(f2) == rle
    if _syszstd() is not None:
        assert _ref_decompress(f2, len(rle)) == rle
