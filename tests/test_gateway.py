"""Gateway integration: real STOMP-over-TCP and MQTT-SN-over-UDP clients
against a live node (the emqx CT style — no protocol mocks), proving
gateway sessions ride the normal broker (routing, retained, MQTT
interop, auth)."""

import asyncio
import json
import socket
import struct

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.gateway.stomp import StompFrame, parse_frames, serialize_frame
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(extra_cfg: str = "", **node_kw):
    cfg = Config(
        file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n'
                  'gateway.stomp.enable = true\n'
                  'gateway.stomp.bind = "127.0.0.1:0"\n'
                  'gateway.mqttsn.enable = true\n'
                  'gateway.mqttsn.bind = "127.0.0.1:0"\n' + extra_cfg
    )
    node = BrokerNode(cfg, **node_kw)
    await node.start()
    return node


def mqtt_port(node):
    return node.listeners.all()[0].port


class StompClient:
    """Minimal test STOMP client over asyncio streams."""

    def __init__(self):
        self.buf = bytearray()

    async def connect(self, port, headers=None):
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port)
        await self.send("CONNECT", {"accept-version": "1.2",
                                    **(headers or {})})
        f = await self.recv()
        return f

    async def send(self, command, headers, body=b""):
        self.writer.write(serialize_frame(StompFrame(command, headers, body)))
        await self.writer.drain()

    async def recv(self, timeout=5.0):
        while True:
            for f in parse_frames(self.buf):
                return f
            data = await asyncio.wait_for(self.reader.read(65536), timeout)
            if not data:
                raise ConnectionError("closed")
            self.buf.extend(data)

    async def close(self):
        self.writer.close()


def test_stomp_connect_sub_send_roundtrip():
    async def main():
        node = await start_node()
        try:
            port = node.gateways.gateways["stomp"].port
            c = StompClient()
            f = await c.connect(port)
            assert f.command == "CONNECTED"
            assert f.headers["version"] == "1.2"

            await c.send("SUBSCRIBE", {"id": "0", "destination": "car/+/speed",
                                       "receipt": "r1"})
            r = await c.recv()
            assert (r.command, r.headers["receipt-id"]) == ("RECEIPT", "r1")

            await c.send("SEND", {"destination": "car/42/speed"}, b"88")
            m = await c.recv()
            assert m.command == "MESSAGE"
            assert m.headers["destination"] == "car/42/speed"
            assert m.headers["subscription"] == "0"
            assert m.body == b"88"
            await c.close()
        finally:
            await node.stop()

    run(main())


def test_stomp_mqtt_interop_and_retained():
    """MQTT publishes reach STOMP subscribers and vice versa; a STOMP
    subscriber receives retained replay through the normal broker."""
    async def main():
        node = await start_node()
        try:
            sport = node.gateways.gateways["stomp"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.publish("news/hot", b"retained!", retain=True)
            await mq.subscribe("from_stomp/#")

            c = StompClient()
            await c.connect(sport)
            await c.send("SUBSCRIBE", {"id": "7", "destination": "news/#"})
            m = await c.recv()
            assert (m.body, m.headers["destination"]) == (
                b"retained!", "news/hot")

            await c.send("SEND", {"destination": "from_stomp/x"}, b"hi mqtt")
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("from_stomp/x", b"hi mqtt")
            await c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_stomp_client_ack_qos1_flow():
    async def main():
        node = await start_node()
        try:
            sport = node.gateways.gateways["stomp"].port
            c = StompClient()
            await c.connect(sport)
            await c.send("SUBSCRIBE", {"id": "1", "destination": "q/1",
                                       "ack": "client-individual"})
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.publish("q/1", b"needs-ack", qos=1)
            m = await c.recv()
            assert m.headers.get("ack")  # ack-able delivery
            sess = node.broker.sessions[
                node.gateways.gateways["stomp"].clients and
                list(node.gateways.gateways["stomp"].clients.values())[0]
                .clientid]
            assert len(sess.inflight) == 1  # unacked
            await c.send("ACK", {"id": m.headers["ack"]})
            for _ in range(50):
                if len(sess.inflight) == 0:
                    break
                await asyncio.sleep(0.01)
            assert len(sess.inflight) == 0
            await c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# MQTT-SN over UDP
# ---------------------------------------------------------------------------

class SnClient:
    """Minimal MQTT-SN test client over a UDP socket."""

    def __init__(self, port):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5.0)
        self.addr = ("127.0.0.1", port)

    def send(self, msgtype, body=b""):
        n = len(body) + 2
        self.sock.sendto(bytes([n, msgtype]) + body, self.addr)

    def recv(self):
        data, _ = self.sock.recvfrom(2048)
        return data[1], data[2:data[0]]

    def connect(self, clientid, keepalive=60, clean=True):
        flags = 0x04 if clean else 0
        self.send(0x04, bytes([flags, 0x01])
                  + struct.pack(">H", keepalive) + clientid.encode())
        t, body = self.recv()
        assert t == 0x05 and body[0] == 0, (t, body)

    def close(self):
        self.sock.close()


def test_mqttsn_connect_register_publish_subscribe():
    async def main():
        node = await start_node()
        try:
            port = node.gateways.gateways["mqttsn"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("sn/up")

            def sn_flow():
                sn = SnClient(port)
                sn.connect("sn-dev-1")
                # REGISTER sn/up -> tid
                sn.send(0x0A, struct.pack(">HH", 0, 1) + b"sn/up")
                t, body = sn.recv()
                assert t == 0x0B and body[4] == 0
                tid = struct.unpack(">H", body[0:2])[0]
                # PUBLISH qos0 via registered tid
                sn.send(0x0C, bytes([0x00]) + struct.pack(">H", tid)
                        + struct.pack(">H", 0) + b"from-sn")
                # SUBSCRIBE to a concrete name -> SUBACK carries its tid
                sn.send(0x12, bytes([0x00]) + struct.pack(">H", 2)
                        + b"sn/down")
                t, body = sn.recv()
                assert t == 0x13 and body[-1] == 0
                down_tid = struct.unpack(">H", body[1:3])[0]
                assert down_tid != 0
                # SUBSCRIBE to a wildcard -> tid 0 (deliveries REGISTER)
                sn.send(0x12, bytes([0x00]) + struct.pack(">H", 3)
                        + b"snw/#")
                t, body = sn.recv()
                assert t == 0x13 and body[-1] == 0
                assert struct.unpack(">H", body[1:3])[0] == 0
                return sn, down_tid

            sn, down_tid = await asyncio.to_thread(sn_flow)
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("sn/up", b"from-sn")

            # concrete-name sub: delivery rides the SUBACK-assigned tid
            await mq.publish("sn/down", b"to-sn")

            def sn_recv_direct():
                t, body = sn.recv()
                assert t == 0x0C, (t, body)
                assert struct.unpack(">H", body[1:3])[0] == down_tid
                return body[5:]

            assert await asyncio.to_thread(sn_recv_direct) == b"to-sn"

            # wildcard sub: unknown topic => gateway REGISTERs first and
            # holds the delivery until REGACK
            await mq.publish("snw/t1", b"via-reg")

            def sn_recv_registered():
                t, body = sn.recv()
                assert t == 0x0A, (t, body)  # REGISTER from gateway
                tid = struct.unpack(">H", body[0:2])[0]
                mid = struct.unpack(">H", body[2:4])[0]
                assert body[4:] == b"snw/t1"
                sn.send(0x0B, struct.pack(">HH", tid, mid) + b"\x00")
                t, body = sn.recv()
                assert t == 0x0C
                assert struct.unpack(">H", body[1:3])[0] == tid
                return body[5:]

            assert await asyncio.to_thread(sn_recv_registered) == b"via-reg"
            sn.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_mqttsn_short_topic_and_ping():
    async def main():
        node = await start_node()
        try:
            port = node.gateways.gateways["mqttsn"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("ab")

            def flow():
                sn = SnClient(port)
                sn.connect("sn-short")
                # short topic 'ab', qos0
                sn.send(0x0C, bytes([0x02]) + b"ab"
                        + struct.pack(">H", 0) + b"short!")
                sn.send(0x16)  # PINGREQ
                t, _ = sn.recv()
                assert t == 0x17  # PINGRESP
                sn.send(0x18)  # DISCONNECT
                t, _ = sn.recv()
                assert t == 0x18
                sn.close()

            await asyncio.to_thread(flow)
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("ab", b"short!")
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_gateway_rest_listing():
    async def main():
        import json

        from emqx_tpu.bridge import httpc

        node = await start_node('dashboard.enable = true\n'
                                'dashboard.auth = false\n'
                                'dashboard.listen = "127.0.0.1:0"\n')
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("GET", f"{base}/gateways")
            names = {g["name"] for g in json.loads(r.body)}
            assert names == {"stomp", "mqttsn"}
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# CoAP over UDP
# ---------------------------------------------------------------------------

class CoapTestClient:
    def __init__(self, port):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5.0)
        self.addr = ("127.0.0.1", port)
        self.mid = 0

    def request(self, code, path, query=(), payload=b"", observe=None,
                token=b"\x01", con=True):
        from emqx_tpu.gateway import coap as C

        self.mid += 1
        opts = []
        if observe is not None:
            opts.append((C.OPT_OBSERVE,
                         observe.to_bytes(1, "big") if observe else b""))
        for seg in path.split("/"):
            opts.append((C.OPT_URI_PATH, seg.encode()))
        for q in query:
            opts.append((C.OPT_URI_QUERY, q.encode()))
        msg = C.CoapMessage(C.CON if con else C.NON, code, self.mid,
                            token, opts, payload)
        self.send_raw(C.encode(msg))

    def send_raw(self, data):
        self.sock.sendto(data, self.addr)

    def recv_raw(self):
        data, _ = self.sock.recvfrom(2048)
        return data

    def recv(self):
        from emqx_tpu.gateway import coap as C

        return C.decode(self.recv_raw())

    def close(self):
        self.sock.close()


class DtlsCoapTestClient(CoapTestClient):
    """CoAP test client tunneled through a DTLS 1.2 PSK session."""

    def __init__(self, port, identity, key):
        super().__init__(port)
        from emqx_tpu.transport.dtls import DtlsConnection

        self.conn = DtlsConnection("client", psk_identity=identity, psk=key)
        self._flush()
        while not self.conn.complete:
            data, _ = self.sock.recvfrom(4096)
            self.conn.receive(data)
            self._flush()

    def _flush(self):
        for dg in self.conn.take_outgoing():
            self.sock.sendto(dg, self.addr)

    def send_raw(self, data):
        self.conn.send(data)
        self._flush()

    def recv_raw(self):
        while True:
            data, _ = self.sock.recvfrom(4096)
            plains = self.conn.receive(data)
            self._flush()
            if plains:
                return plains[0]


def coap_node_cfg():
    return ('gateway.coap.enable = true\n'
            'gateway.coap.bind = "127.0.0.1:0"\n')


def test_coap_publish_observe_and_retained():
    async def main():
        from emqx_tpu.gateway import coap as C

        node = await start_node(coap_node_cfg())
        try:
            cport = node.gateways.gateways["coap"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("sensors/#")

            c = CoapTestClient(cport)
            # publish via PUT -> 2.04, reaches MQTT subscriber
            def put_flow():
                c.request(C.PUT, "ps/sensors/t1", ("c=coap1",), b"23.5")
                r = c.recv()
                assert r.code == C.CHANGED and r.type == C.ACK
            await asyncio.to_thread(put_flow)
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("sensors/t1", b"23.5")

            # observe (subscribe): MQTT publish pushes a notification
            def obs_flow():
                c.request(C.GET, "ps/alerts/a", ("c=coap1",), observe=0,
                          token=b"\x77")
                r = c.recv()
                assert r.code == C.CONTENT
            await asyncio.to_thread(obs_flow)
            await mq.publish("alerts/a", b"fire!")

            def notif_flow():
                n = c.recv()
                assert n.code == C.CONTENT and n.token == b"\x77"
                assert n.payload == b"fire!"
                obs = n.opt(C.OPT_OBSERVE)
                assert obs is not None
            await asyncio.to_thread(notif_flow)

            # retained read via plain GET (qos1 so the store is settled)
            await mq.publish("cfg/v", b"42", retain=True, qos=1)
            for _ in range(100):
                if node.retainer.match("cfg/v"):
                    break
                await asyncio.sleep(0.01)
            def get_flow():
                c.request(C.GET, "ps/cfg/v", ("c=coap1",))
                r = c.recv()
                assert r.code == C.CONTENT and r.payload == b"42"
                c.request(C.GET, "ps/cfg/missing", ("c=coap1",))
                assert c.recv().code == C.NOT_FOUND
            await asyncio.to_thread(get_flow)

            # unobserve stops notifications
            def unobs_flow():
                c.request(C.GET, "ps/alerts/a", ("c=coap1",), observe=1)
                assert c.recv().code == C.CONTENT
            await asyncio.to_thread(unobs_flow)
            await mq.publish("alerts/a", b"again")
            def silent_flow():
                c.sock.settimeout(0.4)
                try:
                    c.recv()
                    return False
                except socket.timeout:
                    return True
            assert await asyncio.to_thread(silent_flow)
            c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_coap_codec_roundtrip():
    from emqx_tpu.gateway import coap as C

    msg = C.CoapMessage(C.CON, C.PUT, 4242, b"\xab\xcd", [
        (C.OPT_OBSERVE, b"\x00"),
        (C.OPT_URI_PATH, b"ps"),
        (C.OPT_URI_PATH, b"some-long-topic-segment-exceeding-12-bytes"),
        (C.OPT_CONTENT_FORMAT, b"\x00"),
        (C.OPT_URI_QUERY, b"c=client1"),
    ], b"payload")
    out = C.decode(C.encode(msg))
    assert out is not None
    assert (out.type, out.code, out.mid, out.token) == (
        C.CON, C.PUT, 4242, b"\xab\xcd")
    assert out.opt_all(C.OPT_URI_PATH) == [
        b"ps", b"some-long-topic-segment-exceeding-12-bytes"]
    assert out.opt_all(C.OPT_URI_QUERY) == [b"c=client1"]
    assert out.payload == b"payload"
    # malformed inputs don't crash
    assert C.decode(b"") is None
    assert C.decode(b"\x00\x00\x00") is None
    assert C.decode(b"\xff\xff\xff\xff\xff") is None


# ---------------------------------------------------------------------------
# LwM2M over UDP (register + device management ops)
# ---------------------------------------------------------------------------

class FakeLwm2mDevice:
    """A device: registers, answers Read/Write, emits Observe notifies."""

    def __init__(self, port):
        from emqx_tpu.gateway import coap as C

        self.C = C
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5.0)
        self.addr = ("127.0.0.1", port)
        self.resources = {"/3/0/0": "emqx-tpu-dev"}
        self.location = None
        self.observe_tokens = {}

    def register(self, ep, lifetime=120):
        C = self.C
        opts = [(C.OPT_URI_PATH, b"rd"),
                (C.OPT_URI_QUERY, f"ep={ep}".encode()),
                (C.OPT_URI_QUERY, f"lt={lifetime}".encode())]
        msg = C.CoapMessage(C.CON, C.POST, 77, b"\x09", opts,
                            b"</3/0>,</4/0>")
        self.sock.sendto(C.encode(msg), self.addr)
        r = self.recv()
        assert r.code == C.code(2, 1), r.code
        segs = r.opt_all(8)  # Location-Path (RFC 7252 option 8)
        assert segs[0] == b"rd"
        self.location = segs[1].decode()

    def recv(self):
        data, _ = self.sock.recvfrom(2048)
        return self.C.decode(data)

    def serve_one(self):
        """Answer ONE incoming management request."""
        C = self.C
        req = self.recv()
        path = "/" + "/".join(v.decode() for v in req.opt_all(C.OPT_URI_PATH))
        obs = req.opt(C.OPT_OBSERVE)
        if req.code == C.GET and obs is not None and obs == b"":
            self.observe_tokens[path] = req.token
            val = self.resources.get(path, "")
            resp = C.CoapMessage(C.ACK, C.CONTENT, req.mid, req.token,
                                 [(C.OPT_OBSERVE, b"\x01")], val.encode())
        elif req.code == C.GET:
            val = self.resources.get(path)
            if val is None:
                resp = C.CoapMessage(C.ACK, C.NOT_FOUND, req.mid, req.token)
            else:
                resp = C.CoapMessage(C.ACK, C.CONTENT, req.mid, req.token,
                                     [], val.encode())
        elif req.code == C.PUT:
            self.resources[path] = req.payload.decode()
            resp = C.CoapMessage(C.ACK, C.code(2, 4), req.mid, req.token)
        else:
            resp = C.CoapMessage(C.ACK, C.code(4, 5), req.mid, req.token)
        self.sock.sendto(C.encode(resp), self.addr)

    def notify(self, path, value, seq=5):
        C = self.C
        tok = self.observe_tokens[path]
        self.sock.sendto(C.encode(C.CoapMessage(
            C.NON, C.CONTENT, 99, tok,
            [(C.OPT_OBSERVE, bytes([seq]))], value.encode())), self.addr)

    def close(self):
        self.sock.close()


def test_lwm2m_register_read_write_observe():
    async def main():
        node = await start_node('gateway.lwm2m.enable = true\n'
                                'gateway.lwm2m.bind = "127.0.0.1:0"\n')
        try:
            lport = node.gateways.gateways["lwm2m"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("lwm2m/dev7/up/#")

            dev = FakeLwm2mDevice(lport)
            await asyncio.to_thread(dev.register, "dev7")

            reg = await mq.recv(timeout=5)
            assert reg.topic == "lwm2m/dev7/up/register"
            doc = json.loads(reg.payload)
            assert doc["op"] == "register" and "</3/0>" in \
                ",".join(doc["objects"]) or doc["objects"]

            # downlink READ -> device answers -> uplink resp
            await mq.publish("lwm2m/dev7/dn/cmd", json.dumps({
                "reqid": "r1", "op": "read", "path": "/3/0/0"}).encode())
            await asyncio.to_thread(dev.serve_one)
            resp = await mq.recv(timeout=5)
            assert resp.topic == "lwm2m/dev7/up/resp"
            rdoc = json.loads(resp.payload)
            assert (rdoc["reqid"], rdoc["code"], rdoc["value"]) == \
                ("r1", "2.05", "emqx-tpu-dev")

            # downlink WRITE
            await mq.publish("lwm2m/dev7/dn/cmd", json.dumps({
                "reqid": "r2", "op": "write", "path": "/3/0/14",
                "value": "+02:00"}).encode())
            await asyncio.to_thread(dev.serve_one)
            rdoc = json.loads((await mq.recv(timeout=5)).payload)
            assert (rdoc["reqid"], rdoc["code"]) == ("r2", "2.04")
            assert dev.resources["/3/0/14"] == "+02:00"

            # OBSERVE + device notification
            await mq.publish("lwm2m/dev7/dn/cmd", json.dumps({
                "reqid": "r3", "op": "observe", "path": "/3/0/0"}).encode())
            await asyncio.to_thread(dev.serve_one)
            rdoc = json.loads((await mq.recv(timeout=5)).payload)
            assert rdoc["reqid"] == "r3" and rdoc["code"] == "2.05"
            await asyncio.to_thread(dev.notify, "/3/0/0", "changed!")
            note = await mq.recv(timeout=5)
            assert note.topic == "lwm2m/dev7/up/notify"
            ndoc = json.loads(note.payload)
            assert ndoc["value"] == "changed!" and ndoc["path"] == "/3/0/0"

            # deregister
            def dereg():
                C = dev.C
                msg = C.CoapMessage(C.CON, C.DELETE, 88, b"\x0a",
                                    [(C.OPT_URI_PATH, b"rd"),
                                     (C.OPT_URI_PATH,
                                      dev.location.encode())])
                dev.sock.sendto(C.encode(msg), dev.addr)
                assert dev.recv().code == C.DELETED
            await asyncio.to_thread(dereg)
            rdoc = json.loads((await mq.recv(timeout=5)).payload)
            assert rdoc["op"] == "deregister"
            assert "dev7" not in node.gateways.gateways["lwm2m"].by_ep
            dev.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_gateway_qos1_retry_redelivers_unacked():
    """An unacked client-ack STOMP delivery is re-sent by the gateway
    retry loop (gateway sessions have no MQTT channel timer)."""
    async def main():
        node = await start_node()
        try:
            gwm = node.gateways
            gwm.RETRY_INTERVAL = 0.2
            # restart the retry loop at test cadence
            if gwm._retry_task is not None:
                gwm._retry_task.cancel()
                gwm._retry_task = asyncio.ensure_future(gwm._retry_loop())
            sport = gwm.gateways["stomp"].port
            c = StompClient()
            await c.connect(sport)
            await c.send("SUBSCRIBE", {"id": "1", "destination": "rt/1",
                                       "ack": "client"})
            sess_cid = list(gwm.gateways["stomp"].clients.values())[0] \
                .clientid
            sess = node.broker.sessions[sess_cid]
            sess.retry_interval = 0.2

            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.publish("rt/1", b"persist-me", qos=1)

            m1 = await c.recv()
            assert m1.body == b"persist-me"
            # do NOT ack: the retry loop must re-send it
            m2 = await c.recv(timeout=5)
            assert m2.body == b"persist-me"
            assert m2.headers["ack"] != m1.headers["ack"]
            # ack the redelivery clears the inflight window
            await c.send("ACK", {"id": m2.headers["ack"]})
            for _ in range(100):
                if len(sess.inflight) == 0:
                    break
                await asyncio.sleep(0.02)
            assert len(sess.inflight) == 0
            await c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_stomp_transactions_commit_and_abort():
    async def main():
        node = await start_node()
        try:
            sport = node.gateways.gateways["stomp"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("txt/#")

            c = StompClient()
            await c.connect(sport)
            await c.send("BEGIN", {"transaction": "t1", "receipt": "b1"})
            assert (await c.recv()).headers["receipt-id"] == "b1"
            await c.send("SEND", {"destination": "txt/a",
                                  "transaction": "t1"}, b"one")
            await c.send("SEND", {"destination": "txt/b",
                                  "transaction": "t1"}, b"two")
            # nothing delivered before COMMIT
            with pytest.raises(asyncio.TimeoutError):
                await mq.recv(timeout=0.3)
            await c.send("COMMIT", {"transaction": "t1", "receipt": "c1"})
            got = {(await mq.recv(timeout=5)).payload for _ in range(2)}
            assert got == {b"one", b"two"}

            # aborted tx delivers nothing
            await c.send("BEGIN", {"transaction": "t2"})
            await c.send("SEND", {"destination": "txt/c",
                                  "transaction": "t2"}, b"nope")
            await c.send("ABORT", {"transaction": "t2"})
            with pytest.raises(asyncio.TimeoutError):
                await mq.recv(timeout=0.3)

            # unknown tx errors
            await c.send("SEND", {"destination": "txt/d",
                                  "transaction": "ghost"}, b"x")
            # drain frames until the ERROR arrives (receipts may precede)
            for _ in range(5):
                fr = await c.recv()
                if fr.command == "ERROR":
                    break
            assert fr.command == "ERROR"
            await c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_mqttsn_qos_minus1_connectionless_publish():
    async def main():
        node = await start_node(
            'gateway.mqttsn.enable = true\n')  # predefined via manager conf
        try:
            gw = node.gateways.gateways["mqttsn"]
            gw.predefined[7] = "sn/minus1"
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("sn/minus1")

            def fire():
                import struct as _s
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                # PUBLISH, flags qos=0b11 + predefined, tid=7, mid=0
                body = bytes([0x61]) + _s.pack(">H", 7) + _s.pack(">H", 0) \
                    + b"fire-and-forget"
                s.sendto(bytes([len(body) + 2, 0x0C]) + body,
                         ("127.0.0.1", gw.port))
                s.close()

            await asyncio.to_thread(fire)
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("sn/minus1",
                                                b"fire-and-forget")
            # no session/connection was created for the anonymous peer
            assert not any(cid.startswith("sn-anon")
                           for cid in node.broker.sessions)
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_mqttsn_sleeping_client_buffers_and_flushes():
    """DISCONNECT(duration) -> ASLEEP: deliveries buffer; PINGREQ
    flushes them; CONNECT wakes (MQTT-SN §6.14)."""
    async def main():
        node = await start_node()
        try:
            port = node.gateways.gateways["mqttsn"].port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()

            def setup():
                sn = SnClient(port)
                sn.connect("sleepy", clean=True)
                sn.send(0x12, bytes([0x00]) + struct.pack(">H", 2)
                        + b"zzz/t")
                t, body = sn.recv()
                assert t == 0x13 and body[-1] == 0
                # DISCONNECT with duration -> ASLEEP ack'd by DISCONNECT
                sn.send(0x18, struct.pack(">H", 60))
                t, _ = sn.recv()
                assert t == 0x18
                return sn

            sn = await asyncio.to_thread(setup)
            # published while asleep: buffered, not lost, not delivered
            await mq.publish("zzz/t", b"while-asleep", qos=1)
            await asyncio.sleep(0.1)

            def wake_and_collect():
                sn.send(0x16, b"sleepy")  # PINGREQ with clientid
                frames = []
                for _ in range(3):
                    t, body = sn.recv()
                    frames.append((t, body))
                    if t == 0x17:   # PINGRESP ends the listen window
                        break
                return frames

            frames = await asyncio.to_thread(wake_and_collect)
            types = [t for t, _ in frames]
            assert 0x17 in types
            pubs = [b for t, b in frames if t == 0x0C]
            regs = [b for t, b in frames if t == 0x0A]
            # the topic was registered pre-sleep (concrete sub) so the
            # buffered message arrives as a direct PUBLISH
            assert pubs and pubs[0][5:] == b"while-asleep", (pubs, regs)
            sn.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_mqttsn_will_fires_on_keepalive_loss_not_clean_disconnect():
    async def main():
        node = await start_node()
        try:
            gw = node.gateways.gateways["mqttsn"]
            port = gw.port
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("wills/#")

            def connect_with_will(cid, keepalive):
                sn = SnClient(port)
                flags = 0x04 | 0x08  # clean + will
                sn.send(0x04, bytes([flags, 0x01])
                        + struct.pack(">H", keepalive) + cid.encode())
                t, _ = sn.recv()
                assert t == 0x06  # WILLTOPICREQ
                sn.send(0x07, bytes([0x00]) + f"wills/{cid}".encode())
                t, _ = sn.recv()
                assert t == 0x08  # WILLMSGREQ
                sn.send(0x09, b"gone!")
                t, body = sn.recv()
                assert t == 0x05 and body[0] == 0  # CONNACK
                return sn

            # clean disconnect: will must NOT fire
            sn1 = await asyncio.to_thread(connect_with_will, "w1", 60)
            def clean_dc():
                sn1.send(0x18)
                assert sn1.recv()[0] == 0x18
            await asyncio.to_thread(clean_dc)
            with pytest.raises(asyncio.TimeoutError):
                await mq.recv(timeout=0.3)
            sn1.close()

            # keepalive loss: will fires
            sn2 = await asyncio.to_thread(connect_with_will, "w2", 1)
            client = next(c for c in gw.by_addr.values()
                          if c.clientid == "w2")
            client.last_seen -= 10  # simulate silence past 1.5x keepalive
            got = await mq.recv(timeout=10)
            assert (got.topic, got.payload) == ("wills/w2", b"gone!")
            sn2.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_gateway_runtime_load_unload_via_rest():
    async def main():
        from emqx_tpu.bridge import httpc

        node = await start_node(
            'dashboard.enable = true\n'
            'dashboard.auth = false\n'
            'dashboard.listen = "127.0.0.1:0"\n'
            'gateway.coap.bind = "127.0.0.1:0"\n')
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            assert "coap" not in node.gateways.gateways
            r = await httpc.request(
                "PUT", f"{base}/gateways/coap/enable/true", body=b"")
            assert r.status == 201
            assert "coap" in node.gateways.gateways
            r = await httpc.request(
                "PUT", f"{base}/gateways/coap/enable/false", body=b"")
            assert r.status == 204
            assert "coap" not in node.gateways.gateways
            r = await httpc.request(
                "PUT", f"{base}/gateways/nope/enable/true", body=b"")
            assert r.status == 400  # unknown gateway kind -> ValueError
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# codec round-trip fuzz (property-style, seeded)
# ---------------------------------------------------------------------------

def test_stomp_frame_codec_fuzz_roundtrip():
    import random as _r

    rng = _r.Random(99)
    specials = ["plain", "with:colon", "with\nnewline", "with\\back",
                "with\rcr", "", "unicode-é中"]
    for _ in range(200):
        cmd = rng.choice(["SEND", "MESSAGE", "SUBSCRIBE", "RECEIPT"])
        headers = {}
        for _ in range(rng.randint(0, 5)):
            headers.setdefault(rng.choice(specials) or "k",
                               rng.choice(specials))
        body = bytes(rng.randrange(256) for _ in range(rng.randint(0, 64)))
        buf = bytearray(serialize_frame(StompFrame(cmd, headers, body)))
        out = next(parse_frames(buf))
        assert out.command == cmd
        assert out.body == body
        for k, v in headers.items():
            assert out.headers[k] == v
    # incremental parse across arbitrary chunk boundaries
    frames = [StompFrame("SEND", {"destination": f"d/{i}"},
                         f"b{i}".encode()) for i in range(10)]
    stream = b"".join(serialize_frame(f) for f in frames)
    buf = bytearray()
    got = []
    for i in range(0, len(stream), 7):
        buf.extend(stream[i:i + 7])
        got.extend(parse_frames(buf))
    assert [f.body for f in got] == [f.body for f in frames]


def test_coap_codec_fuzz_roundtrip_and_garbage():
    import random as _r

    from emqx_tpu.gateway import coap as Cc

    rng = _r.Random(7)
    for _ in range(200):
        opts = []
        nums = sorted(rng.sample([1, 3, 6, 8, 11, 12, 15, 17, 35, 300,
                                  2000], rng.randint(0, 5)))
        for n in nums:
            opts.append((n, bytes(rng.randrange(256)
                                  for _ in range(rng.randint(0, 20)))))
        msg = Cc.CoapMessage(
            rng.randrange(4), rng.randrange(1, 256), rng.randrange(65536),
            bytes(rng.randrange(256) for _ in range(rng.randint(0, 8))),
            opts, bytes(rng.randrange(256)
                        for _ in range(rng.randint(0, 32))))
        out = Cc.decode(Cc.encode(msg))
        assert out is not None
        assert (out.type, out.code, out.mid, out.token) == \
            (msg.type, msg.code, msg.mid, msg.token)
        assert sorted(out.options) == sorted(msg.options)
        assert out.payload == msg.payload
    # random garbage never crashes the decoder
    for _ in range(500):
        blob = bytes(rng.randrange(256) for _ in range(rng.randint(0, 40)))
        Cc.decode(blob)  # may return None or a message; must not raise


def test_mqttsn_unpack_garbage_never_crashes():
    import random as _r

    from emqx_tpu.gateway.mqttsn import _pack, _unpack

    rng = _r.Random(3)
    for _ in range(500):
        blob = bytes(rng.randrange(256) for _ in range(rng.randint(0, 40)))
        _unpack(blob)  # None or (type, body); must not raise
    for _ in range(100):
        t = rng.randrange(256)
        body = bytes(rng.randrange(256) for _ in range(rng.randint(0, 300)))
        out = _unpack(_pack(t, body))
        assert out == (t, body)


def test_lwm2m_bootstrap_interface():
    """LwM2M 1.0 §5.2 bootstrap: POST /bs?ep= -> 2.04, then the server
    pushes the configured Writes and Bootstrap-Finish."""
    async def main():
        node = await start_node('gateway.lwm2m.enable = true\n'
                                'gateway.lwm2m.bind = "127.0.0.1:0"\n')
        try:
            gw = node.gateways.gateways["lwm2m"]
            gw.conf["bootstrap"] = {"writes": [
                {"path": "/0/0/0", "value": "coap://srv:5783"},
                {"path": "/1/0/1", "value": "300"},
            ]}
            lport = gw.port
            mq = Client(clientid="mb", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("lwm2m/bdev/up/#")

            dev = FakeLwm2mDevice(lport)

            def run_bootstrap():
                C = dev.C
                dev.sock.sendto(C.encode(C.CoapMessage(
                    C.CON, C.POST, 901, b"\x0b",
                    [(C.OPT_URI_PATH, b"bs"),
                     (C.OPT_URI_QUERY, b"ep=bdev")])), dev.addr)
                ack = dev.recv()
                assert ack.code == C.code(2, 4), ack.code
                finish = False
                for _ in range(3):        # 2 writes + finish
                    req = dev.recv()
                    path = "/" + "/".join(
                        v.decode() for v in req.opt_all(C.OPT_URI_PATH))
                    if req.code == C.PUT:
                        dev.resources[path] = req.payload.decode()
                    elif req.code == C.POST and path == "/bs":
                        finish = True
                    dev.sock.sendto(C.encode(C.CoapMessage(
                        C.ACK, C.code(2, 4), req.mid, req.token)),
                        dev.addr)
                return finish

            finish = await asyncio.to_thread(run_bootstrap)
            assert finish, "no Bootstrap-Finish"
            assert dev.resources["/0/0/0"] == "coap://srv:5783"
            assert dev.resources["/1/0/1"] == "300"

            ev = await mq.recv(timeout=5)
            assert ev.topic == "lwm2m/bdev/up/bootstrap"
            assert json.loads(ev.payload)["writes"] == 2

            # bad endpoint names are rejected
            def bad_ep():
                C = dev.C
                dev.sock.sendto(C.encode(C.CoapMessage(
                    C.CON, C.POST, 902, b"\x0c",
                    [(C.OPT_URI_PATH, b"bs"),
                     (C.OPT_URI_QUERY, b"ep=a/b")])), dev.addr)
                return dev.recv().code

            assert await asyncio.to_thread(bad_ep) == dev.C.BAD_REQUEST
            dev.close()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# CoAP over DTLS 1.2 PSK
# ---------------------------------------------------------------------------

DTLS_KEY = "6d792073686172656420736563726574"   # "my shared secret"


def dtls_coap_cfg():
    return ('gateway.coap.enable = true\n'
            'gateway.coap.bind = "127.0.0.1:0"\n'
            'gateway.coap.dtls.enable = true\n'
            f'gateway.coap.dtls.psk = "dev1:{DTLS_KEY}"\n')


def test_coap_gateway_over_dtls_psk():
    pytest.importorskip("cryptography")  # DTLS PSK transport needs it
    """VERDICT r4 item 7: full CoAP pub/sub round-trip through the DTLS
    1.2 PSK transport — publish encrypted, MQTT subscriber receives,
    observe notification comes back encrypted."""

    async def main():
        from emqx_tpu.gateway import coap as C

        node = await start_node(dtls_coap_cfg())
        try:
            gw = node.gateways.gateways["coap"]
            assert gw.dtls is not None
            assert gw.info()["transport"] == "udp+dtls"
            mq = Client(clientid="m1", port=mqtt_port(node))
            await mq.connect()
            await mq.subscribe("sensors/#")

            c = await asyncio.to_thread(
                DtlsCoapTestClient, gw.port, "dev1",
                bytes.fromhex(DTLS_KEY))
            assert gw.dtls.handshakes == 1

            def put_flow():
                c.request(C.PUT, "ps/sensors/t9", ("c=dev1",), b"42.0")
                r = c.recv()
                assert r.code == C.CHANGED and r.type == C.ACK
            await asyncio.to_thread(put_flow)
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("sensors/t9", b"42.0")

            # observe over DTLS: server-initiated notify is encrypted too
            def obs_flow():
                c.request(C.GET, "ps/alerts/d", ("c=dev1",), observe=0,
                          token=b"\x55")
                r = c.recv()
                assert r.code == C.CONTENT
            await asyncio.to_thread(obs_flow)
            await mq.publish("alerts/d", b"dtls-notify")

            def notif_flow():
                n = c.recv()
                assert n.token == b"\x55" and n.payload == b"dtls-notify"
            await asyncio.to_thread(notif_flow)
            c.close()
            await mq.disconnect()
        finally:
            await node.stop()

    run(main())


def test_dtls_gateway_rejects_unknown_identity():
    pytest.importorskip("cryptography")  # DTLS PSK transport needs it
    async def main():
        node = await start_node(dtls_coap_cfg())
        try:
            gw = node.gateways.gateways["coap"]

            def bad_handshake():
                with pytest.raises(socket.timeout):
                    c = CoapTestClient(gw.port)
                    c.sock.settimeout(1.0)
                    from emqx_tpu.transport.dtls import DtlsConnection

                    conn = DtlsConnection("client", psk_identity="intruder",
                                          psk=b"wrong-key")
                    for dg in conn.take_outgoing():
                        c.sock.sendto(dg, c.addr)
                    while not conn.complete:
                        data, _ = c.sock.recvfrom(4096)
                        conn.receive(data)
                        for dg in conn.take_outgoing():
                            c.sock.sendto(dg, c.addr)
            await asyncio.to_thread(bad_handshake)
            assert gw.dtls.handshakes == 0
        finally:
            await node.stop()

    run(main())


def test_stomp_ack_run_batches_through_session_with_fanout_enabled():
    """With the batched-stack opt-in on, a run of ACK frames arriving
    in one TCP read releases the whole window through ONE
    session.puback_batch cycle (receipts still answered per frame);
    with it off the per-frame path is unchanged — both drain the
    inflight window completely."""
    async def main():
        for flag in (True, False):
            node = await start_node(
                'broker.fanout.enable = true\n' if flag else '')
            try:
                sport = node.gateways.gateways["stomp"].port
                c = StompClient()
                await c.connect(sport)
                await c.send("SUBSCRIBE", {"id": "1", "destination": "q/#",
                                           "ack": "client-individual"})
                mq = Client(clientid="m1", port=mqtt_port(node))
                await mq.connect()
                for i in range(4):
                    await mq.publish(f"q/{i}", b"m%d" % i, qos=1)
                acks = []
                for _ in range(4):
                    m = await c.recv()
                    assert m.command == "MESSAGE"
                    acks.append(m.headers["ack"])
                conn = list(
                    node.gateways.gateways["stomp"].clients.values())[0]
                assert conn.batched is flag
                sess = node.broker.sessions[conn.clientid]
                assert len(sess.inflight) == 4
                # all four ACKs (with receipts) land in ONE write
                frames = b"".join(
                    serialize_frame(StompFrame(
                        "ACK", {"id": a, "receipt": f"r-{a}"}))
                    for a in acks)
                c.writer.write(frames)
                await c.writer.drain()
                receipts = set()
                for _ in range(4):
                    f = await c.recv()
                    assert f.command == "RECEIPT"
                    receipts.add(f.headers["receipt-id"])
                assert receipts == {f"r-{a}" for a in acks}
                for _ in range(50):
                    if len(sess.inflight) == 0:
                        break
                    await asyncio.sleep(0.01)
                assert len(sess.inflight) == 0
                await c.close()
                await mq.disconnect()
            finally:
                await node.stop()

    run(main())
