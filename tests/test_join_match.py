"""Relational-join match backend (ISSUE 13): sorted edge relations +
searchsorted-intersection level steps as an alternate kernel family
behind the kernel-cache seam, with per-shape autotuned routing.

The load-bearing property is BIT-FOR-BIT parity with the hash kernel —
matches, counts, ``row_meta``, and both overflow vectors — across every
corpus shape the serve plane sees, because the cache routes per shape
and a divergent answer would be a correctness bug, not a perf delta.
Flag off (``match.backend = hash``, the default), every join structure
stays unbuilt.
"""

import asyncio
import json
import os
import random

import numpy as np
import pytest

from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.match_service import MatchService
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.ops import encode_batch
from emqx_tpu.ops.device_table import DeviceNfa
from emqx_tpu.ops.incremental import IncrementalNfa
from emqx_tpu.ops.join_match import (
    OVERLAY_CAP, BackendAutotuner, JoinRelation, OverlayFull,
    relation_capacity,
)
from emqx_tpu.ops.kernel_cache import CompileMiss, MatchKernelCache


def run(coro):
    return asyncio.run(coro)


RESULT_FIELDS = ("matches", "n_matches", "active_overflow",
                 "match_overflow")


def assert_result_parity(rh, rj, ctx=""):
    for f in RESULT_FIELDS:
        a, b = np.asarray(getattr(rh, f)), np.asarray(getattr(rj, f))
        assert np.array_equal(a, b), (ctx, f, a, b)
    if rh.row_meta is not None or rj.row_meta is not None:
        assert np.array_equal(np.asarray(rh.row_meta),
                              np.asarray(rj.row_meta)), ctx


def both(dev, enc, **kw):
    return (dev.match(*enc, backend="hash", **kw),
            dev.match(*enc, backend="join", **kw))


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------

CORPUS = [
    # wildcard spread
    "a/b/c", "a/+/c", "a/#", "+/b/#", "+/+/+", "#", "x/y",
    # $SYS / $share-style (the router strips $share before the table
    # sees the filter — the kernel-level corpus is the plain filter)
    "$SYS/broker/clients/+", "$SYS/#", "queue/jobs/+",
    # deep-ish literals
    "d1/d2/d3/d4/d5/d6", "d1/d2/d3/d4/+/d6",
]

TOPICS = [
    "a/b/c", "a/z/c", "a/b", "x/y", "q/w/e",
    "$SYS/broker/clients/c1", "$SYS/broker/uptime", "$delayed/x",
    "queue/jobs/7", "d1/d2/d3/d4/d5/d6", "d1/d2/d3/d4/zz/d6",
    "a", "", "a/b/c/d/e/f/g/h",
]


def _table(filters, depth=8, **kw):
    inc = IncrementalNfa(depth=depth, **kw)
    for f in filters:
        inc.add(f)
    return inc


def test_kernel_parity_across_corpus():
    inc = _table(CORPUS)
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.enable_join()
    enc = encode_batch(inc, TOPICS, batch=16)
    assert_result_parity(*both(dev, enc), "compact")
    assert_result_parity(*both(dev, enc, flat_cap=8 * 16), "flat")
    # and both agree with the host oracle
    rh = dev.match(*enc, backend="join")
    m = np.asarray(rh.matches)
    for r, t in enumerate(TOPICS):
        got = sorted(x for x in m[r] if x >= 0)
        assert got == sorted(inc.match_host(t)), (t, got)


def test_kernel_parity_empty_frontier_and_empty_batch():
    inc = _table(["only/this"])
    dev = DeviceNfa(inc, active_slots=8, max_matches=8)
    dev.enable_join()
    # topics that die at step 0/1 + padding-only batch
    enc = encode_batch(inc, ["zz/zz/zz", "$SYS/x"], batch=8)
    assert_result_parity(*both(dev, enc), "dead frontier")
    enc = encode_batch(inc, [], batch=8)
    assert_result_parity(*both(dev, enc, flat_cap=64), "empty batch")


def test_kernel_parity_overflow_rows():
    # tiny active set + tiny K: force BOTH spill kinds and assert the
    # fail-open flags agree bit-for-bit (the host re-run set must be
    # THE SAME rows whichever backend served).  "a/3/x" forks into 3
    # live states at step 2 (a→+, +→3, +→+) > A=2 → active spill; the
    # '#'+wildcards push counts past K=2 → match spill.
    filters = ["+/+/#", "a/+/#", "+/3/#", "#"] \
        + [f"+/{i}/#" for i in range(6)]
    inc = _table(filters)
    dev = DeviceNfa(inc, active_slots=2, max_matches=2)
    dev.enable_join()
    enc = encode_batch(inc, ["a/3/x", "a/5/y/z", "q/1/w"], batch=4)
    rh, rj = both(dev, enc)
    assert_result_parity(rh, rj, "overflow")
    assert np.asarray(rh.active_overflow).sum() > 0
    assert np.asarray(rh.match_overflow).sum() > 0
    enc2 = encode_batch(inc, ["a/3/x"], batch=4)
    assert_result_parity(*both(dev, enc2, flat_cap=8), "overflow flat")


@pytest.mark.slow
def test_kernel_parity_random_churn_soak():
    rng = random.Random(71)
    inc = IncrementalNfa(depth=6, state_bucket=32, edge_bucket=64)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    dev.enable_join()
    pool = [f"l{i}/m{j}" + ("/+" if (i + j) % 3 == 0 else f"/n{j}")
            for i in range(40) for j in range(8)]
    present = set()
    for step in range(60):
        for _ in range(31):
            f = rng.choice(pool)
            if f in present:
                inc.remove(f)
                present.discard(f)
            else:
                inc.add(f)
                present.add(f)
        dev.sync()
        names = [t.replace("+", "qq") for t in rng.sample(pool, 8)]
        enc = encode_batch(inc, names, batch=8)
        assert_result_parity(*both(dev, enc), f"step {step}")


# ---------------------------------------------------------------------------
# relation maintenance
# ---------------------------------------------------------------------------

def test_relation_lookup_matches_edge_table():
    inc = _table(CORPUS)
    rel = JoinRelation(inc.S, inc.edge_tab)
    flat = inc.edge_tab.reshape(-1, 4)
    for s, w, n, _pad in flat[flat[:, 0] >= 0].tolist():
        assert rel.lookup(s, w) == n
    assert rel.lookup(0, 999999) == -1
    assert rel.cap == relation_capacity(inc.Hb)


def test_relation_delta_tombstone_revive_and_overlay():
    inc = _table(["a/b", "a/c"])
    rel = JoinRelation(inc.S, inc.edge_tab)
    inc.flush()  # clear dirt from the build
    inc.remove("a/c")            # tombstone
    inc.add("a/d")               # fresh edge -> overlay
    d = inc.flush()
    mpos, mval, opos, orows = rel.apply_bucket_delta(
        d.bucket_idx, d.bucket_rows)
    assert len(mpos) >= 1 and (mval == -1).any()    # tombstone written
    assert len(opos) >= 1                           # overlay append
    assert rel.lookup(0, inc.vocab["a"]) >= 0
    # revive: re-add the tombstoned filter — must land back in the CSR
    inc.add("a/c")
    d = inc.flush()
    mpos, mval, opos, orows = rel.apply_bucket_delta(
        d.bucket_idx, d.bucket_rows)
    assert (mval >= 0).any()
    # every live edge answers; the removed one is dead
    flat = inc.edge_tab.reshape(-1, 4)
    for s, w, n, _pad in flat[flat[:, 0] >= 0].tolist():
        assert rel.lookup(s, w) == n


def test_relation_overlay_overflow_raises_then_rebuild_serves():
    # table shapes large enough that nothing resizes mid-test: the
    # overflow must come from the overlay cap, not a rehash
    inc = _table(["seed/x"], state_bucket=4096, edge_bucket=4096)
    rel = JoinRelation(inc.S, inc.edge_tab)
    inc.flush()
    with pytest.raises(OverlayFull):
        added = 0
        while added < OVERLAY_CAP + 50:
            inc.add(f"o{added}/p{added}")
            added += 2  # two fresh edges per filter
            d = inc.flush()
            rel.apply_bucket_delta(d.bucket_idx, d.bucket_rows)
    assert inc.shape_key() == (4096, 4096, 8)
    # the shadow is already current: a rebuild alone restores service
    rel.rebuild(inc.S)
    flat = inc.edge_tab.reshape(-1, 4)
    for s, w, n, _pad in flat[flat[:, 0] >= 0].tolist():
        assert rel.lookup(s, w) == n


def test_device_overlay_overflow_rebuilds_and_keeps_parity():
    inc = IncrementalNfa(depth=6, state_bucket=4096, edge_bucket=4096)
    for i in range(4):
        inc.add(f"warm/{i}")
    dev = DeviceNfa(inc, active_slots=8, max_matches=8)
    dev.enable_join()
    rebuilds0 = dev.join_rebuilds
    # far more fresh edges than OVERLAY_CAP in one delta, with table
    # shapes big enough that nothing resizes: the overflow path, not
    # the rehash path, must absorb it
    for i in range(OVERLAY_CAP):
        inc.add(f"g{i}/h{i}")
    dev.sync()
    assert inc.shape_key() == (4096, 4096, 6)   # no resize happened
    assert dev.join_rebuilds > rebuilds0
    enc = encode_batch(inc, ["g7/h7", "warm/2", "nope/x"], batch=4)
    assert_result_parity(*both(dev, enc), "post-rebuild")


def test_grow_in_place_rehash_ships_fresh_seeds_regression():
    """The bug the join parity suite surfaced: a cuckoo rehash on the
    grow-in-place path shipped the rehashed edge table WITHOUT its
    fresh seeds, so the hash kernel probed with a stale pair and every
    lookup missed.  The relation is seed-free, which is why the join
    backend kept answering."""
    inc = IncrementalNfa(depth=6, state_bucket=16)
    inc.track_regions = True
    for f in ["a/b", "c/#"]:
        inc.add(f)
    dev = DeviceNfa(inc, active_slots=8, max_matches=8)
    dev.dirty_regions = True
    dev.enable_join()
    for i in range(200):    # forces node growth AND edge rehashes
        inc.add(f"g{i}/h{i}/+")
        if i % 17 == 0:
            dev.sync()
    dev.sync()
    assert dev.grow_applies > 0
    topics = [f"g{i}/h{i}/zz" for i in range(0, 200, 13)] + ["a/b"]
    enc = encode_batch(inc, topics, batch=32)
    rh, rj = both(dev, enc)
    assert_result_parity(rh, rj, "post-rehash")
    m = np.asarray(rh.matches)
    for r, t in enumerate(topics):
        assert sorted(x for x in m[r] if x >= 0) == \
            sorted(inc.match_host(t)), t


def test_flag_off_join_structures_inert():
    inc = _table(CORPUS)
    dev = DeviceNfa(inc, active_slots=8, max_matches=8)
    assert dev._join is None and dev._jarrs is None
    inc.add("later/+")
    dev.sync()
    assert dev._join is None and dev._jarrs is None
    # backend="join" without the mirror silently serves hash (identical
    # answers) instead of failing the batch
    enc = encode_batch(inc, ["a/b/c"], batch=4)
    r = dev.match(*enc, backend="join")
    assert sorted(x for x in np.asarray(r.matches)[0] if x >= 0) == \
        sorted(inc.match_host("a/b/c"))
    b = Broker()
    ms = MatchService(b, table="python")     # backend defaults to hash
    assert ms.backend == "hash" and ms.tuner is None
    assert ms.dev.join_enabled is False


# ---------------------------------------------------------------------------
# kernel cache: backend dimension, prewarm-both bugfix, CompileMiss
# ---------------------------------------------------------------------------

def test_compile_miss_raised_for_uncompiled_join_shape():
    inc = _table(["a/+"])
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.enable_join()
    kc = MatchKernelCache()
    dev.kernel_cache = kc
    enc = encode_batch(inc, ["a/k"], batch=64)
    with pytest.raises(CompileMiss):
        dev.match(*enc, flat_cap=8 * 64, block_compile=False,
                  backend="join")
    import time

    for _ in range(400):
        if kc.info()["entries"]:
            break
        time.sleep(0.02)
    res = dev.match(*enc, flat_cap=8 * 64, block_compile=False,
                    backend="join")
    np.asarray(res.matches)
    assert kc.hits >= 1


def test_prewarm_covers_both_backends_under_auto_zero_compile():
    """ISSUE 13 bugfix, spy-asserted: with auto routing the observed
    combos are hash-first, so prewarm_shape must cross-product them
    with BOTH kernel families — after prewarming the next shape, an
    auto-routed JOIN dispatch on it is a pure cache hit."""
    inc = IncrementalNfa(depth=8, state_bucket=64, edge_bucket=1024)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    dev.enable_join()
    kc = MatchKernelCache()
    kc.auto_backends = ("hash", "join")
    dev.kernel_cache = kc
    for i in range(20):
        inc.add(f"a/{i}/+")
    dev.sync()
    enc = encode_batch(inc, ["a/3/k"], batch=64)
    # observe the combo via the HASH backend only (the auto cold path)
    np.asarray(dev.match(*enc, flat_cap=8 * 64,
                         backend="hash").matches)
    s, hb, _d = inc.shape_key()
    kc.prewarm_shape(2 * s, hb)
    assert kc.shape_covered(2 * s, hb)
    compiles0 = kc.compiles
    for i in range(20):                 # cross the boundary
        inc.add(f"b/{i}/x")
    dev.sync()
    assert inc.shape_key() == (2 * s, hb, 8)
    enc = encode_batch(inc, ["b/5/x"], batch=64)
    # the first JOIN dispatch on the fresh shape: zero compiles
    res = dev.match(*enc, flat_cap=8 * 64, block_compile=False,
                    backend="join")
    np.asarray(res.matches)
    assert kc.compiles == compiles0, \
        "auto-routed join dispatch on a prewarmed shape paid a compile"


def test_prewarm_single_backend_unchanged_without_auto():
    """Without auto_backends the prewarm set is exactly the observed
    combos — no join executables are built behind a hash-only config."""
    inc = _table(["a/+"], state_bucket=64)
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    kc = MatchKernelCache()
    dev.kernel_cache = kc
    enc = encode_batch(inc, ["a/k"], batch=64)
    np.asarray(dev.match(*enc, flat_cap=8 * 64).matches)
    n = kc.prewarm_shape(128, inc.Hb)
    assert n == 1       # one combo, one backend, one fresh shape
    assert all(k[9] == "hash" for k in kc._compiled)


# ---------------------------------------------------------------------------
# segments: the sorted relations survive save/load/compact
# ---------------------------------------------------------------------------

def test_segment_round_trip_preserves_join_relation(tmp_path):
    from emqx_tpu.storage.segments import load_segment, save_segment

    inc = _table(CORPUS)
    path = str(tmp_path / "seg.npz")
    save_segment(path, inc, deep={}, routing_aids=set(),
                 join_relation=True)
    seg = load_segment(path)
    assert seg.join_start is not None
    rel = JoinRelation(inc.S, inc.edge_tab)   # fresh build = oracle
    assert np.array_equal(seg.join_start, rel.state_start)
    assert np.array_equal(seg.join_word, rel.edge_word)
    assert np.array_equal(seg.join_next, rel.edge_next)
    # and a relation seeded from the persisted arrays serves verbatim
    seeded = JoinRelation(inc.S, inc.edge_tab,
                          arrays=(seg.join_start, seg.join_word,
                                  seg.join_next))
    flat = inc.edge_tab.reshape(-1, 4)
    for s, w, n, _pad in flat[flat[:, 0] >= 0].tolist():
        assert seeded.lookup(s, w) == n


def test_segment_without_join_arrays_still_loads(tmp_path):
    from emqx_tpu.storage.segments import load_segment, save_segment

    inc = _table(["a/+"])
    path = str(tmp_path / "seg.npz")
    save_segment(path, inc, deep={}, routing_aids=set())
    seg = load_segment(path)
    assert seg.join_start is None


def test_cold_start_seeds_join_mirror_without_resort(tmp_path,
                                                    monkeypatch):
    """A segment-restored service with the join backend skips the
    build sort at first sync: the persisted arrays seed the mirror
    (epoch-guarded), spy-asserted on JoinRelation._build."""
    seg_dir = str(tmp_path)

    async def first_node():
        b = Broker()
        b.open_session("sub")
        for i in range(30):
            b.subscribe("sub", f"t/{i}/+", SubOpts())
        ms = MatchService(b, table="python", debounce_s=0.01,
                          bypass_rate=0.0, segments=True,
                          segments_dir=seg_dir,
                          compact_interval_s=0.05,
                          compact_min_mutations=1, backend="join")
        await ms.start()
        for _ in range(400):
            if ms._table_gen >= 1:
                break
            await asyncio.sleep(0.02)
        assert ms._table_gen >= 1
        await ms.stop()

    run(first_node())
    builds = []
    monkeypatch.setattr(
        JoinRelation, "_build",
        (lambda orig: lambda self, s: (builds.append(s),
                                       orig(self, s))[1])(
            JoinRelation._build))

    async def second_node():
        b2 = Broker()
        b2.open_session("sub")
        for i in range(30):
            b2.subscribe("sub", f"t/{i}/+", SubOpts())
        ms2 = MatchService(b2, table="python", debounce_s=0.01,
                           bypass_rate=0.0, segments=True,
                           segments_dir=seg_dir, backend="join")
        await ms2.start()
        for _ in range(400):
            if ms2.ready:
                break
            await asyncio.sleep(0.02)
        assert ms2.ready
        assert ms2._segment_loaded
        assert builds == [], "segment cold start re-paid the build sort"
        assert ms2.dev._jarrs is not None
        # and the seeded mirror answers with full parity
        enc = encode_batch(ms2.inc, ["t/3/x", "t/9/y"], batch=4)
        assert_result_parity(*both(ms2.dev, enc), "seeded mirror")
        await ms2.stop()

    run(second_node())


# ---------------------------------------------------------------------------
# autotuner
# ---------------------------------------------------------------------------

def test_autotuner_measure_records_and_persists(tmp_path):
    path = str(tmp_path / "autotune.json")
    t = BackendAutotuner(path=path, reps=2)
    calls = {"hash": 0, "join": 0}

    def mk(name, cost):
        def go():
            calls[name] += 1
            import time
            time.sleep(cost)
        return go

    sig = t.sig(256, 8, 1024, 64)
    pick = t.measure(sig, {"hash": mk("hash", 0.004),
                           "join": mk("join", 0.0)})
    assert pick == "join"
    assert calls["hash"] == 3 and calls["join"] == 3  # warmup + 2 reps
    assert t.pick(sig) == "join"
    # round-trips through the checksummed file
    t2 = BackendAutotuner(path=path)
    assert t2.pick(sig) == "join"
    assert not t2.rejected


def test_autotuner_corrupt_file_rejected(tmp_path):
    """The segment-checksum idiom: a torn/tampered pick table must be
    REJECTED (defaults serve, measuring restarts) — never trusted."""
    path = str(tmp_path / "autotune.json")
    t = BackendAutotuner(path=path, reps=1)
    t.record(t.sig(256, 8, 1024, 64), "join")
    doc = json.loads(open(path).read())
    doc["picks"]["b256:d8:s1024:h64"] = "hash"   # tamper, stale checksum
    open(path, "w").write(json.dumps(doc))
    t2 = BackendAutotuner(path=path)
    assert t2.rejected and t2.picks == {}
    # garbage bytes are equally rejected
    open(path, "w").write("{not json")
    t3 = BackendAutotuner(path=path)
    assert t3.rejected and t3.picks == {}
    # a bogus backend value is structurally rejected too
    open(path, "w").write(json.dumps({
        "version": 1, "checksum": "x", "picks": {"a": "pallas"}}))
    t4 = BackendAutotuner(path=path)
    assert t4.rejected and t4.picks == {}


# ---------------------------------------------------------------------------
# service-level routing
# ---------------------------------------------------------------------------

async def _serve_storm(ms, b, n=48, base=0):
    for i in range(n):
        await ms.prefetch_many({f"t/{base + i}/x": 1})


def test_service_join_backend_serves_and_counts(tmp_path):
    """backend=join: every device dispatch rides the join kernel
    (metric-asserted) and hints are BIT-FOR-BIT what a hash-backend
    service mints for the same router state and traffic."""
    async def serve(backend):
        b = Broker()
        m = Metrics()
        b.open_session("sub")
        for i in range(24):
            b.subscribe("sub", f"t/{i}/+", SubOpts())
        b.subscribe("sub", "t/#", SubOpts())
        b.subscribe("sub", "$share/g1/t/+/x", SubOpts())   # share strips
        b.subscribe("sub", "$SYS/deep/1/2/3/4/5/6/7/8/9/#", SubOpts())
        ms = MatchService(b, metrics=m, table="python",
                          debounce_s=0.01, bypass_rate=0.0,
                          backend=backend)
        await ms.start()
        for _ in range(400):
            if ms.ready:
                break
            await asyncio.sleep(0.02)
        topics = [f"t/{i}/x" for i in range(24)] + ["t/zz/q/deep"]
        await ms.prefetch_many({t: 1 for t in topics})
        hints = {t: ms._hints[t][2:] for t in topics if t in ms._hints}
        joins = m.get("tpu.match.backend_join_dispatches")
        await ms.stop()
        return hints, joins

    hints_h, joins_h = run(serve("hash"))
    hints_j, joins_j = run(serve("join"))
    assert joins_h == 0
    assert joins_j > 0
    assert hints_h == hints_j       # filter strings + rule ids equal
    assert len(hints_j) >= 20


def test_service_auto_measures_then_routes(tmp_path):
    async def main():
        b = Broker()
        m = Metrics()
        b.open_session("sub")
        for i in range(16):
            b.subscribe("sub", f"t/{i}/+", SubOpts())
        ms = MatchService(b, metrics=m, table="python",
                          debounce_s=0.01, bypass_rate=0.0,
                          backend="auto", autotune_reps=1)
        await ms.start()
        for _ in range(400):
            if ms.ready:
                break
            await asyncio.sleep(0.02)
        assert ms.tuner is not None
        for r in range(8):
            await _serve_storm(ms, b, n=16, base=100 * r)
            if ms.tuner.picks:
                break
        for _ in range(300):
            if ms.tuner.picks:
                break
            await asyncio.sleep(0.02)
        assert ms.tuner.picks, "no shape was ever measured"
        assert m.get("tpu.match.autotune_picks") >= 1
        info = ms.info()
        assert info["backend"] == "auto"
        assert info["autotune"]["measured_shapes"] >= 1
        # serve once more: the routed backend is the measured pick
        await _serve_storm(ms, b, n=16, base=9000)
        pick = next(iter(ms.tuner.picks.values()))
        joins = m.get("tpu.match.backend_join_dispatches")
        if pick == "join":
            assert joins > 0
        await ms.stop()

    run(main())


def test_service_auto_with_segments_persists_picks(tmp_path):
    seg_dir = str(tmp_path)

    async def main():
        b = Broker()
        b.open_session("sub")
        for i in range(8):
            b.subscribe("sub", f"t/{i}/+", SubOpts())
        ms = MatchService(b, table="python", debounce_s=0.01,
                          bypass_rate=0.0, segments=True,
                          segments_dir=seg_dir, backend="auto",
                          autotune_reps=1)
        assert ms.kcache is not None
        assert ms.kcache.auto_backends == ("hash", "join")
        await ms.start()
        for _ in range(400):
            if ms.ready:
                break
            await asyncio.sleep(0.02)
        for r in range(8):
            await _serve_storm(ms, b, n=16, base=100 * r)
            if ms.tuner.picks:
                break
        for _ in range(300):
            if ms.tuner.picks:
                break
            await asyncio.sleep(0.02)
        await ms.stop()
        assert os.path.exists(os.path.join(seg_dir, "autotune.json"))
        reloaded = BackendAutotuner(
            path=os.path.join(seg_dir, "autotune.json"))
        assert reloaded.picks == ms.tuner.picks and reloaded.picks

    run(main())


def test_autotuner_family_pick_generalizes_across_pow2_shapes():
    """ROADMAP join residual (d): a pick measured at one pow2 (S, Hb)
    shape serves the whole (B, D) family — a growth step inherits the
    family consensus instead of re-measuring cold."""
    t = BackendAutotuner(reps=1)
    t.record(t.sig(256, 8, 1024, 64), "join")
    t.record(t.sig(256, 8, 2048, 64), "join")
    # exact hit stays exact
    assert t.pick_for(256, 8, 1024, 64) == "join"
    assert t.family_hits == 0
    # unmeasured grown shape inherits the family consensus
    assert t.pick_for(256, 8, 4096, 128) == "join"
    assert t.family_hits == 1
    # a different (B, D) family has no pick
    assert t.pick_for(512, 8, 4096, 128) is None
    assert t.pick_for(256, 4, 4096, 128) is None


def test_autotuner_family_split_measures_exact():
    """A family whose measured shapes DISAGREE returns no consensus:
    the exact shape measures as before (a wrong inherited pick is only
    slow, but a split family is real signal)."""
    t = BackendAutotuner(reps=1)
    t.record(t.sig(256, 8, 1024, 64), "join")
    t.record(t.sig(256, 8, 2048, 128), "hash")
    assert t.pick_for(256, 8, 4096, 256) is None
    assert t.family_hits == 0
    # persisted format stays the versioned checksummed JSON
    assert "family_hits" in t.info()


def test_sorted_overlay_bit_parity_vs_linear_scan():
    """ISSUE 16 satellite: the sorted-overlay lower-bound search must
    be bit-identical to the historical dense overlay compare — same
    matches, counts, and overflow vectors — with the overlay well
    populated and a tombstoned CSR edge in play."""
    from emqx_tpu.ops.join_match import OVERLAY_EMPTY, join_match

    inc = _table(CORPUS, state_bucket=1024, edge_bucket=1024)
    rel = JoinRelation(inc.S, inc.edge_tab)
    inc.flush()
    # fresh edges land in the overlay; a removal tombstones the CSR
    for i in range(40):
        inc.add(f"ov{i}/+/leaf{i}")
    inc.remove("a/+/c")
    d = inc.flush()
    rel.grow_states(inc.S)
    mpos, mval, opos, orows = rel.apply_bucket_delta(
        d.bucket_idx, d.bucket_rows)
    assert (mval == -1).any()          # the tombstone
    assert len(opos) == OVERLAY_CAP    # overlay ships whole, sorted
    # sortedness invariant: live rows ascending, sentinels at the end
    ov = rel.overlay
    live = ov[ov[:, 0] != OVERLAY_EMPTY]
    assert len(live) >= 40
    keys = [tuple(r[:2]) for r in live.tolist()]
    assert keys == sorted(keys)
    assert (ov[len(live):, 0] == OVERLAY_EMPTY).all()

    topics = ["ov3/q/leaf3", "a/b/c", "ov39/x/leaf39", "a/z/c",
              "nope/x", "d1/d2/d3/d4/d5/d6"]
    enc = encode_batch(inc, topics, batch=8)
    kw = dict(active_slots=8, max_matches=16)
    r_sorted = join_match(*enc, inc.node_tab, *rel.arrays(), **kw)
    r_linear = join_match(*enc, inc.node_tab, *rel.arrays(),
                          linear_overlay=True, **kw)
    assert_result_parity(r_sorted, r_linear, "overlay search")
    r_sorted_f = join_match(*enc, inc.node_tab, *rel.arrays(),
                            flat_cap=8 * 16, **kw)
    r_linear_f = join_match(*enc, inc.node_tab, *rel.arrays(),
                            flat_cap=8 * 16, linear_overlay=True, **kw)
    assert_result_parity(r_sorted_f, r_linear_f, "overlay search flat")
    # and the host walk agrees (the overlay answers are REAL edges)
    m = np.asarray(r_sorted.matches)
    for r, t in enumerate(topics):
        got = sorted(x for x in m[r] if x >= 0)
        assert got == sorted(inc.match_host(t)), (t, got)
