"""Network-level integration tests: real asyncio TCP/WS round trips against
a full BrokerNode — the emqx CT style of driving a live broker with the
real client (SURVEY.md §4: integration suites use emqtt over localhost,
no protocol mocks)."""

import asyncio

import pytest

from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.mqtt import packet as P
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(extra_cfg: str = "", **node_kw):
    cfg = Config(
        file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n' + extra_cfg
    )
    node = BrokerNode(cfg, **node_kw)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


async def connected(node, clientid, **kw):
    c = Client(clientid=clientid, port=port_of(node), **kw)
    await c.connect()
    return c


# ---------------------------------------------------------------------------
# basic round trips
# ---------------------------------------------------------------------------

def test_connect_pub_sub_qos0():
    async def main():
        node = await start_node()
        try:
            sub = await connected(node, "sub1")
            await sub.subscribe("t/+/x", qos=0)
            pub = await connected(node, "pub1")
            await pub.publish("t/a/x", b"hello")
            msg = await sub.recv()
            assert (msg.topic, msg.payload) == ("t/a/x", b"hello")
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_qos1_and_qos2_roundtrip():
    async def main():
        node = await start_node()
        try:
            sub = await connected(node, "s")
            await sub.subscribe("q/#", qos=2)
            pub = await connected(node, "p")
            rc1 = await pub.publish("q/1", b"one", qos=1)
            rc2 = await pub.publish("q/2", b"two", qos=2)
            assert rc1 == 0 and rc2 == 0
            got = {(m.topic, m.payload, m.qos) for m in
                   [await sub.recv(), await sub.recv()]}
            assert got == {("q/1", b"one", 1), ("q/2", b"two", 2)}
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_fanout_multiple_subscribers():
    async def main():
        node = await start_node()
        try:
            subs = []
            for i in range(5):
                c = await connected(node, f"fan{i}")
                await c.subscribe("news/#")
                subs.append(c)
            pub = await connected(node, "pp")
            await pub.publish("news/today", b"x", qos=1)
            for c in subs:
                m = await c.recv()
                assert m.payload == b"x"
            for c in subs + [pub]:
                await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_retained_replay_on_subscribe():
    async def main():
        node = await start_node()
        try:
            pub = await connected(node, "rp")
            await pub.publish("state/dev1", b"on", qos=1, retain=True)
            sub = await connected(node, "rs")
            await sub.subscribe("state/+")
            m = await sub.recv()
            assert m.retain and m.payload == b"on"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_shared_subscription_balances():
    async def main():
        node = await start_node(
            'broker.shared_subscription_strategy = "round_robin"\n'
        )
        try:
            a = await connected(node, "ga")
            b = await connected(node, "gb")
            await a.subscribe("$share/g1/job/#", qos=1)
            await b.subscribe("$share/g1/job/#", qos=1)
            pub = await connected(node, "gp")
            for i in range(6):
                await pub.publish("job/run", str(i).encode(), qos=1)
            await asyncio.sleep(0.1)
            na, nb = a.messages.qsize(), b.messages.qsize()
            assert na + nb == 6
            assert na == 3 and nb == 3  # round_robin splits evenly
            for c in (a, b, pub):
                await c.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# session semantics over the wire
# ---------------------------------------------------------------------------

def test_session_takeover_closes_old_connection():
    async def main():
        node = await start_node()
        try:
            c1 = await connected(node, "dup", proto_ver=5, clean_start=False)
            c2 = await connected(node, "dup", proto_ver=5, clean_start=False)
            await asyncio.wait_for(c1.wait_closed(), 5.0)
            assert c1.disconnect_reason == P.RC.SESSION_TAKEN_OVER
            assert c2.connected
            await c2.disconnect()
        finally:
            await node.stop()

    run(main())


def test_session_resume_queues_while_offline():
    async def main():
        node = await start_node()
        try:
            c1 = await connected(
                node, "res", proto_ver=5, clean_start=False,
                properties={"Session-Expiry-Interval": 300},
            )
            await c1.subscribe("keep/#", qos=1)
            await c1.disconnect()
            pub = await connected(node, "pq")
            await pub.publish("keep/1", b"queued", qos=1)
            c2 = await connected(
                node, "res", proto_ver=5, clean_start=False,
                properties={"Session-Expiry-Interval": 300},
            )
            assert c2.connack.session_present
            m = await c2.recv()
            assert m.payload == b"queued"
            await pub.disconnect()
            await c2.disconnect()
        finally:
            await node.stop()

    run(main())


def test_will_message_fired_on_abrupt_close():
    async def main():
        node = await start_node()
        try:
            watcher = await connected(node, "w")
            await watcher.subscribe("wills/#")
            dying = Client(
                clientid="dying", port=port_of(node),
                will=P.Will(topic="wills/dying", payload=b"gone", qos=1),
            )
            await dying.connect()
            dying._writer.close()  # abrupt: no DISCONNECT packet
            m = await watcher.recv()
            assert (m.topic, m.payload) == ("wills/dying", b"gone")
            await watcher.disconnect()
        finally:
            await node.stop()

    run(main())


def test_v5_assigned_clientid_over_wire():
    async def main():
        node = await start_node()
        try:
            c = Client(clientid="", port=port_of(node), proto_ver=5)
            await c.connect()
            assert c.clientid.startswith("emqx_tpu_")
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_banned_clientid_rejected():
    async def main():
        node = await start_node()
        node.banned.add("clientid", "evil", duration=60, by="test",
                        reason="test")
        try:
            with pytest.raises(MqttError):
                await connected(node, "evil")
        finally:
            await node.stop()

    run(main())


def test_kick_client_from_management():
    async def main():
        node = await start_node()
        try:
            c = await connected(node, "victim")
            assert node.kick_client("victim")
            await asyncio.wait_for(c.wait_closed(), 5.0)
            assert not node.kick_client("victim")  # already gone
        finally:
            await node.stop()

    run(main())


def test_keepalive_timeout_closes():
    async def main():
        node = await start_node()
        try:
            c = Client(clientid="sleepy", port=port_of(node), keepalive=1)
            await c.connect()
            for t in c._tasks[1:]:
                t.cancel()  # kill the ping loop: simulate a stuck client
            await asyncio.wait_for(c.wait_closed(), 6.0)
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# WebSocket transport
# ---------------------------------------------------------------------------

def test_websocket_round_trip():
    pytest.importorskip("websockets")
    async def main():
        import websockets

        cfg = Config(
            file_text=(
                'listeners.tcp.default.enable = false\n'
                'listeners.ws.default.enable = true\n'
                'listeners.ws.default.bind = "127.0.0.1:0"\n'
            )
        )
        node = BrokerNode(cfg)
        await node.start()
        try:
            from emqx_tpu.mqtt import frame as F

            port = node.listeners.all()[0].port
            async with websockets.connect(
                f"ws://127.0.0.1:{port}/mqtt", subprotocols=["mqtt"]
            ) as ws:
                await ws.send(F.serialize(P.Connect(clientid="wsc")))
                buf = b"" + await ws.recv()
                ack = F.parse_one(buf)
                assert ack.type == P.CONNACK and ack.reason_code == 0
                await ws.send(F.serialize(
                    P.Subscribe(packet_id=1, topic_filters=[("ws/#", {"qos": 0})])
                ))
                sa = F.parse_one(b"" + await ws.recv())
                assert sa.type == P.SUBACK
                # publish from a TCP-side… no TCP listener; loop back via WS
                await ws.send(F.serialize(
                    P.Publish(topic="ws/echo", payload=b"via-ws")
                ))
                pub = F.parse_one(b"" + await ws.recv())
                assert pub.type == P.PUBLISH and pub.payload == b"via-ws"
        finally:
            await node.stop()

    run(main())


def test_listener_max_connections_sheds():
    async def main():
        node = await start_node()
        node.listeners.all()[0].max_connections = 1
        try:
            c1 = await connected(node, "only")
            c2 = Client(clientid="extra", port=port_of(node))
            with pytest.raises((MqttError, ConnectionError, asyncio.TimeoutError)):
                await c2.connect(timeout=2.0)
            await c1.disconnect()
        finally:
            await node.stop()

    run(main())
