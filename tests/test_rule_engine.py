"""Rule engine — emqx_rule_engine parity (SURVEY.md §2.3, §3.5):
parser, runtime, builtin functions, events, actions, device co-batch."""

import json

import pytest

from emqx_tpu.broker import Broker
from emqx_tpu.broker.message import make_message
from emqx_tpu.rule_engine import (
    RuleEngine, eval_rule, parse_sql, render_template, SqlError,
)
from emqx_tpu.rule_engine.engine import message_columns


def _msg(topic, payload=b"{}", qos=0, **kw):
    return make_message("c1", topic, payload, qos=qos, **kw)


# ---------------------------------------------------------------------------
# parser


def test_parse_select_basics():
    r = parse_sql('SELECT payload.x as x, topic FROM "t/#" WHERE qos > 0')
    assert r.kind == "select"
    assert r.froms == ["t/#"]
    assert r.fields[0] == (("var", ["payload", "x"]), "x")
    assert r.where == ("op", ">", ("var", ["qos"]), ("lit", 0))


def test_parse_star_multi_from_and_errors():
    r = parse_sql('SELECT * FROM "a/+", "$events/client_connected"')
    assert r.fields == [("*", None)]
    assert len(r.froms) == 2
    with pytest.raises(SqlError):
        parse_sql("DELETE FROM x")
    with pytest.raises(SqlError):
        parse_sql('SELECT * FROM "t" WHERE (1 + ')


def test_parse_foreach():
    r = parse_sql(
        "FOREACH payload.sensors AS s DO s.name, s.temp "
        'INCASE s.temp > 30 FROM "t"'
    )
    assert r.kind == "foreach"
    assert r.foreach_alias == "s"
    assert r.incase is not None
    assert len(r.fields) == 2


# ---------------------------------------------------------------------------
# runtime


def _run(sql, columns):
    return eval_rule(parse_sql(sql), columns)


def test_where_filtering_and_select_output():
    cols = message_columns(_msg("t/1", b'{"temp": 31.5, "ok": true}', qos=1))
    out = _run('SELECT payload.temp as temp, topic FROM "t/#" '
               "WHERE payload.temp > 30 and qos = 1", cols)
    assert out == [{"temp": 31.5, "topic": "t/1"}]
    assert _run('SELECT * FROM "t/#" WHERE payload.temp > 100', cols) == []


def test_arithmetic_string_case_in_like():
    cols = {"a": 7, "b": 2, "s": "hello", "topic": "t/x"}
    [out] = _run(
        "SELECT a + b as add, a div b as d, a mod b as m, "
        "upper(s) as up, concat(s, '!') as ex, "
        "case when a > 5 then 'big' else 'small' end as sz, "
        "a in (1, 7) as isin, s like 'he%' as lk "
        'FROM "t/#"', cols)
    assert out == {
        "add": 9, "d": 3, "m": 1, "up": "HELLO", "ex": "hello!",
        "sz": "big", "isin": True, "lk": True,
    }


def test_foreach_incase_fanout():
    payload = json.dumps({"sensors": [
        {"name": "a", "temp": 20}, {"name": "b", "temp": 35},
        {"name": "c", "temp": 40},
    ]}).encode()
    cols = message_columns(_msg("t", payload))
    outs = _run(
        "FOREACH payload.sensors AS s DO s.name as name, s.temp as temp "
        'INCASE s.temp > 30 FROM "t"', cols)
    assert outs == [{"name": "b", "temp": 35}, {"name": "c", "temp": 40}]


def test_builtin_funcs_sampler():
    cols = {"payload": b'{"xs": [1, 2, 3], "m": {"k": "v"}}', "topic": "a/b/c"}
    [out] = _run(
        "SELECT nth(2, payload.xs) as n, length(payload.xs) as l, "
        "map_get('k', payload.m) as mk, first(payload.xs) as f, "
        "json_encode(payload.xs) as js, md5('abc') as h, "
        "nth_topic_level(2, topic) as lvl, "
        "topic_match(topic, 'a/#') as tm "
        'FROM "a/#"', cols)
    assert out["n"] == 2 and out["l"] == 3 and out["mk"] == "v"
    assert out["f"] == 1 and out["js"] == "[1,2,3]"
    assert out["h"] == "900150983cd24fb0d6963f7d28e17f72"
    assert out["lvl"] == "b" and out["tm"] is True


def test_render_template():
    out = {"temp": 31.5, "nested": {"a": 1}}
    cols = {"clientid": "c9", "topic": "t/1"}
    assert render_template("alert/${clientid}/${temp}", out, cols) == "alert/c9/31.5"
    assert render_template("${nested}", out, cols) == '{"a":1}'
    assert render_template("${missing}", out, cols) == ""


# ---------------------------------------------------------------------------
# engine + broker wiring


def test_engine_publish_event_and_republish_action():
    b = Broker()
    eng = RuleEngine(b)
    b.open_session("listener")
    b.subscribe("listener", "alert/#")
    eng.create_rule(
        "r1",
        'SELECT payload.temp as temp, clientid FROM "sensors/+/temp" '
        "WHERE payload.temp > 30",
        actions=[{"function": "republish",
                  "args": {"topic": "alert/${clientid}",
                           "payload": "hot: ${temp}"}}],
    )
    b.publish(_msg("sensors/k/temp", b'{"temp": 35}'))
    sess = b.sessions["listener"]
    # republished message delivered (qos0 → direct send path drains to outbox
    # via publish result of the inner publish; check metrics instead)
    r = eng.rules["r1"]
    assert r.metrics["matched"] == 1
    assert r.metrics["passed"] == 1
    assert r.metrics["actions.success"] == 1
    # non-matching topic / failing WHERE
    b.publish(_msg("sensors/k/hum", b'{"temp": 35}'))
    b.publish(_msg("sensors/k/temp", b'{"temp": 5}'))
    assert r.metrics["matched"] == 2
    assert r.metrics["no_result"] == 1


def test_engine_republish_loop_guard():
    b = Broker()
    eng = RuleEngine(b)
    eng.create_rule(
        "loop",
        'SELECT * FROM "x/#"',
        actions=[{"function": "republish", "args": {"topic": "x/again",
                                                    "payload": "p"}}],
    )
    b.publish(_msg("x/start"))
    # the republish matched x/# but was NOT re-evaluated (loop guard)
    assert eng.rules["loop"].metrics["matched"] == 1


def test_engine_lifecycle_events():
    b = Broker()
    eng = RuleEngine(b)
    seen = []
    eng.create_rule(
        "ev",
        'SELECT clientid, topic, qos FROM "$events/session_subscribed"',
        actions=[lambda out, cols: seen.append(out)],
    )
    b.open_session("c2")
    b.subscribe("c2", "a/b")
    assert seen == [{"clientid": "c2", "topic": "a/b", "qos": 0}]


def test_engine_compile_table_cobatch():
    from emqx_tpu.ops import match_topics

    eng = RuleEngine()
    eng.create_rule("r1", 'SELECT * FROM "s/+/t"', actions=[])
    eng.create_rule("r2", 'SELECT * FROM "s/#", "other/x"', actions=[])
    eng.create_rule("off", 'SELECT * FROM "zzz/#"', actions=[])
    eng.set_enable("off", False)
    table, by_filter = eng.compile_table()
    assert set(by_filter) == {"s/+/t", "s/#", "other/x"}
    [m] = match_topics(table, ["s/1/t"])
    rule_ids = sorted(rid for f in m for rid in by_filter[f])
    assert rule_ids == ["r1", "r2"]


def test_epoch_bumps_on_changes():
    eng = RuleEngine()
    e0 = eng.epoch
    eng.create_rule("a", 'SELECT * FROM "t"', actions=[])
    assert eng.epoch == e0 + 1
    eng.set_enable("a", False)
    eng.delete_rule("a")
    assert eng.epoch == e0 + 3
