"""Batched QoS1/2 inflight admission (PR 2): contiguous packet-id runs,
bulk window inserts, the incremental retry scan, packet-id-space
backpressure, and the batched ack→refill cycle."""

import pytest

from emqx_tpu.broker import (
    MAX_PACKET_ID, Inflight, InflightFullError, Session, make_message,
)
from emqx_tpu.observe.metrics import Metrics


def msg(topic="t", qos=1, payload=b"x", **kw):
    return make_message("pub", topic, payload, qos=qos, **kw)


# ---------------------------------------------------------------------------
# Inflight: incremental expiry scan
# ---------------------------------------------------------------------------

def test_inflight_older_than_incremental_scan():
    inf = Inflight(0)
    inf.insert(1, "a", now=100.0)
    inf.insert(2, "b", now=103.0)
    inf.insert(3, "c", now=106.0)
    assert inf.older_than(10, now=111.0) == [1]
    # a caller that neither touches nor deletes sees it again (the full
    # scan behaved the same way)
    assert inf.older_than(10, now=111.0) == [1]
    inf.touch(1, now=111.0)
    assert inf.older_than(10, now=116.5) == [2, 3]   # oldest first
    inf.delete(2)
    assert inf.older_than(10, now=117.0) == [3]
    # the touched entry comes due again a full interval later
    assert inf.older_than(10, now=121.5) == [3, 1]
    assert inf.older_than(10, now=100.0) == []


def test_inflight_insert_many_single_timestamp_and_order():
    inf = Inflight(8)
    inf.insert_many([(5, "a"), (2, "b"), (9, "c")], now=50.0)
    rows = list(inf.items())
    assert [pid for pid, _, _ in rows] == [5, 2, 9]   # insertion order
    assert all(ts == 50.0 for _, ts, _ in rows)
    # bulk insert past the bound refuses atomically
    with pytest.raises(InflightFullError):
        inf.insert_many([(i, "x") for i in (10, 11, 12, 13, 14, 15)])
    with pytest.raises(KeyError):
        inf.insert_many([(5, "dup")])
    assert len(inf) == 3


def test_inflight_expiry_survives_delete_churn_compaction():
    inf = Inflight(0)
    for i in range(1, 201):
        inf.insert(i, i, now=float(i))
    for i in range(1, 200):   # churn → stale heap entries → compaction
        inf.delete(i)
    assert inf.older_than(0.0, now=1000.0) == [200]


# ---------------------------------------------------------------------------
# packet-id allocation
# ---------------------------------------------------------------------------

def test_alloc_packet_ids_skips_live_ids_across_wrap():
    s = Session("c1", max_inflight=0)
    s._next_pid = 65530
    for pid in (65531, 65533, 1, 3):
        s.inflight.insert(pid, ("publish", None))
    ids = s.alloc_packet_ids(6)
    assert ids == [65532, 65534, 65535, 2, 4, 5]
    assert not any(s.inflight.contains(i) for i in ids)
    assert len(set(ids)) == 6
    # the cursor continues where the run ended, like next_packet_id
    assert s.next_packet_id() == 6


def test_alloc_packet_ids_matches_per_message_sequence():
    a = Session("a", max_inflight=0)
    b = Session("b", max_inflight=0)
    for s in (a, b):
        s._next_pid = 65533
        s.inflight.insert(65535, ("publish", None))
        s.inflight.insert(2, ("publish", None))
    assert a.alloc_packet_ids(4) == [b.next_packet_id() for _ in range(4)]


def test_next_packet_id_backpressure_when_id_space_saturated():
    s = Session("c1", max_inflight=0)
    s.inflight.insert_many(
        [(pid, ("publish", None)) for pid in range(1, MAX_PACKET_ID + 1)],
        now=0.0,
    )
    # O(1) refusal, not a 65535-iteration spin ending in RuntimeError
    with pytest.raises(InflightFullError):
        s.next_packet_id()
    with pytest.raises(InflightFullError):
        s.alloc_packet_ids(1)
    # deliver treats exhaustion as window backpressure: queue, not crash
    out, dropped = s.deliver([msg(qos=1)])
    assert out == [] and dropped == []
    assert len(s.mqueue) == 1


def test_alloc_packet_ids_insufficient_free_raises():
    s = Session("c1", max_inflight=0)
    s.inflight.insert_many(
        [(pid, ("publish", None)) for pid in range(1, MAX_PACKET_ID - 1)])
    assert len(s.alloc_packet_ids(2)) == 2  # exactly the free ids left
    s2 = Session("c2", max_inflight=0)
    s2.inflight.insert_many(
        [(pid, ("publish", None)) for pid in range(1, MAX_PACKET_ID - 1)])
    with pytest.raises(InflightFullError):
        s2.alloc_packet_ids(3)


# ---------------------------------------------------------------------------
# batched deliver / dequeue
# ---------------------------------------------------------------------------

def test_batched_deliver_matches_per_message_deliver():
    batched = Session("a", max_inflight=8)
    serial = Session("b", max_inflight=8)
    msgs = [msg(qos=qos, payload=str(i).encode())
            for i, qos in enumerate([1, 0, 1, 2, 0, 1, 1, 1, 2, 1, 1, 0])]
    out_b, drop_b = batched.deliver(list(msgs))
    out_s, drop_s = [], []
    for m in msgs:
        o, d = serial.deliver([m])
        out_s.extend(o)
        drop_s.extend(d)
    assert [(p.pid, p.msg.payload) for p in out_b] == \
        [(p.pid, p.msg.payload) for p in out_s]
    assert drop_b == drop_s == []
    assert len(batched.inflight) == len(serial.inflight) == 8
    assert len(batched.mqueue) == len(serial.mqueue)  # overflow queued
    assert [m.payload for m in batched.mqueue.to_list()] == \
        [m.payload for m in serial.mqueue.to_list()]


def test_batched_deliver_ids_never_collide_with_live_inflight():
    s = Session("c1", max_inflight=64)
    s._next_pid = 65520
    # live ids scattered across the wrap boundary
    for pid in (65525, 65530, 3, 7, 40):
        s.inflight.insert(pid, ("publish", None))
    out, _ = s.deliver([msg(qos=1) for _ in range(40)])
    pids = [p.pid for p in out]
    assert len(pids) == len(set(pids)) == 40
    assert not set(pids) & {65525, 65530, 3, 7, 40}
    assert len(s.inflight) == 45


def test_batch_admitted_metric_counts_bulk_admissions():
    m = Metrics()
    s = Session("c1", max_inflight=16)
    s.metrics = m
    s.deliver([msg(qos=1)])                       # single: not a batch
    assert m.get("broker.inflight.batch_admitted") == 0
    s.deliver([msg(qos=1) for _ in range(5)])
    assert m.get("broker.inflight.batch_admitted") == 5


def test_puback_batch_matches_sequential_acks():
    batched = Session("a", max_inflight=4)
    serial = Session("b", max_inflight=4)
    msgs = [msg(qos=1, payload=str(i).encode()) for i in range(10)]
    out_b, _ = batched.deliver(list(msgs))
    out_s, _ = serial.deliver(list(msgs))
    pids = [p.pid for p in out_b]
    acked_b, more_b = batched.puback_batch(pids + [999])  # unknown pid ok
    acked_s, more_s = [], []
    for p in out_s:
        a, more = serial.puback(p.pid)
        if a is not None:
            acked_s.append(a)
        more_s.extend(more)
    _, m999 = serial.puback(999)
    assert m999 == []
    assert [m.payload for m in acked_b] == [m.payload for m in acked_s]
    assert [(p.pid, p.msg.payload) for p in more_b] == \
        [(p.pid, p.msg.payload) for p in more_s]
    assert len(batched.inflight) == len(serial.inflight) == 4


def test_retry_fires_exactly_once_per_interval_under_incremental_scan():
    s = Session("c1", max_inflight=8, retry_interval=10.0)
    import time as _t
    now = _t.time()
    out, _ = s.deliver([msg(qos=1, payload=b"a"), msg(qos=1, payload=b"b"),
                        msg(qos=2, payload=b"c")])
    assert len(out) == 3
    assert s.retry(now + 5) == []                  # nothing due yet
    due = s.retry(now + 11)
    assert sorted(p for p, _, _ in due) == sorted(p.pid for p in out)
    assert all(m.dup for _, k, m in due if k == "publish")
    assert s.retry(now + 12) == []                 # touched: not due again
    assert len(s.retry(now + 21.5)) == 3           # due a full interval later
    # acked entries leave the scan entirely
    s.puback(out[0].pid)
    assert sorted(p for p, _, _ in s.retry(now + 40)) == \
        sorted(p.pid for p in out[1:])


# ---------------------------------------------------------------------------
# mqueue expiry short-circuit (the per-ack dequeue hot path)
# ---------------------------------------------------------------------------

def test_mqueue_filter_expired_short_circuits_without_expiring_msgs():
    from emqx_tpu.broker import MQueue
    q = MQueue(max_len=0)
    q.insert_many([msg(qos=1) for _ in range(10)])
    assert q._expiring == 0
    assert q.filter_expired() == []                # O(1), no sweep
    assert len(q) == 10
    expiring = msg(qos=1, properties={"Message-Expiry-Interval": 1})
    q.insert(expiring)
    assert q._expiring == 1
    import time as _t
    assert q.filter_expired(now=_t.time() + 5) == [expiring]
    assert q._expiring == 0 and len(q) == 10
