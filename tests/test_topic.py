"""Oracle tests for emqx_tpu.topic — mirrors emqx_topic_SUITE / prop_emqx
style coverage (SURVEY.md §4): explicit spec cases + property tests."""

import string

import pytest
from _optional import given, settings, st

from emqx_tpu import topic as T


# ---------------------------------------------------------------------------
# words / join / levels
# ---------------------------------------------------------------------------

def test_words_basic():
    assert T.words("a/b/c") == ["a", "b", "c"]
    assert T.words("/a") == ["", "a"]
    assert T.words("a//b") == ["a", "", "b"]
    assert T.words("a/b/") == ["a", "b", ""]
    assert T.join(["a", "", "b"]) == "a//b"
    assert T.levels("a/b/c") == 3


@given(st.lists(st.text(alphabet=string.ascii_letters + string.digits, max_size=5), min_size=1, max_size=8))
def test_words_join_roundtrip(ws):
    assert T.words(T.join(ws)) == ws


# ---------------------------------------------------------------------------
# validate
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("flt", [
    "a/b/c", "+", "#", "a/+/b", "a/b/#", "+/+/+", "/", "//", "a//+",
    "$SYS/#", "$share/g/a/+", "$share/grp/#", "$queue/t", "a/ /b",
])
def test_valid_filters(flt):
    T.validate(flt, "filter")


@pytest.mark.parametrize("flt", [
    "", "a/#/b", "#/a", "a+", "a/b+", "a/#b", "a/b#", "+a/b",
    "$share//t", "$share/g+/t", "$share/g", "$share/g/",
])
def test_invalid_filters(flt):
    assert not T.is_valid(flt, "filter")


@pytest.mark.parametrize("name", ["a/b", "/", "$SYS/broker", "a b/c"])
def test_valid_names(name):
    T.validate(name, "name")


@pytest.mark.parametrize("name", ["", "a/+", "a/#", "#", "+"])
def test_invalid_names(name):
    assert not T.is_valid(name, "name")


def test_validate_too_long():
    assert not T.is_valid("x" * 65536, "name")
    assert T.is_valid("x" * 65535, "name")


def test_validate_nul():
    assert not T.is_valid("a\x00b", "name")


# ---------------------------------------------------------------------------
# match — explicit spec cases (MQTT v5 §4.7, emqx_topic_SUITE style)
# ---------------------------------------------------------------------------

MATCH_CASES = [
    # (name, filter, expected)
    ("a/b/c", "a/b/c", True),
    ("a/b/c", "a/b/d", False),
    ("a/b/c", "+/b/c", True),
    ("a/b/c", "a/+/c", True),
    ("a/b/c", "a/b/+", True),
    ("a/b/c", "+/+/+", True),
    ("a/b/c", "+/+", False),
    ("a/b/c", "+/+/+/+", False),
    ("a/b/c", "#", True),
    ("a/b/c", "a/#", True),
    ("a/b/c", "a/b/#", True),
    ("a/b/c", "a/b/c/#", True),   # '#' matches zero levels
    ("a/b", "a/b/#", True),
    ("a", "a/#", True),
    ("a", "a/+", False),
    ("a/b/c", "a/c/#", False),
    ("a/b/c/d", "a/#", True),
    ("ab", "a+", False),           # '+' is not a glob within a level
    ("a/b", "a/b/", False),        # trailing empty level is significant
    ("a/b/", "a/b/+", True),       # '+' matches an empty level
    ("/b", "+/b", True),
    ("/", "+/+", True),
    ("/", "#", True),
    ("/finance", "+/+", True),
    ("/finance", "/+", True),
    ("/finance", "+", False),
    ("sport/tennis/player1", "sport/tennis/player1/#", True),
    ("sport/tennis/player1/ranking", "sport/tennis/player1/#", True),
    ("sport", "sport/#", True),
    ("sport", "sport/+", False),
    # $-topic protection (first level only)
    ("$SYS/broker", "#", False),
    ("$SYS/broker", "+/broker", False),
    ("$SYS/broker", "$SYS/#", True),
    ("$SYS/broker", "$SYS/+", True),
    ("$SYS/a/b", "$SYS/+/b", True),
    ("$SYS", "#", False),
    ("$whatever/x", "#", False),
    ("a/$SYS/b", "a/+/b", True),   # inner $ levels are not protected
    ("a/$SYS/b", "a/#", True),
]


@pytest.mark.parametrize("name,flt,expected", MATCH_CASES)
def test_match_cases(name, flt, expected):
    assert T.match(name, flt) is expected


def test_match_word_lists():
    assert T.match(["a", "b"], ["a", "+"]) is True


def test_match_share():
    assert T.match_share("a/b", "$share/g/a/+") is True
    assert T.match_share("a/b", "$queue/a/b") is True
    assert T.match("a/b", "$share/g/a/+") is False  # no auto-strip in match


# ---------------------------------------------------------------------------
# share parsing
# ---------------------------------------------------------------------------

def test_parse_share():
    assert T.parse_share("$share/g/a/b") == ("g", "a/b")
    assert T.parse_share("$queue/t") == ("$queue", "t")
    assert T.parse_share("a/b") is None
    assert T.parse_share("$shared/g/t") is None
    assert T.strip_share("$share/g/t") == "t"
    assert T.strip_share("t") == "t"
    assert T.make_share("g", "a/b") == "$share/g/a/b"
    assert T.is_shared("$share/g/t") and not T.is_shared("t")


# ---------------------------------------------------------------------------
# property tests (prop_emqx_topic style)
# ---------------------------------------------------------------------------

word_st = st.text(alphabet=string.ascii_lowercase + string.digits, min_size=0, max_size=4)
# First level occasionally '$'-prefixed so the $-protection rule is fuzz-covered.
first_word_st = st.one_of(word_st, word_st.map(lambda w: "$" + w))
name_words_st = st.builds(
    lambda head, tail: [head] + tail,
    first_word_st,
    st.lists(word_st, min_size=0, max_size=7),
)


@st.composite
def filter_words_st(draw):
    ws = draw(st.lists(st.one_of(word_st, st.just("+")), min_size=1, max_size=8))
    if draw(st.booleans()):
        ws = ws + ["#"]
    return ws


@settings(max_examples=300, deadline=None)
@given(name_words_st)
def test_exact_match_reflexive(ws):
    name = T.join(ws)
    assert T.match(name, name)


@settings(max_examples=300, deadline=None)
@given(name_words_st, filter_words_st())
def test_match_agrees_with_bruteforce(nw, fw):
    """Compare against an independent brute-force recursive matcher."""

    def brute(n, f):
        if not f:
            return not n
        if f[0] == "#":
            return True
        if not n:
            return False
        if f[0] == "+" or f[0] == n[0]:
            return brute(n[1:], f[1:])
        return False

    expected = brute(nw, fw)
    if nw[0].startswith("$") and fw[0] in ("+", "#"):
        expected = False
    assert T.match(nw, fw) is expected


@settings(max_examples=200, deadline=None)
@given(name_words_st)
def test_plus_matches_any_single_level(ws):
    flt = ["+"] * len(ws)
    expected = not ws[0].startswith("$")
    assert T.match(ws, flt) is expected


@settings(max_examples=200, deadline=None)
@given(filter_words_st())
def test_valid_filters_validate(fw):
    flt = T.join(fw)
    if flt == "":  # the singleton empty level joins to the invalid empty topic
        assert not T.is_valid(flt, "filter")
    else:
        T.validate(flt, "filter")


def test_nested_share_rejected():
    # a nested $share would validate but never match after one-layer strip
    assert not T.is_valid("$share/g1/$share/g2/sensor", "filter")
    assert not T.is_valid("$share/g1/$queue/sensor", "filter")
    assert T.is_valid("$share/g1/sensor", "filter")
