"""Dense matmul NFA engine (`ops/dense_match.py`) — parity against the
host oracle and the gather kernel, plus its exactness guarantee (no
active-set spill) on workloads that force the gather kernel to fail
open.  Runs on the CPU mesh; the on-chip A/B is ``bench_dense``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops.compiler import compile_filters, encode_topics
from emqx_tpu.ops.dense_match import (
    DENSE_STATE_CAP, build_dense, dense_match, supports_dense,
)
from emqx_tpu.ops.match_kernel import nfa_match


def _run_dense(tab, dense, topics, max_matches=64):
    words, lens, is_sys = encode_topics(tab, topics, batch=len(topics))
    return dense_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in dense.device_arrays()],
        max_matches=max_matches)


def _decode(tab, res, i):
    row = np.asarray(res.matches)[i]
    return sorted(tab.accept_filters[a] for a in row[row >= 0])


def test_dense_matches_oracle_randomized():
    rng = np.random.default_rng(7)
    filters = sorted({
        "r%d/" % rng.integers(8)
        + "/".join(("+" if rng.random() < 0.35 else "w%d" % rng.integers(10))
                   for _ in range(rng.integers(1, 6)))
        + ("/#" if rng.random() < 0.25 else "")
        for _ in range(400)
    } | {"#", "+/x", "$SYS/broker/+", "a/b/c"})
    tab = compile_filters(filters, depth=8)
    dense = build_dense(tab)
    topics = ["r%d/" % rng.integers(8)
              + "/".join("w%d" % rng.integers(10)
                         for _ in range(rng.integers(1, 8)))
              for _ in range(300)]
    topics += ["$SYS/broker/uptime", "a/b/c", "r1", "none/of/these/words"]
    res = _run_dense(tab, dense, topics, max_matches=128)
    mo = np.asarray(res.match_overflow)
    assert not np.any(np.asarray(res.active_overflow)), "dense cannot spill"
    for i, t in enumerate(topics):
        if mo[i]:
            continue
        assert _decode(tab, res, i) == sorted(
            f for f in filters if T.match(t, f)), t


def test_dense_exact_where_gather_spills():
    # every literal/+ combination over 4 levels: topic a/b/c/d holds
    # 2^4 = 16 trie nodes active at step 4, far past the gather
    # kernel's A=4 cap; the dense walk has no cap and must stay exact
    import itertools

    filters = ["/".join(seg) + "/#" for seg in itertools.product(
        *([w, "+"] for w in "abcd"))]
    tab = compile_filters(filters, depth=8)
    dense = build_dense(tab)
    topics = ["a/b/c/d/tail", "x/y/l7/z"]
    words, lens, is_sys = encode_topics(tab, topics, batch=2)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys))
    g = nfa_match(*args, *[jnp.asarray(a) for a in tab.device_arrays()],
                  active_slots=4, compact_output=True, max_matches=64)
    assert np.asarray(g.active_overflow).sum() > 0, \
        "workload should overflow the gather kernel's active set"
    d = _run_dense(tab, dense, topics)
    assert not np.asarray(d.active_overflow).any()
    assert _decode(tab, d, 0) == sorted(
        f for f in filters if T.match("a/b/c/d/tail", f))


def test_dense_sys_topic_root_suppression():
    tab = compile_filters(["#", "+/status", "$SYS/+", "$SYS/#"], depth=8)
    dense = build_dense(tab)
    res = _run_dense(tab, dense, ["$SYS/status", "node/status"])
    # $-topics must not match root-level `#`/`+` but do match $SYS/...
    assert _decode(tab, res, 0) == ["$SYS/#", "$SYS/+"]
    assert _decode(tab, res, 1) == ["#", "+/status"]


def test_dense_match_overflow_flagged():
    filters = [f"a/+/f{i}" for i in range(40)] + ["a/b/#"]
    tab = compile_filters(filters, depth=8)
    dense = build_dense(tab)
    res = _run_dense(tab, dense, ["a/b/f1"], max_matches=1)
    assert np.asarray(res.match_overflow)[0] == 1
    assert np.asarray(res.n_matches)[0] == 2  # a/+/f1, a/b/#


def test_supports_dense_cap():
    tab = compile_filters(["a/b"], depth=8)
    assert supports_dense(tab)
    assert not supports_dense(tab, state_cap=1)
    assert DENSE_STATE_CAP >= 256   # measured crossover, see dense_match.py


def test_build_dense_structure():
    tab = compile_filters(["a/b", "a/+", "c/#"], depth=8)
    d = build_dense(tab)
    # every literal edge: exactly one nonzero per column; labels set
    cols = d.lmat.sum(axis=0)
    assert set(np.unique(cols)) <= {0.0, 1.0}
    lit_children = np.nonzero(cols)[0]
    assert all(d.label[c] >= 0 for c in lit_children)
    # plus edges come from node_tab column 0
    n = tab.n_states
    src = np.nonzero(tab.node_tab[:n, 0] >= 0)[0]
    assert d.pmat.sum() == len(src)


def test_tiered_dense_hot_engine_parity():
    from emqx_tpu.ops.tiered import TieredMatcher, build_tiered

    rng = np.random.default_rng(9)
    filters = sorted({
        "hot%d/%s" % (rng.integers(3), "/".join(
            ("+" if rng.random() < 0.3 else "w%d" % rng.integers(6))
            for _ in range(rng.integers(1, 4))))
        for _ in range(60)
    } | {"cold%d/+/#" % i for i in range(20)} | {"#"})
    tiered = build_tiered(filters, ["hot0", "hot1", "hot2"], depth=8,
                          fit=supports_dense)
    tm = TieredMatcher(tiered, depth=8, hot_engine="dense")
    topics = ["hot%d/w1/w2" % rng.integers(3) for _ in range(40)] \
        + ["cold3/anything/x", "hot0/w0"]
    got = tm.match(topics)
    for t, rows in zip(topics, got):
        assert sorted(rows) == sorted(
            f for f in filters if T.match(t, f)), t
    assert tm.hot_topics and tm.cold_topics
    assert tm.info()["hot_engine"] == "dense"


def test_tiered_demotes_on_engine_failure(monkeypatch):
    from emqx_tpu.ops import tiered as tiered_mod
    from emqx_tpu.ops.tiered import TieredMatcher, build_tiered

    filters = ["hot0/a", "hot0/+", "cold/x"]
    tiered = build_tiered(filters, ["hot0"], depth=8, fit=supports_dense)
    tm = TieredMatcher(tiered, depth=8, hot_engine="pallas")

    def boom(self, topics):
        raise RuntimeError("Mosaic says no")

    monkeypatch.setattr(TieredMatcher, "_match_hot_pallas", boom)
    got = tm.match(["hot0/a", "cold/x"])
    assert got[0] == ["hot0/+", "hot0/a"] or sorted(got[0]) == [
        "hot0/+", "hot0/a"]
    assert tm.hot_engine == "dense"   # demoted, traffic served
