"""Streaming table lifecycle (ISSUE 9): persistent compacted segments,
background delta compaction with atomic swap, dirty-region device
upload, and the padded-shape kernel compile cache.

Flag off (``match.segments.enable = false``, the default), every
structure is inert and the serve path is the PR-8 lifecycle — asserted
here and covered by the pre-existing match suites, which this PR keeps
passing unchanged.
"""

import asyncio
import os

import numpy as np
import pytest

from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.match_service import MatchService
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.ops.device_table import DeviceNfa
from emqx_tpu.ops.incremental import IncrementalNfa
from emqx_tpu.ops.kernel_cache import CompileMiss, MatchKernelCache
from emqx_tpu.storage.segments import (
    SegmentError, load_segment, restore_incremental, save_segment,
)


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=30.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_service(broker, seg_dir, **kw):
    kw.setdefault("depth", 8)
    kw.setdefault("table", "python")
    kw.setdefault("bypass_rate", 0.0)
    kw.setdefault("segments", True)
    kw.setdefault("segments_dir", str(seg_dir))
    kw.setdefault("compact_interval_s", 0.05)
    kw.setdefault("compact_min_mutations", 1)
    kw.setdefault("metrics", Metrics())
    return MatchService(broker, **kw)


def subscribe_many(b, filters, sessions=16):
    for i, flt in enumerate(filters):
        cid = f"s{i % sessions}"
        if cid not in b.sessions:
            b.open_session(cid)
        b.subscribe(cid, flt, SubOpts())


# ---------------------------------------------------------------------------
# segment round trip (load(save(T)) parity, aliases/aids stable)
# ---------------------------------------------------------------------------

def test_segment_round_trip_parity(tmp_path):
    inc = IncrementalNfa(depth=4)
    filters = [f"a/{i}/+" for i in range(500)] + ["x/#", "+/y", "only"]
    for f in filters:
        inc.add(f)
    inc.remove("a/7/+")           # free-list holes survive
    inc.remove("a/9/+")
    deep = {"d/e/e/p/x/y/z/+": inc.alloc_alias("d/e/e/p/x/y/z/+")}
    routing = {aid for aid, f in enumerate(inc.accept_filters)
               if f is not None}
    p = str(tmp_path / "seg.npz")
    save_segment(p, inc, deep=deep, routing_aids=routing)
    seg = load_segment(p)
    assert seg.kind == "state"
    inc2 = restore_incremental(seg)
    # arrays byte-identical => device matches byte-identical
    assert np.array_equal(inc.node_tab, inc2.node_tab)
    assert np.array_equal(inc.edge_tab, inc2.edge_tab)
    assert np.array_equal(inc.seeds, inc2.seeds)
    assert inc.vocab == inc2.vocab
    assert list(inc.accept_filters) == list(inc2.accept_filters)
    assert inc._alias_aids == inc2._alias_aids
    assert list(inc._free_aids) == list(inc2._free_aids)
    assert set(inc._free_sids) == set(inc2._free_sids)
    assert (inc.n_states, inc.n_edges, inc.n_filters) == \
        (inc2.n_states, inc2.n_edges, inc2.n_filters)
    # aids stable; host matches identical (incl. hole topics)
    for f in ("a/5/+", "x/#", "+/y", "only"):
        assert inc.aid_of(f) == inc2.aid_of(f)
    for t in ("a/5/k", "x/q/r", "z/y", "a/7/k", "only"):
        assert sorted(inc.match_host(t)) == sorted(inc2.match_host(t)), t
    # the restored table stays fully mutable
    assert inc2.add("fresh/+") and inc2.remove("a/11/+")
    assert not inc2.flush().empty


def test_segment_device_serve_parity(tmp_path):
    inc = IncrementalNfa(depth=4)
    for i in range(200):
        inc.add(f"r/{i}/+")
    p = str(tmp_path / "seg.npz")
    save_segment(p, inc, deep={}, routing_aids=set())
    inc2 = restore_incremental(load_segment(p))
    d1 = DeviceNfa(inc, active_slots=8, max_matches=16)
    d2 = DeviceNfa(inc2, active_slots=8, max_matches=16)
    from emqx_tpu.ops import encode_batch

    topics = [f"r/{i}/k" for i in range(20)]
    e1 = encode_batch(inc, topics, batch=32)
    e2 = encode_batch(inc2, topics, batch=32)
    r1 = d1.match(*e1)
    r2 = d2.match(*e2)
    assert np.array_equal(np.asarray(r1.matches), np.asarray(r2.matches))
    assert np.array_equal(np.asarray(r1.n_matches),
                          np.asarray(r2.n_matches))


def test_segment_checksum_reject_and_version_skew(tmp_path):
    inc = IncrementalNfa(depth=4)
    inc.add("a/+")
    p = str(tmp_path / "seg.npz")
    save_segment(p, inc, deep={}, routing_aids=set())
    raw = open(p, "rb").read()
    mid = len(raw) // 2
    with open(p, "wb") as f:
        f.write(raw[:mid] + bytes([raw[mid] ^ 0xFF]) + raw[mid + 1:])
    with pytest.raises(SegmentError):
        load_segment(p)
    with pytest.raises(SegmentError):
        load_segment(str(tmp_path / "missing.npz"))


def test_segment_lazy_hydration_defers_trie_relink(tmp_path):
    inc = IncrementalNfa(depth=4)
    for i in range(50):
        inc.add(f"a/{i}/+")
    p = str(tmp_path / "seg.npz")
    save_segment(p, inc, deep={}, routing_aids=set())
    inc2 = restore_incremental(load_segment(p))
    assert inc2._pending_trie is not None and inc2.root is None
    # any mutation/walk entry point hydrates on demand
    assert sorted(inc2.match_host("a/3/k")) == sorted(
        inc.match_host("a/3/k"))
    assert inc2._pending_trie is None and inc2.root is not None


# ---------------------------------------------------------------------------
# dirty-region device upload (grow-in-place instead of full re-upload)
# ---------------------------------------------------------------------------

def test_dirty_region_grow_in_place_skips_full_upload():
    inc = IncrementalNfa(depth=8, state_bucket=64)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    dev.dirty_full_threshold = 1.0   # threshold behavior tested below
    uploads0 = dev.uploads
    # grow the node table past 64 states with a bounded dirty set
    for i in range(120):
        inc.add(f"g/{i}/x/y")
    dev.sync()
    assert dev.uploads == uploads0, "resize paid a full upload"
    assert dev.grow_applies >= 1
    assert dev.dirty_rows_uploaded > 0
    node, edge, _ = (np.asarray(a) for a in dev.arrays())
    assert np.array_equal(node, inc.node_tab)
    assert np.array_equal(edge, inc.edge_tab)


def test_dirty_region_threshold_falls_back_to_full_upload():
    inc = IncrementalNfa(depth=8, state_bucket=64)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    dev.dirty_full_threshold = 0.0001   # everything is "too dirty"
    uploads0 = dev.uploads
    for i in range(120):
        inc.add(f"g/{i}/x/y")
    dev.sync()
    assert dev.uploads > uploads0      # full upload won, correctly
    assert dev.grow_applies == 0
    node, _, _ = (np.asarray(a) for a in dev.arrays())
    assert np.array_equal(node, inc.node_tab)


def test_dirty_region_off_keeps_legacy_full_upload():
    inc = IncrementalNfa(depth=8, state_bucket=64)
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    uploads0 = dev.uploads
    for i in range(120):
        inc.add(f"g/{i}/x/y")
    dev.sync()
    assert dev.uploads > uploads0      # flag-off path byte-identical
    assert dev.grow_applies == 0


def test_compact_forces_full_upload_even_in_region_mode():
    inc = IncrementalNfa(depth=8, state_bucket=64)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    for i in range(50):
        inc.add(f"c/{i}/+")
    dev.sync()
    uploads0 = dev.uploads
    inc.compact()                       # wholesale rebuild: rows moved
    dev.sync()
    assert dev.uploads > uploads0
    node, edge, _ = (np.asarray(a) for a in dev.arrays())
    assert np.array_equal(node, inc.node_tab)
    assert np.array_equal(edge, inc.edge_tab)


# ---------------------------------------------------------------------------
# padded-shape kernel cache (pow2 resize served without a recompile)
# ---------------------------------------------------------------------------

def test_prewarmed_resize_serves_with_zero_compiles():
    """The compile-counter spy of the acceptance criteria: pre-warm the
    next pow2 shape, grow the table across the boundary, and the resize
    dispatch must be a pure cache hit — zero new compiles."""
    from emqx_tpu.ops import encode_batch

    inc = IncrementalNfa(depth=8, state_bucket=64, edge_bucket=1024)
    inc.track_regions = True
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    dev.dirty_regions = True
    kc = MatchKernelCache()
    dev.kernel_cache = kc
    for i in range(20):
        inc.add(f"a/{i}/+")
    dev.sync()
    enc = encode_batch(inc, ["a/3/k"], batch=64)
    np.asarray(dev.match(*enc, flat_cap=8 * 64).matches)   # observe combo
    s, hb, _d = inc.shape_key()
    kc.prewarm_shape(2 * s, hb)         # the next pow2 state shape
    compiles0 = kc.compiles
    hits0 = kc.hits
    for i in range(20):                 # cross the 64-state boundary
        inc.add(f"b/{i}/x")
    dev.sync()
    assert inc.shape_key() == (2 * s, hb, 8)
    enc = encode_batch(inc, ["b/5/x"], batch=64)
    res = dev.match(*enc, flat_cap=8 * 64, block_compile=False)
    np.asarray(res.matches)
    assert kc.compiles == compiles0, "resize serve paid a compile"
    assert kc.hits > hits0


def test_compile_miss_raises_instead_of_stalling():
    from emqx_tpu.ops import encode_batch

    inc = IncrementalNfa(depth=8)
    inc.add("a/+")
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    kc = MatchKernelCache()
    dev.kernel_cache = kc
    enc = encode_batch(inc, ["a/k"], batch=64)
    with pytest.raises(CompileMiss):
        dev.match(*enc, flat_cap=8 * 64, block_compile=False)
    # the miss kicked a background compile: the same key eventually hits
    import time

    for _ in range(400):
        if kc.info()["entries"]:
            break
        time.sleep(0.02)
    np.asarray(dev.match(*enc, flat_cap=8 * 64,
                         block_compile=False).matches)
    assert kc.hits >= 1


# ---------------------------------------------------------------------------
# service lifecycle: cold start, compaction swap, churn-under-serve
# ---------------------------------------------------------------------------

def test_cold_start_from_segment_with_delta_tail(tmp_path):
    async def main():
        b = Broker()
        filters = [f"room/+/k{i}" for i in range(60)]
        subscribe_many(b, filters)
        ms = make_service(b, tmp_path)
        await ms.start()
        assert await settle(lambda: ms._table_gen >= 1)
        await ms.stop()
        # mutate AFTER the segment was written: the delta tail
        b.open_session("late")
        b.subscribe("late", "late/+/f", SubOpts())
        b.unsubscribe("s0", "room/+/k0")
        m2 = Metrics()
        ms2 = make_service(b, tmp_path, metrics=m2,
                           compact_interval_s=30.0,
                           compact_min_mutations=10**9)
        await ms2.start()
        assert ms2._segment_loaded
        assert m2.get("tpu.table.segment_load_s") > 0
        assert await settle(lambda: ms2.ready)
        for t, flt in (("late/1/f", "late/+/f"),
                       ("room/1/k1", "room/+/k1")):
            await ms2.prefetch(t)
            hint = ms2.hint_routes(t)
            want = b.router.match_routes(t)
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        # the unsubscribed filter is gone from the restored table
        assert ms2.inc.aid_of("room/+/k0") < 0
        await ms2.stop()

    run(main())


def test_hint_freshness_preserved_across_segment_swap(tmp_path):
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(30)])
        ms = make_service(b, tmp_path)
        await ms.start()
        assert await settle(lambda: ms.ready)
        topics = [f"room/{i}/k{i % 30}" for i in range(12)]
        await asyncio.gather(*[ms.prefetch(t) for t in topics])
        for t in topics:
            assert ms._hint_fresh(t, ms._hints[t][0])
        gen0 = ms._table_gen
        assert await settle(lambda: ms._table_gen > gen0, timeout=30)
        # hints carry router epochs + filter STRINGS, never aids: the
        # swap must not invalidate a single one
        for t in topics:
            assert t in ms._hints
            assert ms._hint_fresh(t, ms._hints[t][0]), t
            hint = ms.hint_routes(t)
            want = b.router.match_routes(t)
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        await ms.stop()

    run(main())


def test_churn_under_serve_across_swaps_zero_stalls(tmp_path):
    """Sustained add/remove while the deadline loop serves prefetches:
    waiters never resolve past the prefetch budget, segment swaps land
    mid-churn, and every hint consumed has routing parity."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"base/+/k{i}" for i in range(50)])
        ms = make_service(b, tmp_path, deadline=True, deadline_s=0.1)
        await ms.start()
        assert await settle(lambda: ms.ready)
        import time as _time

        waits = []
        for i in range(120):
            cid = f"c{i % 8}"
            if cid not in b.sessions:
                b.open_session(cid)
            if i % 2 == 0:
                b.subscribe(cid, f"churn/{i}/+", SubOpts())
            elif i > 2:
                b.unsubscribe(f"c{(i - 2) % 8}", f"churn/{i - 2}/+")
            t0 = _time.perf_counter()
            await ms.prefetch(f"serve/{i}/x")
            waits.append(_time.perf_counter() - t0)
        assert ms._table_gen >= 1, "no swap landed during the churn"
        budget = ms.prefetch_timeout_s * 0.9
        assert max(waits) < budget, (max(waits), budget)
        # post-churn parity through the swapped table
        await ms.prefetch("base/9/k9")
        hint = ms.hint_routes("base/9/k9")
        want = b.router.match_routes("base/9/k9")
        assert hint is not None
        assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        await ms.stop()

    run(main())


def test_swap_discards_inflight_batch_via_gen_guard(tmp_path):
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(10)])
        ms = make_service(b, tmp_path, compact_interval_s=30.0)
        await ms.start()
        assert await settle(lambda: ms.ready)
        from emqx_tpu.broker.match_service import _StaleRace

        fut = asyncio.ensure_future(ms._device_serve(["a/1/k1"]))
        await asyncio.sleep(0)         # let it capture gen0
        ms._table_gen += 1             # a swap landed mid-flight
        with pytest.raises(_StaleRace):
            await fut
        await ms.stop()

    run(main())


def test_rules_remap_across_swap(tmp_path):
    async def main():
        b = Broker()
        subscribe_many(b, [f"r/+/k{i}" for i in range(10)])
        ms = make_service(b, tmp_path, compact_interval_s=30.0,
                          compact_min_mutations=1)
        await ms.start()
        assert await settle(lambda: ms.ready)
        ms.register_rule("rule1", ["rule/+/from"])
        assert await settle(lambda: ms._seen_epoch == b.router.epoch)
        ok = await ms._compact_once()
        assert ok and ms._table_gen == 1
        # the rule's aid was remapped into the fresh table's id space
        aid = ms.inc.aid_of("rule/+/from")
        assert aid >= 0 and ms._aid_rules.get(aid) == {"rule1"}
        await ms.prefetch("rule/9/from")
        assert ms.hint_rules("rule/9/from") == ["rule1"]
        await ms.stop()

    run(main())


def test_flag_off_structures_inert():
    b = Broker()
    ms = MatchService(b, depth=8, table="python")
    assert not ms.segments
    assert ms.kcache is None
    assert not ms.dev.dirty_regions
    assert not getattr(ms.inc, "track_regions", False)
    # no compact/hydrate/prewarm machinery arms without the flag
    assert ms._table_gen == 0 and ms._mut_count == 0


def test_xla_cache_dir_configured_under_segments_dir(tmp_path,
                                                    monkeypatch):
    """match.segments.xla_cache (ROADMAP table-lifecycle leftover (d)):
    the persistent XLA compilation cache lands under the segments dir
    so even the FIRST cold-start compile is a disk hit."""
    import jax

    from emqx_tpu.node import enable_xla_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        path = os.path.join(str(tmp_path), "segments", "xla_cache")
        assert enable_xla_cache(path)
        assert jax.config.jax_compilation_cache_dir == path
        assert os.path.isdir(path)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_xla_cache_config_key_registered():
    from emqx_tpu.config import SCHEMA

    field = SCHEMA["match.segments.xla_cache"]
    assert field.default is True


def test_node_wires_xla_cache_only_with_segments_enabled(monkeypatch):
    """The node start path calls enable_xla_cache iff segments AND the
    xla_cache key are on, rooted under the segments dir."""
    import emqx_tpu.node as node_mod
    from emqx_tpu.config import Config

    calls = []
    monkeypatch.setattr(node_mod, "enable_xla_cache",
                        lambda p: calls.append(p) or True)

    class _Cfg:
        def __init__(self, overrides):
            self._c = Config()
            self._o = overrides

        def get(self, key):
            return self._o.get(key, self._c.get(key))

    async def probe(overrides):
        calls.clear()
        n = node_mod.BrokerNode.__new__(node_mod.BrokerNode)
        n.config = _Cfg(overrides)
        await n._start_match_service()

    # tpu.enable off: nothing runs (the early return)
    run(probe({"tpu.enable": False, "match.segments.enable": True}))
    assert calls == []
    # segments off: no cache dir either
    run(probe({"tpu.enable": True, "match.segments.enable": False,
               "tpu.start_timeout": 0.001}))
    assert calls == []
    # segments on + xla_cache off: skipped
    run(probe({"tpu.enable": True, "match.segments.enable": True,
               "match.segments.xla_cache": False,
               "match.segments.dir": "/tmp/segdir",
               "tpu.start_timeout": 0.001}))
    assert calls == []
    # segments on + xla_cache on (default): rooted under segments dir
    run(probe({"tpu.enable": True, "match.segments.enable": True,
               "match.segments.dir": "/tmp/segdir",
               "tpu.start_timeout": 0.001}))
    assert calls == [os.path.join("/tmp/segdir", "xla_cache")]
