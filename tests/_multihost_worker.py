"""Subprocess body for the REAL two-process ``jax.distributed`` test.

Each worker pins itself to a 4-device virtual CPU backend, joins the
coordination service, builds the hybrid ICI x DCN mesh through
``MultihostRuntime`` (the exact production entry point), and executes
cross-process collectives whose results it asserts locally.  The parent
test only checks exit codes + the OK marker — all numeric assertions
happen inside the distributed processes themselves, like the
reference's CT peer-node suites (SURVEY.md §4: multi-node on one host).

Usage: python _multihost_worker.py <rank> <num_processes> <port>
"""

import os
import re
import sys


def main() -> None:
    rank, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", "")).strip()
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

    import jax  # noqa: E402
    import numpy as np  # noqa: E402

    # this box's sitecustomize rewrites jax_platforms to "axon,cpu" for
    # every interpreter; re-pin (same dance as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from emqx_tpu.parallel.multihost import MultihostRuntime

    rt = MultihostRuntime.from_env(
        coordinator=f"127.0.0.1:{port}",
        num_processes=nproc, process_id=rank)
    assert rt.initialized, "two-process bootstrap fell back to passthrough"
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.process_index() == rank
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 4 * nproc, "global device view incomplete"
    assert rt.is_coordinator() == (rank == 0)

    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    # hybrid mesh: dp (outer, crosses processes = DCN), tp (inner = ICI)
    mesh = rt.hybrid_mesh({"tp": 4}, dcn_axis="dp")
    assert dict(mesh.shape) == {"dp": nproc, "tp": 4}, dict(mesh.shape)
    # outer-axis rows must each live on ONE process (DCN only between rows)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1, mesh.devices

    base = np.arange(nproc * 4, dtype=np.float32).reshape(nproc, 4)
    arr = jax.make_array_from_callback(
        base.shape, NamedSharding(mesh, P("dp", "tp")),
        lambda idx: base[idx])

    # collective 1: jitted global sum (all-reduce across both fabrics);
    # the scalar result is fully replicated, so every process can read
    # its own addressable copy
    total = jax.jit(lambda x: x.sum())(arr)
    got = float(np.asarray(total.addressable_shards[0].data))
    assert got == float(base.sum()), (got, base.sum())

    # collective 2: explicit psum over the DCN axis via shard_map
    g = shard_map(lambda b: jax.lax.psum(b, "dp"), mesh=mesh,
                  in_specs=P("dp", "tp"), out_specs=P(None, "tp"))
    out = g(arr)
    col_sums = base.sum(axis=0)
    for shard in out.addressable_shards:
        local = np.asarray(shard.data).ravel()
        tp_col = shard.index[1].start or 0
        assert np.allclose(local, col_sums[tp_col:tp_col + local.size]), (
            rank, local, col_sums)

    # collective 3: ppermute ring over the cross-process axis — the
    # ring_fanout tile-rotation schedule's fabric, proven on real DCN
    ring = shard_map(
        lambda b: jax.lax.ppermute(
            b, "dp", [(i, (i + 1) % nproc) for i in range(nproc)]),
        mesh=mesh, in_specs=P("dp", "tp"), out_specs=P("dp", "tp"))
    rolled = ring(arr)
    want_rolled = np.roll(base, 1, axis=0)
    for shard in rolled.addressable_shards:
        assert np.allclose(np.asarray(shard.data),
                           want_rolled[shard.index]), (
            rank, shard.index, np.asarray(shard.data))

    jax.distributed.shutdown()
    print(f"MULTIHOST_OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
