"""Wire codec tests: explicit spec vectors + parse∘serialize round-trip
property tests (prop_emqx_frame style, SURVEY.md §4)."""

import pytest
from _optional import given, settings, st

from emqx_tpu.mqtt import FrameError, Parser, parse_one, serialize
from emqx_tpu.mqtt import packet as P


def roundtrip(pkt, ver=4):
    return parse_one(serialize(pkt, ver), ver)


# ---------------------------------------------------------------------------
# explicit vectors
# ---------------------------------------------------------------------------

def test_connect_311_wire():
    # canonical 3.1.1 CONNECT from the spec examples
    raw = serialize(P.Connect(clientid="c1", keepalive=30))
    assert raw[0] == 0x10
    pkt = parse_one(raw)
    assert pkt.clientid == "c1" and pkt.proto_ver == 4 and pkt.clean_start


def test_connect_with_will_and_auth():
    pkt = P.Connect(
        clientid="c", clean_start=False, keepalive=10,
        will=P.Will("w/t", b"bye", qos=1, retain=True),
        username="u", password=b"p",
    )
    got = roundtrip(pkt)
    assert got == pkt


def test_connect_v5_properties():
    pkt = P.Connect(
        proto_ver=5, clientid="c5",
        properties={"Session-Expiry-Interval": 3600, "Receive-Maximum": 20,
                    "User-Property": [("a", "1"), ("a", "2")]},
        will=P.Will("w", b"x", properties={"Will-Delay-Interval": 5}),
    )
    assert roundtrip(pkt, 5) == pkt


def test_publish_qos_levels():
    p0 = P.Publish(topic="t", qos=0, payload=b"hello")
    assert roundtrip(p0) == p0
    p1 = P.Publish(topic="t", qos=1, packet_id=7, payload=b"x", dup=True, retain=True)
    assert roundtrip(p1) == p1
    with pytest.raises(FrameError):
        serialize(P.Publish(topic="t", qos=1, packet_id=None))


def test_publish_v5_topic_alias():
    p = P.Publish(topic="", qos=0, payload=b"z", properties={"Topic-Alias": 4})
    assert roundtrip(p, 5) == p


def test_puback_family():
    for t in (P.PUBACK, P.PUBREC, P.PUBREL, P.PUBCOMP):
        pkt = P.PubAck(t, packet_id=9)
        got = roundtrip(pkt)
        assert got.type == t and got.packet_id == 9
    v5 = P.PubAck(P.PUBACK, 3, P.RC.NO_MATCHING_SUBSCRIBERS, {"Reason-String": "n"})
    assert roundtrip(v5, 5) == v5


def test_pubrel_flags_enforced():
    raw = bytearray(serialize(P.PubAck(P.PUBREL, 1)))
    assert raw[0] == 0x62
    raw[0] = 0x60  # clear required 0b0010 flags
    with pytest.raises(FrameError):
        parse_one(bytes(raw))


def test_subscribe_roundtrip_v3_v5():
    s3 = P.Subscribe(packet_id=5, topic_filters=[("a/+", {"qos": 1}), ("b/#", {"qos": 2})])
    g3 = roundtrip(s3)
    assert [(f, o["qos"]) for f, o in g3.topic_filters] == [("a/+", 1), ("b/#", 2)]
    s5 = P.Subscribe(
        packet_id=5,
        topic_filters=[("a", {"qos": 1, "nl": 1, "rap": 1, "rh": 2})],
        properties={"Subscription-Identifier": 99},
    )
    assert roundtrip(s5, 5) == s5


def test_empty_subscribe_is_protocol_error():
    raw = serialize(P.Subscribe(packet_id=1, topic_filters=[("a", {"qos": 0})]))
    # strip the single filter (2+1 utf8 len + 1 opts byte = 4+... ) manually:
    bad = bytes([0x82, 2, 0, 1])
    with pytest.raises(FrameError):
        parse_one(bad)


def test_suback_unsub_roundtrip():
    sa = P.Suback(packet_id=2, reason_codes=[0, 1, 0x80])
    assert roundtrip(sa) == sa
    u = P.Unsubscribe(packet_id=3, topic_filters=["a", "b/#"])
    assert roundtrip(u) == u
    ua5 = P.Unsuback(packet_id=3, reason_codes=[0, 17])
    assert roundtrip(ua5, 5) == ua5


def test_ping_disconnect_auth():
    assert roundtrip(P.PingReq()).type == P.PINGREQ
    assert roundtrip(P.PingResp()).type == P.PINGRESP
    d = P.Disconnect(reason_code=P.RC.SESSION_TAKEN_OVER, properties={"Reason-String": "t"})
    assert roundtrip(d, 5) == d
    assert roundtrip(P.Disconnect()).reason_code == 0
    a = P.Auth(reason_code=0x18, properties={"Authentication-Method": "SCRAM"})
    assert roundtrip(a, 5) == a


# ---------------------------------------------------------------------------
# streaming / incremental
# ---------------------------------------------------------------------------

def test_streaming_partial_feed():
    raw = serialize(P.Publish(topic="t/1", qos=1, packet_id=2, payload=b"abc"))
    raw += serialize(P.PingReq())
    p = Parser()
    got = []
    for i in range(len(raw)):
        got += p.feed(raw[i : i + 1])  # one byte at a time
    assert [g.type for g in got] == [P.PUBLISH, P.PINGREQ]
    assert got[0].payload == b"abc"


def test_parser_upgrades_to_v5_after_connect():
    p = Parser()
    c = P.Connect(proto_ver=5, clientid="x")
    pub5 = P.Publish(topic="t", payload=b"", properties={"Topic-Alias": 1})
    got = p.feed(serialize(c, 5) + serialize(pub5, 5))
    assert got[0].proto_ver == 5
    assert got[1].properties == {"Topic-Alias": 1}


def test_max_packet_size_enforced():
    p = Parser(max_packet_size=16)
    big = serialize(P.Publish(topic="t", payload=b"x" * 64))
    with pytest.raises(FrameError) as e:
        p.feed(big)
    assert e.value.reason_code == P.RC.PACKET_TOO_LARGE


def test_malformed_qos3():
    raw = bytearray(serialize(P.Publish(topic="t", qos=2, packet_id=1)))
    raw[0] |= 0x06  # qos bits = 3
    with pytest.raises(FrameError):
        parse_one(bytes(raw))


def test_connect_reserved_flag():
    raw = bytearray(serialize(P.Connect(clientid="c")))
    # connect flags byte: proto name(6) + ver(1) -> offset = 2 + 6 + 1
    raw[9] |= 0x01
    with pytest.raises(FrameError):
        parse_one(bytes(raw))


# ---------------------------------------------------------------------------
# property round-trips
# ---------------------------------------------------------------------------

topic_st = st.text(
    alphabet=st.characters(min_codepoint=33, max_codepoint=126), min_size=1, max_size=12
)
payload_st = st.binary(max_size=64)


@settings(max_examples=200, deadline=None)
@given(topic_st, payload_st, st.integers(0, 2), st.booleans(), st.booleans())
def test_publish_roundtrip_prop(topic, payload, qos, dup, retain):
    pkt = P.Publish(
        topic=topic, qos=qos, payload=payload, dup=dup, retain=retain,
        packet_id=11 if qos else None,
    )
    for ver in (4, 5):
        assert roundtrip(pkt, ver) == pkt


@settings(max_examples=100, deadline=None)
@given(
    st.text(max_size=10), st.integers(0, 0xFFFF), st.booleans(),
    st.one_of(st.none(), st.text(max_size=5)),
    st.one_of(st.none(), st.binary(max_size=5)),
)
def test_connect_roundtrip_prop(cid, keepalive, clean, user, pw):
    pkt = P.Connect(
        clientid=cid, keepalive=keepalive, clean_start=clean,
        username=user, password=pw,
    )
    assert roundtrip(pkt) == pkt


@settings(max_examples=100, deadline=None)
@given(st.lists(st.tuples(topic_st, st.integers(0, 2)), min_size=1, max_size=5))
def test_subscribe_roundtrip_prop(filters):
    pkt = P.Subscribe(
        packet_id=1, topic_filters=[(f, {"qos": q}) for f, q in filters]
    )
    assert roundtrip(pkt) == pkt


@settings(max_examples=60, deadline=None)
@given(st.binary(max_size=200))
def test_parser_never_crashes_on_garbage(data):
    """Garbage either parses, needs more bytes, or raises FrameError —
    never any other exception (the connection layer maps FrameError to a
    DISCONNECT)."""
    p = Parser()
    try:
        p.feed(data)
    except FrameError:
        pass
