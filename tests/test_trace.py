"""Tracing subsystem: lifecycle + captured content + REST download
(emqx_trace analog; SURVEY.md §5.1)."""

import asyncio
import json
import time

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(tmp_path, extra=""):
    cfg = Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n' + extra))
    node = BrokerNode(cfg)
    node.tracing.dir = str(tmp_path)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


def test_clientid_trace_captures_lifecycle_and_messages(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        try:
            node.tracing.create("t1", "clientid", "dev-1")
            c = Client(clientid="dev-1", port=port_of(node))
            await c.connect()
            await c.subscribe("room/+")
            await c.publish("hall/x", b"from-dev1")
            other = Client(clientid="other", port=port_of(node))
            await other.connect()
            await other.publish("room/5", b"ignored-sender")
            msg = await c.recv()
            assert msg.topic == "room/5"
            await c.disconnect()
            await other.disconnect()
            await asyncio.sleep(0.05)

            lines = [json.loads(x) for x in
                     node.tracing.read("t1").decode().splitlines()]
            events = [x["event"] for x in lines]
            assert "client.connected" in events
            assert "subscribe" in events
            assert "publish" in events       # dev-1's own publish
            assert "deliver" in events       # room/5 delivered TO dev-1
            assert "client.disconnected" in events
            # other's publish traced only as the delivery to dev-1
            pub_clients = {x["clientid"] for x in lines
                           if x["event"] == "publish"}
            assert pub_clients == {"dev-1"}
        finally:
            await node.stop()

    run(main())


def test_topic_trace_filters_by_wildcard(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        try:
            node.tracing.create("byt", "topic", "sensors/#")
            c = Client(clientid="p", port=port_of(node))
            await c.connect()
            await c.publish("sensors/a/temp", b"1")
            await c.publish("unrelated/topic", b"2")
            await c.disconnect()
            for _ in range(100):  # qos0 is fire-and-forget: wait for tap
                if node.tracing.traces["byt"].events:
                    break
                await asyncio.sleep(0.01)
            lines = [json.loads(x) for x in
                     node.tracing.read("byt").decode().splitlines()]
            topics = {x["topic"] for x in lines if x["event"] == "publish"}
            assert topics == {"sensors/a/temp"}
        finally:
            await node.stop()

    run(main())


def test_trace_window_and_stop(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        try:
            tr = node.tracing.create("w", "clientid", "x",
                                     start_at=time.time() + 3600)
            assert tr.info()["status"] == "waiting"
            node.tracing.stop("w")
            assert tr.info()["status"] == "stopped"
            # stopped trace captures nothing
            c = Client(clientid="x", port=port_of(node))
            await c.connect()
            await c.disconnect()
            assert node.tracing.read("w") == b""
            assert node.tracing.delete("w")
            assert node.tracing.list() == []
        finally:
            await node.stop()

    run(main())


def test_trace_rest_lifecycle(tmp_path):
    async def main():
        from emqx_tpu.bridge import httpc

        node = await start_node(
            tmp_path,
            'dashboard.enable = true\ndashboard.auth = false\n'
            'dashboard.listen = "127.0.0.1:0"\n')
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("POST", f"{base}/trace", body=json.dumps(
                {"name": "rt", "type": "clientid", "clientid": "c9"}
            ).encode())
            assert r.status == 201

            c = Client(clientid="c9", port=port_of(node))
            await c.connect()
            await c.publish("a/b", b"x")
            await c.disconnect()

            r = await httpc.request("GET", f"{base}/trace")
            assert json.loads(r.body)[0]["name"] == "rt"
            r = await httpc.request("GET", f"{base}/trace/rt/download")
            events = [json.loads(x) for x in r.body.decode().splitlines()]
            assert any(e["event"] == "publish" for e in events)
            r = await httpc.request("PUT", f"{base}/trace/rt/stop", body=b"")
            assert json.loads(r.body)["status"] == "stopped"
            r = await httpc.request("DELETE", f"{base}/trace/rt")
            assert r.status == 204
        finally:
            await node.stop()

    run(main())


def test_trace_name_and_window_validation(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        try:
            for bad in ("a/b", "x\r\ny", 'q"w', "", "../up", ".hidden"):
                with pytest.raises(ValueError):
                    node.tracing.create(bad, "clientid", "c")
            # non-numeric window from REST-ish input raises, not poisons
            with pytest.raises((TypeError, ValueError)):
                node.tracing.create("ok1", "clientid", "c",
                                    start_at="not-a-time")
            assert "ok1" not in node.tracing.traces
        finally:
            await node.stop()

    run(main())
