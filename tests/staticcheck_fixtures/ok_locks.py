"""Must PASS await-under-lock: a deadline wrapper around the one
exchange the lock serializes, and waits with no lock held."""
import asyncio


class C:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._evt = asyncio.Event()

    async def guarded(self, op):
        async with self._lock:
            return await asyncio.wait_for(op(), 1.0)

    async def unguarded_wait(self):
        await self._evt.wait()
        await asyncio.sleep(0)
