"""Must TRIP await-under-lock: task-waits and nested locks held."""
import asyncio


class C:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._other_lock = asyncio.Lock()
        self._evt = asyncio.Event()

    async def bad_sleep(self):
        async with self._lock:
            await asyncio.sleep(1)

    async def bad_wait(self):
        async with self._lock:
            await self._evt.wait()

    async def bad_nested(self):
        async with self._lock:
            async with self._other_lock:
                pass
