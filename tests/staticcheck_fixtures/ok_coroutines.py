"""Must PASS unawaited-coroutine: awaited, returned, or task-wrapped."""


async def helper():
    pass


async def main(supervisor):
    await helper()
    supervisor.start_child("h", helper)
    return helper()


class C:
    async def flush(self):
        pass

    async def tick(self):
        await self.flush()

    def name_shadow(self, flush):
        flush()  # plain callable param, not the async method


class Base:
    async def aclose(self):
        pass


class E(Base):
    async def shutdown(self):
        await self.aclose()  # inherited, awaited


class F(Base):
    def aclose(self):  # sync override shadows the async base method
        pass

    def shutdown(self):
        self.aclose()  # resolves to the SYNC override via the MRO
