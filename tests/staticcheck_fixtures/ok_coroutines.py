"""Must PASS unawaited-coroutine: awaited, returned, or task-wrapped."""


async def helper():
    pass


async def main(supervisor):
    await helper()
    supervisor.start_child("h", helper)
    return helper()


class C:
    async def flush(self):
        pass

    async def tick(self):
        await self.flush()

    def name_shadow(self, flush):
        flush()  # plain callable param, not the async method
