"""Must TRIP no-swallowed-exceptions (when placed on a delivery path):
broad handlers whose body drops the error, and narrow silent handlers
with no written-down reason (3 findings)."""


def deliver(batch):
    for item in batch:
        try:
            item.send()
        except Exception:
            continue
    try:
        batch.flush()
    except:  # noqa: E722
        pass


def commit(batch):

    try:
        batch.commit()
    except ValueError:
        pass
