"""Must TRIP no-swallowed-exceptions (when placed on a delivery path):
broad handlers whose body drops the error."""


def deliver(batch):
    for item in batch:
        try:
            item.send()
        except Exception:
            continue
    try:
        batch.flush()
    except:  # noqa: E722
        pass
