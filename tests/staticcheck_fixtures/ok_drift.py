"""Must PASS registry-drift: registered names only, and a matched
activate/deactivate pair (literal + f-string prefix)."""


def f(metrics, cfg, alarms, hooks, _injector, name):
    metrics.inc("messages.delivered")
    metrics.set("broker.fanout.depth", 3)
    metrics.get("broker.supervisor.restarts")
    # kernel-backend routing literals (ISSUE 13)
    metrics.inc("tpu.match.backend_join_dispatches")
    metrics.inc("tpu.match.autotune_picks")
    cfg.get("match.backend")
    cfg.get("match.autotune.enable")
    cfg.get("match.autotune.reps")
    cfg.get("mqtt.max_inflight")
    _injector.check("fanout.drain")
    alarms.activate("overload_fixture", {}, "hot")
    alarms.deactivate("overload_fixture")
    alarms.activate(f"degraded_fixture:{name}", {}, "bad")
    alarms.deactivate(f"degraded_fixture:{name}")
    hooks.run("message.dropped", (None, "queue_full"))
    hooks.run("message.dropped", (None, "shared_no_available"))
    # batched admission plane literals (ISSUE 14)
    metrics.inc("broker.admission.shed_qos0")
    metrics.set("broker.admission.tracked_clients", 0)
    cfg.get("admission.enable")
    cfg.get("admission.tick")
    cfg.get("admission.max_topic_fan")
    _injector.check("admission.score")
    alarms.activate("admission_degraded", {}, "scorer down")
    alarms.deactivate("admission_degraded")
    alarms.activate("admission_quarantine", {}, "clients quarantined")
    alarms.deactivate("admission_quarantine")
    hooks.run("message.dropped", (None, "admission_shed"))
    # multichip EP routing literals (ISSUE 16)
    metrics.inc("tpu.match.ep_dispatches")
    metrics.inc("tpu.match.ep_overflow_rows")
    metrics.set("tpu.match.ep_shard_width", 0)
    metrics.inc("tpu.match.ep_ici_bytes")
    cfg.get("match.multichip.native")
    cfg.get("match.multichip.ep.enable")
    cfg.get("match.multichip.ep.capacity_slack")
    cfg.get("match.multichip.ep.micro_matches")
    _injector.check("ep.route")


def g(hooks):
    hooks.add("client.connected", lambda *a: None)
    hooks.run_fold("client.authenticate", (None, None, None, {}), True)
    hooks.has("message.delivered")


def h(hists, flightrec):
    hists.hist("obs.stage.match_dispatch")
    hists.hist("obs.e2e.publish_deliver")
    flightrec.dump("breaker_trip")
    flightrec.dump("manual")
    flightrec.dump("admission_escalation")
