"""Must PASS registry-drift: registered names only, and a matched
activate/deactivate pair (literal + f-string prefix)."""


def f(metrics, cfg, alarms, hooks, _injector, name):
    metrics.inc("messages.delivered")
    metrics.set("broker.fanout.depth", 3)
    metrics.get("broker.supervisor.restarts")
    # kernel-backend routing literals (ISSUE 13)
    metrics.inc("tpu.match.backend_join_dispatches")
    metrics.inc("tpu.match.autotune_picks")
    cfg.get("match.backend")
    cfg.get("match.autotune.enable")
    cfg.get("match.autotune.reps")
    cfg.get("mqtt.max_inflight")
    _injector.check("fanout.drain")
    alarms.activate("overload_fixture", {}, "hot")
    alarms.deactivate("overload_fixture")
    alarms.activate(f"degraded_fixture:{name}", {}, "bad")
    alarms.deactivate(f"degraded_fixture:{name}")
    hooks.run("message.dropped", (None, "queue_full"))
    hooks.run("message.dropped", (None, "shared_no_available"))


def g(hooks):
    hooks.add("client.connected", lambda *a: None)
    hooks.run_fold("client.authenticate", (None, None, None, {}), True)
    hooks.has("message.delivered")


def h(hists, flightrec):
    hists.hist("obs.stage.match_dispatch")
    hists.hist("obs.e2e.publish_deliver")
    flightrec.dump("breaker_trip")
    flightrec.dump("manual")
