"""Fixture: use-after-donate MUST flag these (2 findings)."""


def nfa_match_donated(words, lens, is_sys, table):
    return words


class KernelCache:
    def executable(self, key, donate=False):
        return nfa_match_donated


def serve_batch(words, lens, is_sys, table):
    # (1) the donated twin aliases the words/lens/is_sys buffers into
    # its output; reading `words` afterwards observes freed storage
    out = nfa_match_donated(words, lens, is_sys, table)
    return out, words.sum()


def serve_cached(kc, words, lens, is_sys):
    # (2) a donate-keyed executable is the same seam under an alias:
    # the SECOND dispatch hands the already-donated buffers back in
    fn = kc.executable(1, donate=True)
    m = fn(words, lens, is_sys)
    counts = fn(words, lens, is_sys)
    return m, counts
