"""Fixture: await-torn-read must NOT flag any of these."""


class ShardPool:
    async def _main_handle(self, sess):
        # both group fields read inside ONE critical section; the
        # await comes after the invariant was observed atomically
        with sess.mutex:
            n = len(sess.inflight) + len(sess.mqueue)
        await self.flush()
        return n

    async def flush(self):
        pass

    async def _consume(self, sess, runs):
        # suspension BEFORE the reads: the pair is taken in one
        # uninterrupted stretch of the coroutine
        await self.flush()
        return len(sess.inflight) + len(sess.mqueue)


async def probe(sess):
    # unreached from any main entry: no loop can interleave a mutator
    a = len(sess.inflight)
    await sess.drain()
    return a + len(sess.mqueue)
