"""Fixture: host-sync-in-loop MUST flag these (4 findings)."""

import jax
import numpy as np


class ShardChannel:
    def handle_ack_run(self, acks):
        # shard-loop entry (declared seed): both syncs stall the
        # shard's event loop for a device round trip
        host = jax.device_get(acks)       # (1)
        acks.block_until_ready()          # (2)
        return host


class ShardPool:
    def _main_handle(self, batch):
        # main-loop entry (declared seed): the h2d transfer and the
        # d2h copy np.asarray forces both block the broker loop
        dev = jax.device_put(batch)       # (3)
        rows = np.asarray(dev)            # (4) d2h of a device value
        return rows
