"""Fixture: GENERATED shard-affinity seeds must NOT flag these — the
shard-legal handler only touches RLock-set session state under the
mutex (the documented pattern), and marshals broker work instead."""

import threading


class P:
    PUBACK = 4
    SUBSCRIBE = 8


_SHARD_LOCAL = frozenset((P.PUBACK,))


class Broker:
    def __init__(self):
        self.routes = {}


class Session:
    def __init__(self):
        self.inflight = {}


class Channel:
    def __init__(self, broker, session, pool):
        self.broker = broker
        self.session = session
        self.pool = pool
        self.mutex = threading.RLock()

    def handle_in(self, pkt):
        handler = {
            P.PUBACK: self._handle_puback,
            P.SUBSCRIBE: self._handle_subscribe,
        }.get(pkt.type)
        return handler(pkt)

    def _handle_puback(self, pkt):
        # shard-legal by generation: RLock-set field under the mutex
        with self.mutex:
            self.session.inflight[1] = pkt
        # broker-touching work marshals instead of writing
        self.pool.marshal(self, pkt)

    def _handle_subscribe(self, pkt):
        # not shard-local: main-loop-only, broker writes are its job
        self.broker.routes["x"] = pkt
