"""Fixture: shard-affinity must NOT flag the disciplined pipeline
shape — worker stages are pure compute against captured arguments;
every broker/service write happens back on the event loop."""

import asyncio


class Broker:
    def __init__(self):
        self.routes = {}


class MatchPipeline:
    def __init__(self, broker):
        self.broker = broker

    async def dispatch(self, topics):
        rows = await asyncio.to_thread(self._encode_worker, topics)
        # loop side: minting the answer into broker state is legal here
        self.broker.routes["hint"] = rows
        return rows

    def _encode_worker(self, topics):
        # thread side: reads its arguments, writes nothing shared
        return [t.upper() for t in topics]
