"""Fixture: object-sensitive lock-order must NOT flag this.

A lock chain across three classes: Writer._lock → Journal.mutex →
Index._lock.  Name-keyed identity aliased the two unrelated ``_lock``
attrs into one node and reported a false ``_lock ⇄ mutex`` cycle;
keyed on (owner class, attr) the chain is acyclic."""

import threading


class Index:
    def __init__(self):
        self._lock = threading.Lock()


class Journal:
    def __init__(self):
        self.mutex = threading.Lock()
        self.index = Index()

    def rotate(self):
        with self.mutex:
            with self.index._lock:
                return 1


class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self.journal = Journal()

    def append(self):
        with self._lock:
            with self.journal.mutex:
                return 2
