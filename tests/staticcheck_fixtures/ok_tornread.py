"""Fixture: torn-read must NOT flag any of these."""

import threading


class Session:
    def __init__(self):
        self.inflight = {}
        self.mqueue = []
        self.mutex = None


class ShardChannel:
    def __init__(self, session):
        self.session = session
        self.mutex = threading.RLock()

    def check_keepalive(self):
        # both group fields read inside ONE critical section: the
        # documented shard-side pattern
        with self.mutex:
            return bool(self.session.inflight) or bool(
                self.session.mqueue)

    def retry_deliveries(self):
        # single-field read: no multi-field invariant to tear
        with self.mutex:
            return len(self.session.inflight)


def fanout_deliver(sess):
    # unreached from any shard/thread entry: main-loop readers see a
    # single-threaded view and need no lock
    return len(sess.inflight) + len(sess.mqueue)
