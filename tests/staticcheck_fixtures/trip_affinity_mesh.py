"""Fixture: multichip mesh worker threads (ISSUE 15) — a
``to_thread``-entered partition-apply stage touching Broker state MUST
trip shard-affinity (1 finding).  The mesh matcher owns its own
subtables under its lock; hint minting, epochs, and readiness flips
stay on the event loop."""

import asyncio
import threading


class Broker:
    def __init__(self):
        self.routes = {}


class ShardedMatcher:
    def __init__(self, broker):
        self.broker = broker
        self._lock = threading.Lock()
        self.subtables = {}

    async def sync_once(self):
        await asyncio.to_thread(self.apply_worker)

    def apply_worker(self):
        with self._lock:
            self.subtables["shard0"] = [1, 2, 3]
        # (1) Broker state is main-loop-only: the mesh partition-apply
        # worker must hand results back to the sync loop, never mint
        # routes/hints into broker state from the apply thread
        self.broker.routes["hint"] = list(self.subtables)
