"""Fixture: host-sync-in-loop must NOT flag any of these."""

import jax
import numpy as np


class MatchService:
    def _encode_dispatch(self, reqs):
        # thread-plane worker (the to_thread contract, a declared
        # seed): syncing the device IS the worker's job — the spawn
        # boundary keeps the stall off every loop
        enc = jax.device_put(reqs)
        return np.asarray(enc)


class ShardPool:
    def _main_handle(self, batch):
        # np.asarray over a HOST value: no device round trip, no sync
        rows = np.asarray(batch)
        return rows


def debug_dump(arr):
    # unreached from any loop entry: a cold debugging helper may
    # block its caller
    arr.block_until_ready()
    return jax.device_get(arr)
