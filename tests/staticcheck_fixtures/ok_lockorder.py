"""Fixture: lock-order must NOT flag any of these."""

import threading


class Pair:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.mutex = threading.RLock()

    def fwd(self):
        # one global order, everywhere: a_lock before b_lock
        with self.a_lock:
            with self.b_lock:
                return 1

    def also_fwd(self):
        with self.a_lock:
            return self._grab_b()

    def _grab_b(self):
        with self.b_lock:
            return 2

    def reentrant(self):
        # same-name nesting is the re-entrant RLock pattern, not an
        # ordering edge
        with self.mutex:
            with self.mutex:
                return 3
