"""Fixture: lock-order MUST flag this (1 cycle finding).

``fwd`` nests a_lock → b_lock directly; ``rev`` holds b_lock while
calling a helper that acquires a_lock (the edge crosses a resolved
call).  Interleaved, the two paths deadlock."""

import threading


class Pair:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def fwd(self):
        with self.a_lock:
            with self.b_lock:
                return 1

    def rev(self):
        with self.b_lock:
            return self._grab_a()

    def _grab_a(self):
        with self.a_lock:
            return 2
