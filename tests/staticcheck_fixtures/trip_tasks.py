"""Must TRIP no-unsupervised-task: raw spawns with no supervised path."""
import asyncio


async def boot():
    asyncio.create_task(work())
    asyncio.ensure_future(work())
    asyncio.get_running_loop().create_task(work())


async def work():
    pass
