"""Fixture: use-after-donate must NOT flag any of these."""


def nfa_match(words, lens, is_sys, table):
    return words


def nfa_match_donated(words, lens, is_sys, table):
    return words


class KernelCache:
    def executable(self, key, donate=False):
        return nfa_match_donated


def serve_rebind(words, lens, is_sys, table):
    # the rebind idiom: the name now holds the RESULT buffer, so the
    # later read is of live storage — clean by construction
    words = nfa_match_donated(words, lens, is_sys, table)
    return words.sum()


def serve_result_only(words, lens, is_sys, table):
    # donated operands never read again: the steady-state serve shape
    m = nfa_match_donated(words, lens, is_sys, table)
    return m


def serve_undonated(kc, words, lens, is_sys):
    # donate=False keys the UNdonated executable: re-dispatch is fine
    fn = kc.executable(1, donate=False)
    m = fn(words, lens, is_sys)
    counts = fn(words, lens, is_sys)
    return m, counts


def serve_dispatch(words, lens, is_sys, table, donate_inputs):
    # the real tree's branch-dispatch shape: each return ends its
    # path, so the donation cannot be reused on any path
    fn = nfa_match_donated if donate_inputs else nfa_match
    if donate_inputs:
        return fn(words, lens, is_sys, table)
    return fn(words, lens, is_sys, table)
