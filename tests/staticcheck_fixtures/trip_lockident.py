"""Fixture: object-sensitive lock-order MUST flag this (1 cycle).

Two DIFFERENT classes each own a ``_lock``: ``Front.push`` takes
Front's then Back's, ``Back.drain`` takes Back's then Front's.
Name-keyed identity saw same-name nesting (the re-entrant RLock
pattern) and suppressed both edges — a missed deadlock; keying on
(owner class, attr) yields Front._lock ⇄ Back._lock."""

import threading


class Back:
    def __init__(self):
        self._lock = threading.Lock()
        self.front = Front()

    def drain(self):
        with self._lock:
            with self.front._lock:
                return 1


class Front:
    def __init__(self):
        self._lock = threading.Lock()
        self.back = Back()

    def push(self):
        with self._lock:
            with self.back._lock:
                return 2
