"""Fixture: loop-thread-taint MUST flag these (6 findings)."""

import asyncio
import threading
from asyncio import ensure_future as _ef


def _compute():
    # (1) create_task from a to_thread worker: schedules onto a loop
    # this thread does not run
    asyncio.create_task(asyncio.sleep(0))
    return 42


async def offload():
    return await asyncio.to_thread(_compute)


class Worker:
    def __init__(self, loop):
        self.loop = loop
        self.thread = threading.Thread(target=self._run)

    def _run(self):
        # (2) call_later is not thread-safe; (3) get_running_loop
        # raises in a plain worker thread
        self.loop.call_later(1.0, print)
        asyncio.get_running_loop()


def _notify():
    # (4) innocent-looking helper — but it schedules onto a foreign
    # loop; the taint reaches it transitively through _worker and the
    # finding lands here, at the affine call itself
    asyncio.ensure_future(asyncio.sleep(0))


def _worker():
    # thread entry that delegates: the taint crosses the call
    _notify()
    return 0


async def spawn_transitive():
    return await asyncio.to_thread(_worker)


def _hop2():
    # (5) TWO hops from the thread entry: any-depth propagation
    asyncio.create_task(asyncio.sleep(0))


def _hop1():
    _hop2()


def _deep_worker():
    _hop1()
    return 0


async def spawn_deep():
    return await asyncio.to_thread(_deep_worker)


def _aliased():
    # (6) aliased spawner caught through import resolution
    _ef(asyncio.sleep(0))


async def spawn_aliased():
    return await asyncio.to_thread(_aliased)
