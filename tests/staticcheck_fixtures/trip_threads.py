"""Fixture: loop-thread-taint MUST flag these (4 findings)."""

import asyncio
import threading


def _compute():
    # (1) create_task from a to_thread worker: schedules onto a loop
    # this thread does not run
    asyncio.create_task(asyncio.sleep(0))
    return 42


async def offload():
    return await asyncio.to_thread(_compute)


class Worker:
    def __init__(self, loop):
        self.loop = loop
        self.thread = threading.Thread(target=self._run)

    def _run(self):
        # (2) call_later is not thread-safe; (3) get_running_loop
        # raises in a plain worker thread
        self.loop.call_later(1.0, print)
        asyncio.get_running_loop()


def _notify():
    # innocent-looking helper — but it schedules onto a foreign loop
    asyncio.ensure_future(asyncio.sleep(0))


def _worker():
    # (4) transitive (one level): _worker runs on a thread and calls
    # _notify, whose body is loop-affine
    _notify()
    return 0


async def spawn_transitive():
    return await asyncio.to_thread(_worker)
