"""Must TRIP no-blocking-in-async: sync sleep and file IO on the loop."""
import time


async def handler():
    time.sleep(0.1)
    with open("/etc/hosts") as f:
        return f.read()
