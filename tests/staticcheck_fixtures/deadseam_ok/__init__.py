"""Dead-seam fixture (passing): every point the package's
faultinject module declares has at least one literal gate — both
directions of the registry check hold."""
