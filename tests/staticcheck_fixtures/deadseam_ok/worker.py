"""Every declared point gated: ``act`` and ``check`` both count."""


def drain(_injector, batch):
    _injector.act("fanout.drain", len(batch))
    return batch


def rebuild(_injector, shard):
    if _injector.check("mesh.rebuild"):
        return None
    return shard
