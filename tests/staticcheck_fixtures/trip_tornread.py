"""Fixture: torn-read MUST flag these (2 findings)."""

import threading


class Session:
    def __init__(self):
        self.inflight = {}
        self.mqueue = []
        self.mutex = None


class ShardChannel:
    """Matches the AFFINITY_SEEDS qualname suffixes, so its handler
    surface is shard-affine by declaration (entry unlocked)."""

    def __init__(self, session):
        self.session = session
        self.mutex = threading.RLock()

    def check_keepalive(self):
        # (1) two fields of the session-window invariant group read
        # with NO lock at all on a shard path: the reader can see the
        # inflight map of one moment and the mqueue of another
        if len(self.session.inflight) or len(self.session.mqueue):
            return True
        return False

    def retry_deliveries(self):
        # (2) each read individually under the mutex, but the lock is
        # RELEASED between the two blocks — exactly the torn
        # interleaving ("held at each site" is not "held across")
        with self.mutex:
            a = len(self.session.inflight)
        with self.mutex:
            b = len(self.session.mqueue)
        return a + b
