"""Fixture: serve-pipeline worker threads (ISSUE 11) — a
``to_thread``-entered pipeline stage touching Broker state MUST trip
shard-affinity (1 finding).  Thread entry auto-seeds from the spawn
site, so an UNSEEDED worker cannot slip a broker write past the
analysis; the project-tree workers carry explicit AFFINITY_SEEDS facts
on top (pure compute, writes stay on the loop)."""

import asyncio


class Broker:
    def __init__(self):
        self.routes = {}


class MatchPipeline:
    def __init__(self, broker):
        self.broker = broker

    async def dispatch(self, topics):
        return await asyncio.to_thread(self._encode_worker, topics)

    def _encode_worker(self, topics):
        # (1) Broker state is main-loop-only: a pipeline worker thread
        # must hand its results back to the loop, never write broker
        # state directly
        self.broker.routes["hint"] = topics
        return [t.upper() for t in topics]
