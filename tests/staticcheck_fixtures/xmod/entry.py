"""The entries: thread spawns and call sites live here; every
offending body lives in a sibling module."""

import asyncio

from .aio import drain, flush
from .helper import marshal_ok, relay


async def offload(evt):
    # relay → notify: the affine call is two modules away
    return await asyncio.to_thread(relay, evt)


async def offload_ok(loop, evt):
    return await asyncio.to_thread(marshal_ok, loop, evt)


def consume():
    # cross-module discarded coroutine: flush is ``async def`` in
    # aio.py, imported via ``from .aio import flush``
    flush()


async def consume_ok():
    await drain()


def shard_worker(broker):
    # cross-module main-loop-owned write from a thread entry
    broker.routes["x"] = 1


async def offload_state(broker):
    return await asyncio.to_thread(shard_worker, broker)
