"""Main-loop-owned state, written from the wrong module/context."""


class Broker:
    def __init__(self):
        self.routes = {}
