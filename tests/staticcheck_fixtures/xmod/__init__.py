"""Multi-file fixture package: proves the whole-program analysis
actually crosses module boundaries — the thread/loop entries live in
``entry.py`` while every offending call lives in a sibling module."""
