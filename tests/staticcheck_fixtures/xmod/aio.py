"""Async API consumed (wrongly and rightly) from entry.py."""


async def flush():
    pass


async def drain():
    pass
