"""Innocent-looking helpers; the taint arrives from entry.py."""

import asyncio


def notify(evt):
    # loop-affine, one module away from the thread entry: the finding
    # must land HERE (helper.py), not in entry.py
    asyncio.ensure_future(asyncio.sleep(0))


def relay(evt):
    # second hop, still cross-module
    notify(evt)


def marshal_ok(loop, evt):
    # the sanctioned cross-thread entry point: never flagged
    loop.call_soon_threadsafe(evt.set)
