"""Fixture: shard-affinity must NOT flag any of these."""

import threading


class Broker:
    def __init__(self):
        self.routes = {}


class Session:
    def __init__(self):
        self.inflight = {}
        self.subscriptions = {}


class ShardChannel:
    def __init__(self, broker, session, pool):
        self.broker = broker
        self.session = session
        self.pool = pool
        self.mutex = threading.RLock()

    def handle_ack_run(self, run):
        # RLock-set session field under the mutex: the documented
        # shard-side pattern
        with self.mutex:
            self.session.inflight[1] = run
            self._ack(run)
        # broker-touching work marshals instead of writing
        self.pool.marshal(self, run)

    def _ack(self, run):
        self.session.inflight[2] = ("pubrel", None)


class ShardPool:
    def __init__(self, broker):
        self.broker = broker

    def _main_handle(self, chan, pkt):
        # main-loop surface: broker writes are its job
        self.broker.routes["x"] = pkt


def fanout_deliver(sess, msgs):
    # unreached from any shard/thread entry: main-loop-only helpers
    # write session registry state freely
    sess.subscriptions["t"] = 1
    return msgs
