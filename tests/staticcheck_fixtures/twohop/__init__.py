"""Two-hop fixture package: proves the k=2 call-site contexts.  TWO
shard entries (``entries.ShardChannel.handle_ack_run`` and
``.check_keepalive``) reach the SAME offending helper
(``helper.bump``) through one shared mid-function (``mid.relay``).
Under k=1 both paths collapse at the mid hop — a (plane, entry)
exemption cannot tell them apart; the k=2 chain keeps the grandparent
entry distinct, so exempting one entry leaves the other's finding
standing, with the chain naming the right entry."""
