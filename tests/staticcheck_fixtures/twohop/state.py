"""Lock-protected session state (LOCKED_FIELDS class)."""


class Session:
    def __init__(self):
        self.inflight = {}
        self.mqueue = []
        self.mutex = None
