"""The shared mid hop: k=1 collapses both entries here; the k=2
chain carries each entry one hop further."""

from .helper import bump


def relay(sess):
    bump(sess)
