"""Two distinct shard entries (declared seeds, unlocked) into the
same relay → bump chain."""

from .mid import relay


class ShardChannel:
    def handle_ack_run(self, sess):
        relay(sess)

    def check_keepalive(self, sess):
        relay(sess)
