"""The shared leaf: an unlocked write to RLock-set Session state —
offending on every unlocked shard path that reaches it."""


def bump(sess):
    sess.inflight[0] = 1
