"""Must PASS no-blocking-in-async: async sleep, and sync calls in sync
context."""
import asyncio
import time


def sync_path():
    time.sleep(0)
    with open("/etc/hosts") as f:
        return f.read()


async def handler():
    await asyncio.sleep(0)
    return await asyncio.to_thread(sync_path)
