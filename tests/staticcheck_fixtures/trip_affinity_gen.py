"""Fixture: GENERATED shard-affinity seeds — a handler whose packet
type is in ``_SHARD_LOCAL`` seeds automatically from the ``handle_in``
dispatch dict and MUST trip on a broker-state write (1 finding).  The
``_handle_puback`` twin is NOT in ``_SHARD_LOCAL`` here and is only
reachable through the ``Channel.handle_in`` dispatch barrier, so the
same write does not trip — proving the seed came from the generation,
not from a hand-kept list."""

import threading


class P:
    PUBACK = 4
    SUBSCRIBE = 8


_SHARD_LOCAL = frozenset((P.SUBSCRIBE,))


class Broker:
    def __init__(self):
        self.routes = {}


class Channel:
    def __init__(self, broker):
        self.broker = broker
        self.mutex = threading.RLock()

    def handle_in(self, pkt):
        handler = {
            P.SUBSCRIBE: self._handle_subscribe,
            P.PUBACK: self._handle_puback,
        }.get(pkt.type)
        return handler(pkt)

    def _handle_subscribe(self, pkt):
        # (1) shard-legal by _SHARD_LOCAL generation: Broker state is
        # main-loop-only, so this write is a race even under the mutex
        self.broker.routes["x"] = pkt

    def _handle_puback(self, pkt):
        # same write, but PUBACK is NOT shard-local here: main-loop
        # only, legal
        self.broker.routes["y"] = pkt
