"""Fixture: loop-thread-taint must NOT flag any of these."""

import asyncio
import threading
import time


def _blocking_io(path):
    # plain blocking work is exactly what worker threads are for
    with open(path, "rb") as f:
        return f.read()


async def offload(path):
    return await asyncio.to_thread(_blocking_io, path)


class Notifier:
    def __init__(self, loop, evt):
        self.loop = loop
        self.evt = evt
        self.thread = threading.Thread(target=self._run)

    def _run(self):
        # marshalling through call_soon_threadsafe is the sanctioned
        # cross-thread entry point
        time.sleep(0.1)
        self.loop.call_soon_threadsafe(self.evt.set)


class ShardLike:
    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._thread_main)

    def _thread_main(self):
        # bootstraps its OWN loop: loop-affine calls in here belong to
        # that loop, not a foreign one
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(print)
        self.loop.run_forever()


class DelegatingShard:
    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._main)

    def _main(self):
        # transitive pass: the helper bootstraps its own loop, so its
        # loop-affine calls belong to the loop it runs
        self._boot()

    def _boot(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(print)
        self.loop.run_forever()


def _marshal(loop, evt):
    # transitive pass: the helper only uses the sanctioned
    # cross-thread entry point
    loop.call_soon_threadsafe(evt.set)


def _worker_ok(loop, evt):
    time.sleep(0.05)
    _marshal(loop, evt)


async def offload_marshal(loop, evt):
    await asyncio.to_thread(_worker_ok, loop, evt)
