"""Must TRIP registry-drift on all six surfaces (checked against the
real registries in observe/metrics.py / config.py / faultinject.py /
broker/hooks.py)."""


def f(metrics, cfg, alarms, hooks, _injector):
    metrics.inc("tpu.match.not_a_real_metric")
    metrics.get("tpu.match.not_a_real_read")
    cfg.get("mqtt.not_a_real_key")
    _injector.check("bogus.point")
    alarms.deactivate("never_activated_alarm")
    hooks.run("message.dropped", (None, "not_a_real_reason"))


def g(hooks):
    hooks.add("client.not_a_real_point", lambda: None)
