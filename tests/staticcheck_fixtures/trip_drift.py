"""Must TRIP registry-drift on all eight surfaces (checked against the
real registries in observe/metrics.py / config.py / faultinject.py /
broker/hooks.py / observe/hist.py / observe/flightrec.py)."""


def f(metrics, cfg, alarms, hooks, _injector):
    metrics.inc("tpu.match.not_a_real_metric")
    metrics.get("tpu.match.not_a_real_read")
    cfg.get("mqtt.not_a_real_key")
    _injector.check("bogus.point")
    alarms.deactivate("never_activated_alarm")
    hooks.run("message.dropped", (None, "not_a_real_reason"))


def g(hooks):
    hooks.add("client.not_a_real_point", lambda: None)


def h(hists, flightrec):
    hists.hist("obs.stage.not_a_real_stage")
    flightrec.dump("not_a_declared_reason")
