"""Fixture: shard-affinity must NOT flag the disciplined mesh-worker
shape — the partition apply mutates only the matcher's own state under
its lock; every broker write happens back on the event loop."""

import asyncio
import threading


class Broker:
    def __init__(self):
        self.routes = {}


class ShardedMatcher:
    def __init__(self, broker):
        self.broker = broker
        self._lock = threading.Lock()
        self.subtables = {}

    async def sync_once(self):
        changed = await asyncio.to_thread(self.apply_worker)
        # loop side: publishing the applied partition into broker
        # state is legal here
        if changed:
            self.broker.routes["hint"] = list(self.subtables)

    def apply_worker(self):
        # thread side: the matcher is the single writer of its own
        # subtables; the lock orders it against dispatch snapshots
        with self._lock:
            self.subtables["shard0"] = [1, 2, 3]
        return True
