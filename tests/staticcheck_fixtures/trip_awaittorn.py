"""Fixture: await-torn-read MUST flag these (2 findings)."""


class ShardPool:
    async def _main_handle(self, sess):
        # (1) read inflight, SUSPEND, read mqueue: the await hands the
        # loop to any runnable task, which may admit/refill the window
        # between the two observations of the session-window group
        n = len(sess.inflight)
        await self.flush()
        if n < 4 and len(sess.mqueue):
            return True
        return False

    async def flush(self):
        pass

    async def _consume(self, sess, runs):
        # (2) the async-for header is a suspension point too: each
        # iteration parks the coroutine between the group reads
        total = len(sess.inflight)
        async for run in runs:
            total += run
        return total + len(sess.mqueue)
