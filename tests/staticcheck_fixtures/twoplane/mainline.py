"""Main-loop plane: calls the helper WITH the channel RLock held
(ShardPool._main_handle is a declared main seed)."""

import threading

from .helper import bump


class ShardPool:
    def __init__(self):
        self.mutex = threading.RLock()

    def _main_handle(self, sess):
        # locked-from-main: this path must produce ZERO findings
        with self.mutex:
            bump(sess)
