"""The shared helper: correct when every caller holds the mutex,
racy when one does not.  Which is which depends entirely on the PATH
— this file alone cannot tell."""


def bump(sess):
    # write to an RLock-set Session field with no lock at the site:
    # legal iff every entry path holds the mutex
    sess.inflight[0] = 1
