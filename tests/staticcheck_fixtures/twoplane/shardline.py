"""Shard plane: calls the SAME helper without the mutex
(ShardChannel.handle_ack_run is a declared shard seed, unlocked)."""

from .helper import bump


class ShardChannel:
    def handle_ack_run(self, sess):
        # unlocked-from-shard: THE offending path — the one finding,
        # whose chain must name this entry
        bump(sess)
