"""Two-plane fixture package: proves the affinity lattice is
context-sensitive.  The SAME helper (``helper.bump``) is reached from
the main loop **with** the channel RLock held (``mainline.py``) and
from a shard **without** it (``shardline.py``).  A context-insensitive
analysis must either over-flag (both paths) or over-absorb (neither);
the k=1 lattice flags exactly once, on the shard path, with the chain
naming the shard entry."""
