"""Fixture: shard-affinity MUST flag these (3 findings)."""

import threading


class Broker:
    def __init__(self):
        self.routes = {}
        self.sessions = {}


class Session:
    def __init__(self):
        self.inflight = {}
        self.subscriptions = {}
        self.mutex = None


class ShardChannel:
    """Matches the AFFINITY_SEEDS qualname suffixes, so its handler
    surface is shard-affine by declaration."""

    def __init__(self, broker, session):
        self.broker = broker
        self.session = session
        self.mutex = threading.RLock()

    def handle_ack_run(self, run):
        # (1) Broker state is main-loop-only: a shard-side write is a
        # race whether or not any lock is held
        self.broker.routes["x"] = run
        with self.mutex:
            self._ack(run)
        # (2) Session field in the documented RLock set, written
        # WITHOUT the mutex on a shard path
        self.session.inflight[1] = run

    def _ack(self, run):
        # fine: inflight mutation, reached only under the mutex
        self.session.inflight[2] = ("pubrel", None)

    def check_keepalive(self):
        # (3) Session field OUTSIDE the RLock set: main-loop-only even
        # under the lock (the mutex protects the QoS window, not the
        # subscription registry)
        with self.mutex:
            self.session.subscriptions["t"] = 1
