"""Must PASS no-swallowed-exceptions: narrow catches, logging, status
returns, re-raises, recovery calls."""
import logging

log = logging.getLogger(__name__)


def deliver(batch, conn):
    try:
        batch.flush()
    except Exception:
        log.debug("flush failed", exc_info=True)
    try:
        conn.send(batch)
    except ConnectionError:
        pass  # narrow catch: not overbroad
    try:
        conn.health()
    except Exception:
        return False
    try:
        conn.ping()
    except Exception:
        conn.reconnect()
    try:
        conn.commit()
    except Exception:
        raise


def wait(conn):

    try:
        conn.wait(timeout=1.0)
    except TimeoutError:
        pass
