"""Dead-seam fixture (tripping): the package's faultinject module
declares two points but only one has a literal ``_injector.act`` gate
anywhere in the tree — the other is a registered-but-never-fired
chaos point (one registry-drift finding).  Point names reuse the real
``faultinject.POINTS`` vocabulary so the forward unknown-point check
stays quiet and ONLY the dead-seam direction is exercised."""
