"""Chaos-point declarations for the fixture package."""

POINTS = (
    "fanout.drain",
    "mesh.rebuild",
)
