"""Gates only ONE of the two declared points: "mesh.rebuild" is a
hole in the chaos story — declared, targetable, never fired."""


def drain(_injector, batch):
    _injector.act("fanout.drain", len(batch))
    return batch
