"""Must PASS no-unsupervised-task: the supervised-with-fallback shape,
in both its forms."""
import asyncio


async def boot(supervisor):
    if supervisor is not None:
        supervisor.start_child("x", work)
    else:
        asyncio.ensure_future(work())


def spawn(sup, factory):
    if sup is not None:
        return sup.start_child("x", factory)
    return asyncio.ensure_future(factory())


async def work():
    pass
