"""Must TRIP unawaited-coroutine: discarded coroutine calls."""


async def helper():
    pass


def main():
    helper()


class C:
    async def flush(self):
        pass

    def tick(self):
        self.flush()


class Base:
    async def aclose(self):
        pass


class D(Base):
    def shutdown(self):
        # inherited async method: resolved through the class MRO
        self.aclose()
