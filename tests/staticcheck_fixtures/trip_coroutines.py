"""Must TRIP unawaited-coroutine: discarded coroutine calls."""


async def helper():
    pass


def main():
    helper()


class C:
    async def flush(self):
        pass

    def tick(self):
        self.flush()
