"""LZ4 block/frame codec + xxHash32 (`native/lz4.py`), including
cross-validation against the SYSTEM liblz4 when present — our encoder
must be decodable by the reference implementation and vice versa
(Kafka interop depends on it)."""

import ctypes
import ctypes.util
import os
import random

import pytest

from emqx_tpu.native import lz4


def _cases():
    random.seed(77)
    return [
        b"",
        b"z",
        b"ab" * 30000,
        os.urandom(5000),
        bytes(random.randrange(6) for _ in range(120000)),
        b"the quick brown fox " * 500,
    ]


def test_xxh32_vectors():
    # reference xxhsum values
    assert lz4.xxh32(b"") == 0x02CC5D05
    assert lz4.xxh32(b"", seed=1) == 0x0B2CB792
    for d in (b"a", b"Hello World", os.urandom(999), b"x" * 70000):
        assert lz4.xxh32(d) == lz4._py_xxh32(d)
        assert lz4.xxh32(d, 7) == lz4._py_xxh32(d, 7)


def test_frame_roundtrip():
    for d in _cases():
        f = lz4.compress_frame(d)
        assert lz4.decompress_frame(f) == d


def test_block_roundtrip_native_and_python():
    if not lz4.available():
        pytest.skip("no native toolchain")
    for d in _cases():
        if not d:
            continue
        c = lz4.block_compress(d)
        assert lz4.block_decompress(c, len(d)) == d
        assert lz4._py_block_decompress(c, len(d)) == d


def test_frame_rejects_corruption():
    good = lz4.compress_frame(b"hello world hello world")
    for bad in (b"", b"\x00" * 8,
                good[:6] + bytes([good[6] ^ 0xFF]) + good[7:],  # bad HC
                good[:-3]):                                     # truncated
        with pytest.raises(ValueError):
            lz4.decompress_frame(bad)


def test_block_decompress_bounds():
    with pytest.raises(ValueError):
        lz4.block_decompress(b"\xf0" + b"\xff" * 8, 10)   # runaway length
    with pytest.raises(ValueError):
        lz4.block_decompress(b"\x10a\x05\x00\x00", 100)   # offset > out
    with pytest.raises(ValueError):
        lz4.block_decompress(b"x", 1 << 40)               # cap


_SYS = None


def _syslz4():
    global _SYS
    if _SYS is None:
        path = ctypes.util.find_library("lz4") or "liblz4.so.1"
        try:
            lib = ctypes.CDLL(path)
            lib.LZ4_compress_default.restype = ctypes.c_int
            lib.LZ4_decompress_safe.restype = ctypes.c_int
            _SYS = lib
        except OSError:
            _SYS = False
    return _SYS or None


def test_interop_with_system_liblz4():
    sys_lz4 = _syslz4()
    if sys_lz4 is None or not lz4.available():
        pytest.skip("system liblz4 or toolchain unavailable")
    for d in _cases():
        if not d:
            continue
        # ours -> reference decoder
        c = lz4.block_compress(d)
        out = ctypes.create_string_buffer(len(d))
        n = sys_lz4.LZ4_decompress_safe(c, out, len(c), len(d))
        assert n == len(d) and out.raw[:n] == d, \
            f"reference lz4 rejected our encoding ({len(d)} bytes)"
        # reference encoder -> ours
        cap = len(d) + len(d) // 250 + 64
        enc = ctypes.create_string_buffer(cap)
        m = sys_lz4.LZ4_compress_default(d, enc, len(d), cap)
        assert m > 0
        assert lz4.block_decompress(enc.raw[:m], len(d)) == d
        assert lz4._py_block_decompress(enc.raw[:m], len(d)) == d


def test_frame_interop_with_system_lz4f():
    """Frames produced by the reference LZ4F compressor (which sets
    header fields ours doesn't, e.g. content checksums) must decode —
    and a content-size-bearing descriptor must pass the HC check
    (review finding: HC covers FLG..dictID, not just FLG+BD)."""
    path = ctypes.util.find_library("lz4") or "liblz4.so.1"
    try:
        lib = ctypes.CDLL(path)
        lib.LZ4F_compressFrameBound.restype = ctypes.c_size_t
        lib.LZ4F_compressFrame.restype = ctypes.c_size_t
        lib.LZ4F_isError.restype = ctypes.c_uint
    except (OSError, AttributeError):
        pytest.skip("system liblz4 frame API unavailable")
    for d in _cases():
        cap = int(lib.LZ4F_compressFrameBound(len(d), None)) + 64
        dst = ctypes.create_string_buffer(cap)
        n = int(lib.LZ4F_compressFrame(dst, cap, d, len(d), None))
        assert not lib.LZ4F_isError(n)
        assert lz4.decompress_frame(dst.raw[:n]) == d, len(d)
    # and the reverse: reference decoder accepts OUR frames
    try:
        ctx = ctypes.c_void_p()
        lib.LZ4F_createDecompressionContext.restype = ctypes.c_size_t
        assert not lib.LZ4F_isError(
            lib.LZ4F_createDecompressionContext(ctypes.byref(ctx), 100))
        for d in _cases():
            frame = lz4.compress_frame(d)
            out = ctypes.create_string_buffer(max(1, len(d)))
            dst_sz = ctypes.c_size_t(len(d))
            src_sz = ctypes.c_size_t(len(frame))
            rc = lib.LZ4F_decompress(ctx, out, ctypes.byref(dst_sz),
                                     frame, ctypes.byref(src_sz), None)
            assert not lib.LZ4F_isError(rc), f"liblz4 rejected our frame"
            assert out.raw[:dst_sz.value] == d, len(d)
    finally:
        lib.LZ4F_freeDecompressionContext(ctx)
