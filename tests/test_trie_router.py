"""Trie + router behavioral tests, mirroring emqx_trie_SUITE /
emqx_router_SUITE coverage (SURVEY.md §4), plus property tests proving
trie.match ≡ the topic.match oracle over the inserted key set."""

import string

from _optional import given, settings, st

from emqx_tpu import topic as T
from emqx_tpu.broker import FilterTrie, TopicTrie, Router


# ---------------------------------------------------------------------------
# FilterTrie
# ---------------------------------------------------------------------------

def test_filter_trie_basic():
    tr = FilterTrie()
    for f in ["a/b/c", "a/+/c", "a/#", "#", "+/b/c", "x/y"]:
        tr.insert(f)
    assert sorted(tr.match("a/b/c")) == sorted(["a/b/c", "a/+/c", "a/#", "#", "+/b/c"])
    assert sorted(tr.match("a/b")) == sorted(["a/#", "#"])
    assert sorted(tr.match("a")) == sorted(["a/#", "#"])
    assert sorted(tr.match("x/y")) == sorted(["x/y", "#"])
    assert tr.match("$SYS/x") == []


def test_filter_trie_sys_protection():
    tr = FilterTrie()
    for f in ["#", "+/x", "$SYS/#", "$SYS/+", "$SYS/x"]:
        tr.insert(f)
    assert sorted(tr.match("$SYS/x")) == sorted(["$SYS/#", "$SYS/+", "$SYS/x"])
    assert sorted(tr.match("a/x")) == sorted(["#", "+/x"])


def test_filter_trie_refcount_delete():
    tr = FilterTrie()
    assert tr.insert("a/+") is True
    assert tr.insert("a/+") is False
    assert tr.refcount("a/+") == 2
    assert tr.delete("a/+") is False  # one ref remains
    assert tr.match("a/b") == ["a/+"]
    assert tr.delete("a/+") is True
    assert tr.match("a/b") == []
    assert tr.is_empty()
    assert tr.node_count() == 0  # edges pruned


def test_filter_trie_delete_shared_prefix():
    tr = FilterTrie()
    tr.insert("a/b/c")
    tr.insert("a/b")
    tr.delete("a/b/c")
    assert tr.match("a/b") == ["a/b"]
    assert tr.match("a/b/c") == []
    tr.delete("a/b")
    assert tr.node_count() == 0


def test_filter_trie_delete_absent():
    tr = FilterTrie()
    assert tr.delete("nope") is False


# ---------------------------------------------------------------------------
# property: trie.match ≡ oracle over key set
# ---------------------------------------------------------------------------

word_st = st.sampled_from(["a", "b", "c", "", "x1"])
name_st = st.lists(
    st.one_of(word_st, st.just("$sys")), min_size=1, max_size=5
).map(T.join)
filter_st = st.lists(
    st.one_of(word_st, st.just("+")), min_size=1, max_size=5
).flatmap(lambda ws: st.sampled_from([ws, ws + ["#"], ["#"]])).map(T.join)


@settings(max_examples=200, deadline=None)
@given(st.lists(filter_st, min_size=0, max_size=20), st.lists(name_st, min_size=1, max_size=5))
def test_trie_match_equals_oracle(filters, names):
    tr = FilterTrie()
    for f in filters:
        tr.insert(f)
    for n in names:
        expected = {f for f in set(filters) if T.match(n, f)}
        assert set(tr.match(n)) == expected


@settings(max_examples=100, deadline=None)
@given(st.lists(filter_st, min_size=1, max_size=15), name_st)
def test_trie_insert_delete_inverse(filters, name):
    tr = FilterTrie()
    for f in filters:
        tr.insert(f)
    for f in filters:
        tr.delete(f)
    assert tr.is_empty()
    assert tr.node_count() == 0
    assert tr.match(name) == []


# ---------------------------------------------------------------------------
# TopicTrie (retained-replay direction)
# ---------------------------------------------------------------------------

def test_topic_trie_basic():
    tt = TopicTrie()
    for t in ["a/b", "a/c", "a/b/c", "x", "$SYS/up"]:
        tt.insert(t)
    assert sorted(tt.match("a/+")) == sorted(["a/b", "a/c"])
    assert sorted(tt.match("a/#")) == sorted(["a/b", "a/c", "a/b/c"])
    assert sorted(tt.match("#")) == sorted(["a/b", "a/c", "a/b/c", "x"])
    assert tt.match("$SYS/up") == ["$SYS/up"]
    assert tt.match("+/up") == []
    assert sorted(tt.match("$SYS/#")) == ["$SYS/up"]


@settings(max_examples=200, deadline=None)
@given(st.lists(name_st, min_size=0, max_size=20), filter_st)
def test_topic_trie_equals_oracle(names, flt):
    tt = TopicTrie()
    for n in names:
        tt.insert(n)
    expected = {n for n in set(names) if T.match(n, flt)}
    assert set(tt.match(flt)) == expected


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

def test_router_exact_and_wildcard():
    r = Router()
    r.add_route("a/b", "node1")
    r.add_route("a/+", "node2")
    r.add_route("a/b", "node2")
    assert r.match_dests("a/b") == {"node1", "node2"}
    assert r.match_dests("a/c") == {"node2"}
    assert r.match_dests("zzz") == set()
    assert r.route_count() == 3
    assert r.has_route("a/+", "node2")


def test_router_delete_and_cleanup():
    r = Router()
    r.add_route("a/b", "n1")
    r.add_route("a/+", "n1")
    r.add_route("a/+", "n2")
    assert r.cleanup_routes("n1") == 2
    assert r.match_dests("a/b") == {"n2"}
    assert not r.has_route("a/b", "n1")
    r.delete_route("a/+", "n2")
    assert r.route_count() == 0
    assert r.match_routes("a/b") == []


def test_router_duplicate_add_is_noop():
    r = Router()
    assert r.add_route("t/+", "n1") is True
    assert r.add_route("t/+", "n1") is False
    assert r.route_count() == 1
    e = r.epoch
    assert r.add_route("t/+", "n1") is False
    assert r.epoch == e  # no-op does not bump epoch


def test_router_delta_log():
    r = Router(delta_log_cap=4)
    r.add_route("a", "n1")
    e1 = r.epoch
    r.add_route("b/+", "n1")
    r.delete_route("a", "n1")
    assert [d.op for d in r.deltas_since(e1)] == ["add", "del"]
    assert r.deltas_since(r.epoch) == []
    # overflow the log -> None forces resnapshot
    for i in range(10):
        r.add_route(f"c/{i}", "n2")
    assert r.deltas_since(e1) is None


def test_router_share_destinations():
    # shared subs route with (group, node) style dests — opaque to router
    r = Router()
    r.add_route("t/#", ("g1", "node1"))
    r.add_route("t/#", ("g1", "node2"))
    assert r.match_dests("t/x") == {("g1", "node1"), ("g1", "node2")}


def test_deep_filters_no_recursion_limit():
    # validate() admits very deep topics; walks must not recurse per level
    deep = "/".join(["x"] * 5000)
    tr = FilterTrie()
    tr.insert(deep)
    tr.insert("/".join(["x"] * 4999 + ["+"]))
    assert len(tr.match(deep)) == 2
    tt = TopicTrie()
    tt.insert(deep)
    assert tt.match("#") == [deep]
    assert tt.match("/".join(["x"] * 4999 + ["+"])) == [deep]
