"""MySQL authn/authz against an in-test mock speaking protocol 41
(handshake v10 + mysql_native_password + COM_QUERY text resultsets) —
with live CONNECT round trips (emqx_authn/mysql analogs)."""

import asyncio
import hashlib
import struct

import pytest

from emqx_tpu.auth import AuthChain, Authz
from emqx_tpu.auth.authn import Credentials, hash_password
from emqx_tpu.auth.mysql import (
    MysqlAuthenticator, MysqlAuthzSource, MysqlClient, escape_literal,
    render_query, _native_password,
)
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def _lenenc_str(s):
    b = s.encode() if isinstance(s, str) else s
    assert len(b) < 0xFB
    return bytes([len(b)]) + b


class MockMysql:
    """handshake + native-password verify + substring-dispatched
    COM_QUERY over (cols, rows) handlers."""

    SCRAMBLE = b"abcdefgh12345678901j"  # 20 bytes

    def __init__(self, tables, user="broker", password="dbpw"):
        self.tables = tables
        self.user = user
        self.password = password
        self.queries = []
        self.prepares = []          # COM_STMT_PREPARE sql texts
        self.executes = []          # (stmt_id, params)
        self._conns = set()
        self.port = 0

    async def start(self):
        async def rd_packet(reader):
            head = await reader.readexactly(4)
            ln = int.from_bytes(head[:3], "little")
            return await reader.readexactly(ln), head[3]

        def wr_packet(writer, payload, seq):
            writer.write(len(payload).to_bytes(3, "little")
                         + bytes([seq]) + payload)

        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                greeting = (b"\x0a" + b"8.0-mock\x00"
                            + struct.pack("<I", 7)
                            + self.SCRAMBLE[:8] + b"\x00"
                            + struct.pack("<H", 0xFFFF) + b"\x21"
                            + struct.pack("<H", 2)
                            + struct.pack("<H", 0xC000)
                            + bytes([21]) + b"\x00" * 10
                            + self.SCRAMBLE[8:] + b"\x00"
                            + b"mysql_native_password\x00")
                wr_packet(writer, greeting, 0)
                await writer.drain()
                resp, _ = await rd_packet(reader)
                off = 4 + 4 + 1 + 23
                end = resp.index(b"\x00", off)
                user = resp[off:end].decode()
                off = end + 1
                alen = resp[off]
                auth = resp[off + 1:off + 1 + alen]
                want = _native_password(self.password, self.SCRAMBLE)
                if user != self.user or auth != want:
                    wr_packet(writer, b"\xff" + struct.pack("<H", 1045)
                              + b"#28000" + b"denied", 2)
                    await writer.drain()
                    return
                wr_packet(writer, b"\x00\x00\x00" + struct.pack("<HH",
                                                                2, 0), 2)
                await writer.drain()
                stmts = {}
                next_stmt = [1]

                def coldef(c, s, writer):
                    cd = (_lenenc_str("def") + _lenenc_str("")
                          + _lenenc_str("t") + _lenenc_str("t")
                          + _lenenc_str(c) + _lenenc_str(c)
                          + b"\x0c" + struct.pack("<HIBHB", 33, 256,
                                                  0xFD, 0, 0)
                          + b"\x00\x00")
                    wr_packet(writer, cd, s)

                while True:
                    p, seq = await rd_packet(reader)
                    if p[:1] == b"\x16":        # COM_STMT_PREPARE
                        sql = p[1:].decode()
                        self.prepares.append(sql)
                        sid = next_stmt[0]
                        next_stmt[0] += 1
                        stmts[sid] = sql
                        np_ = sql.count("?")
                        wr_packet(writer, b"\x00"
                                  + struct.pack("<IHHBH", sid, 0, np_,
                                                0, 0), 1)
                        s = 2
                        if np_:
                            for i in range(np_):
                                coldef(f"p{i}", s, writer)
                                s += 1
                            wr_packet(writer, b"\xfe"
                                      + struct.pack("<HH", 0, 2), s)
                        await writer.drain()
                        continue
                    if p[:1] == b"\x17":        # COM_STMT_EXECUTE
                        (sid,) = struct.unpack_from("<I", p, 1)
                        sql = stmts[sid]
                        np_ = sql.count("?")
                        params = []
                        off = 10
                        if np_:
                            nullmap = p[off:off + (np_ + 7) // 8]
                            off += (np_ + 7) // 8 + 1   # + rebound flag
                            off += 2 * np_              # types
                            from emqx_tpu.auth.mysql import _lenenc
                            for i in range(np_):
                                if nullmap[i // 8] & (1 << (i % 8)):
                                    params.append(None)
                                    continue
                                ln, off = _lenenc(p, off)
                                params.append(
                                    p[off:off + ln].decode())
                                off += ln
                        self.executes.append((sid, params))
                        # substitute (quoted) to reuse the substring-
                        # dispatched fixtures
                        final = sql
                        for v in params:
                            final = final.replace(
                                "?", "'" + (v or "") + "'", 1)
                        cols, rows = [], []
                        for needle, fn in self.tables.items():
                            if needle in final:
                                cols, rows = fn(final)
                                break
                        s = 1
                        if not cols:
                            wr_packet(writer, b"\x00\x00\x00"
                                      + struct.pack("<HH", 2, 0), s)
                            await writer.drain()
                            continue
                        wr_packet(writer, bytes([len(cols)]), s)
                        s += 1
                        for c in cols:
                            coldef(c, s, writer)
                            s += 1
                        wr_packet(writer, b"\xfe"
                                  + struct.pack("<HH", 0, 2), s)
                        s += 1
                        for r in rows:
                            nb = (len(cols) + 9) // 8
                            bm = bytearray(nb)
                            vals = bytearray()
                            for i, v in enumerate(r):
                                if v is None:
                                    bit = i + 2
                                    bm[bit // 8] |= 1 << (bit % 8)
                                else:
                                    vals += _lenenc_str(str(v))
                            wr_packet(writer,
                                      b"\x00" + bytes(bm) + bytes(vals),
                                      s)
                            s += 1
                        wr_packet(writer, b"\xfe"
                                  + struct.pack("<HH", 0, 2), s)
                        await writer.drain()
                        continue
                    if p[:1] != b"\x03":
                        return
                    sql = p[1:].decode()
                    self.queries.append(sql)
                    cols, rows = [], []
                    for needle, fn in self.tables.items():
                        if needle in sql:
                            cols, rows = fn(sql)
                            break
                    s = 1
                    if not cols:
                        # statements without a resultset (INSERT /
                        # SELECT 1 fallthrough) answer with OK, like
                        # a real server
                        wr_packet(writer, b"\x00\x00\x00"
                                  + struct.pack("<HH", 2, 0), s)
                        await writer.drain()
                        continue
                    wr_packet(writer, bytes([len(cols)]), s)
                    s += 1
                    for c in cols:
                        cd = (_lenenc_str("def") + _lenenc_str("")
                              + _lenenc_str("t") + _lenenc_str("t")
                              + _lenenc_str(c) + _lenenc_str(c)
                              + b"\x0c" + struct.pack("<HIBHB", 33, 256,
                                                      0xFD, 0, 0)
                              + b"\x00\x00")
                        wr_packet(writer, cd, s)
                        s += 1
                    wr_packet(writer, b"\xfe" + struct.pack("<HH", 0, 2),
                              s)
                    s += 1
                    for r in rows:
                        rp = b"".join(
                            b"\xfb" if v is None else _lenenc_str(str(v))
                            for v in r)
                        wr_packet(writer, rp, s)
                        s += 1
                    wr_packet(writer, b"\xfe" + struct.pack("<HH", 0, 2),
                              s)
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


SALT = "mysalt"


def user_table(sql):
    if "'manu'" in sql:
        return (["password_hash", "salt", "is_superuser"],
                [[hash_password(b"mpw", "sha256", SALT.encode()),
                  SALT, "0"]])
    return ["password_hash", "salt", "is_superuser"], []


def acl_table(sql):
    if "'manu'" in sql:
        return (["permission", "action", "topic"],
                [["allow", "all", "open/#"],
                 ["deny", "subscribe", "secret/#"]])
    return ["permission", "action", "topic"], []


def test_escape_literal_blocks_injection():
    # quotes doubled (valid in EVERY sql_mode incl NO_BACKSLASH_ESCAPES)
    assert escape_literal("a'b") == "a''b"
    assert escape_literal("x\\") == "x\\\\"   # trailing backslash can't
    sql = render_query("SELECT 1 FROM t WHERE u = ${username}",
                       {"username": "x' OR '1'='1"})
    assert sql == "SELECT 1 FROM t WHERE u = 'x'' OR ''1''=''1'"


def test_render_query_single_pass_no_smuggling():
    """A credential containing another placeholder must NOT get that
    field spliced inside its literal (sequential-replace injection)."""
    sql = render_query(
        "SELECT 1 FROM t WHERE u = ${username} AND c = ${clientid}",
        {"username": "${clientid}",
         "clientid": "' UNION SELECT 'allow' -- "})
    assert "UNION SELECT" not in sql.split("AND")[0]
    assert sql.split("AND")[0].strip().endswith("'${clientid}'")


def test_mysql_authn_authz_roundtrip():
    async def main():
        my = await MockMysql({"mqtt_user": user_table,
                              "mqtt_acl": acl_table}).start()
        server = f"127.0.0.1:{my.port}"
        chain = AuthChain(allow_anonymous=False).add(
            MysqlAuthenticator(server, user="broker", password="dbpw"))
        authz = Authz(sources=[MysqlAuthzSource(server, user="broker",
                                                password="dbpw")],
                      no_match="deny", cache_enable=False)
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg, auth_chain=chain, authz=authz)
        await node.start()
        port = node.listeners.all()[0].port
        try:
            ok = Client(clientid="c1", port=port,
                        username="manu", password=b"mpw")
            await ok.connect()
            assert await ok.subscribe("open/news") == [0]
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            await ok.disconnect()
            with pytest.raises(MqttError):
                await Client(clientid="c2", port=port, username="manu",
                             password=b"wrong").connect()
            with pytest.raises(MqttError):
                await Client(clientid="c3", port=port, username="ghost",
                             password=b"x").connect()
            # credentials rode through ESCAPED literals
            assert any("'manu'" in q for q in my.queries)
        finally:
            await node.stop()
            await my.stop()

    run(main())


def test_mysql_bad_db_password_and_down_server():
    async def main():
        my = await MockMysql({"mqtt_user": user_table}).start()
        wrong = MysqlAuthenticator(f"127.0.0.1:{my.port}", user="broker",
                                   password="nope", timeout=2.0)
        res = await wrong.authenticate_async(
            Credentials("c", "manu", b"mpw"))
        assert res.outcome == "ignore"
        await my.stop()

        dead = MysqlAuthenticator("127.0.0.1:1", timeout=0.3)
        assert (await dead.authenticate_async(
            Credentials("c", "manu", b"mpw"))).outcome == "ignore"

    run(main())


def test_mysql_client_reconnects():
    async def main():
        my = await MockMysql({"mqtt_user": user_table}).start()
        c = MysqlClient(f"127.0.0.1:{my.port}", user="broker",
                        password="dbpw")
        cols, rows = await c.query(
            "SELECT password_hash, salt, is_superuser FROM mqtt_user "
            "WHERE username = 'manu'")
        assert cols[0] == "password_hash" and len(rows) == 1
        for w in list(my._conns):
            w.close()
        await asyncio.sleep(0.05)
        with pytest.raises(Exception):
            await c.query("SELECT 1 FROM mqtt_user WHERE username = 'x'")
        cols, rows = await c.query(
            "SELECT 1 FROM mqtt_user WHERE username = 'ghost'")
        assert rows == []
        await c.close()
        await my.stop()

    run(main())


def test_mysql_bridge_insert_via_rule():
    async def main():
        inserts = []

        def insert_log(sql):
            inserts.append(sql)
            return [], []

        my = await MockMysql({"mqtt_messages": insert_log}).start()
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg)
        await node.start()
        try:
            await node.bridges.create("mysql", "myb", {
                "server": f"127.0.0.1:{my.port}",
                "user": "broker", "password": "dbpw",
                "sql": "INSERT INTO mqtt_messages (c, t, p) "
                       "VALUES (${1}, ${2}, ${3})",
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rmy", 'SELECT clientid, topic, payload FROM "ev/#"',
                actions=["mysql:myb"])
            pub = Client(clientid="mypub",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/9", b"it's payload")  # quote escapes
            br = node.bridges.get("mysql:myb")
            for _ in range(400):
                if br.worker.metrics["success"] >= 1:
                    break
                await asyncio.sleep(0.01)
            assert br.worker.metrics["success"] == 1
            assert inserts == [
                "INSERT INTO mqtt_messages (c, t, p) VALUES "
                "('mypub', 'ev/9', 'it''s payload')"]
            await pub.disconnect()
        finally:
            await node.stop()
            await my.stop()

    run(main())


def test_sql_mode_probe_no_backslash_escapes():
    """ADVICE r3 #5: under NO_BACKSLASH_ESCAPES a backslash is literal
    data; the client probes @@sql_mode at handshake and stops doubling
    backslashes, so a username like 'dom\\user' matches its row."""
    # unit: escaping is mode-dependent
    assert escape_literal("a\\b") == "a\\\\b"
    assert escape_literal("a\\b", no_backslash_escapes=True) == "a\\b"
    assert escape_literal("a'b", no_backslash_escapes=True) == "a''b"

    async def main():
        def sql_mode(_sql):
            return ["@@sql_mode"], [["ANSI_QUOTES,NO_BACKSLASH_ESCAPES"]]

        my = await MockMysql({"@@sql_mode": sql_mode,
                              "mqtt_user": user_table}).start()
        auth = MysqlAuthenticator(f"127.0.0.1:{my.port}", user="broker",
                                  password="dbpw")
        await auth.authenticate_async(
            Credentials("c", "dom\\user", b"pw"))
        lookup = [q for q in my.queries if "mqtt_user" in q]
        assert lookup and "'dom\\user'" in lookup[0]  # NOT doubled
        assert auth.client.no_backslash_escapes is True
        await auth.client.close()
        await my.stop()

    run(main())


def test_render_prepared_binds_instead_of_splicing():
    from emqx_tpu.auth.mysql import render_prepared

    sql, params = render_prepared(
        "SELECT h FROM u WHERE username = ${username} "
        "AND clientid = ${clientid}",
        {"username": "eve'--", "clientid": "c${username}1"})
    assert sql == ("SELECT h FROM u WHERE username = ? "
                   "AND clientid = ?")
    # hostile values stay DATA in the param list, never SQL text
    assert params == ["eve'--", "c${username}1"]


def test_mysql_prepared_statement_authn_roundtrip():
    """prepared: true drives COM_STMT_PREPARE/EXECUTE with binary bind
    params and the binary resultset decoder; the statement handle is
    reused across executions (round 5: flips the 'no server-side
    prepare' limitation)."""
    from emqx_tpu.auth.mysql import MysqlAuthenticator

    async def scenario():
        mock = await MockMysql({"mqtt_user": user_table}).start()
        try:
            auth = MysqlAuthenticator(
                f"127.0.0.1:{mock.port}", user="broker",
                password="dbpw", prepared=True)
            ok = await auth.authenticate_async(Credentials(
                clientid="c1", username="manu", password=b"mpw"))
            assert ok.outcome == "ok"
            bad = await auth.authenticate_async(Credentials(
                clientid="c1", username="manu", password=b"nope"))
            assert bad.outcome == "deny"
            missing = await auth.authenticate_async(Credentials(
                clientid="c1", username="ghost", password=b"x"))
            assert missing.outcome == "ignore"
            # one PREPARE, three EXECUTEs (handle reuse), zero text
            # queries carrying credentials
            assert len(mock.prepares) == 1
            assert "?" in mock.prepares[0]
            assert len(mock.executes) == 3
            assert mock.executes[0][1] == ["manu"]
            assert not any("manu" in q for q in mock.queries)
            await auth.client.close()
        finally:
            await mock.stop()

    run(scenario())
