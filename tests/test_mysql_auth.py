"""MySQL authn/authz against an in-test mock speaking protocol 41
(handshake v10 + mysql_native_password + COM_QUERY text resultsets) —
with live CONNECT round trips (emqx_authn/mysql analogs)."""

import asyncio
import base64
import hashlib
import random
import struct

import pytest

from emqx_tpu.auth import AuthChain, Authz
from emqx_tpu.auth.authn import Credentials, hash_password
from emqx_tpu.auth.mysql import (
    MysqlAuthenticator, MysqlAuthzSource, MysqlClient, escape_literal,
    render_query, _caching_sha2, _native_password,
)
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def _lenenc_str(s):
    b = s.encode() if isinstance(s, str) else s
    assert len(b) < 0xFB
    return bytes([len(b)]) + b


# -- throwaway RSA keypair for the caching_sha2 full-auth mock ---------------

def _probable_prime(n):
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _gen_key():
    rng = random.Random(20260731)

    def prime():
        while True:
            c = rng.getrandbits(256) | (1 << 255) | 1
            if _probable_prime(c):
                return c

    while True:
        p, q = prime(), prime()
        phi = (p - 1) * (q - 1)
        if p != q and phi % 65537 != 0:
            return p * q, 65537, pow(65537, -1, phi)


_RSA_N, _RSA_E, _RSA_D = _gen_key()


def _der_len(n):
    if n < 128:
        return bytes([n])
    b = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(b)]) + b


def _der_int(x):
    b = x.to_bytes((x.bit_length() + 7) // 8 or 1, "big")
    if b[0] & 0x80:
        b = b"\x00" + b
    return b"\x02" + _der_len(len(b)) + b


_der_body = _der_int(_RSA_N) + _der_int(_RSA_E)
_RSA_PEM = (b"-----BEGIN RSA PUBLIC KEY-----\n"
            + base64.encodebytes(b"\x30" + _der_len(len(_der_body))
                                 + _der_body)
            + b"-----END RSA PUBLIC KEY-----\n")


def _mgf1(seed, ln):
    out = b""
    for i in range((ln + 19) // 20):
        out += hashlib.sha1(seed + struct.pack(">I", i)).digest()
    return out[:ln]


def _oaep_decrypt(ct):
    k = (_RSA_N.bit_length() + 7) // 8
    if len(ct) != k:
        return None
    em = pow(int.from_bytes(ct, "big"), _RSA_D, _RSA_N).to_bytes(k, "big")
    if em[0] != 0:
        return None
    masked_seed, masked_db = em[1:21], em[21:]
    seed = bytes(a ^ b for a, b in zip(masked_seed, _mgf1(masked_db, 20)))
    db = bytes(a ^ b for a, b in zip(masked_db, _mgf1(seed, k - 21)))
    if db[:20] != hashlib.sha1(b"").digest():
        return None
    try:
        i = db.index(b"\x01", 20)
    except ValueError:
        return None
    return db[i + 1:]


class MockMysql:
    """handshake + native-password/caching_sha2 verify + substring-
    dispatched COM_QUERY over (cols, rows) handlers.

    ``plugin`` selects the advertised auth plugin; for
    ``caching_sha2_password``, ``auth_mode`` picks the server flow:
    ``fast`` (scramble verified, 0x01 0x03 then OK — the cache-hit
    path), ``full_rsa`` (0x01 0x04, serve the RSA public key, verify
    the OAEP-encrypted scramble-masked password — the cache-miss
    path), or ``switch_native`` (AuthSwitchRequest back to
    mysql_native_password with a FRESH nonce)."""

    SCRAMBLE = b"abcdefgh12345678901j"  # 20 bytes
    SCRAMBLE2 = b"ZYXWVUTSRQPONMLKJIH2"  # post-switch nonce

    def __init__(self, tables, user="broker", password="dbpw",
                 plugin="mysql_native_password", auth_mode="fast"):
        self.tables = tables
        self.user = user
        self.password = password
        self.plugin = plugin
        self.auth_mode = auth_mode
        self.queries = []
        self.prepares = []          # COM_STMT_PREPARE sql texts
        self.executes = []          # (stmt_id, params)
        self._conns = set()
        self.port = 0

    async def _auth_server_side(self, reader, writer, rd_packet,
                                wr_packet, user, auth, seq, deny):
        ok_pkt = b"\x00\x00\x00" + struct.pack("<HH", 2, 0)
        if user != self.user:
            deny()
            return False
        if self.plugin == "mysql_native_password":
            if auth != _native_password(self.password, self.SCRAMBLE):
                deny()
                return False
            wr_packet(writer, ok_pkt, seq[0])
            return True
        assert self.plugin == "caching_sha2_password"
        if self.auth_mode == "switch_broken":
            # malformed AuthSwitchRequest: plugin name not terminated
            wr_packet(writer, b"\xfemysql_native_password", seq[0])
            return False
        if self.auth_mode == "switch_nononce":
            wr_packet(writer, b"\xfemysql_native_password\x00", seq[0])
            return False
        if self.auth_mode == "switch_native":
            wr_packet(writer, b"\xfe" + b"mysql_native_password\x00"
                      + self.SCRAMBLE2 + b"\x00", seq[0])
            seq[0] += 1
            await writer.drain()
            resp, _ = await rd_packet(reader)
            seq[0] += 1
            if resp != _native_password(self.password, self.SCRAMBLE2):
                deny()
                return False
            wr_packet(writer, ok_pkt, seq[0])
            return True
        if self.auth_mode == "fast":
            if auth != _caching_sha2(self.password, self.SCRAMBLE):
                deny()
                return False
            wr_packet(writer, b"\x01\x03", seq[0])
            seq[0] += 1
            wr_packet(writer, ok_pkt, seq[0])
            return True
        assert self.auth_mode == "full_rsa"
        wr_packet(writer, b"\x01\x04", seq[0])
        seq[0] += 1
        await writer.drain()
        req, _ = await rd_packet(reader)
        seq[0] += 1
        if req != b"\x02":          # client must request the public key
            deny()
            return False
        wr_packet(writer, b"\x01" + _RSA_PEM, seq[0])
        seq[0] += 1
        await writer.drain()
        blob, _ = await rd_packet(reader)
        seq[0] += 1
        msg = _oaep_decrypt(blob)
        if msg is None:
            deny()
            return False
        pwd = bytes(c ^ self.SCRAMBLE[i % len(self.SCRAMBLE)]
                    for i, c in enumerate(msg))
        if pwd != self.password.encode() + b"\x00":
            deny()
            return False
        wr_packet(writer, ok_pkt, seq[0])
        return True

    async def start(self):
        async def rd_packet(reader):
            head = await reader.readexactly(4)
            ln = int.from_bytes(head[:3], "little")
            return await reader.readexactly(ln), head[3]

        def wr_packet(writer, payload, seq):
            writer.write(len(payload).to_bytes(3, "little")
                         + bytes([seq]) + payload)

        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                greeting = (b"\x0a" + b"8.0-mock\x00"
                            + struct.pack("<I", 7)
                            + self.SCRAMBLE[:8] + b"\x00"
                            + struct.pack("<H", 0xFFFF) + b"\x21"
                            + struct.pack("<H", 2)
                            + struct.pack("<H", 0xC000)
                            + bytes([21]) + b"\x00" * 10
                            + self.SCRAMBLE[8:] + b"\x00"
                            + self.plugin.encode() + b"\x00")
                wr_packet(writer, greeting, 0)
                await writer.drain()
                resp, _ = await rd_packet(reader)
                off = 4 + 4 + 1 + 23
                end = resp.index(b"\x00", off)
                user = resp[off:end].decode()
                off = end + 1
                alen = resp[off]
                auth = resp[off + 1:off + 1 + alen]
                seq = [2]

                def deny():
                    wr_packet(writer, b"\xff" + struct.pack("<H", 1045)
                              + b"#28000" + b"denied", seq[0])

                ok = await self._auth_server_side(
                    reader, writer, rd_packet, wr_packet, user, auth,
                    seq, deny)
                await writer.drain()
                if not ok:
                    return
                stmts = {}
                next_stmt = [1]

                def coldef(c, s, writer):
                    cd = (_lenenc_str("def") + _lenenc_str("")
                          + _lenenc_str("t") + _lenenc_str("t")
                          + _lenenc_str(c) + _lenenc_str(c)
                          + b"\x0c" + struct.pack("<HIBHB", 33, 256,
                                                  0xFD, 0, 0)
                          + b"\x00\x00")
                    wr_packet(writer, cd, s)

                while True:
                    p, seq = await rd_packet(reader)
                    if p[:1] == b"\x16":        # COM_STMT_PREPARE
                        sql = p[1:].decode()
                        self.prepares.append(sql)
                        sid = next_stmt[0]
                        next_stmt[0] += 1
                        stmts[sid] = sql
                        np_ = sql.count("?")
                        wr_packet(writer, b"\x00"
                                  + struct.pack("<IHHBH", sid, 0, np_,
                                                0, 0), 1)
                        s = 2
                        if np_:
                            for i in range(np_):
                                coldef(f"p{i}", s, writer)
                                s += 1
                            wr_packet(writer, b"\xfe"
                                      + struct.pack("<HH", 0, 2), s)
                        await writer.drain()
                        continue
                    if p[:1] == b"\x17":        # COM_STMT_EXECUTE
                        (sid,) = struct.unpack_from("<I", p, 1)
                        sql = stmts[sid]
                        np_ = sql.count("?")
                        params = []
                        off = 10
                        if np_:
                            nullmap = p[off:off + (np_ + 7) // 8]
                            off += (np_ + 7) // 8 + 1   # + rebound flag
                            off += 2 * np_              # types
                            from emqx_tpu.auth.mysql import _lenenc
                            for i in range(np_):
                                if nullmap[i // 8] & (1 << (i % 8)):
                                    params.append(None)
                                    continue
                                ln, off = _lenenc(p, off)
                                params.append(
                                    p[off:off + ln].decode())
                                off += ln
                        self.executes.append((sid, params))
                        # substitute (quoted) to reuse the substring-
                        # dispatched fixtures
                        final = sql
                        for v in params:
                            final = final.replace(
                                "?", "'" + (v or "") + "'", 1)
                        cols, rows = [], []
                        for needle, fn in self.tables.items():
                            if needle in final:
                                cols, rows = fn(final)
                                break
                        s = 1
                        if not cols:
                            wr_packet(writer, b"\x00\x00\x00"
                                      + struct.pack("<HH", 2, 0), s)
                            await writer.drain()
                            continue
                        wr_packet(writer, bytes([len(cols)]), s)
                        s += 1
                        for c in cols:
                            coldef(c, s, writer)
                            s += 1
                        wr_packet(writer, b"\xfe"
                                  + struct.pack("<HH", 0, 2), s)
                        s += 1
                        for r in rows:
                            nb = (len(cols) + 9) // 8
                            bm = bytearray(nb)
                            vals = bytearray()
                            for i, v in enumerate(r):
                                if v is None:
                                    bit = i + 2
                                    bm[bit // 8] |= 1 << (bit % 8)
                                else:
                                    vals += _lenenc_str(str(v))
                            wr_packet(writer,
                                      b"\x00" + bytes(bm) + bytes(vals),
                                      s)
                            s += 1
                        wr_packet(writer, b"\xfe"
                                  + struct.pack("<HH", 0, 2), s)
                        await writer.drain()
                        continue
                    if p[:1] != b"\x03":
                        return
                    sql = p[1:].decode()
                    self.queries.append(sql)
                    cols, rows = [], []
                    for needle, fn in self.tables.items():
                        if needle in sql:
                            cols, rows = fn(sql)
                            break
                    s = 1
                    if not cols:
                        # statements without a resultset (INSERT /
                        # SELECT 1 fallthrough) answer with OK, like
                        # a real server
                        wr_packet(writer, b"\x00\x00\x00"
                                  + struct.pack("<HH", 2, 0), s)
                        await writer.drain()
                        continue
                    wr_packet(writer, bytes([len(cols)]), s)
                    s += 1
                    for c in cols:
                        cd = (_lenenc_str("def") + _lenenc_str("")
                              + _lenenc_str("t") + _lenenc_str("t")
                              + _lenenc_str(c) + _lenenc_str(c)
                              + b"\x0c" + struct.pack("<HIBHB", 33, 256,
                                                      0xFD, 0, 0)
                              + b"\x00\x00")
                        wr_packet(writer, cd, s)
                        s += 1
                    wr_packet(writer, b"\xfe" + struct.pack("<HH", 0, 2),
                              s)
                    s += 1
                    for r in rows:
                        rp = b"".join(
                            b"\xfb" if v is None else _lenenc_str(str(v))
                            for v in r)
                        wr_packet(writer, rp, s)
                        s += 1
                    wr_packet(writer, b"\xfe" + struct.pack("<HH", 0, 2),
                              s)
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


SALT = "mysalt"


def user_table(sql):
    if "'manu'" in sql:
        return (["password_hash", "salt", "is_superuser"],
                [[hash_password(b"mpw", "sha256", SALT.encode()),
                  SALT, "0"]])
    return ["password_hash", "salt", "is_superuser"], []


def acl_table(sql):
    if "'manu'" in sql:
        return (["permission", "action", "topic"],
                [["allow", "all", "open/#"],
                 ["deny", "subscribe", "secret/#"]])
    return ["permission", "action", "topic"], []


def test_escape_literal_blocks_injection():
    # quotes doubled (valid in EVERY sql_mode incl NO_BACKSLASH_ESCAPES)
    assert escape_literal("a'b") == "a''b"
    assert escape_literal("x\\") == "x\\\\"   # trailing backslash can't
    sql = render_query("SELECT 1 FROM t WHERE u = ${username}",
                       {"username": "x' OR '1'='1"})
    assert sql == "SELECT 1 FROM t WHERE u = 'x'' OR ''1''=''1'"


def test_render_query_single_pass_no_smuggling():
    """A credential containing another placeholder must NOT get that
    field spliced inside its literal (sequential-replace injection)."""
    sql = render_query(
        "SELECT 1 FROM t WHERE u = ${username} AND c = ${clientid}",
        {"username": "${clientid}",
         "clientid": "' UNION SELECT 'allow' -- "})
    assert "UNION SELECT" not in sql.split("AND")[0]
    assert sql.split("AND")[0].strip().endswith("'${clientid}'")


def test_mysql_authn_authz_roundtrip():
    async def main():
        my = await MockMysql({"mqtt_user": user_table,
                              "mqtt_acl": acl_table}).start()
        server = f"127.0.0.1:{my.port}"
        chain = AuthChain(allow_anonymous=False).add(
            MysqlAuthenticator(server, user="broker", password="dbpw"))
        authz = Authz(sources=[MysqlAuthzSource(server, user="broker",
                                                password="dbpw")],
                      no_match="deny", cache_enable=False)
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg, auth_chain=chain, authz=authz)
        await node.start()
        port = node.listeners.all()[0].port
        try:
            ok = Client(clientid="c1", port=port,
                        username="manu", password=b"mpw")
            await ok.connect()
            assert await ok.subscribe("open/news") == [0]
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            await ok.disconnect()
            with pytest.raises(MqttError):
                await Client(clientid="c2", port=port, username="manu",
                             password=b"wrong").connect()
            with pytest.raises(MqttError):
                await Client(clientid="c3", port=port, username="ghost",
                             password=b"x").connect()
            # credentials rode through ESCAPED literals
            assert any("'manu'" in q for q in my.queries)
        finally:
            await node.stop()
            await my.stop()

    run(main())


def test_mysql_bad_db_password_and_down_server():
    async def main():
        my = await MockMysql({"mqtt_user": user_table}).start()
        wrong = MysqlAuthenticator(f"127.0.0.1:{my.port}", user="broker",
                                   password="nope", timeout=2.0)
        res = await wrong.authenticate_async(
            Credentials("c", "manu", b"mpw"))
        assert res.outcome == "ignore"
        await my.stop()

        dead = MysqlAuthenticator("127.0.0.1:1", timeout=0.3)
        assert (await dead.authenticate_async(
            Credentials("c", "manu", b"mpw"))).outcome == "ignore"

    run(main())


def test_mysql_client_reconnects():
    async def main():
        my = await MockMysql({"mqtt_user": user_table}).start()
        c = MysqlClient(f"127.0.0.1:{my.port}", user="broker",
                        password="dbpw")
        cols, rows = await c.query(
            "SELECT password_hash, salt, is_superuser FROM mqtt_user "
            "WHERE username = 'manu'")
        assert cols[0] == "password_hash" and len(rows) == 1
        for w in list(my._conns):
            w.close()
        await asyncio.sleep(0.05)
        with pytest.raises(Exception):
            await c.query("SELECT 1 FROM mqtt_user WHERE username = 'x'")
        cols, rows = await c.query(
            "SELECT 1 FROM mqtt_user WHERE username = 'ghost'")
        assert rows == []
        await c.close()
        await my.stop()

    run(main())


def test_mysql_bridge_insert_via_rule():
    async def main():
        inserts = []

        def insert_log(sql):
            inserts.append(sql)
            return [], []

        my = await MockMysql({"mqtt_messages": insert_log}).start()
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg)
        await node.start()
        try:
            await node.bridges.create("mysql", "myb", {
                "server": f"127.0.0.1:{my.port}",
                "user": "broker", "password": "dbpw",
                "sql": "INSERT INTO mqtt_messages (c, t, p) "
                       "VALUES (${1}, ${2}, ${3})",
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rmy", 'SELECT clientid, topic, payload FROM "ev/#"',
                actions=["mysql:myb"])
            pub = Client(clientid="mypub",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/9", b"it's payload")  # quote escapes
            br = node.bridges.get("mysql:myb")
            for _ in range(400):
                if br.worker.metrics["success"] >= 1:
                    break
                await asyncio.sleep(0.01)
            assert br.worker.metrics["success"] == 1
            assert inserts == [
                "INSERT INTO mqtt_messages (c, t, p) VALUES "
                "('mypub', 'ev/9', 'it''s payload')"]
            await pub.disconnect()
        finally:
            await node.stop()
            await my.stop()

    run(main())


def test_sql_mode_probe_no_backslash_escapes():
    """ADVICE r3 #5: under NO_BACKSLASH_ESCAPES a backslash is literal
    data; the client probes @@sql_mode at handshake and stops doubling
    backslashes, so a username like 'dom\\user' matches its row."""
    # unit: escaping is mode-dependent
    assert escape_literal("a\\b") == "a\\\\b"
    assert escape_literal("a\\b", no_backslash_escapes=True) == "a\\b"
    assert escape_literal("a'b", no_backslash_escapes=True) == "a''b"

    async def main():
        def sql_mode(_sql):
            return ["@@sql_mode"], [["ANSI_QUOTES,NO_BACKSLASH_ESCAPES"]]

        my = await MockMysql({"@@sql_mode": sql_mode,
                              "mqtt_user": user_table}).start()
        auth = MysqlAuthenticator(f"127.0.0.1:{my.port}", user="broker",
                                  password="dbpw")
        await auth.authenticate_async(
            Credentials("c", "dom\\user", b"pw"))
        lookup = [q for q in my.queries if "mqtt_user" in q]
        assert lookup and "'dom\\user'" in lookup[0]  # NOT doubled
        assert auth.client.no_backslash_escapes is True
        await auth.client.close()
        await my.stop()

    run(main())


def test_render_prepared_binds_instead_of_splicing():
    from emqx_tpu.auth.mysql import render_prepared

    sql, params = render_prepared(
        "SELECT h FROM u WHERE username = ${username} "
        "AND clientid = ${clientid}",
        {"username": "eve'--", "clientid": "c${username}1"})
    assert sql == ("SELECT h FROM u WHERE username = ? "
                   "AND clientid = ?")
    # hostile values stay DATA in the param list, never SQL text
    assert params == ["eve'--", "c${username}1"]


def test_mysql_prepared_statement_authn_roundtrip():
    """prepared: true drives COM_STMT_PREPARE/EXECUTE with binary bind
    params and the binary resultset decoder; the statement handle is
    reused across executions (round 5: flips the 'no server-side
    prepare' limitation)."""
    from emqx_tpu.auth.mysql import MysqlAuthenticator

    async def scenario():
        mock = await MockMysql({"mqtt_user": user_table}).start()
        try:
            auth = MysqlAuthenticator(
                f"127.0.0.1:{mock.port}", user="broker",
                password="dbpw", prepared=True)
            ok = await auth.authenticate_async(Credentials(
                clientid="c1", username="manu", password=b"mpw"))
            assert ok.outcome == "ok"
            bad = await auth.authenticate_async(Credentials(
                clientid="c1", username="manu", password=b"nope"))
            assert bad.outcome == "deny"
            missing = await auth.authenticate_async(Credentials(
                clientid="c1", username="ghost", password=b"x"))
            assert missing.outcome == "ignore"
            # one PREPARE, three EXECUTEs (handle reuse), zero text
            # queries carrying credentials
            assert len(mock.prepares) == 1
            assert "?" in mock.prepares[0]
            assert len(mock.executes) == 3
            assert mock.executes[0][1] == ["manu"]
            assert not any("manu" in q for q in mock.queries)
            await auth.client.close()
        finally:
            await mock.stop()

    run(scenario())


def _sha2_connect(auth_mode, password="dbpw"):
    """MysqlClient against a caching_sha2_password mock in the given
    server flow; returns (mock, rows-from-a-real-query)."""
    async def main():
        my = await MockMysql({"mqtt_user": user_table},
                             plugin="caching_sha2_password",
                             auth_mode=auth_mode,
                             password="dbpw").start()
        cli = MysqlClient(f"127.0.0.1:{my.port}", user="broker",
                          password=password, timeout=2.0)
        try:
            _, rows = await cli.query(
                "SELECT password_hash FROM mqtt_user WHERE u = 'manu'")
            return rows
        finally:
            await cli.close()
            await my.stop()

    return run(main())


def test_caching_sha2_fast_auth():
    rows = _sha2_connect("fast")
    assert rows and rows[0]


def test_caching_sha2_full_auth_over_rsa():
    rows = _sha2_connect("full_rsa")
    assert rows and rows[0]


def test_caching_sha2_auth_switch_to_native():
    rows = _sha2_connect("switch_native")
    assert rows and rows[0]


def test_caching_sha2_wrong_password_denied():
    from emqx_tpu.auth.mysql import MysqlError
    for mode in ("fast", "full_rsa"):
        with pytest.raises(MysqlError, match="denied"):
            _sha2_connect(mode, password="wrong")


def test_rsa_key_parser_accepts_spki_and_pkcs1():
    """MySQL sends SubjectPublicKeyInfo PEM; the PKCS#1 form must parse
    too (some proxies re-wrap)."""
    from emqx_tpu.auth.mysql import _parse_rsa_public_key
    assert _parse_rsa_public_key(_RSA_PEM) == (_RSA_N, _RSA_E)
    # wrap the PKCS#1 body in SPKI: SEQ{ SEQ{oid rsaEncryption, NULL},
    # BIT STRING{ pkcs#1 } }
    alg = bytes.fromhex("300d06092a864886f70d0101010500")
    pkcs1 = b"\x30" + _der_len(len(_der_body)) + _der_body
    bits = b"\x03" + _der_len(len(pkcs1) + 1) + b"\x00" + pkcs1
    spki = b"\x30" + _der_len(len(alg) + len(bits)) + alg + bits
    pem = (b"-----BEGIN PUBLIC KEY-----\n" + base64.encodebytes(spki)
           + b"-----END PUBLIC KEY-----\n")
    assert _parse_rsa_public_key(pem) == (_RSA_N, _RSA_E)


def test_malformed_auth_switch_raises_mysql_error():
    """Unterminated plugin name / missing nonce in an AuthSwitchRequest
    must surface as MysqlError (the auth path's contract), never a
    bare ValueError/ZeroDivisionError."""
    from emqx_tpu.auth.mysql import MysqlError
    for mode in ("switch_broken", "switch_nononce"):
        with pytest.raises(MysqlError, match="malformed|denied|closed"):
            _sha2_connect(mode)
