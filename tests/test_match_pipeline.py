"""Overlapped serve pipeline (ISSUE 11): double-buffered
encode/dispatch/readback with match-proportional two-phase d2h.

Flag off (``match.pipeline.enable = false``, the default) the serial
serve path is byte-identical to the PR-10 shape — asserted here by the
inertness + parity tests; the pre-existing tests/test_match_service.py
suite keeps passing unchanged on top.
"""

import asyncio
import threading

import pytest

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.match_service import MatchService, _StaleRace
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.observe.metrics import Metrics


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=30.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_service(broker, **kw):
    kw.setdefault("depth", 8)
    kw.setdefault("table", "python")
    kw.setdefault("bypass_rate", 0.0)
    kw.setdefault("metrics", Metrics())
    return MatchService(broker, **kw)


def subscribe_many(b, filters, sessions=8):
    for i, flt in enumerate(filters):
        cid = f"s{i % sessions}"
        if cid not in b.sessions:
            b.open_session(cid)
        b.subscribe(cid, flt, SubOpts())


async def synced(ms, b):
    return await settle(
        lambda: ms.ready and ms._seen_epoch == b.router.epoch
        and ms.dev.epoch == ms.inc.epoch)


# ---------------------------------------------------------------------------
# flag off: the pipeline machinery is inert, the serial path serves
# ---------------------------------------------------------------------------

def test_flag_off_pipeline_inert_and_slab_readback(monkeypatch):
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(6)])
        ms = make_service(b)
        assert not ms.pipeline
        calls = {"twophase": 0, "slab": 0}
        orig = MatchService._readback_rows
        monkeypatch.setattr(
            MatchService, "_readback_rows",
            staticmethod(lambda res, n, k: (
                calls.__setitem__("slab", calls["slab"] + 1)
                or orig(res, n, k))))
        monkeypatch.setattr(
            MatchService, "_readback_rows_twophase",
            staticmethod(lambda res, n, k: (
                calls.__setitem__("twophase", calls["twophase"] + 1))))
        await ms.start()
        assert ms._inflight_q is None     # no queue, no readback child
        assert await synced(ms, b)
        await ms.prefetch("room/1/k1")
        assert ms.hint_routes("room/1/k1") is not None
        # flag off reads the FULL slab exactly as PR 10 did — the
        # two-phase path never runs
        assert calls["slab"] >= 1
        assert calls["twophase"] == 0
        await ms.stop()

    run(main())


def test_flag_onoff_hints_identical():
    """The pipelined chain must mint byte-identical hints to the
    serial path for the same table + batch (flag-off parity)."""
    async def hints_with(pipeline):
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(8)] + ["deep/#"])
        ms = make_service(b, pipeline=pipeline)
        await ms.start()
        assert await synced(ms, b)
        topics = [f"room/{i}/k{i % 8}" for i in range(20)] + ["deep/a/b"]
        await ms.prefetch_many({t: 1 for t in topics})
        out = {}
        for t in topics:
            hint = ms._hints.get(t)
            assert hint is not None, (pipeline, t)
            out[t] = (sorted(hint[2]), sorted(hint[3]))
        await ms.stop()
        return out

    async def main():
        serial = await hints_with(False)
        piped = await hints_with(True)
        assert serial == piped

    run(main())


# ---------------------------------------------------------------------------
# pipelined serving: parity, readback bytes, metrics
# ---------------------------------------------------------------------------

def test_pipeline_serves_with_parity_and_proportional_bytes():
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(8)])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert ms._inflight_q is not None
        assert await synced(ms, b)
        topics = [f"room/{i}/k{i % 8}" for i in range(32)]
        await ms.prefetch_many({t: 1 for t in topics})
        for t in topics:
            hint = ms.hint_routes(t)
            want = b.router.match_routes(t)
            assert hint is not None, t
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        # two-phase d2h: bytes shipped are meta + ids, never the
        # FLAT_MULT·B slab; the batch was 32 topics padded to 64
        nbytes = m.get("tpu.match.readback_bytes")
        assert 0 < nbytes
        slab = 4 * (ms.FLAT_MULT * 64 + 3 * 64)
        assert nbytes < slab, (nbytes, slab)
        # quiesced: no slots left in flight, metric reads 0
        assert ms._inflight_n == 0
        assert m.get("broker.match.pipeline_inflight") == 0
        assert m.get("tpu.match.batches") >= 1   # device really served
        await ms.stop()

    run(main())


def test_two_phase_readback_exact_bytes_and_row_parity():
    """Spy-level contract: the two-phase readback ships EXACTLY
    4·(B + sum(counts)) bytes — counts vector first, then the dense
    ids — and decodes the same rows as the full-slab path."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)] + ["a/#"])
        ms = make_service(b, pipeline=True)
        await ms.start()
        assert await synced(ms, b)
        topics = [f"a/{i}/k{i % 6}" for i in range(24)]
        handles, _enc_ns, _disp_ns = ms._encode_dispatch(
            ms.inc, ms.dev, topics,
            [(list(range(len(topics))), ms.depth)], False)
        (res, n) = handles[0]
        import jax
        import numpy as np

        B = int(res.row_meta.shape[0])
        counts_raw = int(np.asarray(
            jax.device_get(res.n_matches))[:n].sum())
        rows2, sp2, nbytes = ms._readback_rows_twophase(
            res, n, ms.dev.max_matches)
        rows1, sp1 = ms._readback_rows(res, n, ms.dev.max_matches)
        assert rows2 == rows1
        assert sp2 == sp1
        # exact: 4·B meta + 4·Σ min(counts, K) ids — within the ISSUE
        # bound of 4·(B + sum(counts)), vs the 4·FLAT_MULT·B slab
        total = sum(len(r) for r in rows2)
        assert nbytes == 4 * (B + total)
        assert nbytes <= 4 * (B + counts_raw)
        assert nbytes < 4 * ms.FLAT_MULT * B
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# satellite bugfix: encode runs OFF the event loop in BOTH modes
# ---------------------------------------------------------------------------

def test_encode_runs_off_loop_flag_off(monkeypatch):
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        ms = make_service(b)          # flag OFF — the serial path
        await ms.start()
        assert await synced(ms, b)
        loop_thread = threading.get_ident()
        seen = []
        import emqx_tpu.ops as ops
        orig = ops.encode_batch

        def spy(*a, **kw):
            seen.append(threading.get_ident())
            return orig(*a, **kw)

        monkeypatch.setattr(ops, "encode_batch", spy)
        await ms.prefetch("room/1/k1")
        hint = ms.hint_routes("room/1/k1")
        assert hint is not None
        want = b.router.match_routes("room/1/k1")
        assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        # the serve-path encode ran in a worker thread, not on the loop
        # (the ~2.3 ms/dispatch loop stall the satellite bugfix kills)
        assert seen and all(t != loop_thread for t in seen)
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# per-slot staleness guards: swap / aid reuse discard exactly one slot
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mutate", ["gen", "reuse"])
def test_inflight_slot_swap_or_reuse_discards_via_guards(mutate):
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)])
        m = Metrics()
        ms = make_service(b, pipeline=True, deadline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        topics = ["a/1/k1", "a/2/k2"]
        loop = asyncio.get_running_loop()
        pending = [(t, loop.create_future(), loop.time() + 1.0)
                   for t in topics]
        groups = [(list(range(len(topics))), ms.depth)]
        handles, enc_ns, disp_ns = ms._encode_dispatch(
            ms.inc, ms.dev, topics, groups, True)
        slot = (pending, topics, groups, handles, ms.inc, ms.dev,
                ms.inc.aid_reuses, ms._table_gen, ms._synced_epoch,
                ms._synced_rule_gen, loop.time(), True,
                enc_ns + disp_ns)
        # the swap/reuse lands while the slot is in flight
        if mutate == "gen":
            ms._table_gen += 1
        else:
            ms.inc.aid_reuses += 1
        await ms._finish_slot(slot)
        # every waiter resolved NOW, answers minted via the CPU tables,
        # and no breaker strike (the device is healthy)
        for _t, fut, _d in pending:
            assert fut.done()
        for t in topics:
            hint = ms._hints.get(t)
            assert hint is not None, t
            want = b.router.match_routes(t)
            got = ms.router.routes_with_wild(t, hint[2])
            assert sorted(map(tuple, got)) == sorted(map(tuple, want))
        assert ms._breaker_failures == 0
        assert m.get("broker.match.cpu_fallback") >= len(topics)
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# match.readback chaos seam + failover
# ---------------------------------------------------------------------------

def test_readback_fault_raise_falls_to_cpu_promptly():
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 1},
        ]))
        try:
            t0 = asyncio.get_running_loop().time()
            await ms.prefetch("room/1/k1")
            waited = asyncio.get_running_loop().time() - t0
            # the faulted slot answers from the CPU tables in one hop,
            # far under the prefetch timeout
            assert waited < ms.prefetch_timeout_s * 0.9
            hint = ms.hint_routes("room/1/k1")
            assert hint is not None
            want = b.router.match_routes("room/1/k1")
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
            assert m.get("broker.match.cpu_fallback") >= 1
            # fixed-window mode: a readback fault is not a breaker
            # strike (only the deadline loop feeds the breaker)
            assert not ms._breaker_open
        finally:
            faultinject.uninstall()
        # the seam is one-shot: the next batch rides the device again
        await ms.prefetch("room/2/k2")
        assert ms.hint_routes("room/2/k2") is not None
        await ms.stop()

    run(main())


def test_readback_fault_in_flag_off_path_shared_seam():
    """The match.readback seam also covers the serial (flag-off)
    loop's d2h boundary — both loops share one chaos surface."""
    async def main():
        b = Broker()
        subscribe_many(b, ["room/+/x"])
        m = Metrics()
        ms = make_service(b, metrics=m)    # flag OFF
        await ms.start()
        assert await synced(ms, b)
        inj = faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 1},
        ]))
        try:
            await ms.prefetch("room/9/x")
            assert inj.fired.get("match.readback") == 1
            # failure path: waiter resolved, host trie serves (the
            # serial loop resolves the batch empty-handed)
            assert b.router.match_routes("room/9/x")
        finally:
            faultinject.uninstall()
        await ms.stop()

    run(main())


def test_stop_resolves_inflight_slot_waiters():
    async def main():
        b = Broker()
        subscribe_many(b, ["t/+"])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        # park a fake in-flight slot, then stop: the readback child's
        # failover must resolve the waiter immediately
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # hang the readback child so the slot stays queued
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "hang", "times": 1},
        ]))
        try:
            await ms.prefetch("t/1")      # consumes the hang
        finally:
            faultinject.uninstall()
        ms._inflight_q.put_nowait(([("t/2", fut)], ["t/2"], [], [],
                                   ms.inc, ms.dev, 0, 0, 0, 0, 0.0,
                                   False))
        await ms.stop()
        await asyncio.sleep(0.01)
        assert fut.done()
        assert ms._inflight_n == 0

    run(main())


# ---------------------------------------------------------------------------
# composition with the deadline loop
# ---------------------------------------------------------------------------

def test_pipeline_composes_with_deadline_breaker():
    """Pipelined readback failures FEED the deadline-mode breaker:
    persistent faults trip CPU-serve mode exactly like dispatch
    failures do."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        m = Metrics()
        ms = make_service(b, pipeline=True, deadline=True,
                          breaker_threshold=3, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 3},
        ]))
        try:
            for i in range(3):
                await ms.prefetch(f"room/{i}/k{i}")
            assert await settle(lambda: ms._breaker_open, timeout=5)
        finally:
            faultinject.uninstall()
        # breaker open: prefetches short-circuit to the CPU path
        await ms.prefetch("room/9/k1")
        assert m.get("broker.match.cpu_fallback") >= 1
        await ms.stop()

    run(main())
