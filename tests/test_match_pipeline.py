"""Overlapped serve pipeline (ISSUE 11): double-buffered
encode/dispatch/readback with match-proportional two-phase d2h.

Flag off (``match.pipeline.enable = false``, the default) the serial
serve path is byte-identical to the PR-10 shape — asserted here by the
inertness + parity tests; the pre-existing tests/test_match_service.py
suite keeps passing unchanged on top.
"""

import asyncio
import threading

import pytest

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.match_service import MatchService, _StaleRace
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.observe.metrics import Metrics


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=30.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_service(broker, **kw):
    kw.setdefault("depth", 8)
    kw.setdefault("table", "python")
    kw.setdefault("bypass_rate", 0.0)
    kw.setdefault("metrics", Metrics())
    return MatchService(broker, **kw)


def subscribe_many(b, filters, sessions=8):
    for i, flt in enumerate(filters):
        cid = f"s{i % sessions}"
        if cid not in b.sessions:
            b.open_session(cid)
        b.subscribe(cid, flt, SubOpts())


async def synced(ms, b):
    return await settle(
        lambda: ms.ready and ms._seen_epoch == b.router.epoch
        and ms.dev.epoch == ms.inc.epoch)


# ---------------------------------------------------------------------------
# flag off: the pipeline machinery is inert, the serial path serves
# ---------------------------------------------------------------------------

def test_flag_off_pipeline_inert_and_slab_readback(monkeypatch):
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(6)])
        ms = make_service(b)
        assert not ms.pipeline
        calls = {"twophase": 0, "slab": 0}
        orig = MatchService._readback_rows
        monkeypatch.setattr(
            MatchService, "_readback_rows",
            staticmethod(lambda res, n, k: (
                calls.__setitem__("slab", calls["slab"] + 1)
                or orig(res, n, k))))
        monkeypatch.setattr(
            MatchService, "_readback_rows_twophase",
            staticmethod(lambda res, n, k, mode="chunked": (
                calls.__setitem__("twophase", calls["twophase"] + 1))))
        await ms.start()
        assert ms._inflight_q is None     # no queue, no readback child
        assert await synced(ms, b)
        await ms.prefetch("room/1/k1")
        assert ms.hint_routes("room/1/k1") is not None
        # flag off reads the FULL slab exactly as PR 10 did — the
        # two-phase path never runs
        assert calls["slab"] >= 1
        assert calls["twophase"] == 0
        await ms.stop()

    run(main())


def test_flag_onoff_hints_identical():
    """The pipelined chain must mint byte-identical hints to the
    serial path for the same table + batch (flag-off parity)."""
    async def hints_with(pipeline):
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(8)] + ["deep/#"])
        ms = make_service(b, pipeline=pipeline)
        await ms.start()
        assert await synced(ms, b)
        topics = [f"room/{i}/k{i % 8}" for i in range(20)] + ["deep/a/b"]
        await ms.prefetch_many({t: 1 for t in topics})
        out = {}
        for t in topics:
            hint = ms._hints.get(t)
            assert hint is not None, (pipeline, t)
            out[t] = (sorted(hint[2]), sorted(hint[3]))
        await ms.stop()
        return out

    async def main():
        serial = await hints_with(False)
        piped = await hints_with(True)
        assert serial == piped

    run(main())


# ---------------------------------------------------------------------------
# pipelined serving: parity, readback bytes, metrics
# ---------------------------------------------------------------------------

def test_pipeline_serves_with_parity_and_proportional_bytes():
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(8)])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert ms._inflight_q is not None
        assert await synced(ms, b)
        topics = [f"room/{i}/k{i % 8}" for i in range(32)]
        await ms.prefetch_many({t: 1 for t in topics})
        for t in topics:
            hint = ms.hint_routes(t)
            want = b.router.match_routes(t)
            assert hint is not None, t
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        # two-phase d2h: bytes shipped are meta + ids, never the
        # FLAT_MULT·B slab; the batch was 32 topics padded to 64
        nbytes = m.get("tpu.match.readback_bytes")
        assert 0 < nbytes
        slab = 4 * (ms.FLAT_MULT * 64 + 3 * 64)
        assert nbytes < slab, (nbytes, slab)
        # quiesced: no slots left in flight, metric reads 0
        assert ms._inflight_n == 0
        assert m.get("broker.match.pipeline_inflight") == 0
        assert m.get("tpu.match.batches") >= 1   # device really served
        await ms.stop()

    run(main())


def test_two_phase_readback_exact_bytes_and_row_parity():
    """Spy-level contract: the two-phase readback ships EXACTLY
    4·(B + sum(counts)) bytes — counts vector first, then the dense
    ids — and decodes the same rows as the full-slab path."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)] + ["a/#"])
        ms = make_service(b, pipeline=True)
        await ms.start()
        assert await synced(ms, b)
        topics = [f"a/{i}/k{i % 6}" for i in range(24)]
        handles, _enc_ns, _disp_ns = ms._encode_dispatch(
            ms.inc, ms.dev, topics,
            [(list(range(len(topics))), ms.depth)], False)
        (res, n) = handles[0]
        import jax
        import numpy as np

        B = int(res.row_meta.shape[0])
        counts_raw = int(np.asarray(
            jax.device_get(res.n_matches))[:n].sum())
        rows2, sp2, nbytes, trips = ms._readback_rows_twophase(
            res, n, ms.dev.max_matches)
        rows1, sp1 = ms._readback_rows(res, n, ms.dev.max_matches)
        assert rows2 == rows1
        assert sp2 == sp1
        # exact: 4·B meta + 4·Σ min(counts, K) ids — within the ISSUE
        # bound of 4·(B + sum(counts)), vs the 4·FLAT_MULT·B slab
        total = sum(len(r) for r in rows2)
        assert nbytes == 4 * (B + total)
        assert nbytes <= 4 * (B + counts_raw)
        assert nbytes < 4 * ms.FLAT_MULT * B
        # chunked trips: the meta fetch + one per pow2 chunk
        assert trips == 1 + bin(total).count("1")
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# satellite bugfix: encode runs OFF the event loop in BOTH modes
# ---------------------------------------------------------------------------

def test_encode_runs_off_loop_flag_off(monkeypatch):
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        ms = make_service(b)          # flag OFF — the serial path
        await ms.start()
        assert await synced(ms, b)
        loop_thread = threading.get_ident()
        seen = []
        import emqx_tpu.ops as ops
        orig = ops.encode_batch

        def spy(*a, **kw):
            seen.append(threading.get_ident())
            return orig(*a, **kw)

        monkeypatch.setattr(ops, "encode_batch", spy)
        await ms.prefetch("room/1/k1")
        hint = ms.hint_routes("room/1/k1")
        assert hint is not None
        want = b.router.match_routes("room/1/k1")
        assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        # the serve-path encode ran in a worker thread, not on the loop
        # (the ~2.3 ms/dispatch loop stall the satellite bugfix kills)
        assert seen and all(t != loop_thread for t in seen)
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# per-slot staleness guards: swap / aid reuse discard exactly one slot
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mutate", ["gen", "reuse"])
def test_inflight_slot_swap_or_reuse_discards_via_guards(mutate):
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)])
        m = Metrics()
        ms = make_service(b, pipeline=True, deadline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        topics = ["a/1/k1", "a/2/k2"]
        loop = asyncio.get_running_loop()
        pending = [(t, loop.create_future(), loop.time() + 1.0)
                   for t in topics]
        groups = [(list(range(len(topics))), ms.depth)]
        handles, enc_ns, disp_ns = ms._encode_dispatch(
            ms.inc, ms.dev, topics, groups, True)
        slot = (pending, topics, groups, handles, ms.inc, ms.dev,
                ms.inc.aid_reuses, ms._table_gen, ms._synced_epoch,
                ms._synced_rule_gen, loop.time(), True,
                enc_ns + disp_ns)
        # the swap/reuse lands while the slot is in flight
        if mutate == "gen":
            ms._table_gen += 1
        else:
            ms.inc.aid_reuses += 1
        await ms._finish_slot(slot)
        # every waiter resolved NOW, answers minted via the CPU tables,
        # and no breaker strike (the device is healthy)
        for _t, fut, _d in pending:
            assert fut.done()
        for t in topics:
            hint = ms._hints.get(t)
            assert hint is not None, t
            want = b.router.match_routes(t)
            got = ms.router.routes_with_wild(t, hint[2])
            assert sorted(map(tuple, got)) == sorted(map(tuple, want))
        assert ms._breaker_failures == 0
        assert m.get("broker.match.cpu_fallback") >= len(topics)
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# match.readback chaos seam + failover
# ---------------------------------------------------------------------------

def test_readback_fault_raise_falls_to_cpu_promptly():
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 1},
        ]))
        try:
            t0 = asyncio.get_running_loop().time()
            await ms.prefetch("room/1/k1")
            waited = asyncio.get_running_loop().time() - t0
            # the faulted slot answers from the CPU tables in one hop,
            # far under the prefetch timeout
            assert waited < ms.prefetch_timeout_s * 0.9
            hint = ms.hint_routes("room/1/k1")
            assert hint is not None
            want = b.router.match_routes("room/1/k1")
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
            assert m.get("broker.match.cpu_fallback") >= 1
            # fixed-window mode: a readback fault is not a breaker
            # strike (only the deadline loop feeds the breaker)
            assert not ms._breaker_open
        finally:
            faultinject.uninstall()
        # the seam is one-shot: the next batch rides the device again
        await ms.prefetch("room/2/k2")
        assert ms.hint_routes("room/2/k2") is not None
        await ms.stop()

    run(main())


def test_readback_fault_in_flag_off_path_shared_seam():
    """The match.readback seam also covers the serial (flag-off)
    loop's d2h boundary — both loops share one chaos surface."""
    async def main():
        b = Broker()
        subscribe_many(b, ["room/+/x"])
        m = Metrics()
        ms = make_service(b, metrics=m)    # flag OFF
        await ms.start()
        assert await synced(ms, b)
        inj = faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 1},
        ]))
        try:
            await ms.prefetch("room/9/x")
            assert inj.fired.get("match.readback") == 1
            # failure path: waiter resolved, host trie serves (the
            # serial loop resolves the batch empty-handed)
            assert b.router.match_routes("room/9/x")
        finally:
            faultinject.uninstall()
        await ms.stop()

    run(main())


def test_stop_resolves_inflight_slot_waiters():
    async def main():
        b = Broker()
        subscribe_many(b, ["t/+"])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        # park a fake in-flight slot, then stop: the readback child's
        # failover must resolve the waiter immediately
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # hang the readback child so the slot stays queued
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "hang", "times": 1},
        ]))
        try:
            await ms.prefetch("t/1")      # consumes the hang
        finally:
            faultinject.uninstall()
        ms._inflight_q.put_nowait(([("t/2", fut)], ["t/2"], [], [],
                                   ms.inc, ms.dev, 0, 0, 0, 0, 0.0,
                                   False))
        await ms.stop()
        await asyncio.sleep(0.01)
        assert fut.done()
        assert ms._inflight_n == 0

    run(main())


# ---------------------------------------------------------------------------
# composition with the deadline loop
# ---------------------------------------------------------------------------

def test_pipeline_composes_with_deadline_breaker():
    """Pipelined readback failures FEED the deadline-mode breaker:
    persistent faults trip CPU-serve mode exactly like dispatch
    failures do."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        m = Metrics()
        ms = make_service(b, pipeline=True, deadline=True,
                          breaker_threshold=3, metrics=m)
        await ms.start()
        assert await synced(ms, b)
        faultinject.install(FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 3},
        ]))
        try:
            for i in range(3):
                await ms.prefetch(f"room/{i}/k{i}")
            assert await settle(lambda: ms._breaker_open, timeout=5)
        finally:
            faultinject.uninstall()
        # breaker open: prefetches short-circuit to the CPU path
        await ms.prefetch("room/9/k1")
        assert m.get("broker.match.cpu_fallback") >= 1
        await ms.stop()

    run(main())


# ---------------------------------------------------------------------------
# one-round-trip serve (ISSUE 17): ragged single-transfer readback
# ---------------------------------------------------------------------------

def _dispatch_one(ms, topics):
    """Encode + dispatch one batch through the real device path and
    hand back its (res, n) handle for direct readback assertions."""
    handles, _enc_ns, _disp_ns = ms._encode_dispatch(
        ms.inc, ms.dev, topics,
        [(list(range(len(topics))), ms.depth)], False)
    return handles[0]


def _count_device_gets(monkeypatch):
    """Spy on jax.device_get — every d2h round trip of the readback
    path funnels through it."""
    import jax

    calls = {"n": 0}
    orig = jax.device_get

    def spy(x):
        calls["n"] += 1
        return orig(x)

    monkeypatch.setattr(jax, "device_get", spy)
    return calls


def test_ragged_readback_two_transfers_and_bit_parity(monkeypatch):
    """The tentpole contract: ragged mode reads a batch in EXACTLY two
    d2h round trips (4·B meta + one padded payload) and decodes rows
    bit-identical to the chunked decomposition AND the full slab."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)] + ["a/#"])
        ms = make_service(b, pipeline=True)
        await ms.start()
        assert await synced(ms, b)
        res, n = _dispatch_one(ms, [f"a/{i}/k{i % 6}" for i in range(24)])
        k = ms.dev.max_matches
        rows_c, sp_c, nb_c, tr_c = ms._readback_rows_twophase(
            res, n, k, mode="chunked")
        rows_s, sp_s = ms._readback_rows(res, n, k)
        calls = _count_device_gets(monkeypatch)
        rows_r, sp_r, nb_r, tr_r = ms._readback_rows_twophase(
            res, n, k, mode="ragged")
        assert rows_r == rows_c == rows_s
        assert sp_r == sp_c == sp_s
        # the spy-level bound: TWO device_get round trips, agreeing
        # with the trip count the metrics pipeline reports
        assert tr_r <= 2
        assert calls["n"] == tr_r == 2
        total = sum(len(r) for r in rows_r)
        # chunked pays popcount(total) payload trips for exact bytes;
        # ragged pays ≤ 2x bytes for exactly one payload trip
        assert tr_c == 1 + bin(total).count("1")
        from emqx_tpu.ops.match_kernel import ragged_capacity

        B = int(res.row_meta.shape[0])
        cap = ragged_capacity(total, int(res.matches.shape[0]))
        assert nb_r == 4 * (B + cap)
        assert nb_c == 4 * (B + total)
        assert nb_r <= 4 * B + 8 * max(4 * total, 4)
        await ms.stop()

    run(main())


def test_ragged_readback_meta_only_when_no_matches(monkeypatch):
    """Σcounts == 0: phase 2 vanishes — ONE d2h (the meta vector),
    every row empty, in both ragged and auto modes."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        ms = make_service(b, pipeline=True)
        await ms.start()
        assert await synced(ms, b)
        res, n = _dispatch_one(ms, ["zzz/1", "zzz/2", "zzz/3"])
        for mode in ("ragged", "auto"):
            calls = _count_device_gets(monkeypatch)
            rows, sp, nbytes, trips = ms._readback_rows_twophase(
                res, n, ms.dev.max_matches, mode=mode)
            assert rows == [[], [], []]
            assert sp == []
            assert trips == 1
            assert calls["n"] == 1
            assert nbytes == 4 * int(res.row_meta.shape[0])
        await ms.stop()

    run(main())


def test_ragged_readback_all_spill_batch():
    """Every row overflowing K stays fail-open through the ragged
    contract: counts clamp to K, every row lands in the spilled set,
    and the two-transfer bound holds."""
    async def main():
        b = Broker()
        # 8 overlapping filters vs max_matches=4: every topic spills
        subscribe_many(b, [f"s/+/k{i}" for i in range(4)]
                       + ["s/#", "s/+/#", "#", "+/+/+"])
        ms = make_service(b, pipeline=True, max_matches=4)
        await ms.start()
        assert await synced(ms, b)
        topics = [f"s/{i}/k{i % 4}" for i in range(6)]
        res, n = _dispatch_one(ms, topics)
        rows_r, sp_r, _nb, trips = ms._readback_rows_twophase(
            res, n, ms.dev.max_matches, mode="ragged")
        rows_c, sp_c, _nb2, _t2 = ms._readback_rows_twophase(
            res, n, ms.dev.max_matches, mode="chunked")
        assert sp_r == sp_c == list(range(len(topics)))
        assert rows_r == rows_c
        assert all(len(r) == 4 for r in rows_r)  # clamped to K
        assert trips <= 2
        await ms.stop()

    run(main())


def test_ragged_capacity_class_boundary_matches_chunked():
    """total == its capacity class (exact pow2): ragged pads nothing,
    bytes equal chunked exactly, and auto picks the chunked shape (a
    pow2 total is one chunk either way — same bytes AND trips)."""
    async def main():
        b = Broker()
        # disjoint single-wildcard filters: each topic matches exactly
        # one (literal filters answer off-device via the exact dict)
        subscribe_many(b, [f"p{i}/+" for i in range(4)])
        ms = make_service(b, pipeline=True)
        await ms.start()
        assert await synced(ms, b)
        res, n = _dispatch_one(ms, [f"p{i}/x" for i in range(4)])
        k = ms.dev.max_matches
        rows_r, _sp, nb_r, tr_r = ms._readback_rows_twophase(
            res, n, k, mode="ragged")
        rows_c, _sp2, nb_c, tr_c = ms._readback_rows_twophase(
            res, n, k, mode="chunked")
        _rows_a, _sp3, nb_a, tr_a = ms._readback_rows_twophase(
            res, n, k, mode="auto")
        total = sum(len(r) for r in rows_r)
        assert total == 4 and total & (total - 1) == 0
        assert rows_r == rows_c
        # pow2 boundary: capacity class == total, zero padding bytes
        assert nb_r == nb_c == nb_a
        assert tr_r == tr_c == tr_a == 2
        await ms.stop()

    run(main())


def test_midflight_swap_discards_ragged_slot():
    """A table swap landing while a ragged slot is in flight discards
    exactly that slot: waiters answer from the CPU tables, no breaker
    strike (same _StaleRace fail-open as the chunked path)."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"a/+/k{i}" for i in range(6)])
        m = Metrics()
        ms = make_service(b, pipeline=True, deadline=True, metrics=m,
                          readback_mode="ragged")
        await ms.start()
        assert await synced(ms, b)
        topics = ["a/1/k1", "a/2/k2"]
        loop = asyncio.get_running_loop()
        pending = [(t, loop.create_future(), loop.time() + 1.0)
                   for t in topics]
        groups = [(list(range(len(topics))), ms.depth)]
        handles, enc_ns, disp_ns = ms._encode_dispatch(
            ms.inc, ms.dev, topics, groups, True)
        slot = (pending, topics, groups, handles, ms.inc, ms.dev,
                ms.inc.aid_reuses, ms._table_gen, ms._synced_epoch,
                ms._synced_rule_gen, loop.time(), True,
                enc_ns + disp_ns)
        ms._table_gen += 1          # the swap lands mid-flight
        await ms._finish_slot(slot)
        for _t, fut, _d in pending:
            assert fut.done()
        for t in topics:
            hint = ms._hints.get(t)
            assert hint is not None, t
            want = b.router.match_routes(t)
            got = ms.router.routes_with_wild(t, hint[2])
            assert sorted(map(tuple, got)) == sorted(map(tuple, want))
        assert ms._breaker_failures == 0
        assert m.get("broker.match.cpu_fallback") >= len(topics)
        await ms.stop()

    run(main())


def test_readback_mode_flag_off_byte_identity(monkeypatch):
    """``match.readback.mode = chunked`` (the default) leaves BOTH
    serve loops byte-identical to the PR-16 shape: the serial path
    reads the slab, the pipelined path runs the chunked two-phase —
    fetch_flat_ragged never executes (spy-asserted)."""
    async def main():
        from emqx_tpu.ops import match_kernel

        def boom(*a, **kw):  # pragma: no cover - must never run
            raise AssertionError("ragged fetch ran with the flag off")

        monkeypatch.setattr(match_kernel, "fetch_flat_ragged", boom)
        for pipeline in (False, True):
            b = Broker()
            subscribe_many(b, [f"room/+/k{i}" for i in range(6)])
            ms = make_service(b, pipeline=pipeline)
            assert ms.readback_mode == "chunked"
            await ms.start()
            assert await synced(ms, b)
            await ms.prefetch_many(
                {f"room/{i}/k{i % 6}": 1 for i in range(12)})
            for i in range(12):
                t = f"room/{i}/k{i % 6}"
                hint = ms.hint_routes(t)
                want = b.router.match_routes(t)
                assert hint is not None, t
                assert sorted(map(tuple, hint)) == \
                    sorted(map(tuple, want))
            await ms.stop()

    run(main())


def test_ragged_serve_parity_and_roundtrip_metric():
    """End-to-end through BOTH serve loops with the flag on: hints
    match the CPU router, and ``tpu.match.readback_roundtrips`` stays
    ≤ 2 per served batch."""
    async def main():
        for pipeline in (False, True):
            b = Broker()
            subscribe_many(b,
                           [f"room/+/k{i}" for i in range(8)] + ["deep/#"])
            m = Metrics()
            ms = make_service(b, pipeline=pipeline, metrics=m,
                              readback_mode="ragged")
            await ms.start()
            assert await synced(ms, b)
            topics = [f"room/{i}/k{i % 8}" for i in range(20)] \
                + ["deep/a/b"]
            await ms.prefetch_many({t: 1 for t in topics})
            for t in topics:
                hint = ms.hint_routes(t)
                want = b.router.match_routes(t)
                assert hint is not None, (pipeline, t)
                assert sorted(map(tuple, hint)) == \
                    sorted(map(tuple, want))
            batches = m.get("tpu.match.batches")
            trips = m.get("tpu.match.readback_roundtrips")
            assert batches >= 1
            assert 0 < trips <= 2 * batches, (trips, batches)
            await ms.stop()

    run(main())


def test_ragged_faultinject_readback_seam_covered():
    """The ``match.readback`` chaos seam sits upstream of the mode
    switch: a raise faults the ragged path exactly like chunked and
    the slot fails over to the CPU tables."""
    async def main():
        b = Broker()
        subscribe_many(b, [f"room/+/k{i}" for i in range(4)])
        m = Metrics()
        ms = make_service(b, pipeline=True, metrics=m,
                          readback_mode="ragged")
        await ms.start()
        assert await synced(ms, b)
        inj = FaultInjector([
            {"point": "match.readback", "action": "raise", "times": 1},
        ])
        faultinject.install(inj)
        try:
            await ms.prefetch("room/1/k1")
            assert inj.fired.get("match.readback") == 1
            hint = ms.hint_routes("room/1/k1")
            want = b.router.match_routes("room/1/k1")
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
            assert m.get("broker.match.cpu_fallback") >= 1
        finally:
            faultinject.uninstall()
        await ms.stop()

    run(main())
