"""Full-subsystem soak: one node with gateways, bridges, rules,
retainer, delayed, tracing, slow-subs, topic-metrics and the dashboard
ALL enabled, under a mixed workload — cross-subsystem integration
invariants (no lost deliveries, no errored hooks, consistent counters).
The reference's CT suites soak similar all-app nodes (SURVEY.md §4)."""

import asyncio
import json
import logging

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode

from test_kafka_bridge import MockKafka


def run(coro):
    return asyncio.run(coro)


def test_all_subsystems_soak(caplog, tmp_path):
    async def main():
        mk = await MockKafka(topics={"soak": 1}).start()
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'listeners.ws.default.enable = true\n'
            'listeners.ws.default.bind = "127.0.0.1:0"\n'
            'gateway.stomp.enable = true\n'
            'gateway.stomp.bind = "127.0.0.1:0"\n'
            'gateway.mqttsn.enable = true\n'
            'gateway.mqttsn.bind = "127.0.0.1:0"\n'
            'gateway.coap.enable = true\n'
            'gateway.coap.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\n'
            'dashboard.listen = "127.0.0.1:0"\n'
            'api_key.enable = true\n'
            'api_key.key = "k"\napi_key.secret = "s"\n'
            'slow_subs.enable = true\n'
            'flapping_detect.enable = true\n'
            'delayed.enable = true\n'
            'retainer.enable = true\n'))
        node = BrokerNode(cfg)
        node.tracing.dir = str(tmp_path)   # keep trace files out of cwd
        await node.start()
        try:
            port = node.listeners.all()[0].port
            node.topic_metrics.register("soak/hot")
            await node.bridges.create("kafka", "sk", {
                "server": f"127.0.0.1:{mk.port}", "topic": "soak",
                "resource_opts": {"batch_size": 16, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rsk", 'SELECT topic, payload, clientid FROM "soak/#"',
                actions=["kafka:sk"])
            node.tracing.create("t1", "topic", "soak/#")

            subs = []
            for i in range(8):
                c = Client(clientid=f"soak-s{i}", port=port)
                await c.connect()
                await c.subscribe("soak/#", qos=1)
                subs.append(c)

            pubs = []
            for i in range(4):
                c = Client(clientid=f"soak-p{i}", port=port)
                await c.connect()
                pubs.append(c)

            # subscribed BEFORE the $delayed publishes: their 1s fuse
            # can burn down during the hot-drain loop on a loaded box
            late = Client(clientid="soak-late", port=port)
            await late.connect()
            await late.subscribe("soak/later", qos=0)

            N = 40
            for n in range(N):
                p = pubs[n % len(pubs)]
                await p.publish("soak/hot", b"m%d" % n, qos=1,
                                retain=(n % 10 == 0))
                if n % 7 == 0:
                    await p.publish("$delayed/1/soak/later", b"d%d" % n,
                                    qos=0)

            # every subscriber gets every soak/hot message; count ONLY
            # soak/hot (the delayed soak/later fan-out also lands in
            # these queues and must not satisfy the wait early)
            want = N * len(subs)
            hot_seen = 0

            async def got():
                nonlocal hot_seen
                for s in subs:
                    while not s.messages.empty():
                        if s.messages.get_nowait().topic == "soak/hot":
                            hot_seen += 1
                return hot_seen >= want

            for _ in range(400):
                if await got():
                    break
                await asyncio.sleep(0.02)
            assert await got(), (hot_seen, want)



            # delayed publishes fire
            m = await asyncio.wait_for(late.messages.get(), 10)
            assert m.topic == "soak/later"

            # retained replay for a late subscriber
            r = Client(clientid="soak-ret", port=port)
            await r.connect()
            await r.subscribe("soak/hot", qos=0)
            m = await asyncio.wait_for(r.messages.get(), 5)
            assert m.retain

            # bridge egressed everything
            br = node.bridges.get("kafka:sk")
            for _ in range(400):
                if br.worker.metrics["success"] >= N:
                    break
                await asyncio.sleep(0.02)
            assert br.worker.metrics["success"] >= N
            assert len(mk.all_records("soak")) >= N

            # counters consistent
            tm = node.topic_metrics.info("soak/hot")
            assert tm["messages.in"] == N
            assert tm["messages.out"] >= want
            stats = node.observed.stats.all()
            assert stats["connections.count"] == len(subs) + len(pubs) + 2
            # trace captured publish events
            node.tracing.stop("t1")
            data = node.tracing.read("t1")
            assert b"soak/hot" in data

            for c in subs + pubs + [late, r]:
                await c.disconnect()
        finally:
            await node.stop()
            await mk.stop()

    # no ERROR-level records from any subsystem during the soak
    with caplog.at_level(logging.ERROR):
        run(main())
    errors = [r for r in caplog.records if r.levelno >= logging.ERROR]
    assert not errors, [r.getMessage() for r in errors]
