"""Retainer / delayed / rewrite / auto-subscribe — the emqx_retainer,
emqx_delayed, emqx_modules, emqx_auto_subscribe parity surface
(SURVEY.md §2.3)."""

import pytest

from emqx_tpu import topic as T
from emqx_tpu.broker import Broker, SubOpts
from emqx_tpu.broker.message import make_message
from emqx_tpu.services import (
    AutoSubscribe, DelayedPublish, Retainer, RewriteRule, TopicRewrite,
)


def _msg(topic, payload=b"x", retain=False, qos=0, props=None, sender="p"):
    return make_message(sender, topic, payload, qos=qos, retain=retain,
                        properties=props or {})


# ---------------------------------------------------------------------------
# Retainer


def test_retainer_store_and_empty_payload_delete():
    r = Retainer()
    r.insert(_msg("a/b", b"1", retain=True))
    assert len(r) == 1
    r.insert(_msg("a/b", b"", retain=True))  # MQTT §3.3.1.3 delete
    assert len(r) == 0


def test_retainer_wildcard_match_host():
    r = Retainer()
    for t in ("a/b", "a/c", "a/b/c", "x/y", "$SYS/broker/uptime"):
        r.insert(_msg(t, b"1", retain=True))
    assert {m.topic for m in r.match("a/+")} == {"a/b", "a/c"}
    assert {m.topic for m in r.match("a/#")} == {"a/b", "a/c", "a/b/c"}
    assert {m.topic for m in r.match("#")} == {"a/b", "a/c", "a/b/c", "x/y"}
    assert {m.topic for m in r.match("+/y")} == {"x/y"}
    # $-topics need an explicit prefix (MQTT §4.7.2)
    assert {m.topic for m in r.match("$SYS/#")} == {"$SYS/broker/uptime"}
    # parity with the oracle on every pair
    for flt in ("a/+", "a/#", "#", "+/y", "$SYS/#", "+/+"):
        got = {m.topic for m in r.match(flt)}
        want = {t for t in r.topics() if T.match(t, flt)}
        assert got == want, flt


def test_retainer_expiry_and_limits():
    r = Retainer(max_payload_size=4, max_retained_messages=2)
    assert not r.insert(_msg("big", b"12345", retain=True))
    assert r.insert(_msg("a", b"1", retain=True))
    assert r.insert(_msg("b", b"1", retain=True))
    assert not r.insert(_msg("c", b"1", retain=True))  # table full
    assert r.insert(_msg("a", b"2", retain=True))      # replace ok
    r2 = Retainer()
    r2.insert(_msg("t", b"1", retain=True,
                   props={"Message-Expiry-Interval": 0.0001}))
    import time as _t
    _t.sleep(0.001)
    assert r2.match("t") == []
    assert r2.clean_expired() == 1 and len(r2) == 0


def test_retainer_replay_batch_device_matches_host():
    r = Retainer()
    topics = ["s/1/temp", "s/2/temp", "s/1/hum", "b/x", "deep/a/b/c/d"]
    for t in topics:
        r.insert(_msg(t, b"1", retain=True))
    filters = ["s/+/temp", "s/#", "b/x", "nope/+", "#"]
    out = r.replay_batch(filters)
    for f in filters:
        want = sorted(t for t in topics if T.match(t, f))
        assert [m.topic for m in out[f]] == want, f


def test_retainer_broker_replay_rh_semantics():
    b = Broker()
    Retainer().attach(b)
    b.open_session("pub")
    res = b.publish(_msg("news/1", b"old", retain=True, qos=1))
    b.open_session("s1")
    # rh=0: replay on subscribe
    b.subscribe("s1", "news/+", SubOpts(qos=1, rh=0))
    pubs = b.take_outbox("s1")
    assert len(pubs) == 1 and pubs[0].msg.retain and pubs[0].msg.payload == b"old"
    # rh=1: replay only if new — resubscribe is not new
    b.subscribe("s1", "news/+", SubOpts(qos=1, rh=1))
    assert b.take_outbox("s1") == []
    # rh=2: never
    b.open_session("s2")
    b.subscribe("s2", "news/+", SubOpts(qos=1, rh=2))
    assert b.take_outbox("s2") == []
    # $share subscriptions get no retained replay
    b.open_session("s3")
    b.subscribe("s3", "$share/g/news/+", SubOpts(qos=1))
    assert b.take_outbox("s3") == []


def test_retained_flag_set_on_replay_but_cleared_on_route():
    b = Broker()
    Retainer().attach(b)
    b.open_session("live")
    b.subscribe("live", "t", SubOpts())  # rap=0
    res = b.publish(_msg("t", b"v", retain=True))
    [pub] = res.publishes["live"]
    assert pub.msg.retain is False  # live route clears retain (RAP off)
    b.open_session("late")
    b.subscribe("late", "t", SubOpts())
    [pub] = b.take_outbox("late")
    assert pub.msg.retain is True   # replay keeps it


# ---------------------------------------------------------------------------
# Delayed


def test_delayed_intercept_and_due():
    d = DelayedPublish()
    now = 1000.0
    assert d.intercept(_msg("$delayed/5/a/b"), now=now) is None
    assert d.intercept(_msg("$delayed/bogus/a"), now=now) is None  # dropped
    assert d.stats["dropped_bad_topic"] == 1
    kept = d.intercept(_msg("normal/topic"), now=now)
    assert kept is not None
    assert len(d) == 1
    assert d.due(now=1004.9) == []
    [m] = d.due(now=1005.1)
    assert m.topic == "a/b"
    assert len(d) == 0


def test_delayed_through_broker_pipeline():
    b = Broker()
    d = DelayedPublish().attach(b)
    b.open_session("s")
    b.subscribe("s", "room/light")
    res = b.publish(_msg("$delayed/1/room/light", b"on"))
    assert res.no_subscribers  # swallowed now
    assert len(d) == 1
    import time as _t
    assert d.tick(now=_t.time() + 2) == 1
    # delivered through the normal pipeline to the live session outbox
    sess = b.sessions["s"]
    assert sess.info()["mqueue_len"] == 0  # qos0 sends immediately


def test_delayed_max_table():
    d = DelayedPublish(max_delayed_messages=1)
    d.intercept(_msg("$delayed/1/a"), now=0)
    d.intercept(_msg("$delayed/1/b"), now=0)
    assert len(d) == 1 and d.stats["dropped_full"] == 1


# ---------------------------------------------------------------------------
# Rewrite


def test_rewrite_rules_last_match_wins():
    rw = TopicRewrite([
        RewriteRule("pub", "x/#", r"^x/y/(.+)$", "z/y/$1"),
        RewriteRule("all", "x/y/1", r"^x/y/(.+)$", "b/y/$1"),
    ])
    assert rw.rewrite("x/y/1", "pub") == "b/y/1"   # later rule wins
    assert rw.rewrite("x/y/2", "pub") == "z/y/2"
    assert rw.rewrite("x/1/2", "pub") == "x/1/2"   # regex miss
    assert rw.rewrite("other", "pub") == "other"


def test_rewrite_placeholders_and_broker_hooks():
    b = Broker()
    TopicRewrite([
        RewriteRule("pub", "u/#", r"^u/(.+)$", "user/%c/$1"),
    ]).attach(b)
    b.open_session("c1")
    b.subscribe("c1", "user/c1/data")
    res = b.publish(_msg("u/data", b"1", sender="c1"))
    assert res.matched == 1  # rewritten to user/c1/data


def test_rewrite_subscribe_packet():
    from emqx_tpu.mqtt import packet as P

    b = Broker()
    TopicRewrite([
        RewriteRule("sub", "old/#", r"^old/(.+)$", "new/$1"),
    ]).attach(b)
    pkt = P.Subscribe(packet_id=1, topic_filters=[("old/a", {"qos": 1})])
    b.hooks.run("client.subscribe", ("c", pkt))
    assert pkt.topic_filters == [("new/a", {"qos": 1})]


# ---------------------------------------------------------------------------
# Auto-subscribe


def test_auto_subscribe_on_connected():
    b = Broker()
    a = AutoSubscribe()
    a.add("inbox/%c", SubOpts(qos=1))
    a.attach(b)
    b.open_session("dev42")
    b.hooks.run("client.connected", ("dev42", {"username": "u"}))
    assert "inbox/dev42" in b.sessions["dev42"].subscriptions
    res = b.publish(_msg("inbox/dev42", b"hello", qos=1))
    assert res.matched == 1
