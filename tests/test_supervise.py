"""Supervision tree (supervise.py) + fault injector (faultinject.py):
restart policies, deterministic backoff/jitter under an injected clock,
restart-intensity escalation to degraded mode (alarm + metric), reverse
shutdown ordering with drain, and the zero-cost-when-disabled guarantee
of the injection seams."""

import asyncio

import pytest

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, make_message
from emqx_tpu.faultinject import FaultInjector, InjectedFault
from emqx_tpu.observe.alarm import Alarms
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.supervise import Supervisor


def run(coro):
    return asyncio.run(coro)


async def until(pred, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred() and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.002)
    return pred()


def fast_sup(**kw):
    kw.setdefault("backoff_base", 0.001)
    kw.setdefault("backoff_max", 0.01)
    kw.setdefault("jitter", 0.0)
    return Supervisor(**kw)


# ---------------------------------------------------------------------------
# restart policies
# ---------------------------------------------------------------------------

def test_permanent_restarts_on_crash_and_normal_exit():
    async def main():
        runs = {"n": 0}

        async def worker():
            runs["n"] += 1
            if runs["n"] == 1:
                raise RuntimeError("boom")
            if runs["n"] == 2:
                return                      # normal exit: still restarted
            await asyncio.Event().wait()    # park

        m = Metrics()
        sup = fast_sup(metrics=m)
        child = sup.start_child("w", worker, restart="permanent")
        assert await until(lambda: runs["n"] >= 3 and child.alive())
        assert child.restarts == 2
        assert m.get("broker.supervisor.restarts") == 2
        await sup.stop()

    run(main())


def test_transient_restarts_on_crash_only():
    async def main():
        runs = {"n": 0}

        async def worker():
            runs["n"] += 1
            if runs["n"] == 1:
                raise RuntimeError("boom")
            # second run returns cleanly → transient is DONE

        sup = fast_sup()
        child = sup.start_child("w", worker, restart="transient")
        assert await until(lambda: child.state == "done")
        assert runs["n"] == 2
        await asyncio.sleep(0.02)
        assert runs["n"] == 2               # no further restarts
        await sup.stop()

    run(main())


def test_temporary_never_restarts():
    async def main():
        runs = {"n": 0}

        async def worker():
            runs["n"] += 1
            raise RuntimeError("boom")

        sup = fast_sup()
        child = sup.start_child("w", worker, restart="temporary")
        assert await until(lambda: child.state == "done")
        assert runs["n"] == 1
        await sup.stop()

    run(main())


def test_kill_restarts_cancel_stops():
    async def main():
        runs = {"n": 0}

        async def worker():
            runs["n"] += 1
            await asyncio.Event().wait()

        sup = fast_sup()
        child = sup.start_child("w", worker)
        assert await until(lambda: child.alive())
        # kill = chaos: the current run dies, supervision restarts it
        assert child.kill()
        assert await until(lambda: runs["n"] == 2 and child.alive())
        # cancel = stop: no restart
        child.cancel()
        assert await until(lambda: child.done())
        await asyncio.sleep(0.02)
        assert runs["n"] == 2
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# backoff determinism + intensity escalation
# ---------------------------------------------------------------------------

def _crashy_delays(seed):
    """Record the backoff delays a seeded supervisor produces for a
    child that crashes 5 times then parks."""
    async def main():
        delays = []

        async def fake_sleep(d):
            delays.append(d)
            await asyncio.sleep(0)

        runs = {"n": 0}

        async def flaky():
            runs["n"] += 1
            if runs["n"] <= 5:
                raise RuntimeError("boom")
            await asyncio.Event().wait()

        sup = Supervisor(seed=seed, sleep=fake_sleep,
                         backoff_base=0.05, backoff_max=5.0, jitter=0.1)
        child = sup.start_child("w", flaky)
        assert await until(lambda: runs["n"] == 6 and child.alive())
        await sup.stop()
        return delays

    return run(main())


def test_backoff_exponential_with_deterministic_jitter():
    a = _crashy_delays(seed=7)
    b = _crashy_delays(seed=7)
    c = _crashy_delays(seed=8)
    assert a == b                           # same seed → same jitter
    assert a != c                           # different seed → different
    assert len(a) == 5
    for i, d in enumerate(a):
        base = 0.05 * (2 ** i)
        assert base <= d <= base * 1.1 + 1e-9   # jitter adds ≤ 10%
    assert a[0] < a[1] < a[2] < a[3] < a[4]


def test_intensity_escalates_to_degraded_with_alarm():
    async def main():
        async def fake_sleep(d):
            await asyncio.sleep(0)

        tnow = [0.0]

        async def always_crash():
            raise RuntimeError("boom")

        m = Metrics()
        alarms = Alarms()
        sup = Supervisor(metrics=m, alarms=alarms, max_restarts=3,
                         window_s=10.0, seed=1, sleep=fake_sleep,
                         clock=lambda: tnow[0])
        child = sup.start_child("w", always_crash)
        # intensity: >3 restarts inside the (frozen-clock) window
        assert await until(lambda: child.degraded)
        assert alarms.is_active("supervisor_degraded:w")
        assert m.get("broker.supervisor.degraded") == 1
        assert m.get("broker.supervisor.restarts") >= 4
        assert sup.degraded
        # escalation did NOT kill supervision: restarts keep coming
        before = child.restarts
        assert await until(lambda: child.restarts > before)
        await sup.stop()
        # stop clears the degraded alarm + metric
        assert not alarms.is_active("supervisor_degraded:w")
        assert m.get("broker.supervisor.degraded") == 0

    run(main())


def test_degraded_clears_after_long_clean_run():
    async def main():
        async def fake_sleep(d):
            await asyncio.sleep(0)

        tnow = [0.0]
        mode = {"park": False}

        async def worker():
            if not mode["park"]:
                raise RuntimeError("boom")
            await asyncio.Event().wait()

        alarms = Alarms()
        sup = Supervisor(alarms=alarms, max_restarts=2, window_s=10.0,
                         seed=1, sleep=fake_sleep, clock=lambda: tnow[0])
        child = sup.start_child("w", worker)
        assert await until(lambda: child.degraded)
        mode["park"] = True
        assert await until(lambda: child.alive())
        tnow[0] += 100.0                    # "ran" well past the window
        child.kill()                        # exit with long uptime
        assert await until(lambda: child.alive() and not child.degraded)
        assert not alarms.is_active("supervisor_degraded:w")
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# shutdown ordering + drain
# ---------------------------------------------------------------------------

def test_stop_reverse_registration_order():
    async def main():
        order = []

        def make(name):
            async def worker():
                try:
                    await asyncio.Event().wait()
                except asyncio.CancelledError:
                    order.append(name)
                    raise
            return worker

        sup = fast_sup()
        for name in ("a", "b", "c"):
            sup.start_child(name, make(name))
        await asyncio.sleep(0.01)
        await sup.stop()
        assert order == ["c", "b", "a"]     # reverse-dependency order

    run(main())


def test_supervised_fanout_stop_preserves_remainder():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        sup = fast_sup()
        # window 60 s: the batch never flushes on its own, so the queue
        # still holds everything when the SUPERVISOR stops the child
        p = FanoutPipeline(b, window_s=60.0, supervisor=sup)
        await p.start()
        b.fanout = p
        for i in range(3):
            assert p.offer(make_message("pub", "t", str(i).encode()))
        await sup.stop()                    # not p.stop(): drain callback
        assert [int(x.msg.payload) for x in got["sub"]] == [0, 1, 2]

    run(main())


def test_supervised_fanout_restarts_after_kill_without_stall():
    async def main():
        b = Broker()
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            int(p.msg.payload) for p in pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        sup = fast_sup(metrics=m)
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        for i in range(10):
            assert p.offer(make_message("pub", "t", str(i).encode()))
        assert await until(lambda: len(got) == 10)
        assert p._child.kill()
        # messages offered while the child is down must deliver after
        # the restart (the restarted drain loop re-arms its own wake)
        for i in range(10, 20):
            assert p.offer(make_message("pub", "t", str(i).encode()))
        assert await until(lambda: len(got) == 20)
        assert got == list(range(20))       # order preserved throughout
        assert m.get("broker.supervisor.restarts") >= 1
        await p.stop()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# fault injector: schedules, determinism, zero-cost when disabled
# ---------------------------------------------------------------------------

def test_injector_schedule_skip_every_times():
    inj = FaultInjector([{"point": "cluster.rpc", "action": "drop",
                          "skip": 2, "every": 3, "times": 2}])
    acts = [inj.act("cluster.rpc") for _ in range(12)]
    # eligible passes start after skip=2; fire on passes 3, 6 (every 3rd
    # eligible), capped at times=2
    assert acts == [None, None, "drop", None, None, "drop",
                    None, None, None, None, None, None]
    assert inj.fired["cluster.rpc"] == 2


def test_injector_unlimited_and_first_rule_wins():
    inj = FaultInjector([
        {"point": "bridge.sink", "action": "delay", "times": 1,
         "delay_s": 0.5},
        {"point": "bridge.sink", "action": "raise", "times": 0},
    ])
    assert inj.act("bridge.sink") == "delay"
    assert inj._last_delay == 0.5
    # first rule exhausted → the unlimited raise rule serves forever
    assert [inj.act("bridge.sink") for _ in range(3)] == ["raise"] * 3


def test_injector_prob_deterministic_by_seed():
    def seq(seed):
        inj = FaultInjector([{"point": "frame.parse", "action": "raise",
                              "prob": 0.5, "times": 0}], seed=seed)
        return [inj.act("frame.parse") for _ in range(40)]

    assert seq(5) == seq(5)
    assert seq(5) != seq(6)
    fired = [a for a in seq(5) if a]
    assert fired and len(fired) < 40        # some fired, some passed


def test_injector_check_raises():
    inj = FaultInjector([{"point": "inflight.insert", "action": "raise"}])
    with pytest.raises(InjectedFault):
        inj.check("inflight.insert")
    assert inj.check("inflight.insert") is None     # times exhausted


def test_injector_rejects_unknown_point_and_action():
    with pytest.raises(ValueError):
        FaultInjector([{"point": "nope", "action": "raise"}])
    with pytest.raises(ValueError):
        FaultInjector([{"point": "frame.parse", "action": "explode"}])


def test_faultinject_disabled_is_zero_calls_on_hot_path(monkeypatch):
    """The acceptance bar for the seams: with no injector installed the
    hot path makes ZERO fault-injection calls — the guard is one module
    attribute load + an identity test."""
    assert faultinject.get() is None        # default state: disabled
    calls = {"n": 0}
    orig_act = FaultInjector.act
    orig_check = FaultInjector.check

    def spy_act(self, point):
        calls["n"] += 1
        return orig_act(self, point)

    def spy_check(self, point):
        calls["n"] += 1
        return orig_check(self, point)

    monkeypatch.setattr(FaultInjector, "act", spy_act)
    monkeypatch.setattr(FaultInjector, "check", spy_check)

    async def main():
        from emqx_tpu.broker.inflight import Inflight
        from emqx_tpu.mqtt import frame as F
        from emqx_tpu.mqtt import packet as P

        # frame.parse seam
        parser = F.Parser()
        parser.feed(F.serialize(P.Publish(qos=0, topic="t", payload=b"x")))
        # inflight.insert / inflight.retry seams
        inf = Inflight(max_size=8)
        inf.insert_many([(1, "a"), (2, "b")])
        inf.older_than(0.0)
        # fanout.drain seam (full pipeline round trip)
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = FanoutPipeline(b)
        await p.start()
        for i in range(5):
            p.offer(make_message("pub", "t", b"%d" % i))
        await until(lambda: not p._q and not p._busy)
        await p.stop()

    run(main())
    assert calls["n"] == 0


def test_faultinject_seams_fire_when_installed():
    """Sanity inverse of the zero-cost test: installed rules actually
    reach the seams."""
    async def main():
        from emqx_tpu.broker.inflight import Inflight
        from emqx_tpu.mqtt import frame as F
        from emqx_tpu.mqtt import packet as P

        inj = faultinject.install(FaultInjector([
            {"point": "frame.parse", "action": "raise"},
            {"point": "inflight.insert", "action": "raise"},
        ]))
        try:
            parser = F.Parser()
            with pytest.raises(F.FrameError, match="injected"):
                parser.feed(F.serialize(
                    P.Publish(qos=0, topic="t", payload=b"x")))
            inf = Inflight(max_size=8)
            with pytest.raises(InjectedFault):
                inf.insert(1, "a")
            assert inj.fired == {"frame.parse": 1, "inflight.insert": 1}
        finally:
            faultinject.uninstall()

    run(main())
