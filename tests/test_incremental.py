"""Incremental NFA: O(delta) add/remove parity vs from-scratch compile.

Mirrors the reference's trie mutation coverage (``emqx_trie_SUITE``-style
insert/delete/match [U], SURVEY.md §4) plus the mirror-specific delta
machinery the reference doesn't need (device scatter sync).
"""

import random

import numpy as np
import pytest
from _optional import given, settings, st

from emqx_tpu import topic as T
from emqx_tpu.ops import (
    DeviceNfa,
    IncrementalNfa,
    compile_filters,
    encode_topics,
    nfa_match,
)

WORDS = ["a", "b", "c", "d", "sensor", "t1"]


@st.composite
def filter_strategy(draw):
    ws = draw(st.lists(st.sampled_from(WORDS + ["+"]), max_size=6))
    if draw(st.booleans()) or not ws:
        ws.append("#")
    return "/".join(ws)


def topic_strategy():
    return st.lists(
        st.sampled_from(WORDS + ["zz"]), min_size=1, max_size=7
    ).map("/".join)


def kernel_filter_sets(table, names, active_slots=32, max_matches=64):
    """Match via the kernel, return sorted filter-string lists per topic."""
    import jax.numpy as jnp

    w, l, s = encode_topics(table, names)
    res = nfa_match(
        jnp.asarray(w), jnp.asarray(l), jnp.asarray(s),
        *[jnp.asarray(a) for a in table.device_arrays()],
        active_slots=active_slots, max_matches=max_matches,
    )
    assert int(np.asarray(res.active_overflow).sum()) == 0
    m = np.asarray(res.matches)
    c = np.asarray(res.n_matches)
    return [
        sorted(table.accept_filters[a] for a in m[r, : c[r]])
        for r in range(len(names))
    ]


def test_add_remove_roundtrip():
    inc = IncrementalNfa(depth=8)
    assert inc.add("a/+/c")
    assert not inc.add("a/+/c")
    assert inc.add("a/#")
    assert inc.n_filters == 2
    assert inc.remove("a/+/c")
    assert not inc.remove("a/+/c")
    assert inc.remove("a/#")
    assert inc.n_filters == 0
    # everything pruned back to the root
    assert inc.n_states == 1
    assert inc.n_edges == 0


def test_prune_keeps_shared_prefix():
    inc = IncrementalNfa(depth=8)
    inc.add("a/b/c")
    inc.add("a/b")
    inc.remove("a/b/c")
    assert inc.filters() == ["a/b"]
    assert inc.n_states == 3  # root, a, b


def test_deep_filter_rejected():
    inc = IncrementalNfa(depth=4)
    with pytest.raises(ValueError):
        inc.add("a/b/c/d/e")
    assert not inc.remove("a/b/c/d/e")


def test_hash_only_filter():
    inc = IncrementalNfa(depth=4)
    inc.add("#")
    snap = inc.snapshot()
    assert kernel_filter_sets(snap, ["x/y", "$SYS/x"]) == [["#"], []]
    inc.remove("#")
    assert inc.n_filters == 0


@settings(max_examples=30, deadline=None)
@given(
    st.lists(
        st.tuples(st.booleans(), filter_strategy()),
        min_size=1, max_size=60,
    ),
    st.lists(topic_strategy(), min_size=1, max_size=20),
)
def test_incremental_matches_scratch_compile(ops, topics):
    """After any interleaving of adds/removes the snapshot matches a
    from-scratch compile AND the pure-Python oracle."""
    inc = IncrementalNfa(depth=8, state_bucket=8, edge_bucket=8)
    live = set()
    for is_remove, flt in ops:
        if is_remove and live:
            victim = sorted(live)[len(live) // 2]
            assert inc.remove(victim)
            live.discard(victim)
        else:
            assert inc.add(flt) == (flt not in live)
            live.add(flt)
    assert sorted(inc.filters()) == sorted(live)

    got = kernel_filter_sets(inc.snapshot(), topics)
    oracle = [
        sorted(f for f in live if T.match(t, f)) for t in topics
    ]
    assert got == oracle
    if live:
        ref = kernel_filter_sets(compile_filters(sorted(live), depth=8), topics)
        assert got == ref


def test_state_and_edge_growth():
    """Exceed the initial buckets; shapes double and parity holds."""
    inc = IncrementalNfa(depth=8, state_bucket=8, edge_bucket=8)
    fs = [f"lvl{i}/x{i % 7}/y{i % 13}" for i in range(300)]
    for f in fs:
        inc.add(f)
    assert inc.S > 8 and inc.Hb > 2
    got = kernel_filter_sets(inc.snapshot(), ["lvl5/x5/y5", "none/a/b"])
    assert got == [["lvl5/x5/y5"], []]
    # free-list reuse after mass delete
    for f in fs[:250]:
        inc.remove(f)
    for f in fs[:250]:
        inc.add(f)
    got = kernel_filter_sets(inc.snapshot(), ["lvl5/x5/y5"])
    assert got == [["lvl5/x5/y5"]]


def test_device_nfa_delta_sync():
    """Deltas scatter in place: no full re-upload while shapes hold."""
    rng = random.Random(5)
    inc = IncrementalNfa(depth=8, state_bucket=1024, edge_bucket=256)
    live = set()
    for i in range(400):
        f = f"root{i % 40}/{'+' if i % 5 == 0 else f'w{i % 17}'}/t{i % 3}"
        if inc.add(f):
            live.add(f)
    dev = DeviceNfa(inc)
    assert dev.uploads == 1

    topics = [f"root{i % 40}/w{i % 17}/t{i % 3}" for i in range(64)]

    def check():
        res = dev.match_names(topics)
        m = np.asarray(res.matches)
        c = np.asarray(res.n_matches)
        sp = np.asarray(res.spilled_rows())
        for r, t in enumerate(topics):
            if sp[r]:
                continue
            got = sorted(inc.accept_filters[a] for a in m[r, : c[r]])
            want = sorted(f for f in live if T.match(t, f))
            assert got == want

    check()
    for _ in range(3):
        for _ in range(50):
            if live and rng.random() < 0.5:
                f = rng.choice(sorted(live))
                live.discard(f)
                inc.remove(f)
            else:
                f = f"n{rng.randint(0, 500)}/{rng.randint(0, 9)}"
                if inc.add(f):
                    live.add(f)
        dev.sync()
        check()
    assert dev.uploads == 1, "churn within capacity must not re-upload"
    assert dev.delta_applies >= 3


def test_device_nfa_resync_after_growth():
    inc = IncrementalNfa(depth=8, state_bucket=8, edge_bucket=8)
    inc.add("a/b")
    dev = DeviceNfa(inc)
    for i in range(200):
        inc.add(f"grow{i}/x")
    dev.sync()
    assert dev.uploads >= 2  # growth forced a full re-upload
    res = dev.match_names(["grow7/x", "a/b"])
    m = np.asarray(res.matches)
    c = np.asarray(res.n_matches)
    assert [inc.accept_filters[a] for a in m[0, : c[0]]] == ["grow7/x"]


def test_compact_resets_garbage():
    inc = IncrementalNfa(depth=8)
    for i in range(100):
        inc.add(f"tmp{i}/x")
    for i in range(100):
        inc.remove(f"tmp{i}/x")
    inc.add("keep/+")
    assert len(inc.vocab) > 2
    inc.compact()
    assert inc.filters() == ["keep/+"]
    assert len(inc.vocab) == 1  # only 'keep' (+/# are not vocab words)
    assert kernel_filter_sets(inc.snapshot(), ["keep/x"]) == [["keep/+"]]


@settings(max_examples=30, deadline=None)
@given(
    st.lists(filter_strategy(), min_size=0, max_size=40),
    st.lists(topic_strategy(), min_size=1, max_size=15),
)
def test_match_host_is_oracle(filters, topics):
    """The host-side walk (the fail-open authority) ≡ the pure oracle."""
    inc = IncrementalNfa(depth=8, state_bucket=8, edge_bucket=8)
    live = set()
    for f in filters:
        inc.add(f)
        live.add(f)
    for t in topics + ["$SYS/x", "$share"]:
        got = sorted(
            inc.accept_filters[a] for a in inc.match_host(t)
        )
        want = sorted(f for f in live if T.match(t, f))
        assert got == want, (t, got, want)


def test_aid_reuse_deferred_until_device_ack():
    """A freed accept id must not be handed out while the device mirror
    still serves the epoch that could fire it (review finding)."""
    inc = IncrementalNfa(depth=8)
    inc.device_epoch = -1  # device consumer attached, nothing acked
    inc.add("a/b")
    aid = inc.aid_of("a/b")
    inc.remove("a/b")
    inc.add("x/y")
    assert inc.aid_of("x/y") != aid, "aid reused before device ack"
    # ack the removal epoch: now the id is reusable
    inc.device_epoch = inc.epoch
    inc.add("z/q")
    assert inc.aid_of("z/q") == aid
