"""Optional test-dependency shims.

The tier-1 container has no ``hypothesis``; importing it at module top
made six test modules fail COLLECTION, taking every non-property test in
them down too (ROADMAP "seed tests failing").  This shim re-exports the
real package when present and otherwise substitutes stubs that mark the
property-based tests as skipped while letting the rest of the module
collect and run.
"""

try:
    from hypothesis import given, settings, strategies as st  # noqa: F401

    HAVE_HYPOTHESIS = True
except ImportError:
    import pytest

    HAVE_HYPOTHESIS = False

    class _Strategy:
        """Stands in for any strategy expression built at import time."""

        def __getattr__(self, name):
            return lambda *a, **k: _Strategy()

        def __call__(self, *a, **k):
            return _Strategy()

        def __or__(self, other):
            return self

    class _St:
        def __getattr__(self, name):
            return lambda *a, **k: _Strategy()

    st = _St()

    def given(*a, **k):
        return lambda fn: pytest.mark.skip(
            reason="hypothesis not installed"
        )(fn)

    def settings(*a, **k):
        if a and callable(a[0]) and not k:
            return a[0]  # bare @settings
        return lambda fn: fn
