"""Batched admission plane (broker/admission.py, ISSUE 14): O(1)
feature accumulation, vectorized scoring, the quarantine ladder with
hysteresis, fail-open degradation, zero-cost-when-off, per-client state
bounds, and the ctl/REST explain surface."""

import asyncio
import json
import threading

import pytest

from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, make_message
from emqx_tpu.broker.admission import FEATURES, LEVELS, Admission
from emqx_tpu.broker.banned import Banned
from emqx_tpu.broker.limiter import LimiterGroup, TokenBucket
from emqx_tpu.observe.alarm import Alarms
from emqx_tpu.observe.metrics import Metrics


def run(coro):
    return asyncio.run(coro)


class Harness:
    """One Admission on an injected clock with recording callbacks."""

    def __init__(self, **kw):
        self.now = [0.0]
        self.banned = Banned()
        self.alarms = Alarms()
        self.metrics = Metrics()
        self.throttles = {}
        self.kicked = []
        kw.setdefault("tick_s", 1.0)
        kw.setdefault("hold_ticks", 2)
        kw.setdefault("decay_ticks", 3)
        kw.setdefault("max_publish_rate", 100.0)
        kw.setdefault("max_topic_fan", 20.0)
        kw.setdefault("ban_time", 60.0)
        self.adm = Admission(
            banned=self.banned, alarms=self.alarms, metrics=self.metrics,
            clock=lambda: self.now[0], wall=lambda: self.now[0], **kw)
        self.adm.throttle_cb = \
            lambda cid, rate: self.throttles.__setitem__(cid, rate)
        self.adm.kick_cb = self.kicked.append

    def tick(self, dt=1.0):
        self.now[0] += dt
        self.adm.score_tick()

    def flood(self, cid, rate=1000, distinct=True, tag=0):
        for i in range(rate):
            topic = f"scan/{tag}/{i}" if distinct else "tele/1"
            self.adm.note_publish(cid, topic, 64)


# ---------------------------------------------------------------------------
# features
# ---------------------------------------------------------------------------

def test_feature_rows_accumulate_and_ewma_fold():
    h = Harness(alpha=0.5)
    for _ in range(100):
        h.adm.note_publish("c", "t/1", 64)
    h.adm.note_connect("c")
    h.adm.note_auth_failure("c")
    h.tick()
    row = h.adm.explain("c")
    f = row["features"]
    # first tick: EWMA folds alpha * rate from zero
    assert f["publish_rate"] == pytest.approx(50.0, rel=0.01)
    assert f["publish_bytes_rate"] == pytest.approx(3200.0, rel=0.01)
    assert f["connect_rate"] == pytest.approx(1.0, rel=0.01)
    assert f["auth_fail_rate"] == pytest.approx(0.5, rel=0.01)
    # second identical tick folds toward the true rate
    for _ in range(100):
        h.adm.note_publish("c", "t/1", 64)
    h.tick()
    f2 = h.adm.explain("c")["features"]
    assert f2["publish_rate"] == pytest.approx(75.0, rel=0.01)
    # counters were reset at each tick (rates, not totals)
    h.tick()
    assert h.adm.explain("c")["features"]["publish_rate"] \
        < f2["publish_rate"]
    assert list(f) == list(FEATURES)


def test_topic_fan_sketch_separates_scan_from_telemetry():
    h = Harness()
    h.flood("scanner", rate=500, distinct=True)
    h.flood("telemetry", rate=500, distinct=False)
    h.tick()
    fan_scan = h.adm.explain("scanner")["features"]["topic_fan"]
    fan_tele = h.adm.explain("telemetry")["features"]["topic_fan"]
    # one topic sets one sketch bit; 500 distinct topics saturate it
    assert fan_tele < 2.0
    assert fan_scan > 10 * max(fan_tele, 0.1)


def test_publish_batch_note_matches_per_message_notes():
    class Pkt:
        def __init__(self, topic, payload):
            self.topic = topic
            self.payload = payload

    h1, h2 = Harness(), Harness()
    pkts = [Pkt(f"a/{i % 7}", b"x" * 32) for i in range(64)]
    for p in pkts:
        h1.adm.note_publish("c", p.topic, len(p.payload))
    h2.adm.note_publish_batch("c", pkts)
    h1.tick()
    h2.tick()
    assert h1.adm.explain("c")["features"] == h2.adm.explain("c")["features"]


def test_malformed_notes_are_thread_safe_and_key_on_peer():
    h = Harness(max_malformed_rate=1.0)
    done = threading.Event()

    def shard_thread():
        for _ in range(50):
            h.adm.note_malformed(None, ("10.1.2.3", 55000))
            h.adm.note_malformed("evil", ("10.9.9.9", 1))
        done.set()

    t = threading.Thread(target=shard_thread)
    t.start()
    t.join(5.0)
    assert done.is_set()
    h.tick()
    assert h.adm.explain("ip:10.1.2.3")["features"]["malformed_rate"] > 0
    assert h.adm.explain("evil")["features"]["malformed_rate"] > 0


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------

def test_ladder_escalates_with_hysteresis_throttle_shed_ban():
    h = Harness()
    # one hot tick is NOT enough (hold_ticks=2)
    h.flood("atk", tag=0)
    h.tick()
    assert h.adm.explain("atk")["level"] == 0
    # second consecutive hot tick -> throttle
    h.flood("atk", tag=1)
    h.tick()
    assert h.adm.explain("atk")["level_name"] == "throttle"
    assert h.throttles["atk"] == h.adm.throttle_rate
    assert not h.adm.shed_qos0("atk")
    # two more -> quarantine: QoS0 shed engages
    for t in (2, 3):
        h.flood("atk", tag=t)
        h.tick()
    assert h.adm.explain("atk")["level_name"] == "quarantine"
    assert h.adm.shed_qos0("atk")
    assert h.alarms.is_active("admission_quarantine")
    assert h.metrics.get("broker.admission.quarantined") == 1
    # two more -> temp-ban: Banned row, kick, feature row dropped
    for t in (4, 5):
        h.flood("atk", tag=t)
        h.tick()
    assert h.banned.check(clientid="atk", now=h.now[0])
    assert h.kicked == ["atk"]
    assert h.adm.explain("atk") is None       # row dropped with the ban
    assert h.throttles["atk"] is None         # throttle restored
    assert not h.adm.shed_qos0("atk")
    assert h.metrics.get("broker.admission.banned") == 1
    # quarantine alarm clears once nobody is quarantined
    assert not h.alarms.is_active("admission_quarantine")
    # the ban expires on the SAME injected clock -> clean reconnect
    assert not h.banned.check(clientid="atk", now=h.now[0] + 61.0)


def test_ladder_decays_and_restores_throttle():
    h = Harness()
    for t in range(4):
        h.flood("atk", tag=t)
        h.tick()
    assert h.adm.explain("atk")["level_name"] == "quarantine"
    # the attacker STOPS: escalation freezes (a hot-but-idle EWMA must
    # not march to a ban on stale memory), the score drains, and each
    # decay_ticks run of calm ticks climbs one level back down
    seen = []
    for _ in range(40):
        h.tick()
        row = h.adm.explain("atk")
        assert row is not None, "stopped client must never be banned"
        if not seen or seen[-1] != row["level_name"]:
            seen.append(row["level_name"])
        if row["level"] == 0:
            break
    assert seen == ["quarantine", "throttle", "observe"]
    assert not h.adm.shed_qos0("atk")
    assert h.throttles["atk"] is None  # bucket restored
    assert h.adm.bans == 0


def test_honest_client_never_climbs():
    h = Harness()
    for t in range(10):
        h.flood("honest", rate=50, distinct=False)
        h.tick()
    assert h.adm.explain("honest")["level"] == 0
    assert h.adm.list_decisions() == []


def test_olp_brownout_tightens_threshold():
    class HotOlp:
        def brownout_level(self):
            return 2

    calm, hot = Harness(), Harness()
    hot.adm.olp = HotOlp()
    # a borderline flood: ~70% of the publish threshold, under the
    # normal gate but past the brownout-tightened one (1 - 0.25*2)
    for t in range(8):
        for harness in (calm, hot):
            for i in range(70):
                harness.adm.note_publish("gray", "tele/x", 64)
            harness.tick()
    assert calm.adm.explain("gray")["level"] == 0
    assert hot.adm.explain("gray")["level"] >= 1


def test_flightrec_dumps_once_per_escalation_tick():
    class Rec:
        def __init__(self):
            self.reasons = []

        def dump(self, reason, note=None):
            self.reasons.append(reason)

    h = Harness()
    rec = Rec()
    h.adm.flightrec = rec
    # two attackers reach quarantine on the SAME tick -> one dump
    for t in range(4):
        h.flood("a1", tag=t)
        h.flood("a2", tag=100 + t)
        h.tick()
    assert rec.reasons == ["admission_escalation"]


def test_explain_clear_and_list_decisions():
    h = Harness()
    for t in range(4):
        h.flood("atk", tag=t)
        h.tick()
    rows = h.adm.list_decisions()
    assert [r["clientid"] for r in rows] == ["atk"]
    assert rows[0]["level_name"] == "quarantine"
    assert set(rows[0]["features"]) == set(FEATURES)
    assert rows[0]["score"] > 1.0
    # operator clear lifts the decision now; the row survives
    assert h.adm.clear("atk")
    assert h.adm.explain("atk")["level"] == 0
    assert not h.adm.shed_qos0("atk")
    assert h.throttles["atk"] is None
    assert not h.adm.clear("ghost")
    # levels vocabulary is stable (the REST/CLI contract)
    assert LEVELS == ("observe", "throttle", "quarantine", "ban")


# ---------------------------------------------------------------------------
# fail-open
# ---------------------------------------------------------------------------

def test_fail_open_clears_decisions_raises_alarm_recovers():
    h = Harness()
    for t in range(4):
        h.flood("atk", tag=t)
        h.tick()
    assert h.adm.shed_qos0("atk")
    h.adm._fail_open("crashed")
    # every standing decision cleared: traffic flows unscreened
    assert not h.adm.shed_qos0("atk")
    assert h.adm.explain("atk")["level"] == 0
    assert h.throttles["atk"] is None
    assert h.alarms.is_active("admission_degraded")
    assert h.metrics.get("broker.admission.fail_open") == 1
    # the next successful tick clears the alarm; the attacker re-climbs
    for t in range(4):
        h.flood("atk", tag=10 + t)
        h.tick()
    assert not h.alarms.is_active("admission_degraded")
    assert h.adm.explain("atk")["level_name"] == "quarantine"


def test_scorer_child_crash_fails_open_via_run_loop():
    async def main():
        h = Harness()
        h.adm.tick_s = 0.005
        for t in range(4):
            h.flood("atk", tag=t)
            h.tick()
        assert h.adm.shed_qos0("atk")

        boom = [False]
        orig = h.adm.score_tick

        def tick_or_boom():
            if boom[0]:
                raise RuntimeError("scorer bug")
            orig()

        h.adm.score_tick = tick_or_boom
        task = asyncio.ensure_future(h.adm.run())
        boom[0] = True
        with pytest.raises(RuntimeError):
            await task
        assert not h.adm.shed_qos0("atk")
        assert h.alarms.is_active("admission_degraded")
        # a KILL (cancellation) fails open too
        h2 = Harness()
        h2.adm.tick_s = 10.0
        task2 = asyncio.ensure_future(h2.adm.run())
        await asyncio.sleep(0)
        task2.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task2
        assert h2.alarms.is_active("admission_degraded")

    run(main())


def test_shed_goes_stale_when_scorer_hangs():
    h = Harness()
    for t in range(4):
        h.flood("atk", tag=t)
        h.tick()
    assert h.adm.shed_qos0("atk")
    # no tick for > 4 tick periods (a HUNG scorer, not a crashed one):
    # the staleness guard fails open without any cleanup running
    h.now[0] += 5.0 * h.adm.tick_s
    assert not h.adm.shed_qos0("atk")


# ---------------------------------------------------------------------------
# per-client state bounds (churn audit)
# ---------------------------------------------------------------------------

def test_idle_rows_evicted_tracked_clients_bounded():
    h = Harness(idle_expiry=30.0)
    for i in range(500):
        h.adm.note_connect(f"churn{i}")
        h.adm.note_disconnect(f"churn{i}")
    # an attacker with a standing decision must SURVIVE eviction
    for t in range(4):
        h.flood("atk", tag=t)
        h.tick(dt=0.1)
    assert h.adm.explain("atk")["level_name"] == "quarantine"
    assert h.metrics.get("broker.admission.tracked_clients") == 501
    h.tick(dt=31.0)
    assert h.metrics.get("broker.admission.tracked_clients") == 1
    assert h.adm.explain("atk") is not None
    assert h.adm.explain("churn0") is None
    # slots are REUSED after eviction (free-list, no slab growth)
    cap_before = len(h.adm._keys)
    for i in range(400):
        h.adm.note_connect(f"wave2_{i}")
    assert len(h.adm._keys) == cap_before


def test_reconnect_churn_keeps_all_keyed_state_bounded():
    """The audit satellite end-to-end: feature rows, flapping deques
    and limiter bucket pairs all stay bounded through 1000 reconnect
    cycles + sweeps."""
    from emqx_tpu.broker.flapping import Flapping

    h = Harness(idle_expiry=10.0)
    now = [0.0]
    banned = Banned()
    flap = Flapping(banned, max_count=50, window_time=5.0,
                    clock=lambda: now[0])
    lg = LimiterGroup(max_messages_rate=100.0, max_bytes_rate=0.0)
    for i in range(1000):
        cid = f"churner{i}"
        h.adm.note_connect(cid)
        flap.record_disconnect(cid)
        lg.allow_publish(cid, 10, now=now[0])
        now[0] += 0.01
    h.now[0] = now[0]
    h.tick(dt=60.0)
    now[0] += 60.0
    flap.sweep(now[0])
    lg.sweep_idle(30.0, now=now[0])
    assert h.adm.info()["tracked_clients"] == 0
    assert flap.tracked() == 0
    assert lg.tracked() == 0


# ---------------------------------------------------------------------------
# enforcement seams: broker.publish / fanout.offer / token bucket
# ---------------------------------------------------------------------------

def _quarantine(h, cid="atk"):
    for t in range(4):
        h.flood(cid, tag=t)
        h.tick()
    assert cid in h.adm._shed


def test_broker_publish_sheds_quarantined_qos0_only():
    h = Harness()
    b = Broker()
    b.metrics = h.metrics
    h.adm.attach(b)
    b.open_session("sub")
    b.subscribe("sub", "#", SubOpts(qos=1))
    _quarantine(h)
    dropped = []
    b.hooks.add("message.dropped",
                lambda msg, reason: dropped.append((msg.sender, reason)))
    res = b.publish(make_message("atk", "t/x", b"flood", qos=0))
    assert res.no_subscribers and res.matched == 0
    assert dropped == [("atk", "admission_shed")]
    assert h.metrics.get("broker.admission.shed_qos0") >= 1
    # QoS1 from the same sender rides the throttle, NOT a drop path
    res = b.publish(make_message("atk", "t/x", b"acked", qos=1))
    assert res.matched == 1
    # honest senders are untouched
    res = b.publish(make_message("honest", "t/x", b"ok", qos=0))
    assert res.matched == 1


def test_fanout_offer_sheds_quarantined_qos0_only():
    async def main():
        h = Harness()
        b = Broker()
        b.metrics = h.metrics
        h.adm.attach(b)
        b.open_session("sub")
        b.subscribe("sub", "#", SubOpts(qos=0))
        _quarantine(h)
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(pubs)
        p = FanoutPipeline(b, window_s=0.0, metrics=h.metrics)
        await p.start()
        b.fanout = p
        assert p.offer(make_message("atk", "t/x", b"flood", qos=0))
        assert p.offer(make_message("honest", "t/x", b"ok", qos=0))
        await asyncio.sleep(0.05)
        payloads = [bytes(pub.msg.payload) for pub in got]
        assert payloads == [b"ok"]       # consumed-by-policy, not queued
        assert h.metrics.get("broker.admission.shed_qos0") >= 1
        await p.stop()

    run(main())


def test_token_bucket_retune_in_place():
    tb = TokenBucket(0.0)           # unlimited (the default limiter)
    assert tb.unlimited
    tb.retune(5.0)
    assert not tb.unlimited and tb.burst == 5.0
    ok, _ = tb.consume(5.0, now=0.0)
    assert ok
    ok, wait = tb.consume(1.0, now=0.0)
    assert not ok and wait > 0
    tb.retune(0.0)                  # restore unlimited
    assert tb.unlimited
    assert tb.consume(1000.0)[0]


def test_limiter_sweep_idle_evicts_stale_pairs():
    lg = LimiterGroup(max_messages_rate=10.0)
    lg.allow_publish("old", 1, now=0.0)
    lg.allow_publish("fresh", 1, now=500.0)
    assert lg.tracked() == 2
    assert lg.sweep_idle(100.0, now=550.0) == 1
    assert lg.tracked() == 1
    # recreation on demand is seamless
    assert lg.allow_publish("old", 1, now=551.0)[0]


# ---------------------------------------------------------------------------
# zero-cost when disabled
# ---------------------------------------------------------------------------

def test_flag_off_is_zero_call(monkeypatch):
    """The None-guard contract: with admission off, NO Admission method
    runs on any seam — class-level spies would catch a stray call."""
    for name in ("note_publish", "note_publish_batch", "note_connect",
                 "note_disconnect", "note_auth_failure",
                 "note_malformed", "shed_qos0"):
        monkeypatch.setattr(
            Admission, name,
            lambda self, *a, **kw: pytest.fail(
                "admission seam called while disabled"),
        )
    b = Broker()
    assert b.admission is None
    b.open_session("sub")
    b.subscribe("sub", "#", SubOpts(qos=0))
    res = b.publish(make_message("c", "t/x", b"m", qos=0))
    assert res.matched == 1

    async def fanout_path():
        p = FanoutPipeline(b, window_s=0.0)
        await p.start()
        b.fanout = p
        assert p.offer(make_message("c", "t/x", b"m2", qos=0))
        await asyncio.sleep(0.02)
        await p.stop()

    run(fanout_path())


def test_node_flag_off_builds_no_admission():
    async def main():
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", False)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.admission is None
            assert node.broker.admission is None
            assert node.supervisor.lookup("admission.score") is None
            assert node.info()["admission"] is None
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# node wiring: live seams, throttle retune, REST/CLI surface
# ---------------------------------------------------------------------------

async def _start_admission_node(extra=""):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n'
        'admission.enable = true\n'
        'admission.tick = 0.02\n'
        'admission.hold_ticks = 2\n'
        'admission.decay_ticks = 1000\n'
        'admission.max_topic_fan = 20\n'
        'admission.max_publish_rate = 1000000\n'
        + extra
    ))
    cfg.put("tpu.enable", False)
    node = BrokerNode(cfg)
    await node.start()
    return node


async def _until(pred, timeout=8.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred() and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.005)
    return pred()


def test_node_live_seams_score_and_throttle_real_connection():
    """A real attacker connection over TCP: the channel publish seam
    feeds the rows, the scorer child escalates, and the level-1
    throttle retunes the LIVE connection's message bucket in place;
    the operator clear restores it."""
    from emqx_tpu.client import Client

    async def main():
        node = await _start_admission_node()
        port = node.listeners.all()[0].port
        atk = Client(clientid="atk", port=port)
        await atk.connect()
        try:
            ok = False
            for wave in range(200):
                for i in range(40):
                    await atk.publish(f"scan/{wave}/{i}", b"x", qos=0)
                if node.admission.explain("atk") and \
                        node.admission.explain("atk")["level"] >= 1:
                    ok = True
                    break
                await asyncio.sleep(0.01)
            assert ok, node.admission.list_decisions(all_rows=True)
            conn = node.connections["atk"]
            assert await _until(
                lambda: conn._msg_bucket.rate
                == node.admission.throttle_rate)
            # operator clear restores the configured (unlimited) rate
            node.admission.clear("atk")
            assert conn._msg_bucket.unlimited
            # the connect/disconnect hooks feed rows too
            row = node.admission.explain("atk")
            assert row is not None
        finally:
            await atk.disconnect()
            await node.stop()

    run(main())


def test_node_frame_error_and_auth_failure_seams():
    async def main():
        node = await _start_admission_node()
        port = node.listeners.all()[0].port
        # garbage bytes -> FrameError -> malformed note keyed on peer
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"\xff\xff\xff\xff\xff\xff\xff\xff")
        try:
            await asyncio.wait_for(reader.read(64), 2.0)
        except asyncio.TimeoutError:
            pass
        writer.close()
        assert await _until(
            lambda: (node.admission.explain("ip:127.0.0.1") or {})
            .get("features", {}).get("malformed_rate", 0) > 0)
        # failed CONNECT (banned clientid) -> auth-failure note
        node.banned.add("clientid", "mallory")
        from emqx_tpu.mqtt import frame as F
        from emqx_tpu.mqtt import packet as P

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(F.serialize(P.Connect(proto_ver=4,
                                           clientid="mallory")))
        data = await asyncio.wait_for(reader.read(64), 5.0)
        assert len(data) >= 4 and data[3] != 0  # refused
        writer.close()
        assert await _until(
            lambda: (node.admission.explain("mallory") or {})
            .get("features", {}).get("auth_fail_rate", 0) > 0)
        await node.stop()

    run(main())


def test_gateway_flood_feeds_admission_plane():
    """The ISSUE 16 gateway seams: a publish storm through the gateway
    publish seam climbs the same quarantine ladder as an MQTT flood,
    the gateway connect rides the client.connected hook with its
    peerhost, auth failure notes the feature row, and a garbled-CoAP
    datagram flood registers malformed notes keyed on the source
    address pre-CONNECT."""
    from emqx_tpu.gateway.base import GatewayConn
    from emqx_tpu.gateway.coap import CoapGateway

    h = Harness()
    b = Broker()
    h.adm.attach(b)

    class _Node:
        broker = b
        connections = {}

    node = _Node()
    conn = GatewayConn(node, "coap")
    conn.addr = ("10.9.9.9", 40123)
    conn.send_deliveries = lambda pubs: None
    conn.close_transport = lambda reason: None
    conn.attach_session("gw-atk")
    h.tick()
    row = h.adm.explain("gw-atk")
    assert row is not None and row["features"]["connect_rate"] > 0
    # distinct-topic publish storm through GatewayConn.publish — the
    # same shape as Harness.flood but riding the gateway datapath
    for t in range(4):
        for i in range(1000):
            conn.publish(f"scan/{t}/{i}", b"x" * 64)
        h.tick()
    assert h.adm.explain("gw-atk")["level_name"] == "quarantine"
    assert h.adm.shed_qos0("gw-atk")
    # auth failure through the gateway authn fold
    b.hooks.add("client.authenticate", lambda cid, u, p, info, acc: False)
    assert conn.authenticate("eve", b"bad") is False
    h.tick()
    assert h.adm.explain("gw-atk")["features"]["auth_fail_rate"] > 0
    # garbled datagrams key the malformed feature on the peer address
    gw = CoapGateway(node, {})
    for _ in range(5):
        gw.on_datagram(b"\xff\xff", ("10.7.7.7", 5683))
    h.tick()
    mrow = h.adm.explain("ip:10.7.7.7")
    assert mrow is not None and mrow["features"]["malformed_rate"] > 0


def test_gateway_seams_zero_call_when_disabled(monkeypatch):
    """Flag-off discipline extends to the gateway seams: no Admission
    method may run from attach/publish/auth/datagram paths when the
    plane is off."""
    from emqx_tpu.gateway.base import GatewayConn
    from emqx_tpu.gateway.coap import CoapGateway

    for name in ("note_publish", "note_connect", "note_disconnect",
                 "note_auth_failure", "note_malformed"):
        monkeypatch.setattr(
            Admission, name,
            lambda self, *a, **kw: pytest.fail(
                "gateway admission seam called while disabled"),
        )
    b = Broker()
    assert b.admission is None

    class _Node:
        broker = b
        connections = {}

    node = _Node()
    conn = GatewayConn(node, "stomp")
    conn.addr = ("127.0.0.1", 1)
    conn.send_deliveries = lambda pubs: None
    conn.close_transport = lambda reason: None
    conn.attach_session("quiet")
    conn.publish("t/x", b"m")
    assert conn.authenticate(None, None) is True
    gw = CoapGateway(node, {})
    gw.on_datagram(b"\xff\xff", ("127.0.0.1", 2))
    conn.detach_session()


def test_admission_rest_and_cli_surface():
    """GET /api/v5/admission lists decisions WITH feature rows (the
    explainability contract); DELETE lifts one; the ctl subcommand
    drives the same endpoints."""
    import io
    from contextlib import redirect_stdout
    from urllib.request import urlopen

    from emqx_tpu.mgmt.cli import main as ctl_main

    async def main():
        node = await _start_admission_node(
            'dashboard.enable = true\n'
            'dashboard.auth = false\n'
            'dashboard.listen = "127.0.0.1:0"\n'
        )
        adm = node.admission
        try:
            # quarantine an attacker through the plane itself
            for t in range(4):
                for i in range(300):
                    adm.note_publish("atk", f"scan/{t}/{i}", 64)
                adm.score_tick(now=float(t + 1))
            assert "atk" in adm._shed
            mport = node.mgmt_server.port

            def rest(method, path):
                import urllib.request
                req = urllib.request.Request(
                    f"http://127.0.0.1:{mport}{path}", method=method)
                with urlopen(req, timeout=5) as resp:
                    body = resp.read()
                    return resp.status, \
                        json.loads(body) if body else None

            status, out = await asyncio.to_thread(
                rest, "GET", "/api/v5/admission")
            assert status == 200 and out["enabled"]
            row = next(d for d in out["data"]
                       if d["clientid"] == "atk")
            assert row["level_name"] == "quarantine"
            assert set(row["features"]) == set(FEATURES)
            # ctl admission renders the same payload
            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = await asyncio.to_thread(
                    ctl_main,
                    ["--url", f"http://127.0.0.1:{mport}", "admission"])
            assert rc == 0 and '"atk"' in buf.getvalue()
            # DELETE lifts the decision
            status, _ = await asyncio.to_thread(
                rest, "DELETE", "/api/v5/admission/atk")
            assert status == 204
            assert adm.explain("atk")["level"] == 0
            status, out = await asyncio.to_thread(
                rest, "GET", "/api/v5/admission")
            assert out["data"] == []
            # ?all=true shows tracked-but-clean rows
            status, out = await asyncio.to_thread(
                rest, "GET", "/api/v5/admission?all=true")
            assert any(d["clientid"] == "atk" for d in out["data"])
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# peerhost-keyed CONNECT-storm rows (ROADMAP admission residual (c))
# ---------------------------------------------------------------------------

def test_distributed_clientid_storm_concentrates_on_ip_row():
    """A CONNECT storm rotating clientids from ONE host spreads one
    connect per fresh per-client row (each stays calm) but SUMS on the
    ip: row, which climbs the ladder to a peerhost temp-ban — the
    dilution hole the per-clientid keying left open."""
    h = Harness(hold_ticks=1, decay_ticks=2)
    for tick in range(4):
        for i in range(60):
            h.adm.note_connect(f"bot-{tick}-{i}", peerhost="10.0.0.9")
        h.tick()
    # no individual bot ever scored hot (1 connect each, threshold 2/s)
    assert all(h.adm.explain(f"bot-0-{i}")["level"] == 0
               for i in range(5)
               if h.adm.explain(f"bot-0-{i}") is not None)
    # the host row concentrated the storm: observe -> throttle(no-op)
    # -> quarantine(no-op) -> peerhost temp-ban
    assert h.banned.check(peerhost="10.0.0.9", now=h.now[0])
    # ip rows never retune a token bucket nor kick a single channel
    assert "ip:10.0.0.9" not in h.throttles
    assert "ip:10.0.0.9" not in h.kicked


def test_auth_failure_storm_keys_on_ip_row():
    """Credential stuffing rotates clientids freely; the auth-failure
    seam feeds the stable source-host row alongside the per-client
    one."""
    h = Harness()
    for i in range(40):
        h.adm.note_auth_failure(f"stuff{i}", peerhost="10.9.9.9")
    h.tick()
    row = h.adm.explain("ip:10.9.9.9")
    assert row is not None
    assert row["features"]["auth_fail_rate"] > 0
    assert row["features"]["connect_rate"] > 0
    # per-client rows saw exactly their own single failure
    one = h.adm.explain("stuff0")
    assert one["features"]["auth_fail_rate"] < \
        row["features"]["auth_fail_rate"]


def test_note_connect_without_peerhost_adds_no_ip_row():
    h = Harness()
    h.adm.note_connect("plain")
    h.tick()
    assert not any(k.startswith("ip:") for k in h.adm._slots)
