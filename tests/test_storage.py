"""Durable storage tests: store crash-tolerance, node restart
persistence, data export/import, NFA checkpoint parity
(SURVEY.md §5.4)."""

import asyncio
import base64
import json
import os

import pytest

from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode
from emqx_tpu.storage import (
    Store,
    export_data,
    import_data,
    load_table,
    save_table,
)


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# store engine
# ---------------------------------------------------------------------------


def test_table_put_delete_reload(tmp_path):
    s = Store(str(tmp_path))
    t = s.table("t1")
    t.put("a", {"x": 1})
    t.put("b", {"y": [1, 2]})
    t.delete("a")
    s.close()

    s2 = Store(str(tmp_path))
    t2 = s2.table("t1")
    assert t2.get("a") is None
    assert t2.get("b") == {"y": [1, 2]}
    assert len(t2) == 1
    s2.close()


def test_table_survives_torn_tail_write(tmp_path):
    s = Store(str(tmp_path))
    t = s.table("t1")
    for i in range(5):
        t.put(f"k{i}", i)
    # simulate a crash mid-append: garbage tail in the wal
    wal = os.path.join(str(tmp_path), "t1", "wal.jsonl")
    with open(wal, "a") as f:
        f.write('{"op":"put","k":"k9","v"')  # torn record
    s2 = Store(str(tmp_path))
    t2 = s2.table("t1")
    assert t2.get("k4") == 4 and "k9" not in t2
    s2.close()


def test_wal_kill9_recovers_acked_writes(tmp_path):
    """Durability bound (VERDICT r2 weak 6): with per-append fsync, every
    write acknowledged before a SIGKILL must survive recovery."""
    import subprocess
    import sys

    prog = (
        "import os, sys\n"
        "from emqx_tpu.storage.store import Table\n"
        "t = Table(sys.argv[1])\n"
        "for i in range(50):\n"
        "    t.put(f'k{i}', i)\n"
        "    print(f'k{i}', flush=True)\n"
        "    if i == 37:\n"
        "        os.kill(os.getpid(), 9)\n"
    )
    p = subprocess.run(
        [sys.executable, "-c", prog, str(tmp_path / "tbl")],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    acked = [ln for ln in p.stdout.split() if ln]
    assert p.returncode != 0 and len(acked) >= 1  # died by SIGKILL
    from emqx_tpu.storage.store import Table

    t2 = Table(str(tmp_path / "tbl"))
    for k in acked:
        assert k in t2, f"acked write {k} lost after kill -9"


def test_table_compaction(tmp_path):
    s = Store(str(tmp_path))
    t = s.table("t1")
    for i in range(500):
        t.put("hot", i)  # same key: wal grows, data stays size 1
    assert t._wal_records < 500  # compaction kicked in
    assert t.get("hot") == 499
    s.close()
    s2 = Store(str(tmp_path))
    assert s2.table("t1").get("hot") == 499
    s2.close()


# ---------------------------------------------------------------------------
# node persistence across restart
# ---------------------------------------------------------------------------


async def start_node(tmp_path, extra=""):
    cfg = Config(
        file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            f'node.data_dir = "{tmp_path}/data"\n'
            'durable_storage.sync_interval = 100ms\n'
            + extra
        )
    )
    node = BrokerNode(cfg)
    await node.start()
    return node


def mqtt_port(node):
    return node.listeners.all()[0].port


def test_node_restart_restores_state(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        c = Client(clientid="keeper", port=mqtt_port(node), proto_ver=5,
                   clean_start=False,
                   properties={"Session-Expiry-Interval": 600})
        await c.connect()
        await c.subscribe("stay/+", qos=1)
        await c.disconnect()
        pub = Client(clientid="p", port=mqtt_port(node))
        await pub.connect()
        await pub.publish("retain/me", b"sticky", qos=1, retain=True)
        # queued while away
        await pub.publish("stay/x", b"queued", qos=1)
        await pub.disconnect()
        node.banned.add("clientid", "villain", reason="test")
        await node.stop()  # final sync

        node2 = await start_node(tmp_path)
        try:
            # banned + retained survive
            assert any(e.who == "villain" for e in node2.banned.list())
            assert node2.retainer.match("retain/me")[0].payload == b"sticky"
            # session + subscriptions + queued message survive
            sess = node2.broker.sessions.get("keeper")
            assert sess is not None and "stay/+" in sess.subscriptions
            c2 = Client(clientid="keeper", port=mqtt_port(node2),
                        proto_ver=5, clean_start=False)
            ack = await c2.connect()
            assert ack.session_present
            msg = await c2.recv()
            assert msg.payload == b"queued"
            await c2.disconnect()
        finally:
            await node2.stop()

    run(main())


def test_delayed_messages_survive_restart(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        sub_cfg_port = mqtt_port(node)
        pub = Client(clientid="p", port=sub_cfg_port)
        await pub.connect()
        await pub.publish("$delayed/2/later/t", b"tick", qos=1)
        await pub.disconnect()
        assert len(node.delayed) == 1
        await node.stop()

        node2 = await start_node(tmp_path)
        try:
            assert len(node2.delayed) == 1
            sub = Client(clientid="s", port=mqtt_port(node2))
            await sub.connect()
            await sub.subscribe("later/t", qos=0)
            msg = await sub.recv(timeout=5.0)
            assert msg.payload == b"tick"
            await sub.disconnect()
        finally:
            await node2.stop()

    run(main())


def test_v311_persistent_session_not_swept(tmp_path):
    """3.1.1 clean_session=0 sessions have no expiry on the wire; the
    configured default applies, not immediate expiry."""

    async def main():
        node = await start_node(tmp_path)
        try:
            c = Client(clientid="v3keep", port=mqtt_port(node),
                       proto_ver=4, clean_start=False)
            await c.connect()
            await c.subscribe("v3/t", qos=1)
            await c.disconnect()
            sess = node.broker.sessions["v3keep"]
            assert sess.expiry_interval == 7200.0  # configured default
            await asyncio.sleep(1.5)  # past a sweep cycle
            assert "v3keep" in node.broker.sessions
        finally:
            await node.stop()

    run(main())


def test_kick_evicts_offline_durable_session(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        try:
            c = Client(clientid="ghost", port=mqtt_port(node), proto_ver=5,
                       clean_start=False,
                       properties={"Session-Expiry-Interval": 600})
            await c.connect()
            await c.disconnect()
            assert "ghost" in node.broker.sessions
            assert node.kick_client("ghost") is True
            assert "ghost" not in node.broker.sessions
            assert node.kick_client("ghost") is False
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# export / import
# ---------------------------------------------------------------------------


def test_export_import_roundtrip(tmp_path):
    async def main():
        node = await start_node(tmp_path)
        c = Client(clientid="keeper", port=mqtt_port(node), proto_ver=5,
                   clean_start=False,
                   properties={"Session-Expiry-Interval": 600})
        await c.connect()
        await c.subscribe("exp/+", qos=1)
        await c.disconnect()
        pub = Client(clientid="p", port=mqtt_port(node))
        await pub.connect()
        await pub.publish("keep/this", b"r", qos=1, retain=True)
        await pub.disconnect()
        node.banned.add("clientid", "bad", reason="t")
        node.rule_engine.create_rule("r1", 'SELECT * FROM "a/#"')
        archive = export_data(node)
        await node.stop()

        # import into a FRESH node (different data dir)
        node2 = await start_node(str(tmp_path) + "/other")
        try:
            counts = import_data(node2, archive)
            assert counts["sessions"] == 1
            assert counts["retained"] == 1
            assert counts["banned"] == 1
            assert counts["rules"] == 1
            assert "keeper" in node2.broker.sessions
            assert node2.retainer.match("keep/this")
            assert "r1" in node2.rule_engine.rules
        finally:
            await node2.stop()

    run(main())


def test_export_via_rest(tmp_path):
    async def main():
        node = await start_node(
            tmp_path,
            'dashboard.enable = true\ndashboard.auth = false\n'
            'dashboard.listen = "127.0.0.1:0"\n',
        )
        try:
            pub = Client(clientid="p", port=mqtt_port(node))
            await pub.connect()
            await pub.publish("keep/this", b"r", qos=1, retain=True)
            await pub.disconnect()
            mport = node.mgmt_server.port
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", mport
            )
            writer.write(
                b"POST /api/v5/data/export HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: 0\r\nConnection: close\r\n\r\n"
            )
            await writer.drain()
            resp = await reader.read()
            writer.close()
            head, _, payload = resp.partition(b"\r\n\r\n")
            assert b"200" in head.split(b"\r\n")[0]
            assert payload[:2] == b"\x1f\x8b"  # gzip magic
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# NFA checkpoint
# ---------------------------------------------------------------------------


def test_nfa_checkpoint_roundtrip(tmp_path):
    from emqx_tpu.ops import compile_filters, match_topics

    filters = ["a/+/c", "a/#", "x/y", "$SYS/up", "+/b/#"]
    table = compile_filters(filters, depth=8)
    path = str(tmp_path / "nfa.npz")
    save_table(table, path)
    loaded = load_table(path)
    assert loaded is not None
    assert loaded.n_states == table.n_states
    assert loaded.accept_filters == table.accept_filters
    topics = ["a/q/c", "a/deep/er", "x/y", "$SYS/up", "q/b/z", "none"]
    for topic in topics:
        got = sorted(match_topics(loaded, [topic])[0])
        want = sorted(f for f in filters if T.match(topic, f))
        assert got == want, (topic, got, want)


def test_sidecar_checkpoint_restore(tmp_path):
    import grpc.aio

    from emqx_tpu.exhook.rpc import (
        HookProviderStub,
        MirrorSyncStub,
        add_hook_provider_to_server,
        add_mirror_sync_to_server,
        pb,
    )
    from emqx_tpu.exhook.server import TpuMatchSidecar

    async def settle(pred, timeout=5.0):
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            if pred():
                return True
            await asyncio.sleep(0.02)
        return pred()

    ckpt = str(tmp_path / "sidecar.npz")

    async def phase1():
        sidecar = TpuMatchSidecar(
            rebuild_debounce_s=0.01, checkpoint_path=ckpt
        )
        server = grpc.aio.server()
        add_hook_provider_to_server(sidecar, server)
        port = server.add_insecure_port("127.0.0.1:0")
        await sidecar.start()
        await server.start()
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        for flt in ("ck/+/a", "ck/#"):
            await hooks.OnSessionSubscribed(
                pb.SessionSubscribedRequest(
                    clientinfo=pb.ClientInfo(clientid="c"), topic=flt
                )
            )
        assert await settle(lambda: os.path.exists(ckpt))
        await chan.close()
        await sidecar.stop()
        await server.stop(None)

    async def phase2():
        # fresh sidecar restores the compiled table from the checkpoint
        sidecar = TpuMatchSidecar(checkpoint_path=ckpt)
        server = grpc.aio.server()
        add_mirror_sync_to_server(sidecar, server)
        port = server.add_insecure_port("127.0.0.1:0")
        await sidecar.start()
        await server.start()
        assert sidecar._engine is not None  # no rebuild needed
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        mirror = MirrorSyncStub(chan)
        resp = await mirror.MatchBatch(
            pb.MatchBatchRequest(topics=["ck/1/a", "nope"])
        )
        table = sidecar.filter_table()
        got = sorted(table[i] for i in resp.results[0].filter_ids)
        assert got == ["ck/#", "ck/+/a"]
        assert list(resp.results[1].filter_ids) == []
        await chan.close()
        await sidecar.stop()
        await server.stop(None)

    run(phase1())
    run(phase2())
