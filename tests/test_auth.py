"""AuthN chain + AuthZ sources — emqx_authn/emqx_authz/emqx_access_control
parity (SURVEY.md §2.3), incl. the NFA-compiled device ACL batch path."""

import base64
import hashlib
import hmac
import json
import time

from emqx_tpu.auth import (
    AclRule, AuthChain, Authz, BuiltinDbAuthenticator, BuiltinDbSource,
    Credentials, FileSource, JwtAuthenticator, attach_auth,
)
from emqx_tpu.auth.authz import batch_authorize, compile_acl_batch
from emqx_tpu.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.mqtt import packet as P


# ---------------------------------------------------------------------------
# authn


def test_builtin_db_sha256_chain():
    a = BuiltinDbAuthenticator(algo="sha256", salt_position="prefix")
    a.add_user("alice", b"secret", is_superuser=True)
    chain = AuthChain(allow_anonymous=False).add(a)
    ok = chain.authenticate(Credentials("c1", "alice", b"secret"))
    assert ok.outcome == "ok" and ok.is_superuser
    assert chain.authenticate(Credentials("c1", "alice", b"wrong")).outcome == "deny"
    # unknown user → ignore → anonymous policy (deny here)
    assert chain.authenticate(Credentials("c1", "bob", b"x")).outcome == "deny"
    assert AuthChain(allow_anonymous=True).authenticate(
        Credentials("c1")).outcome == "ok"


def test_builtin_db_pbkdf2_and_clientid_type():
    a = BuiltinDbAuthenticator(user_id_type="clientid", algo="pbkdf2")
    a.add_user("dev1", b"pw")
    assert a.authenticate(Credentials("dev1", None, b"pw")).outcome == "ok"
    assert a.authenticate(Credentials("dev2", None, b"pw")).outcome == "ignore"


def _make_jwt(secret: bytes, claims: dict, alg="HS256") -> bytes:
    def enc(d):
        return base64.urlsafe_b64encode(json.dumps(d).encode()).rstrip(b"=")

    h = enc({"alg": alg, "typ": "JWT"})
    b = enc(claims)
    digest = {"HS256": "sha256", "HS384": "sha384", "HS512": "sha512"}[alg]
    sig = base64.urlsafe_b64encode(
        hmac.new(secret, h + b"." + b, digest).digest()
    ).rstrip(b"=")
    return h + b"." + b + b"." + sig


def test_jwt_authenticator():
    j = JwtAuthenticator(b"topsecret", verify_claims={"sub": "%c"})
    good = _make_jwt(b"topsecret", {"sub": "c1", "exp": time.time() + 60})
    assert j.authenticate(Credentials("c1", password=good)).outcome == "ok"
    # wrong clientid claim
    assert j.authenticate(Credentials("c2", password=good)).outcome == "deny"
    # expired
    old = _make_jwt(b"topsecret", {"sub": "c1", "exp": time.time() - 1})
    assert j.authenticate(Credentials("c1", password=old)).outcome == "deny"
    # bad signature
    forged = _make_jwt(b"wrong", {"sub": "c1"})
    assert j.authenticate(Credentials("c1", password=forged)).outcome == "deny"
    # not a JWT → ignore (next in chain)
    assert j.authenticate(Credentials("c1", password=b"plain")).outcome == "ignore"
    # superuser + acl claims carried through
    su = _make_jwt(b"topsecret", {"sub": "c1", "is_superuser": True, "acl": ["t/#"]})
    res = j.authenticate(Credentials("c1", password=su))
    assert res.is_superuser and res.attrs["acl"] == ["t/#"]


# ---------------------------------------------------------------------------
# authz


def _authz(rules, **kw):
    return Authz([FileSource(rules)], **kw)


def test_acl_first_match_wins_and_no_match_policy():
    az = _authz([
        AclRule("deny", "publish", ["forbidden/#"]),
        AclRule("allow", "all", ["#"]),
    ])
    assert not az.authorize("c", "publish", "forbidden/x")
    assert az.authorize("c", "publish", "ok/x")
    az2 = _authz([AclRule("allow", "subscribe", ["a/b"])], no_match="deny")
    assert not az2.authorize("c", "publish", "a/b")   # action mismatch → nomatch → deny
    assert az2.authorize("c", "subscribe", "a/b")


def test_acl_placeholders_and_eq():
    az = _authz([
        AclRule("allow", "all", ["own/%c/#"]),
        AclRule("allow", "subscribe", ["eq priv/+/x"]),
        AclRule("deny", "all", ["#"]),
    ], cache_enable=False)
    assert az.authorize("c1", "publish", "own/c1/data")
    assert not az.authorize("c1", "publish", "own/c2/data")
    # 'eq' is literal: only the verbatim topic with '+' matches
    assert az.authorize("c1", "subscribe", "priv/+/x")
    assert not az.authorize("c1", "subscribe", "priv/a/x")


def test_acl_who_dimensions():
    az = _authz([
        AclRule("deny", "all", ["#"], who="user:mallory"),
        AclRule("deny", "all", ["#"], who="ip:10.0.0.0/8"),
        AclRule("allow", "all", ["#"]),
    ], cache_enable=False)
    assert not az.authorize("c", "publish", "t", username="mallory")
    assert not az.authorize("c", "publish", "t", peerhost="10.1.2.3")
    assert az.authorize("c", "publish", "t", username="alice", peerhost="192.168.0.1")


def test_authz_cache_and_superuser():
    az = _authz([AclRule("deny", "all", ["#"])], no_match="deny")
    assert az.authorize("root", "publish", "t", is_superuser=True)
    assert not az.authorize("c", "publish", "t", now=100.0)
    assert not az.authorize("c", "publish", "t", now=101.0)
    assert az.metrics["cache_hit"] == 1
    # ttl expiry forces re-eval
    assert not az.authorize("c", "publish", "t", now=1000.0)
    assert az.metrics["cache_miss"] == 2


def test_builtin_db_source_precedence():
    src = BuiltinDbSource()
    src.set_rules([AclRule("allow", "all", ["a/#"])], clientid="c1")
    src.set_rules([AclRule("deny", "all", ["a/#"])], username="u1")
    az = Authz([src], no_match="deny", cache_enable=False)
    # client rules take precedence over user rules
    assert az.authorize("c1", "publish", "a/x", username="u1")
    assert not az.authorize("c2", "publish", "a/x", username="u1")


def test_acl_device_batch_matches_host():
    rules = [
        AclRule("deny", "publish", ["secret/#"]),
        AclRule("allow", "publish", ["s/+/temp", "pub/#"]),
        AclRule("deny", "all", ["#"]),
    ]
    src = FileSource(rules)
    table, idx = compile_acl_batch([src])
    assert table is not None
    topics = ["secret/a", "s/1/temp", "pub/x/y", "other/t", "s/1/hum"]
    got = batch_authorize(table, idx, topics, "publish", no_match="allow")
    az = Authz([src], cache_enable=False)
    want = [az.authorize("cX", "publish", t) for t in topics]
    assert got == want == [False, True, True, False, False]


def test_acl_device_batch_refuses_non_static_rules():
    # all-or-nothing: ANY rule the table can't express keeps authz on host
    for bad in (
        AclRule("allow", "all", ["own/%c/#"]),           # placeholder
        AclRule("deny", "all", ["#"], who="user:m"),     # who-specific
        AclRule("deny", "publish", ["t"], retain=True),  # retain constraint
        AclRule("deny", "publish", ["t"], qos=[1, 2]),   # qos constraint
    ):
        table, idx = compile_acl_batch(
            [FileSource([AclRule("allow", "all", ["ok/#"]), bad])]
        )
        assert table is None and idx == {}


def test_acl_placeholder_wildcard_injection_blocked():
    az = _authz([
        AclRule("allow", "all", ["own/%c/#"]),
        AclRule("deny", "all", ["#"]),
    ], no_match="deny", cache_enable=False)
    # a clientid of '+' must NOT become the pattern 'own/+/#'
    assert not az.authorize("+", "publish", "own/alice/data")
    assert not az.authorize("a/b", "publish", "own/a/b")  # '/' injection
    assert az.authorize("alice", "publish", "own/alice/data")


def test_ip_acl_enforced_through_channel_hook():
    broker = Broker()
    cm = ConnectionManager(broker)
    attach_auth(
        broker, AuthChain(allow_anonymous=True),
        Authz([FileSource([
            AclRule("deny", "all", ["#"], who="ip:10.0.0.0/8"),
            AclRule("allow", "all", ["#"]),
        ])]),
    )
    ch = Channel(broker, cm, conninfo={"peerhost": "10.1.2.3"})
    ch.handle_in(P.Connect(proto_ver=5, clientid="c1"))
    acts = ch.handle_in(P.Publish(qos=1, topic="t", packet_id=1, payload=b"x"))
    assert acts[0][1].reason_code == P.RC.NOT_AUTHORIZED


def test_unsubscribe_runs_rewrite_hook():
    from emqx_tpu.services import RewriteRule, TopicRewrite

    broker = Broker()
    cm = ConnectionManager(broker)
    TopicRewrite([RewriteRule("sub", "old/#", r"^old/(.+)$", "new/$1")]
                 ).attach(broker)
    ch = Channel(broker, cm)
    ch.handle_in(P.Connect(proto_ver=5, clientid="c1"))
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("old/a", {"qos": 0})]))
    assert "new/a" in broker.sessions["c1"].subscriptions
    acts = ch.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["old/a"]))
    assert acts[0][1].reason_codes == [P.RC.SUCCESS]
    assert "new/a" not in broker.sessions["c1"].subscriptions


# ---------------------------------------------------------------------------
# end-to-end through the channel


def test_connect_auth_and_publish_acl_through_channel():
    broker = Broker()
    cm = ConnectionManager(broker)
    a = BuiltinDbAuthenticator()
    a.add_user("alice", b"pw")
    chain = AuthChain(allow_anonymous=False).add(a)
    authz = Authz(
        [FileSource([
            AclRule("allow", "all", ["ok/#"]),
            AclRule("deny", "all", ["#"]),
        ])],
        no_match="deny",
    )
    attach_auth(broker, chain, authz)

    # bad credentials → CONNACK error
    ch = Channel(broker, cm)
    acts = ch.handle_in(P.Connect(proto_ver=5, clientid="c1",
                                  username="alice", password=b"no"))
    connack = [a[1] for a in acts if a[0] == "send"][0]
    assert connack.reason_code == P.RC.BAD_USER_NAME_OR_PASSWORD

    # good credentials → connected; ACL enforced on publish+subscribe
    ch2 = Channel(broker, cm)
    acts = ch2.handle_in(P.Connect(proto_ver=5, clientid="c1",
                                   username="alice", password=b"pw"))
    assert [a[1] for a in acts if a[0] == "send"][0].reason_code == P.RC.SUCCESS
    acts = ch2.handle_in(P.Publish(qos=1, topic="denied/t", packet_id=1,
                                   payload=b"x"))
    assert acts[0][1].reason_code == P.RC.NOT_AUTHORIZED
    acts = ch2.handle_in(P.Subscribe(packet_id=2,
                                     topic_filters=[("ok/#", {"qos": 0}),
                                                    ("denied/#", {"qos": 0})]))
    assert acts[0][1].reason_codes == [0, P.RC.NOT_AUTHORIZED]


# ---------------------------------------------------------------------------
# round-4: auto allow_anonymous + secret redaction


def test_auto_anonymous_denies_once_chain_populated():
    """ADVICE r3 #1: a REST-created chain (no explicit allow_anonymous)
    must NOT admit unknown users or everyone during a backend outage.
    Unset policy = open while empty, deny-on-exhaustion once populated."""
    from emqx_tpu.auth.authn import Credentials

    chain = AuthChain()  # policy unset -> auto
    assert chain.authenticate(Credentials(clientid="c")).outcome == "ok"

    class IgnoringBackend:  # e.g. network authn during an outage
        def authenticate(self, creds):
            from emqx_tpu.auth.authn import IGNORE
            return IGNORE

    chain.add(IgnoringBackend())
    assert chain.authenticate(Credentials(clientid="c")).outcome == "deny"
    # explicit opt-out still honored
    chain.allow_anonymous = True
    assert chain.authenticate(Credentials(clientid="c")).outcome == "ok"


def test_describe_redacts_password_hash_and_salt():
    """ADVICE r3 #3: REST-stored users carry password_hash+salt; GET
    /authentication must not leak them to dashboard users."""
    from emqx_tpu.auth.factory import describe

    out = describe({
        "type": "built_in_database",
        "users": [{"username": "u", "password_hash": "deadbeef",
                   "salt": "s3cr3t", "is_superuser": False}],
    })
    u = out["users"][0]
    assert u["password_hash"] == "******"
    assert u["salt"] == "******"
    assert u["username"] == "u"
    assert u["is_superuser"] is False


def test_cm_total_vs_live_connection_count():
    """ADVICE r3 #4: connections.count includes disconnected persistent
    sessions; live_connections.count is connected-only."""
    broker = Broker()
    cm = ConnectionManager(broker)
    broker.open_session("gone", clean_start=False, expiry_interval=3600)
    cm.register_channel("here", object())
    broker.open_session("here", clean_start=True)
    assert cm.connection_count() == 1
    assert cm.total_connection_count() == 2


def test_saslprep_rfc4013_vectors():
    """RFC 4013 §3 examples + prohibited/bidi rules."""
    import pytest as _pytest

    from emqx_tpu.auth.scram import saslprep

    assert saslprep("I­X") == "IX"        # soft hyphen mapped away
    assert saslprep("user") == "user"
    assert saslprep("USER") == "USER"          # case preserved
    assert saslprep("ª") == "a"           # NFKC
    assert saslprep("Ⅸ") == "IX"
    assert saslprep("a b") == "a b"       # nbsp -> space
    for bad in ("\x07", "ا\x31"):         # control; broken bidi
        with _pytest.raises(ValueError):
            saslprep(bad)


def test_scram_unicode_credentials_normalize_consistently():
    """A password typed as a compatibility form must authenticate
    against the same password stored in another form."""
    from emqx_tpu.auth.scram import (
        ScramAuthenticator, scram_client_first, scram_client_final,
    )

    auth = ScramAuthenticator(iterations=256)
    auth.add_user("rené", "paⅨs".encode())   # roman numeral IX
    first, ctx = scram_client_first("rené")      # combining accent
    r = auth.start("c", None, first)
    assert r[0] == "continue", r
    final, ctx = scram_client_final(ctx, b"paIXs", r[1])
    r2 = auth.continue_auth(r[2], final)
    assert r2[0] == "ok", r2
