"""MQTT-over-QUIC (RFC 9000/9001): crypto pinned to the RFC test
vectors, sans-IO handshake/stream exchanges, and a full MQTT session
over a live node's QUIC listener (the quicer-listener analog)."""

import asyncio
import datetime
import socket

import pytest

pytest.importorskip("cryptography")

from emqx_tpu.transport.quic import QuicClient, QuicServerConnection
from emqx_tpu.transport.quic.crypto import initial_keys
from emqx_tpu.transport.quic.packet import (
    decode_varint, encode_varint,
)


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# PKI helper
# ---------------------------------------------------------------------------

def make_cert(cn="broker.test"):
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    cert = (x509.CertificateBuilder().subject_name(name).issuer_name(name)
            .public_key(key.public_key()).serial_number(7)
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=30))
            .sign(key, hashes.SHA256()))
    return (cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(serialization.Encoding.PEM,
                              serialization.PrivateFormat.TraditionalOpenSSL,
                              serialization.NoEncryption()))


CERT_PEM, KEY_PEM = make_cert()


# ---------------------------------------------------------------------------
# RFC 9001 Appendix A vectors
# ---------------------------------------------------------------------------

def test_rfc9001_a1_initial_secrets():
    ks = initial_keys(bytes.fromhex("8394c8f03e515708"))
    assert ks.client.key.hex() == "1f369613dd76d5467730efcbe3b1a22d"
    assert ks.client.iv.hex() == "fa044b2f42a3fd3b46fb255c"
    assert ks.client.hp.hex() == "9f50449e04a0e810283a1e9933adedd2"
    assert ks.server.key.hex() == "cf3a5331653c364c88f0f379b6067e37"
    assert ks.server.iv.hex() == "0ac1493ca1905853b0bba03e"
    assert ks.server.hp.hex() == "c206b8d9b9f0f37644430b490eeaa314"


def test_rfc9001_a2_client_initial_protection():
    """Seal the RFC's client Initial and compare the first protected
    bytes + the header-protection result with A.2."""
    dcid = bytes.fromhex("8394c8f03e515708")
    ks = initial_keys(dcid)
    crypto_frame = bytes.fromhex(
        "060040f1010000ed0303ebf8fa56f12939b9584a3896472ec40bb863cfd3e868"
        "04fe3a47f06a2b69484c00000413011302010000c000000010000e00000b6578"
        "616d706c652e636f6d ff01000100000a 00080006001d00170018001000070005"
        "04616c706e 000500050100000000 0033 0026 0024 001d 0020 9370b2c9caa4"
        "7fbabaf4559fedba753de171fa71f50f1ce15d43e994ec74d748 002b 0003 02"
        "3004 000d 0010 000e 0403050306030203080408050806 002d 0002 0101"
        "001c 0002 4001 0039 0032 04 08 ffffffffffffffff 05 04 8000ffff 07 04"
        "8000ffff 08 01 10 01 04 80 00 75 30 09 01 10 0f 08 8394c8f03e5157"
        "08 06 04 80 00 ffff".replace(" ", ""))
    payload = crypto_frame + b"\x00" * (1162 - len(crypto_frame))
    from emqx_tpu.transport.quic.packet import protect

    pkt = protect("initial", ks.client, 2, payload, dcid=dcid,
                  scid=b"", token=b"", pn_len=4)
    want_prefix = bytes.fromhex(
        "c000000001088394c8f03e5157080000449e7b9aec34d1b1c98dd7689fb8ec11"
        "d242b123dc9bd8bab936b47d92ec356c0bab7df5976d27cd449f63300099f399"
        "1c260ec4c60d17b31f8429157bb35a1282a643a8d2262cad67500cadb8e7378c")
    assert pkt[:len(want_prefix)] == want_prefix, pkt[:48].hex()


def test_varint_roundtrip():
    for v in (0, 1, 63, 64, 16383, 16384, 2**30 - 1, 2**30, 2**40):
        buf = encode_varint(v)
        got, off = decode_varint(buf, 0)
        assert got == v and off == len(buf)


# ---------------------------------------------------------------------------
# sans-IO handshake + streams
# ---------------------------------------------------------------------------

def pump(client, server_box, limit=12):
    for _ in range(limit):
        moved = False
        for dg in client.take_outgoing():
            moved = True
            if server_box[0] is None:
                dcil = dg[5]
                server_box[0] = QuicServerConnection(
                    dg[6:6 + dcil], CERT_PEM, KEY_PEM)
            server_box[0].receive(dg)
        if server_box[0] is not None:
            for dg in server_box[0].take_outgoing():
                moved = True
                client.receive(dg)
        if not moved:
            return


def test_sansio_handshake_and_bidirectional_stream():
    client = QuicClient()
    box = [None]
    pump(client, box)
    server = box[0]
    assert client.established and server.established
    assert client.tls.peer_tp and server.tls.peer_tp
    client.send_stream(b"x" * 5000)      # spans several packets
    pump(client, box)
    assert server.pop_stream_data() == b"x" * 5000
    server.send_stream(b"downlink")
    pump(client, box)
    assert client.pop_stream_data() == b"downlink"


def test_sansio_cert_verification():
    client = QuicClient(verify_cert=True, ca_pem=CERT_PEM)
    box = [None]
    pump(client, box)
    assert client.established    # self-signed cert verifies against itself


def test_sansio_wrong_ca_rejected():
    other_ca, _ = make_cert("evil")
    client = QuicClient(verify_cert=True, ca_pem=other_ca)
    box = [None]
    with pytest.raises(Exception):
        pump(client, box)
    assert not client.established


def test_first_client_datagram_padded():
    client = QuicClient()
    (first,) = client.take_outgoing()
    assert len(first) >= 1200    # RFC 9000 §14.1


def test_connection_close_propagates():
    client = QuicClient()
    box = [None]
    pump(client, box)
    client.close(3, "going away")
    pump(client, box)
    assert box[0].closed and box[0].close_reason == "going away"


# ---------------------------------------------------------------------------
# live node: full MQTT session over the QUIC listener
# ---------------------------------------------------------------------------

class MqttOverQuic:
    """Minimal blocking MQTT client over our QUIC client + UDP socket."""

    def __init__(self, port):
        from emqx_tpu.mqtt import frame as F

        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5.0)
        self.addr = ("127.0.0.1", port)
        self.conn = QuicClient()
        self.parser = F.Parser()
        self._flush()
        while not self.conn.established:
            self._rx_once()
            self._flush()

    def _flush(self):
        for dg in self.conn.take_outgoing():
            self.sock.sendto(dg, self.addr)

    def _rx_once(self):
        data, _ = self.sock.recvfrom(65536)
        self.conn.receive(data)

    def send_pkt(self, pkt):
        from emqx_tpu.mqtt import frame as F

        self.conn.send_stream(F.serialize(pkt))
        self._flush()

    def recv_pkt(self):
        while True:
            data = self.conn.pop_stream_data()
            if data:
                pkts = self.parser.feed(data)
                if pkts:
                    return pkts[0]
            self._rx_once()
            self._flush()

    def close(self):
        self.sock.close()


def test_mqtt_session_over_quic_listener(tmp_path):
    from emqx_tpu.config import Config
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.node import BrokerNode

    (tmp_path / "c.pem").write_bytes(CERT_PEM)
    (tmp_path / "k.pem").write_bytes(KEY_PEM)

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'listeners.quic.default.enable = true\n'
            'listeners.quic.default.bind = "127.0.0.1:0"\n'
            f'listeners.quic.default.certfile = "{tmp_path}/c.pem"\n'
            f'listeners.quic.default.keyfile = "{tmp_path}/k.pem"\n'
        ))
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.quic is not None and node.quic_port
            q = await asyncio.to_thread(MqttOverQuic, node.quic_port)
            assert node.quic.handshakes == 1

            def mqtt_flow():
                q.send_pkt(P.Connect(proto_ver=4, clientid="quic-dev",
                                     clean_start=True, keepalive=60))
                ack = q.recv_pkt()
                assert ack.type == P.CONNACK and ack.reason_code == 0
                q.send_pkt(P.Subscribe(packet_id=1,
                                       topic_filters=[("q/t", {"qos": 0})]))
                suback = q.recv_pkt()
                assert suback.type == P.SUBACK
                # publish over QUIC, receive our own subscription's copy
                q.send_pkt(P.Publish(qos=0, topic="q/t",
                                     payload=b"over-quic"))
                msg = q.recv_pkt()
                assert msg.type == P.PUBLISH
                assert (msg.topic, msg.payload) == ("q/t", b"over-quic")
            await asyncio.to_thread(mqtt_flow)
            # the session rode the normal broker machinery
            assert "quic-dev" in node.broker.sessions
            # MQTT arriving from TCP reaches the QUIC subscriber too
            from emqx_tpu.client import Client

            mq = Client(clientid="tcp-side",
                        port=node.listeners.all()[0].port)
            await mq.connect()
            await mq.publish("q/t", b"cross-transport")

            def recv_cross():
                msg = q.recv_pkt()
                assert (msg.topic, msg.payload) == ("q/t",
                                                    b"cross-transport")
            await asyncio.to_thread(recv_cross)
            await mq.disconnect()
            # the listener row surfaces recovery/path state (RFC 9002
            # fast retransmits, DPLPMTUD) for operators
            row = node.quic_listener_info()[0]
            assert row["mtu_probes_sent"] >= 1
            assert row["mtu_validated_max"] > 1252   # loopback probes
            assert row["fast_retransmits"] >= 0
            q.close()
        finally:
            await node.stop()

    run(main())


def test_stream_datagrams_respect_min_mtu():
    """RFC 9000 §14: a 5 KB publish must be segmented, never emitted as
    one IP-fragmenting datagram (review finding, round 5)."""
    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    client.send_stream(b"y" * 5000)
    for dg in client.take_outgoing():
        assert len(dg) <= 1252, len(dg)
        box[0].receive(dg)
    assert box[0].pop_stream_data() == b"y" * 5000


def test_send_before_keys_is_queued_not_dropped():
    """App data written mid-handshake must flush after key derivation
    instead of being silently discarded (review finding, round 5)."""
    client = QuicClient()
    client.send_stream(b"early CONNECT")   # no 1-RTT keys yet
    box = [None]
    pump(client, box)
    assert client.established
    assert box[0].pop_stream_data() == b"early CONNECT"


def test_endpoint_ignores_garbage_long_headers():
    """Unknown-DCID datagrams that are not well-formed v1 Initials must
    not allocate connection state (review finding, round 5)."""
    from emqx_tpu.transport.quic.connection import QuicEndpoint

    sent = []

    class FakeTransport:
        def sendto(self, data, addr=None):
            sent.append(data)

    ep = QuicEndpoint(FakeTransport(), CERT_PEM, KEY_PEM,
                      on_connection=lambda s, i: None)
    addr = ("127.0.0.1", 12345)
    # long header, wrong type (handshake=0x20), right version, padded
    ep.datagram_received(
        bytes([0xE0]) + b"\x00\x00\x00\x01" + b"\x08" + b"A" * 8
        + b"\x00" * 1200, addr)
    # right type, bogus version
    ep.datagram_received(
        bytes([0xC0]) + b"\xde\xad\xbe\xef" + b"\x08" + b"B" * 8
        + b"\x00" * 1200, addr)
    # right type+version but runt (below the 1200-byte Initial floor)
    ep.datagram_received(
        bytes([0xC0]) + b"\x00\x00\x00\x01" + b"\x08" + b"C" * 8, addr)
    # short header for unknown cid
    ep.datagram_received(b"\x40" + b"D" * 20, addr)
    assert ep.by_cid == {}

    # a REAL client initial still creates state
    client = QuicClient()
    for dg in client.take_outgoing():
        ep.datagram_received(dg, addr)
    assert len(ep.by_cid) == 2             # dcid + server scid


def test_endpoint_caps_connection_state():
    """Past max_connections, well-formed spoofed Initials are dropped
    instead of allocating state + an RSA sign (review finding, r5)."""
    from emqx_tpu.transport.quic.connection import QuicEndpoint

    class FakeTransport:
        def sendto(self, data, addr=None):
            pass

    ep = QuicEndpoint(FakeTransport(), CERT_PEM, KEY_PEM,
                      on_connection=lambda s, i: None, max_connections=2)
    for i in range(5):
        client = QuicClient()
        for dg in client.take_outgoing():
            ep.datagram_received(dg, ("127.0.0.1", 40000 + i))
    assert len(ep.by_cid) == 4                 # 2 conns x 2 cid entries
    assert ep.dropped_initials >= 3


def test_frames_queued_before_keys_stay_segmented():
    """Chunks parked while app keys were absent must flush as multiple
    MTU-sized packets, not one merged jumbo (review finding, r5)."""
    client = QuicClient()
    client.send_stream(b"z" * 5000)        # queued: no 1-RTT keys yet
    box = [None]
    pump(client, box)
    assert client.established
    assert box[0].pop_stream_data() == b"z" * 5000


def test_initial_datagrams_exactly_at_or_above_floor_never_over_mtu():
    """Padded Initial-bearing datagrams land exactly on 1200, never
    1201 (varint-boundary probe fix, review finding, r5)."""
    client = QuicClient(mtu_discovery=False)
    box = [None]
    for _ in range(12):
        moved = False
        for dg in client.take_outgoing():
            moved = True
            assert len(dg) <= 1252, len(dg)
            has_initial = bool(dg[0] & 0x80) and (dg[0] & 0x30) == 0
            if has_initial:
                # exactly 1200 normally; a few bytes over only when the
                # pad budget was below a minimal pad packet
                assert 1200 <= len(dg) <= 1252, len(dg)
            if box[0] is None:
                from emqx_tpu.transport.quic import QuicServerConnection
                box[0] = QuicServerConnection(dg[6:6 + dg[5]],
                                              CERT_PEM, KEY_PEM)
            box[0].receive(dg)
        if box[0] is not None:
            for dg in box[0].take_outgoing():
                moved = True
                client.receive(dg)
        if not moved:
            break
    assert client.established


def test_lost_stream_datagram_retransmitted():
    """RFC 9002 analog: a dropped datagram's STREAM frames re-send
    after the PTO instead of stalling the stream forever."""
    import time as _time

    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    assert client.established
    client.send_stream(b"will be lost")
    lost = client.take_outgoing()
    assert lost                              # dropped on the floor
    assert box[0].pop_stream_data() == b""
    # PTO fires -> frames re-queued -> new datagrams
    fired = client.on_timer(_time.monotonic() + 10)
    assert fired and client.retransmits == 1
    for dg in client.take_outgoing():
        box[0].receive(dg)
    assert box[0].pop_stream_data() == b"will be lost"
    # the server's ACK clears the client's in-flight state
    for dg in box[0].take_outgoing():
        client.receive(dg)
    assert not any(client._sent.values())
    assert client.on_timer(_time.monotonic() + 100) is False


def test_acked_frames_not_retransmitted_and_backoff():
    import time as _time

    client = QuicClient()
    box = [None]
    pump(client, box)
    client.send_stream(b"delivered")
    pump(client, box)                        # delivered + ACKed
    assert box[0].pop_stream_data() == b"delivered"
    assert client.on_timer(_time.monotonic() + 100) is False
    # un-acked data: PTO backs off exponentially
    client.send_stream(b"lost")
    client.take_outgoing()
    p0 = client.pto()
    assert client.on_timer(_time.monotonic() + 10)
    client.take_outgoing()
    assert client.pto() > p0


def test_handshake_crypto_retransmission():
    """First flight lost entirely: the handshake still completes."""
    import time as _time

    client = QuicClient()
    client.take_outgoing()                   # initial flight lost
    box = [None]
    assert client.on_timer(_time.monotonic() + 10)
    pump(client, box)
    assert client.established and box[0].established


def test_large_write_survives_loss_of_early_datagram():
    """A multi-MB write must stay fully retransmittable: the send
    window keeps in-flight packets under the _sent tracking cap, so
    losing an EARLY datagram cannot leave an un-retransmittable hole
    (review finding, round 5)."""
    import time as _time

    client = QuicClient()
    box = [None]
    pump(client, box)
    payload = bytes(range(256)) * 6000       # ~1.5 MB, > window
    client.send_stream(payload, fin=True)
    first_burst = client.take_outgoing()
    assert len(first_burst) <= client._tx_window + 4
    assert len(client._sent["1rtt"]) <= client._tx_window
    # drop the FIRST datagram, deliver the rest
    for dg in first_burst[1:]:
        box[0].receive(dg)
    # drain: acks release the window; PTO recovers the lost datagram
    for _ in range(200):
        for dg in box[0].take_outgoing():
            client.receive(dg)
        client.on_timer(_time.monotonic() + 100)
        for dg in client.take_outgoing():
            box[0].receive(dg)
        if bytes(box[0]._stream_in) == payload:
            break
    assert bytes(box[0]._stream_in) == payload
    assert client.retransmits >= 1


def test_rtt_estimation_tightens_pto():
    """The PTO shifts from the 0.4 s default to srtt + 4*rttvar once
    ack round trips are measured (RFC 6298/9002 analog)."""
    client = QuicClient()
    box = [None]
    pump(client, box)
    assert client.established
    default_pto = 0.4
    client.send_stream(b"ping")
    pump(client, box)                        # delivered + ACKed fast
    assert client._srtt is not None
    assert client._srtt < 0.1                # in-memory pump: ~instant
    assert client.pto() < default_pto        # tighter than the default
    assert client.pto() >= 0.02              # floor holds


def test_quic_listener_recovers_from_datagram_loss(tmp_path):
    """The ENDPOINT's retransmission timer (not just the sans-io core)
    recovers a lost server->client datagram over real UDP: the client
    drops the first PUBLISH-bearing datagram and only the server's PTO
    retransmit delivers it."""
    from emqx_tpu.config import Config
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.node import BrokerNode

    (tmp_path / "c.pem").write_bytes(CERT_PEM)
    (tmp_path / "k.pem").write_bytes(KEY_PEM)

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'listeners.quic.default.enable = true\n'
            'listeners.quic.default.bind = "127.0.0.1:0"\n'
            f'listeners.quic.default.certfile = "{tmp_path}/c.pem"\n'
            f'listeners.quic.default.keyfile = "{tmp_path}/k.pem"\n'
        ))
        node = BrokerNode(cfg)
        await node.start()
        try:
            q = await asyncio.to_thread(MqttOverQuic, node.quic_port)

            def connect_and_sub():
                q.send_pkt(P.Connect(proto_ver=4, clientid="lossy",
                                     clean_start=True, keepalive=60))
                assert q.recv_pkt().type == P.CONNACK
                q.send_pkt(P.Subscribe(packet_id=1,
                                       topic_filters=[("l/t", {"qos": 0})]))
                assert q.recv_pkt().type == P.SUBACK
            await asyncio.to_thread(connect_and_sub)

            def publish_and_drop_then_recover():
                import time as _t

                q.send_pkt(P.Publish(qos=0, topic="l/t",
                                     payload=b"will drop"))
                # DROP every inbound datagram for 250 ms — whatever
                # carried the delivery is gone
                deadline = _t.monotonic() + 0.25
                q.sock.settimeout(0.05)
                dropped = 0
                while _t.monotonic() < deadline:
                    try:
                        q.sock.recvfrom(65536)
                        dropped += 1
                    except socket.timeout:
                        pass
                assert dropped >= 1
                # the endpoint's 200 ms PTO tick must retransmit it
                q.sock.settimeout(5.0)
                pkt = q.recv_pkt()
                assert pkt.type == P.PUBLISH
                assert pkt.payload == b"will drop"
            await asyncio.to_thread(publish_and_drop_then_recover)
            assert node.quic.retransmits >= 1
        finally:
            await node.stop()

    run(main())


def test_fast_retransmit_on_ack_evidence_no_pto():
    """RFC 9002 §6.1: a packet 3+ below the largest acked is declared
    lost AT ACK RECEIPT and retransmits immediately — the stream heals
    without any PTO timer firing."""
    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    assert client.established
    payload = bytes(range(256)) * 30         # ~7.7 KB -> 7 packets
    cwnd_before = client._cwnd
    client.send_stream(payload, fin=True)
    burst = client.take_outgoing()
    assert len(burst) >= 5
    for dg in burst[1:]:                     # FIRST datagram dropped
        box[0].receive(dg)
    assert bytes(box[0]._stream_in) != payload
    # the server's ACK (largest >> lost pn) triggers the fast path
    for dg in box[0].take_outgoing():
        client.receive(dg)
    assert client.fast_retransmits == 1
    assert client.retransmits == 0           # the PTO never fired
    for dg in client.take_outgoing():
        box[0].receive(dg)
    assert bytes(box[0]._stream_in) == payload
    # one multiplicative decrease for the loss event
    assert client._cwnd < cwnd_before


def test_cwnd_grows_on_acks_and_collapses_on_persistent_pto():
    import time as _time

    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    grown = client._cwnd
    client.send_stream(b"z" * 5000)          # 5 packets
    pump(client, box)
    assert client._cwnd >= grown + 4         # slow start: +1 per ack
    # persistent congestion: two consecutive PTOs with no ack between
    client.send_stream(b"lost")
    client.take_outgoing()
    assert client.on_timer(_time.monotonic() + 10)
    client.take_outgoing()
    assert client.on_timer(_time.monotonic() + 100)
    assert client._cwnd == 2.0


def test_stream_release_respects_cwnd():
    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    client._cwnd = 3.0                       # squeeze the window
    client.send_stream(b"y" * 1130 * 20)
    client.take_outgoing()
    assert len(client._sent["1rtt"]) <= 3
    assert client._stream_txq               # remainder queued, not lost


def test_third_pto_does_not_clobber_ssthresh():
    """The persistent-congestion collapse runs only on the TRANSITION
    (2nd consecutive PTO); later PTOs of the same outage must leave
    ssthresh intact so post-outage slow start can climb back."""
    import time as _time

    client = QuicClient()
    box = [None]
    pump(client, box)
    client.send_stream(b"z" * 5000)
    pump(client, box)                        # acks grow cwnd
    client._cwnd = 100.0
    client.send_stream(b"lost")
    client.take_outgoing()
    t = _time.monotonic()
    assert client.on_timer(t + 10)
    client.take_outgoing()
    assert client.on_timer(t + 100)          # transition: collapse
    client.take_outgoing()
    assert client._cwnd == 2.0 and client._ssthresh == 50.0
    assert client.on_timer(t + 1000)         # third PTO: no re-collapse
    assert client._ssthresh == 50.0


# ---------------------------------------------------------------------------
# DPLPMTUD + pacing (round-5 close-out of the stated QUIC cuts)
# ---------------------------------------------------------------------------

def test_pmtud_raises_datagram_budget_on_clean_path():
    """RFC 8899 analog: PING+PADDING probes walk the ladder on a path
    that carries them; each acked probe raises the validated size and
    the stream chunk, so bulk writes use far fewer datagrams."""
    client = QuicClient()
    box = [None]
    pump(client, box, limit=30)
    assert client.established
    assert client.mtu_probes_sent >= 1
    assert client.mtu_validated == 63000       # ladder exhausted
    assert client._mtu_chunk == 63000 - 70
    assert not client._mtu_ladder
    # a 100 KB write now rides in 2 datagrams, not ~90
    client.send_stream(b"m" * 100_000)
    dgs = client.take_outgoing()
    assert len(dgs) <= 3
    assert max(len(d) for d in dgs) > 1252
    for dg in dgs:
        box[0].receive(dg)
    assert box[0].pop_stream_data() == b"m" * 100_000


def test_pmtud_probe_loss_freezes_ladder_without_congestion_signal():
    """A path capped at 1252 bytes drops every probe: after one retry
    per size the ladder freezes at the floor — and probe loss must NOT
    halve the congestion window or count as a retransmission."""
    import time as _time

    client = QuicClient()
    box = [None]
    # a 1252-byte path: probe datagrams never arrive
    for _ in range(40):
        moved = False
        for dg in client.take_outgoing():
            if len(dg) > 1252:
                moved = True                     # dropped by the path
                continue
            moved = True
            if box[0] is None:
                box[0] = QuicServerConnection(dg[6:6 + dg[5]],
                                              CERT_PEM, KEY_PEM,
                                              mtu_discovery=False)
            box[0].receive(dg)
        if box[0] is not None:
            for dg in box[0].take_outgoing():
                moved = True
                client.receive(dg)
        # PTO tick declares the in-flight probe lost, sends the next
        client.on_timer(_time.monotonic() + 10)
        if box[0] is not None and box[0].established \
                and not client._mtu_ladder and client._mtu_probe is None:
            break
        if not moved and box[0] is not None and not client._mtu_ladder:
            break
    assert client.established
    assert not client._mtu_ladder                # gave up
    assert client.mtu_validated == 1252         # floor kept
    assert client._mtu_chunk == 1130
    assert client.mtu_probes_sent >= 2           # one retry happened
    assert client.fast_retransmits == 0          # loss != congestion
    # stream traffic still flows at the floor
    client.send_stream(b"still fine")
    for dg in client.take_outgoing():
        assert len(dg) <= 1252
        box[0].receive(dg)
    assert box[0].pop_stream_data() == b"still fine"


def test_pacing_bounds_release_bursts():
    """RFC 9002 §7.7 analog: with a measured (slow) RTT, one
    _service() releases at most the burst cap, and tokens refill with
    elapsed time rather than all at once."""
    client = QuicClient(mtu_discovery=False)
    box = [None]
    pump(client, box)
    assert client.established
    client._srtt = 1.0                  # pretend a 1 s RTT path
    client._rttvar = 0.0
    client._cwnd = 400.0                # huge window: pacing must bind
    client._pace_tokens = 0.0
    client._pace_last = __import__("time").monotonic()
    client.send_stream(b"q" * 1130 * 100)        # 100 chunks queued
    released = len(client._sent["1rtt"]) + \
        len(client._pending_frames["1rtt"])
    burst = max(16, int(client._cwnd / 2))
    assert released <= burst            # one call != the whole window
    assert client._stream_txq           # remainder paced, not dropped
    # simulate 100 ms passing: ~50 more packets (1.25*400/1.0*0.1)
    client._pace_last -= 0.1
    client.on_timer()                   # timer tick drains the queue
    released2 = len(client._sent["1rtt"]) + \
        len(client._pending_frames["1rtt"]) + \
        sum(1 for _ in client.take_outgoing())
    assert released2 > released         # refill released more


def test_pmtud_black_hole_falls_back_to_base_mtu():
    """RFC 8899 §4.3 analog: after a larger MTU is validated, a path
    shrink (route change) makes every full-size packet vanish.  Two
    consecutive PTOs must reset the budget to the base PLPMTU and
    re-segment queued jumbo STREAM frames so the stream heals."""
    import time as _time

    client = QuicClient()
    box = [None]
    pump(client, box, limit=30)
    assert client.mtu_validated == 63000        # clean path validated
    payload = bytes(range(256)) * 2000           # 512 KB
    client.send_stream(payload, fin=True)
    # the path now drops anything over 1252 bytes
    def shuttle():
        for dg in client.take_outgoing():
            if len(dg) <= 1252:
                box[0].receive(dg)
        for dg in box[0].take_outgoing():
            client.receive(dg)
    shuttle()                                    # jumbo frames all lost
    assert bytes(box[0]._stream_in) != payload
    t = _time.monotonic()
    assert client.on_timer(t + 10)               # first PTO
    shuttle()
    assert client.on_timer(t + 100)              # second: fallback
    assert client.mtu_validated == 1252
    assert client._mtu_chunk == 1130
    assert not client._mtu_ladder                # ladder stays retired
    # drain to completion at the base MTU
    for _ in range(600):
        shuttle()
        client.on_timer(_time.monotonic() + 100)
        if bytes(box[0]._stream_in) == payload:
            break
    assert bytes(box[0]._stream_in) == payload


# ---------------------------------------------------------------------------
# PLPMTUD black-hole detection under mixed traffic (ADVICE round 5)
# ---------------------------------------------------------------------------

def test_blackhole_streak_fires_despite_ack_resets():
    """Regression: on a path whose MTU shrank while SMALL packets keep
    flowing, every ack resets _pto_count, so the old _pto_count==2
    fallback never fired and jumbo frames retransmitted at the dead
    size forever.  The streak counter (consecutive losses of packets
    larger than the base PLPMTU, RFC 8899 §4.3) must fire regardless."""
    from emqx_tpu.transport.quic import frames as FR
    from emqx_tpu.transport.quic.tls13 import LEVEL_APP

    client = QuicClient(mtu_discovery=True)
    # pretend DPLPMTUD validated a jumbo path earlier
    client.mtu_validated = 9000
    client._mtu_chunk = 9000 - 70
    big = FR.encode_stream(0, 0, b"x" * 5000)
    for i in range(client.BLACK_HOLE_STREAK):
        client._sent[LEVEL_APP][100 + i] = (0.0, [big])
        assert client.on_timer(now=1e9) is True   # jumbo declared lost
        # mixed traffic: an interleaved small-packet ack keeps resetting
        # the PTO backoff counter — the OLD trigger can never reach 2
        client._pto_count = 0
    assert client.mtu_validated == 1252
    assert client._mtu_chunk == client._MTU_STREAM_CHUNK
    assert not client._mtu_ladder                 # ladder stays retired
    # everything still pending was re-segmented to the base chunk
    for fr in client._pending_frames[LEVEL_APP]:
        if 0x08 <= fr[0] <= 0x0F:
            assert len(fr) <= client._MTU_STREAM_CHUNK + 16


def test_blackhole_streak_resets_when_big_packet_acked():
    """A delivered full-size packet proves the path still carries the
    validated MTU: the loss streak must restart from zero."""
    from emqx_tpu.transport.quic import frames as FR
    from emqx_tpu.transport.quic.packet import PKT_1RTT, PlainPacket
    from emqx_tpu.transport.quic.tls13 import LEVEL_APP

    client = QuicClient(mtu_discovery=True)
    client.mtu_validated = 9000
    client._mtu_chunk = 9000 - 70
    big = FR.encode_stream(0, 0, b"x" * 5000)
    for i in range(client.BLACK_HOLE_STREAK - 1):
        client._sent[LEVEL_APP][100 + i] = (0.0, [big])
        client.on_timer(now=1e9)
        client._pto_count = 0
    assert client._big_loss_streak == client.BLACK_HOLE_STREAK - 1
    # a big packet gets through and is acked
    client._sent[LEVEL_APP][200] = (0.0, [big])
    client._on_packet(PlainPacket(kind=PKT_1RTT, dcid=b"", scid=b"",
                                  pn=0, payload=FR.encode_ack([200])))
    assert client._big_loss_streak == 0
    assert client.mtu_validated == 9000           # no fallback


def test_resegment_on_requeue_at_flush_time():
    """Regression (ADVICE round 5, second half): a jumbo stream frame
    requeued from _sent AFTER the fallback transition must be split at
    flush time, not re-sent oversized indefinitely."""
    from emqx_tpu.transport.quic import frames as FR
    from emqx_tpu.transport.quic.tls13 import LEVEL_APP

    client = QuicClient(mtu_discovery=True)
    client._keys[LEVEL_APP] = initial_keys(b"\x00" * 8)
    # a frame built when the validated MTU was 9000 ...
    big = FR.encode_stream(0, 0, b"y" * 4000)
    # ... lands in the pending queue after the path shrank back
    client._mtu_chunk = client._MTU_STREAM_CHUNK
    client._pending_frames[LEVEL_APP].append(big)
    out = client._flush_level(LEVEL_APP)
    assert out
    assert all(len(pkt) <= 1252 for pkt in out)
    assert not client._pending_frames[LEVEL_APP]  # all flushed, none jumbo


def test_probe_ack_excluded_from_cwnd_growth():
    """ADVICE round 5 (low): an acked DPLPMTUD probe is discovery
    traffic, not congestion feedback — it must not grow cwnd."""
    from emqx_tpu.transport.quic import frames as FR
    from emqx_tpu.transport.quic.packet import PKT_1RTT, PlainPacket
    from emqx_tpu.transport.quic.tls13 import LEVEL_APP

    client = QuicClient(mtu_discovery=True)
    client._mtu_probe = (7, 4096)
    client._sent[LEVEL_APP][7] = (0.0, [])
    cwnd0 = client._cwnd
    client._on_packet(PlainPacket(kind=PKT_1RTT, dcid=b"", scid=b"",
                                  pn=0, payload=FR.encode_ack([7])))
    assert client._cwnd == cwnd0                 # no growth
    assert client.mtu_validated == 4096          # probe result applied


def test_no_mtu_probe_while_in_recovery():
    """ADVICE round 5 (low): discovery probes must not compete with
    retransmissions for a shrunken window — skip probing until the
    loss edge is acked."""
    from emqx_tpu.transport.quic.tls13 import LEVEL_APP

    client = QuicClient(mtu_discovery=True)
    client._keys[LEVEL_APP] = initial_keys(b"\x00" * 8)
    client.handshake_done = True
    client._mtu_ladder = [1452]
    client._recovery_until[LEVEL_APP] = 10
    client._largest_acked[LEVEL_APP] = 2          # edge not acked yet
    client._maybe_send_mtu_probe()
    assert client._mtu_probe is None              # held back
    client._largest_acked[LEVEL_APP] = 10         # recovery over
    client._maybe_send_mtu_probe()
    assert client._mtu_probe is not None


def test_quic_listener_survives_parse_faults_mid_handshake(tmp_path):
    """The ROADMAP chaos item for the QUIC listener, both wound shapes:

    1. a corrupted datagram arriving MID-QUIC-HANDSHAKE (valid routing
       prefix, garbage payload) must at worst drop that connection —
       never the endpoint or the event loop;
    2. an injected MQTT frame-parse fault on the stream — i.e. mid
       MQTT handshake, the CONNECT itself — takes the native
       FrameError path and closes that session while the listener
       keeps accepting and serving new handshakes."""
    from emqx_tpu import faultinject
    from emqx_tpu.config import Config
    from emqx_tpu.faultinject import FaultInjector
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.node import BrokerNode

    (tmp_path / "c.pem").write_bytes(CERT_PEM)
    (tmp_path / "k.pem").write_bytes(KEY_PEM)

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'listeners.quic.default.enable = true\n'
            'listeners.quic.default.bind = "127.0.0.1:0"\n'
            f'listeners.quic.default.certfile = "{tmp_path}/c.pem"\n'
            f'listeners.quic.default.keyfile = "{tmp_path}/k.pem"\n'
        ))
        node = BrokerNode(cfg)
        await node.start()
        try:
            port = node.quic_port

            # -- wound 1: corrupted datagram mid-QUIC-handshake -------
            def corrupt_mid_handshake():
                sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                sock.settimeout(5.0)
                addr = ("127.0.0.1", port)
                c = QuicClient()
                dgs = c.take_outgoing()
                assert dgs
                for d in dgs:
                    sock.sendto(d, addr)
                sock.recvfrom(65536)       # server engaged the handshake
                # replay the first client flight with its payload bytes
                # flipped: routes to the live conn, fails packet parse
                d0 = dgs[0]
                corrupted = d0[:40] + bytes(b ^ 0xFF for b in d0[40:])
                sock.sendto(corrupted, addr)
                sock.close()
            await asyncio.to_thread(corrupt_mid_handshake)
            await asyncio.sleep(0.05)

            # the endpoint survived: a fresh client completes
            q1 = await asyncio.to_thread(MqttOverQuic, port)

            # -- wound 2: injected MQTT parse fault on the CONNECT ----
            inj = faultinject.install(FaultInjector([
                {"point": "frame.parse", "action": "raise", "times": 1},
            ]))
            try:
                def poke():
                    q1.send_pkt(P.Connect(proto_ver=4, clientid="qc1",
                                          clean_start=True, keepalive=60))
                await asyncio.to_thread(poke)
                # the server's reader hit the injected FrameError and
                # closed that stream — without killing the listener
                deadline = asyncio.get_event_loop().time() + 5.0
                while (inj.fired.get("frame.parse", 0) < 1
                       and asyncio.get_event_loop().time() < deadline):
                    await asyncio.sleep(0.01)
                assert inj.fired.get("frame.parse") == 1
            finally:
                faultinject.uninstall()
            q1.close()
            assert "qc1" not in node.broker.sessions

            # listener still serves: full MQTT session over a new conn
            q2 = await asyncio.to_thread(MqttOverQuic, port)

            def full_flow():
                q2.send_pkt(P.Connect(proto_ver=4, clientid="qc2",
                                      clean_start=True, keepalive=60))
                ack = q2.recv_pkt()
                assert ack.type == P.CONNACK and ack.reason_code == 0
                q2.send_pkt(P.Subscribe(
                    packet_id=1, topic_filters=[("cq/t", {"qos": 1})]))
                assert q2.recv_pkt().type == P.SUBACK
                q2.send_pkt(P.Publish(qos=0, topic="cq/t",
                                      payload=b"alive"))
                msg = q2.recv_pkt()
                assert (msg.topic, msg.payload) == ("cq/t", b"alive")
            await asyncio.to_thread(full_flow)
            assert "qc2" in node.broker.sessions
            q2.close()
        finally:
            await node.stop()

    run(main())
