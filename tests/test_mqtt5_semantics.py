"""Regression tests for MQTT5 edge semantics found in review:
will-on-abnormal-disconnect, RAP vs DUP, Subscription-Identifier echo,
shared-sub eviction-is-not-a-nack."""

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.message import make_message
from emqx_tpu.broker.mqueue import MQueue
from emqx_tpu.broker.session import Session, SubOpts
from emqx_tpu.mqtt import packet as P


def _connected_channel(broker, cm, clientid, will=None, proto_ver=5):
    ch = Channel(broker, cm)
    acts = ch.handle_in(P.Connect(
        proto_ver=proto_ver, clientid=clientid, clean_start=True, will=will,
    ))
    assert any(a[0] == "send" and a[1].type == P.CONNACK for a in acts)
    return ch


def test_will_discarded_on_normal_disconnect():
    b = Broker()
    cm = ConnectionManager(b)
    hits = []
    b.hooks.add("message.publish", lambda m: hits.append(m.topic))
    will = P.Will(topic="wills/c2", payload=b"gone", qos=0, retain=False)
    ch = _connected_channel(b, cm, "c2", will=will)
    ch.handle_in(P.Disconnect(reason_code=0))
    ch.handle_close("client disconnect")
    assert "wills/c2" not in hits


def test_will_published_on_reason_0x04_and_0x80():
    for rc in (0x04, 0x80, 0x8E):
        b = Broker()
        cm = ConnectionManager(b)
        hits = []
        b.hooks.add("message.publish", lambda m: hits.append(m.topic))
        will = P.Will(topic="wills/x", payload=b"gone", qos=1, retain=False)
        ch = _connected_channel(b, cm, "x", will=will)
        ch.handle_in(P.Disconnect(reason_code=rc))
        ch.handle_close("bye")
        assert hits == ["wills/x"], f"reason 0x{rc:02x}"


def test_rap_clears_retain_even_on_dup_retransmit():
    b = Broker()
    b.open_session("s")
    b.subscribe("s", "t/1", SubOpts(qos=1, rap=False))
    msg = make_message("p", "t/1", b"x", qos=1, retain=True).clone(dup=True)
    res = b.publish(msg)
    pubs = res.publishes["s"]
    assert len(pubs) == 1 and pubs[0].msg.retain is False


def test_subscription_identifier_echoed_in_delivery():
    b = Broker()
    b.open_session("s")
    b.subscribe("s", "t/+", SubOpts(qos=0, subid=7))
    res = b.publish(make_message("p", "t/9", b"x"))
    [pub] = res.publishes["s"]
    assert pub.msg.properties.get("Subscription-Identifier") == 7


def test_shared_sub_eviction_is_not_a_nack():
    """A full mqueue that evicts an *older* message still accepts the new
    one — the shared dispatcher must not redispatch (no duplicates)."""
    b = Broker(shared_strategy="round_robin",
               session_defaults={"max_inflight": 1})
    b.open_session("a")
    b.sessions["a"].mqueue = MQueue(max_len=1)
    b.open_session("bb")
    b.subscribe("a", "$share/g/t")
    b.subscribe("bb", "$share/g/t")

    # fill a's inflight (1) and mqueue (1) with prior traffic
    b.sessions["a"].deliver(
        [make_message("p", "t", b"0", qos=1), make_message("p", "t", b"1", qos=1)]
    )
    assert len(b.sessions["a"].mqueue) == 1

    deliveries = []
    b.hooks.add("message.delivered", lambda cid, m: deliveries.append((cid, m.payload)))
    # round_robin picks 'a' first; its queue evicts msg "1" but accepts "2"
    res = b.publish(make_message("p", "t", b"2", qos=1))
    got = [cid for cid, pay in deliveries if pay == b"2"]
    # accepted by exactly one member — never both
    assert len(got) <= 1
    # and message "2" is either queued at a or sent to someone, not dropped
    dropped_new = [m for _, m in res.dropped if m.payload == b"2"]
    assert not dropped_new


def test_stats_watermark_monotone_across_all():
    from emqx_tpu.observe import Stats

    s = Stats()
    vals = {"v": 10}
    s.provide("sessions.count", lambda: vals["v"])
    assert s.all()["sessions.max"] == 10
    vals["v"] = 3
    out = s.all()
    assert out["sessions.count"] == 3
    assert out["sessions.max"] == 10  # watermark persisted
