"""Tier-1 enforcement of the project-invariant static analysis suite
(emqx_tpu/devtools/staticcheck) — the dialyzer/xref analog.

Layers:

* **the tree is clean**: all thirteen rules over ``emqx_tpu/`` plus
  the bench drivers (``bench.py``, ``scripts/bench_e2e.py``) produce
  zero non-waived findings, and every waiver (if any ever lands) is an
  explicit, justified, expiring entry — no silent suppressions;
* **the rules work**: each rule has a tripping and a passing fixture
  under ``tests/staticcheck_fixtures/``, waiver keys are line-stable,
  and expiry/staleness behave;
* **the whole-program analysis crosses modules**: the ``xmod`` fixture
  package puts every offending call in a different module than its
  thread/loop entry and the findings land at the right file:line; the
  ``twoplane``/``twohop`` packages pin the context-sensitive lattice
  (k=2 caller chains keep two entries through one shared mid-function
  distinct, so per-entry exemptions scope correctly);
* **the cache is sound**: warm runs reuse summaries+findings, a dep
  edit invalidates exactly its dependents, ``--changed`` re-checks
  changed files plus reverse import-graph dependents;
* **the CLI works**: a violation seeded into a copy of
  ``broker/fanout.py`` is caught with a file:line finding and exit 1;
  a clean run exits 0.

Satellite coverage rides along: the event-loop lag probe
(broker/olp.py) and the QUIC-timer / kafka-poll supervised children.
"""

import asyncio
import datetime
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from emqx_tpu.devtools.staticcheck import (
    Registries, WaiverFile, check_paths, get_rules,
)
from emqx_tpu.devtools.staticcheck.rules import ALL_RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "emqx_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "staticcheck_fixtures")
WAIVER_FILE = os.path.join(REPO, "staticcheck-waivers.json")
CLI = os.path.join(REPO, "scripts", "staticcheck.py")


def run(coro):
    return asyncio.run(coro)


def check_fixture(name, rules, tmp_path, relpath="emqx_tpu/broker"):
    """Run ``rules`` over one fixture file, staged under a repo-shaped
    temp tree so path-scoped rules (delivery-path prefixes, allowlists)
    see the intended relative path."""
    dest_dir = tmp_path / relpath
    dest_dir.mkdir(parents=True, exist_ok=True)
    dest = dest_dir / name
    shutil.copy(os.path.join(FIXTURES, name), dest)
    return check_paths([str(dest)], get_rules(rules), root=str(tmp_path))


# ---------------------------------------------------------------------------
# the tree is clean (the tier-1 gate)
# ---------------------------------------------------------------------------

#: the tier-1 scan set: the package plus the bench drivers whose
#: metric/config literals have silently drifted before
SCAN_PATHS = [PKG, os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "scripts", "bench_e2e.py")]


def test_tree_has_zero_nonwaived_findings():
    findings = check_paths(SCAN_PATHS, get_rules(), root=REPO)
    wf = WaiverFile.load(WAIVER_FILE)
    new, waived, expired, stale = wf.apply(findings)
    assert not new, (
        "staticcheck found new violations (fix them or add an expiring "
        "waiver with a reason):\n"
        + "\n".join(
            f"  {f.location()}: [{f.rule}] {f.message}"
            + (f"\n      path: {' -> '.join(f.chain)}" if f.chain
               else "")
            for f in new)
    )
    assert not expired, (
        "expired waivers still have live findings: "
        + ", ".join(w.key for w in expired)
    )


def test_waiver_file_has_no_silent_suppressions():
    with open(WAIVER_FILE) as f:
        data = json.load(f)
    for w in data.get("waivers", []):
        assert w.get("reason"), f"waiver {w.get('key')} has no reason"
        # a malformed date must fail here, not silently never expire
        datetime.date.fromisoformat(w["expires"])


# ---------------------------------------------------------------------------
# per-rule fixtures: each rule trips and passes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule,trip,ok,n_trip", [
    ("no-unsupervised-task", "trip_tasks.py", "ok_tasks.py", 3),
    ("loop-thread-taint", "trip_threads.py", "ok_threads.py", 6),
    ("shard-affinity", "trip_affinity.py", "ok_affinity.py", 3),
    # seeds GENERATED from _SHARD_LOCAL x handle_in dispatch facts: a
    # shard-legal handler can no longer silently miss its seed
    ("shard-affinity", "trip_affinity_gen.py", "ok_affinity_gen.py", 1),
    # serve-pipeline worker threads (ISSUE 11): an unseeded to_thread
    # pipeline stage writing Broker state trips; the pure-compute
    # worker + loop-side-write shape passes
    ("shard-affinity", "trip_affinity_pipeline.py",
     "ok_affinity_pipeline.py", 1),
    # multichip mesh worker threads (ISSUE 15): an unseeded to_thread
    # partition-apply writing MatchService state trips; the
    # matcher-owns-its-own-state + loop-side-readiness shape passes
    ("shard-affinity", "trip_affinity_mesh.py",
     "ok_affinity_mesh.py", 1),
    ("torn-read", "trip_tornread.py", "ok_tornread.py", 2),
    ("lock-order", "trip_lockorder.py", "ok_lockorder.py", 1),
    # object-sensitive lock identity (ISSUE 17): two unrelated _lock
    # attrs on different classes no longer alias — the cross-class
    # same-name deadlock trips, the cross-class chain passes
    ("lock-order", "trip_lockident.py", "ok_lockident.py", 1),
    ("no-blocking-in-async", "trip_blocking.py", "ok_blocking.py", 2),
    ("no-swallowed-exceptions", "trip_exceptions.py",
     "ok_exceptions.py", 3),
    ("await-under-lock", "trip_locks.py", "ok_locks.py", 3),
    ("registry-drift", "trip_drift.py", "ok_drift.py", 9),
    ("unawaited-coroutine", "trip_coroutines.py", "ok_coroutines.py", 3),
    # device-plane dataflow rules (ISSUE 19): reuse after a donated
    # dispatch trips (rebind/result-only/branch-dispatch pass), a
    # device sync on a main/shard path trips (thread worker, host
    # asarray and unreached helper pass), and an await between the
    # reads of one invariant group on an unlocked main path trips
    # (one critical section, await-before, unreached pass)
    ("use-after-donate", "trip_donate.py", "ok_donate.py", 2),
    ("host-sync-in-loop", "trip_hostsync.py", "ok_hostsync.py", 4),
    ("await-torn-read", "trip_awaittorn.py", "ok_awaittorn.py", 2),
])
def test_rule_fixture_pair(rule, trip, ok, n_trip, tmp_path):
    tripped = check_fixture(trip, [rule], tmp_path)
    assert len(tripped) == n_trip, (
        f"{rule} on {trip}: expected {n_trip} findings, got "
        f"{[(f.line, f.message) for f in tripped]}"
    )
    assert all(f.rule == rule for f in tripped)
    assert all(f.line > 0 for f in tripped)
    passed = check_fixture(ok, [rule], tmp_path)
    assert passed == [], (
        f"{rule} on {ok} should be clean, got "
        f"{[(f.line, f.message) for f in passed]}"
    )


def test_swallowed_exceptions_scoped_to_delivery_paths(tmp_path):
    # the same tripping file is FINE outside the delivery-path prefixes
    out = check_fixture("trip_exceptions.py", ["no-swallowed-exceptions"],
                        tmp_path, relpath="emqx_tpu/ops")
    assert out == []


def test_task_allowlist_honors_site_and_reason(tmp_path):
    # stage the tripping file at an allowlisted (path, qualname):
    # client.py / Client.connect is allowlisted as request-scoped
    dest_dir = tmp_path / "emqx_tpu"
    dest_dir.mkdir(parents=True, exist_ok=True)
    dest = dest_dir / "client.py"
    dest.write_text(
        "import asyncio\n\n\n"
        "class Client:\n"
        "    async def connect(self):\n"
        "        asyncio.ensure_future(self._read_loop())\n\n"
        "    async def other(self):\n"
        "        asyncio.ensure_future(self._read_loop())\n\n"
        "    async def _read_loop(self):\n"
        "        pass\n"
    )
    out = check_paths([str(dest)], get_rules(["no-unsupervised-task"]),
                      root=str(tmp_path))
    # connect() is allowlisted, other() is not
    assert len(out) == 1 and out[0].context == "Client.other"


# ---------------------------------------------------------------------------
# waivers: keys, expiry, staleness
# ---------------------------------------------------------------------------

def _fixture_findings(tmp_path):
    out = check_fixture("trip_blocking.py", ["no-blocking-in-async"],
                        tmp_path)
    assert out
    return out


def test_waiver_suppresses_until_expiry_then_resurfaces(tmp_path):
    findings = _fixture_findings(tmp_path)
    t0 = datetime.date(2026, 8, 1)
    wf = WaiverFile.baseline(findings, days=30, today=t0)
    # live: everything waived, run is clean
    new, waived, expired, stale = wf.apply(
        findings, today=t0 + datetime.timedelta(days=15))
    assert not new and len(waived) == len(findings) and not expired
    # past expiry: findings come back AND the expired entries surface
    new, waived, expired, stale = wf.apply(
        findings, today=t0 + datetime.timedelta(days=31))
    assert len(new) == len(findings) and not waived
    assert len(expired) == len(wf.waivers)


def test_stale_waivers_are_reported(tmp_path):
    findings = _fixture_findings(tmp_path)
    wf = WaiverFile.baseline(findings, today=datetime.date(2026, 8, 1))
    new, waived, expired, stale = wf.apply(
        [], today=datetime.date(2026, 8, 2))
    assert len(stale) == len(wf.waivers) and not new


def test_waiver_keys_survive_line_drift(tmp_path):
    a = check_fixture("trip_blocking.py", ["no-blocking-in-async"],
                      tmp_path)
    # same code shifted two lines down: same keys, different lines
    src = open(os.path.join(FIXTURES, "trip_blocking.py")).read()
    shifted = tmp_path / "emqx_tpu" / "broker" / "trip_blocking.py"
    shifted.write_text("# shim\n# shim\n" + src)
    b = check_paths([str(shifted)], get_rules(["no-blocking-in-async"]),
                    root=str(tmp_path))
    assert [f.key for f in a] == [f.key for f in b]
    assert [f.line for f in a] != [f.line for f in b]


def test_waiver_file_roundtrip(tmp_path):
    findings = _fixture_findings(tmp_path)
    wf = WaiverFile.baseline(findings, today=datetime.date(2026, 8, 1))
    p = tmp_path / "w.json"
    wf.save(str(p))
    loaded = WaiverFile.load(str(p))
    assert [w.key for w in loaded.waivers] == [w.key for w in wf.waivers]


# ---------------------------------------------------------------------------
# registries extract the real registration sites
# ---------------------------------------------------------------------------

def test_registries_extract_from_tree():
    reg = Registries.load()
    assert "messages.delivered" in reg.metric_names
    assert "broker.olp.loop_lag_us" in reg.metric_names
    assert "messages.dropped.olp_shed" in reg.metric_names
    assert "mqtt.max_inflight" in reg.config_keys
    assert "overload_protection.lag_probe_interval" in reg.config_keys
    assert "fanout.drain" in reg.fault_points
    assert "message.acked" in reg.hook_points
    assert "client.enhanced_authenticate" in reg.hook_points
    assert "obs.stage.match_readback" in reg.hist_names
    assert "obs.e2e.publish_deliver" in reg.hist_names
    assert "breaker_trip" in reg.dump_reasons
    assert "supervisor_degraded" in reg.dump_reasons
    assert "obs.flightrec.dumps" in reg.metric_names
    assert "obs.hist.enable" in reg.config_keys


def test_registries_match_runtime_tables():
    # the AST extraction and the live modules must agree, or the drift
    # rule itself has drifted
    from emqx_tpu import faultinject
    from emqx_tpu.config import SCHEMA
    from emqx_tpu.observe.metrics import Metrics

    reg = Registries.load()
    assert reg.metric_names == set(Metrics().all().keys())
    assert reg.config_keys == set(SCHEMA.keys())
    assert reg.fault_points == set(faultinject.POINTS)
    from emqx_tpu.broker.hooks import HOOK_POINTS
    assert reg.hook_points == set(HOOK_POINTS)
    from emqx_tpu.observe.flightrec import DUMP_REASONS
    from emqx_tpu.observe.hist import HIST_NAMES
    assert reg.hist_names == set(HIST_NAMES)
    assert reg.dump_reasons == set(DUMP_REASONS)


# ---------------------------------------------------------------------------
# whole-program analysis: cross-module resolution (the xmod package)
# ---------------------------------------------------------------------------

def _stage_xmod(tmp_path):
    dest = tmp_path / "xmod"
    shutil.copytree(os.path.join(FIXTURES, "xmod"), dest)
    return dest


def test_cross_module_taint_lands_in_the_helper_module(tmp_path):
    dest = _stage_xmod(tmp_path)
    out = check_paths([str(dest)], get_rules(["loop-thread-taint"]),
                      root=str(tmp_path))
    # the thread entry is entry.py; the affine call (and the finding)
    # is two modules away in helper.py, at the ensure_future line
    assert len(out) == 1, [(f.path, f.line, f.message) for f in out]
    f = out[0]
    assert f.path == "xmod/helper.py"
    src = open(os.path.join(FIXTURES, "xmod", "helper.py")).read()
    want = src[:src.index("asyncio.ensure_future")].count("\n") + 1
    assert f.line == want
    assert "notify" in f.message
    # the thread-entry chain rides the structured chain field now
    assert "relay" in f.chain and f.chain[-1] == "notify"


def test_cross_module_unawaited_coroutine(tmp_path):
    dest = _stage_xmod(tmp_path)
    out = check_paths([str(dest)], get_rules(["unawaited-coroutine"]),
                      root=str(tmp_path))
    assert len(out) == 1, [(f.path, f.line, f.message) for f in out]
    assert out[0].path == "xmod/entry.py"
    assert "flush" in out[0].message


def test_cross_module_shard_affinity_write(tmp_path):
    dest = _stage_xmod(tmp_path)
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1, [(f.path, f.line, f.message) for f in out]
    f = out[0]
    assert f.path == "xmod/entry.py" and f.context == "shard_worker"
    assert "main-loop-only" in f.message


def test_generated_seeds_cover_real_shard_local_handlers():
    """The real tree's Channel._handle_puback/... seeds come from the
    _SHARD_LOCAL x handle_in join, not from a hand-kept list: every
    packet type shards handle locally has its dispatch handler seeded
    (shard, locked), and the main-only handlers (SUBSCRIBE, ...) do
    not."""
    import ast as _ast

    from emqx_tpu.devtools.staticcheck.graph import Project
    from emqx_tpu.devtools.staticcheck.symbols import extract_module

    summaries = []
    for rel in ("emqx_tpu/transport/shards.py",
                "emqx_tpu/broker/channel.py"):
        with open(os.path.join(REPO, rel)) as f:
            src = f.read()
        summaries.append(extract_module(rel, _ast.parse(src), src))
    shards, channel = summaries
    assert "PUBACK" in shards.shard_local
    assert channel.classes["Channel"].dispatch["PUBACK"] == \
        "_handle_puback"
    aff = Project(summaries).affinity()
    for m in ("_handle_puback", "_handle_pubrec", "_handle_pubrel",
              "_handle_pubcomp"):
        fqid = f"emqx_tpu.broker.channel:Channel.{m}"
        assert fqid in aff.generated_seeds, (m, aff.generated_seeds)
        assert ("shard", True) in aff.contexts(fqid)
    # a main-only dispatch target must NOT be seeded by generation
    assert "emqx_tpu.broker.channel:Channel._handle_subscribe" \
        not in aff.generated_seeds


# ---------------------------------------------------------------------------
# context sensitivity: the twoplane package (k=1 paths)
# ---------------------------------------------------------------------------

def _stage_twoplane(tmp_path, drop=None):
    dest = tmp_path / "twoplane"
    shutil.copytree(os.path.join(FIXTURES, "twoplane"), dest)
    if drop:
        (dest / drop).unlink()
    return dest


def test_twoplane_flags_only_the_shard_path(tmp_path):
    """The SAME helper is called locked-from-main and unlocked-from-
    shard: exactly one finding, on the shard path, chain naming the
    shard entry — the context-insensitive lattice had to over-flag or
    over-absorb here."""
    dest = _stage_twoplane(tmp_path)
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1, [(f.path, f.line, f.message) for f in out]
    f = out[0]
    assert f.path == "twoplane/helper.py" and f.context == "bump"
    assert f.chain[0] == "ShardChannel.handle_ack_run"
    assert "ShardPool._main_handle" not in f.chain


def test_twoplane_locked_main_path_alone_is_clean(tmp_path):
    # with the shard caller gone, the only path is locked-from-main:
    # zero findings
    dest = _stage_twoplane(tmp_path, drop="shardline.py")
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert out == [], [(f.path, f.line, f.message) for f in out]


def test_per_context_allow_fact_scopes_to_the_path(tmp_path, monkeypatch):
    """An AFFINITY_ALLOWED_SITES entry scoped (plane, entry) exempts
    only that path: scoping it to the main entry keeps the shard
    finding; scoping it to the shard entry clears the tree."""
    from emqx_tpu.devtools.staticcheck import project as facts

    dest = _stage_twoplane(tmp_path)
    site = ("twoplane/helper.py", "bump")
    # scoped to the benign main path: the shard finding survives
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: ("main path holds the mutex by construction", "main",
               "ShardPool._main_handle"),
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1 and out[0].chain[0] == \
        "ShardChannel.handle_ack_run"
    # scoped to the offending shard path: tree goes clean
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: ("hypothetical: shard entry serializes via its own loop",
               "shard", "ShardChannel.handle_ack_run"),
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert out == []
    # the old over-broad string form still exempts every path
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: "over-broad: every path exempt",
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert out == []


# ---------------------------------------------------------------------------
# context sensitivity: the twohop package (k=2 caller chains)
# ---------------------------------------------------------------------------

def _stage_twohop(tmp_path):
    dest = tmp_path / "twohop"
    shutil.copytree(os.path.join(FIXTURES, "twohop"), dest)
    return dest


def test_twohop_keeps_grandparent_entries_distinct(tmp_path):
    """TWO shard entries reach the same offending helper through ONE
    shared mid-function: k=1 collapses both at the mid hop; the k=2
    chain keeps the grandparent entry, so the lattice records two
    distinct contexts and each traces to its own entry."""
    from emqx_tpu.devtools.staticcheck import analyze

    dest = _stage_twohop(tmp_path)
    res = analyze([str(dest)], get_rules(["shard-affinity"]),
                  root=str(tmp_path))
    aff = res.project.affinity()
    fqid = "twohop.helper:bump"
    paths = aff.paths(fqid)
    assert ("shard", False,
            ("twohop.mid:relay",
             "twohop.entries:ShardChannel.handle_ack_run")) in paths
    assert ("shard", False,
            ("twohop.mid:relay",
             "twohop.entries:ShardChannel.check_keepalive")) in paths
    traces = sorted(tuple(aff.trace_ctx(fqid, c)) for c in paths)
    assert traces == [
        ("ShardChannel.check_keepalive", "relay", "bump"),
        ("ShardChannel.handle_ack_run", "relay", "bump"),
    ]


def test_twohop_scoped_exemption_needs_k2(tmp_path, monkeypatch):
    """A (plane, entry) exemption scoped to ONE of the two entries
    must leave the OTHER entry's finding standing — impossible under
    k=1, where both paths share the mid-hop context."""
    from emqx_tpu.devtools.staticcheck import project as facts

    dest = _stage_twohop(tmp_path)
    site = ("twohop/helper.py", "bump")
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: ("hypothetical: the ack-run entry serializes its own "
               "loop", "shard", "ShardChannel.handle_ack_run"),
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1, [(f.path, f.line, f.chain) for f in out]
    assert out[0].chain[0] == "ShardChannel.check_keepalive"
    # exempting the other entry flips which finding survives
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: ("hypothetical", "shard", "ShardChannel.check_keepalive"),
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1
    assert out[0].chain[0] == "ShardChannel.handle_ack_run"
    # the bare (every-path) form still clears the tree
    monkeypatch.setattr(facts, "AFFINITY_ALLOWED_SITES", {
        site: "over-broad: every path exempt",
    })
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert out == []


def test_torn_read_locked_entry_path_is_clean(tmp_path, monkeypatch):
    """A (shard, locked) entry covers every read in the function: only
    the unlocked path makes the group reads a finding."""
    from emqx_tpu.devtools.staticcheck import project as facts

    dest_dir = tmp_path / "emqx_tpu" / "broker"
    dest_dir.mkdir(parents=True)
    dest = dest_dir / "lockedreader.py"
    # _handle_publish is seeded (shard, locked=True): reads need no
    # site-level lock
    dest.write_text(
        "class Session:\n"
        "    def __init__(self):\n"
        "        self.inflight = {}\n"
        "        self.mqueue = []\n\n\n"
        "class ShardChannel:\n"
        "    def _handle_publish(self, sess):\n"
        "        return len(sess.inflight) + len(sess.mqueue)\n"
    )
    out = check_paths([str(dest)], get_rules(["torn-read"]),
                      root=str(tmp_path))
    assert out == [], [(f.line, f.message) for f in out]


def test_finding_chain_rides_json_and_text_reports(tmp_path):
    from emqx_tpu.devtools.staticcheck.report import (
        format_json, format_text)

    dest = _stage_twoplane(tmp_path)
    out = check_paths([str(dest)], get_rules(["shard-affinity"]),
                      root=str(tmp_path))
    assert len(out) == 1
    blob = json.loads(format_json(out))
    assert blob["findings"][0]["chain"] == [
        "ShardChannel.handle_ack_run", "bump"]
    text = format_text(out)
    assert "path: ShardChannel.handle_ack_run -> bump" in text


def test_lock_order_allowed_fact_suppresses_cycle(tmp_path, monkeypatch):
    from emqx_tpu.devtools.staticcheck import project as facts

    monkeypatch.setattr(facts, "LOCK_ORDER_ALLOWED", {
        ("Pair.a_lock", "Pair.b_lock"):
            "fixture locks never contend (test)",
    })
    out = check_fixture("trip_lockorder.py", ["lock-order"], tmp_path)
    assert out == []


def test_lock_order_witnesses_name_both_edges(tmp_path):
    out = check_fixture("trip_lockorder.py", ["lock-order"], tmp_path)
    assert len(out) == 1
    chain = " | ".join(out[0].chain)
    assert ("Pair.a_lock->Pair.b_lock" in chain
            and "Pair.b_lock->Pair.a_lock" in chain)
    assert "Pair._grab_a" in chain  # the cross-call edge is named


def test_real_tree_lock_graph_has_no_cycle_and_known_edge():
    """The real tree's lock graph: the shard fast path takes the
    handoff lock under the channel mutex (ShardChannel.mutex →
    Handoff._lock, object-qualified) and nothing acquires them in the
    opposite order."""
    from emqx_tpu.devtools.staticcheck import analyze

    res = analyze([PKG], get_rules([]), root=REPO)
    lo = res.project.lock_order()
    assert ("ShardChannel.mutex", "Handoff._lock") in lo.edges
    assert lo.cycles() == []


def test_affinity_paths_expose_k2_callers():
    """The real tree's lattice keeps per-caller-chain paths: Channel
    ack handlers generated-seeded (shard, locked) AND reachable from
    main-plane consumers stay separable, and non-seed contexts carry
    up to two call-site hops (nearest first)."""
    from emqx_tpu.devtools.staticcheck import analyze

    res = analyze([PKG], get_rules([]), root=REPO)
    aff = res.project.affinity()
    fqid = "emqx_tpu.broker.channel:Channel._handle_puback"
    paths = aff.paths(fqid)
    assert ("shard", True, ()) in paths  # the generated seed
    # every recorded path resolves to an exact, non-guessed chain,
    # and every context chain is a ≤2-hop tuple of fqids (or the
    # merged-hub star)
    for ctx in paths:
        chain = aff.trace_ctx(fqid, ctx)
        assert chain[-1] == "Channel._handle_puback"
        assert isinstance(ctx[2], tuple) and len(ctx[2]) <= 2
        for hop in ctx[2]:
            assert hop == "*" or ":" in hop, ctx


def test_affinity_keys_survive_line_drift(tmp_path):
    a = check_fixture("trip_affinity.py", ["shard-affinity"], tmp_path)
    src = open(os.path.join(FIXTURES, "trip_affinity.py")).read()
    shifted = tmp_path / "emqx_tpu" / "broker" / "trip_affinity.py"
    shifted.write_text("# shim\n# shim\n" + src)
    b = check_paths([str(shifted)], get_rules(["shard-affinity"]),
                    root=str(tmp_path))
    assert [f.key for f in a] == [f.key for f in b]
    assert [f.line for f in a] != [f.line for f in b]


def test_delivery_path_scope_covers_post_pr4_modules():
    from emqx_tpu.devtools.staticcheck import project

    for mod in project.DELIVERY_PATH_REQUIRED_MODULES:
        assert mod.startswith(project.DELIVERY_PATH_PREFIXES), mod
        assert os.path.exists(os.path.join(REPO, mod)), mod


def test_drift_checks_metric_reads_like_the_bench_drivers(tmp_path):
    # bench.py / scripts/bench_e2e.py read metrics by literal name
    # (metrics.get); a drifted name must trip like a write would
    dest_dir = tmp_path / "emqx_tpu" / "broker"
    dest_dir.mkdir(parents=True)
    dest = dest_dir / "snap.py"
    dest.write_text(
        "def snap(metrics):\n"
        "    ok = metrics.get(\"broker.supervisor.restarts\")\n"
        "    bad = metrics.get(\"broker.not_a_real_metric\")\n"
        "    return ok, bad\n"
    )
    out = check_paths([str(dest)], get_rules(["registry-drift"]),
                      root=str(tmp_path))
    assert len(out) == 1 and out[0].line == 3


def _stage_deadseam(tmp_path, pkg):
    dest = tmp_path / pkg
    shutil.copytree(os.path.join(FIXTURES, pkg), dest)
    return dest


def test_dead_seam_declared_but_ungated_point_trips(tmp_path):
    """A point the package's faultinject module declares with NO
    literal act/check gate anywhere in the scanned tree is a
    registered-but-never-fired chaos point: one drift finding at the
    declaration."""
    dest = _stage_deadseam(tmp_path, "deadseam_trip")
    out = check_paths([str(dest)], get_rules(["registry-drift"]),
                      root=str(tmp_path))
    assert len(out) == 1, [(f.path, f.line, f.message) for f in out]
    f = out[0]
    assert f.path == "deadseam_trip/faultinject.py"
    assert "mesh.rebuild" in f.message and "ever gates" in f.message


def test_dead_seam_fully_gated_package_is_clean(tmp_path):
    # both declared points gated (one .act, one .check): no findings —
    # and trees that declare no points at all stay silent (every other
    # fixture run in this file would trip otherwise)
    dest = _stage_deadseam(tmp_path, "deadseam_ok")
    out = check_paths([str(dest)], get_rules(["registry-drift"]),
                      root=str(tmp_path))
    assert out == [], [(f.path, f.line, f.message) for f in out]


def test_real_tree_has_no_dead_fault_seams():
    """Every point emqx_tpu/faultinject.py declares has ≥1 literal
    gate in the scan set (pass-1 facts, not a grep): the chaos
    surface cannot silently grow points nothing fires."""
    from emqx_tpu import faultinject
    from emqx_tpu.devtools.staticcheck import analyze

    res = analyze(SCAN_PATHS, get_rules([]), root=REPO)
    declared, used = set(), set()
    for s in res.project.modules.values():
        declared.update(p for p, _ in s.fault_points)
        used.update(s.fault_uses)
    assert declared == set(faultinject.POINTS)
    assert declared <= used, declared - used


def test_cli_default_scan_set_includes_bench_drivers():
    import importlib.util

    spec = importlib.util.spec_from_file_location("sc_cli", CLI)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "bench.py" in mod.DEFAULT_SCAN_PATHS
    assert "scripts/bench_e2e.py" in mod.DEFAULT_SCAN_PATHS


# ---------------------------------------------------------------------------
# the analysis cache: warm reuse, dep-edit invalidation, --changed
# ---------------------------------------------------------------------------

def _mini_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text("async def go():\n    pass\n")
    (pkg / "b.py").write_text(
        "from .a import go\n\n\ndef run():\n    go()\n")
    return pkg


def _mini_analyze(tmp_path, pkg):
    from emqx_tpu.devtools.staticcheck import analyze
    from emqx_tpu.devtools.staticcheck.cache import (
        AnalysisCache, environment_digest)

    env = environment_digest(["unawaited-coroutine"])
    cache = AnalysisCache(str(tmp_path / "cc"), env)
    return analyze([str(pkg)], get_rules(["unawaited-coroutine"]),
                   root=str(tmp_path), cache=cache)


def test_cache_warm_run_reuses_everything(tmp_path):
    pkg = _mini_pkg(tmp_path)
    r1 = _mini_analyze(tmp_path, pkg)
    assert len(r1.findings) == 1 and r1.files_walked == 3
    r2 = _mini_analyze(tmp_path, pkg)
    assert [f.key for f in r2.findings] == [f.key for f in r1.findings]
    assert r2.files_walked == 0 and r2.files_cached == 3


def test_cache_invalidates_on_dependency_edit(tmp_path):
    pkg = _mini_pkg(tmp_path)
    assert len(_mini_analyze(tmp_path, pkg).findings) == 1
    # a.go becomes sync: b.py is byte-identical but its finding must
    # disappear — the transitive deps digest invalidates it
    (pkg / "a.py").write_text("def go():\n    pass\n")
    r = _mini_analyze(tmp_path, pkg)
    assert r.findings == []
    assert r.files_walked >= 2  # a.py (changed) AND b.py (dependent)


def test_cache_invalidates_on_content_edit(tmp_path):
    pkg = _mini_pkg(tmp_path)
    assert len(_mini_analyze(tmp_path, pkg).findings) == 1
    (pkg / "b.py").write_text(
        "from .a import go\n\n\nasync def run():\n    await go()\n")
    assert _mini_analyze(tmp_path, pkg).findings == []


def test_cli_no_cache_flag_skips_the_cache(tmp_path):
    pkg = _mini_pkg(tmp_path)
    cache_dir = tmp_path / "cachedir"
    r = _cli("--root", str(tmp_path), "--cache-dir", str(cache_dir),
             "--no-cache", str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert not cache_dir.exists()
    r = _cli("--root", str(tmp_path), "--cache-dir", str(cache_dir),
             str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert (cache_dir / "cache.json").exists()


def _git(tmp_path, *args):
    return subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
         "-c", "user.name=t", *args],
        capture_output=True, text=True, timeout=30)


def test_cli_changed_mode_rechecks_reverse_dependents(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text("def go():\n    pass\n")
    (pkg / "b.py").write_text(
        "from .a import go\n\n\ndef run():\n    go()\n")
    assert _git(tmp_path, "init", "-q").returncode == 0
    assert _git(tmp_path, "add", "-A").returncode == 0
    assert _git(tmp_path, "commit", "-qm", "seed").returncode == 0
    # clean at HEAD: --changed with nothing changed is a no-op pass
    r = _cli("--root", str(tmp_path), "--no-cache", "--changed",
             str(pkg))
    assert r.returncode == 0, r.stdout + r.stderr
    # flip a.go to async: b.py (UNCHANGED per git) now discards a
    # coroutine — --changed must re-check it as a reverse dependent
    (pkg / "a.py").write_text("async def go():\n    pass\n")
    r = _cli("--root", str(tmp_path), "--no-cache", "--changed",
             str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "b.py" in r.stdout and "unawaited-coroutine" in r.stdout


def test_cli_changed_mode_facts_edit_rechecks_everything(tmp_path):
    """Editing the ownership-facts module (project.py INVARIANT_GROUPS
    et al.) re-surfaces per-context findings in files git considers
    UNCHANGED: --changed widens to the full tree because nothing
    imports the checker."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    shutil.copy(os.path.join(FIXTURES, "trip_tornread.py"),
                pkg / "reader.py")
    facts_dir = tmp_path / "emqx_tpu" / "devtools" / "staticcheck"
    facts_dir.mkdir(parents=True)
    facts_file = facts_dir / "project.py"
    facts_file.write_text("# stand-in for the facts module\n")
    assert _git(tmp_path, "init", "-q").returncode == 0
    assert _git(tmp_path, "add", "-A").returncode == 0
    assert _git(tmp_path, "commit", "-qm", "seed").returncode == 0
    # nothing changed: --changed is a no-op pass (findings and all)
    r = _cli("--root", str(tmp_path), "--no-cache", "--changed",
             "--rule", "torn-read", str(pkg))
    assert r.returncode == 0, r.stdout + r.stderr
    # a facts edit: reader.py is unchanged per git, its per-context
    # findings must re-surface anyway
    facts_file.write_text(
        "# stand-in for the facts module\n# INVARIANT_GROUPS edited\n")
    r = _cli("--root", str(tmp_path), "--no-cache", "--changed",
             "--rule", "torn-read", str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "reader.py" in r.stdout and "torn-read" in r.stdout


def test_changed_targets_helper_widens_on_facts_edit():
    import importlib.util

    spec = importlib.util.spec_from_file_location("sc_cli2", CLI)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from emqx_tpu.devtools.staticcheck import analyze

    res = analyze([PKG], get_rules([]), root=REPO)
    # a facts/rules edit → None (full re-check)
    assert mod.changed_targets(
        res.project,
        {"emqx_tpu/devtools/staticcheck/project.py"}) is None
    # an ordinary edit → the file + reverse dependents only
    targets = mod.changed_targets(
        res.project, {"emqx_tpu/broker/inflight.py"})
    assert "emqx_tpu/broker/inflight.py" in targets
    assert "emqx_tpu/broker/session.py" in targets  # imports inflight
    assert "emqx_tpu/topic.py" not in targets


def test_cache_version_bump_invalidates_prior_payloads(tmp_path):
    """v4 payloads (no device-plane sites, k=1 contexts) must never
    be read back into the v5 analysis: a version-stamp mismatch
    forces a full re-walk instead of deserializing stale summaries."""
    from emqx_tpu.devtools.staticcheck.cache import CACHE_VERSION

    # the ISSUE-19 bump: ModuleSummary grew await/donate/device-sync
    # sites and fault-point decl/use facts; contexts went k=2
    assert CACHE_VERSION == 5
    pkg = _mini_pkg(tmp_path)
    r1 = _mini_analyze(tmp_path, pkg)
    assert r1.files_walked == 3
    cache_file = tmp_path / "cc" / "cache.json"
    data = json.loads(cache_file.read_text())
    data["version"] = CACHE_VERSION - 1
    cache_file.write_text(json.dumps(data))
    r2 = _mini_analyze(tmp_path, pkg)
    assert r2.files_walked == 3 and r2.files_cached == 0
    assert [f.key for f in r2.findings] == [f.key for f in r1.findings]


def _jobs_pkg(tmp_path, n=6):
    """≥ _POOL_MIN_FILES modules, each with one unawaited-coroutine
    finding, so the pooled pass-1 has real work and a deterministic
    finding set to compare against serial."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for i in range(n):
        (pkg / f"m{i}.py").write_text(
            f"async def go{i}():\n    pass\n\n\n"
            f"def run{i}():\n    go{i}()\n")
    return pkg


def test_analyze_jobs_pool_matches_serial_and_caches(tmp_path):
    """jobs>1 routes the cold pass-1 parse through a process pool:
    identical findings to serial, and the pooled run still stores
    every summary (the next run is fully warm)."""
    from emqx_tpu.devtools.staticcheck import analyze
    from emqx_tpu.devtools.staticcheck.cache import (
        AnalysisCache, environment_digest)

    pkg = _jobs_pkg(tmp_path)
    env = environment_digest(["unawaited-coroutine"])
    rules = get_rules(["unawaited-coroutine"])
    cold = analyze([str(pkg)], rules, root=str(tmp_path),
                   cache=AnalysisCache(str(tmp_path / "cc"), env),
                   jobs=4)
    assert len(cold.findings) == 6 and cold.files_walked == 7
    serial = analyze([str(pkg)], rules, root=str(tmp_path), jobs=1)
    assert [f.key for f in cold.findings] == \
        [f.key for f in serial.findings]
    warm = analyze([str(pkg)], rules, root=str(tmp_path),
                   cache=AnalysisCache(str(tmp_path / "cc"), env),
                   jobs=4)
    assert warm.files_walked == 0 and warm.files_cached == 7
    assert [f.key for f in warm.findings] == \
        [f.key for f in cold.findings]


def test_cli_jobs_flag_output_matches_serial(tmp_path):
    pkg = _jobs_pkg(tmp_path)
    r_serial = _cli("--root", str(tmp_path), "--no-cache",
                    "--jobs", "1", str(pkg))
    r_par = _cli("--root", str(tmp_path), "--no-cache",
                 "--jobs", "4", str(pkg))
    assert r_serial.returncode == 1, r_serial.stdout + r_serial.stderr
    assert r_par.returncode == 1, r_par.stdout + r_par.stderr
    assert r_par.stdout == r_serial.stdout


def test_cache_findings_roundtrip_context_chain(tmp_path):
    """Cached per-file findings keep the chain field across the
    save/load cycle (v3 cache payload)."""
    from emqx_tpu.devtools.staticcheck.cache import (
        _finding_from_dict, _finding_to_dict)
    from emqx_tpu.devtools.staticcheck.core import Finding

    f = Finding(rule="torn-read", path="p.py", line=3, col=1,
                message="m", context="C.f",
                chain=("ShardChannel.handle_ack_run", "C.f"))
    assert _finding_from_dict(_finding_to_dict(f)) == f


def test_new_rules_are_in_the_tier1_battery():
    names = {r.name for r in ALL_RULES}
    assert {"shard-affinity", "torn-read", "lock-order",
            "use-after-donate", "host-sync-in-loop",
            "await-torn-read"} <= names
    assert len(ALL_RULES) == 13


@pytest.mark.slow
def test_full_tree_scan_cold_and_warm_budgets(tmp_path):
    # all 13 rules active (the battery assert keeps this honest): the
    # cold bound moved 3.0 → 4.0 s for the three device-plane rules +
    # the k=2 lattice; warm stays ≤1 s — the dev-loop contract
    assert len(ALL_RULES) == 13
    cache_dir = tmp_path / "cc"
    t0 = time.monotonic()
    r = _cli("--cache-dir", str(cache_dir))
    cold = time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    t0 = time.monotonic()
    r = _cli("--cache-dir", str(cache_dir))
    warm = time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert cold <= 4.0, f"cold full-tree scan took {cold:.2f}s"
    assert warm <= 1.0, f"warm full-tree scan took {warm:.2f}s"


# ---------------------------------------------------------------------------
# CLI: exit codes + seeded-violation catch
# ---------------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, CLI, *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_cli_catches_seeded_fanout_violation(tmp_path):
    src = open(os.path.join(PKG, "broker", "fanout.py")).read()
    seeded = (
        src
        + "\n\nasync def _seeded_violation():\n"
          "    time.sleep(0.001)\n"
    )
    dest_dir = tmp_path / "emqx_tpu" / "broker"
    dest_dir.mkdir(parents=True)
    dest = dest_dir / "fanout.py"
    dest.write_text(seeded)
    seed_line = seeded[:seeded.index("    time.sleep")].count("\n") + 1
    r = _cli(str(dest))
    assert r.returncode == 1, r.stdout + r.stderr
    assert f"fanout.py:{seed_line}:" in r.stdout
    assert "no-blocking-in-async" in r.stdout


def test_cli_clean_file_exits_zero(tmp_path):
    r = _cli(os.path.join(FIXTURES, "ok_blocking.py"))
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_unknown_rule_exits_two():
    r = _cli("--rule", "no-such-rule")
    assert r.returncode == 2


def test_cli_baseline_write_then_clean(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n\n\nasync def f():\n    time.sleep(1)\n")
    wpath = tmp_path / "waivers.json"
    r = _cli(str(bad), "--waivers", str(wpath), "--baseline", "write")
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.load(open(wpath))["waivers"]
    r = _cli(str(bad), "--waivers", str(wpath))
    assert r.returncode == 0, r.stdout + r.stderr  # all waived now


@pytest.mark.slow
def test_cli_full_tree_under_ten_seconds():
    t0 = time.monotonic()
    r = _cli()
    dt = time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert dt < 10.0, f"staticcheck took {dt:.1f}s over the tree"


# ---------------------------------------------------------------------------
# satellite: event-loop lag probe → Olp.report
# ---------------------------------------------------------------------------

def test_lag_probe_trips_overload_without_queue_growth():
    from emqx_tpu.broker.olp import LoopLagProbe, Olp
    from emqx_tpu.observe.alarm import Alarms
    from emqx_tpu.observe.metrics import Metrics

    alarms = Alarms()
    olp = Olp(alarms=alarms, max_loop_lag=0.05, cooloff=10.0)
    m = Metrics()
    probe = LoopLagProbe(olp, metrics=m, interval=0.01, alpha=1.0)
    assert not olp.overloaded()
    probe.observe(0.2)  # 200 ms drift >> 50 ms budget, queue depth 0
    assert olp.overloaded()
    assert alarms.is_active("overload")
    assert m.get("broker.olp.loop_lag_us") == 200_000


def test_lag_probe_ewma_smooths_one_off_spikes():
    from emqx_tpu.broker.olp import LoopLagProbe, Olp

    olp = Olp(max_loop_lag=0.5, cooloff=10.0)
    probe = LoopLagProbe(olp, interval=0.01, alpha=0.3)
    probe.observe(0.0)
    probe.observe(1.0)  # single spike: EWMA stays under the 0.5 budget
    assert probe.lag == pytest.approx(0.3)
    assert not olp.overloaded()
    for _ in range(10):  # sustained saturation does trip it
        probe.observe(1.0)
    assert olp.overloaded()


def test_lag_probe_run_measures_sleep_drift():
    from emqx_tpu.broker.olp import LoopLagProbe, Olp

    ticks = iter([0.0, 0.05, 0.05, 0.10])  # two samples of 40ms drift

    async def fake_sleep(_):
        try:
            return None
        finally:
            fake_sleep.calls += 1
            if fake_sleep.calls >= 2:
                raise asyncio.CancelledError

    fake_sleep.calls = 0
    probe = LoopLagProbe(
        Olp(max_loop_lag=10.0), interval=0.01,
        clock=lambda: next(ticks), sleep=fake_sleep, alpha=1.0,
    )

    async def go():
        with pytest.raises(asyncio.CancelledError):
            await probe.run()

    run(go())
    assert probe.samples == 1  # second sleep cancelled before sampling
    assert probe.last_raw == pytest.approx(0.04)


# ---------------------------------------------------------------------------
# satellite: QUIC endpoint timer + kafka poll as supervised children
# ---------------------------------------------------------------------------

def test_quic_timer_registers_as_transient_child_and_reaps():
    pytest.importorskip(
        "cryptography", reason="quic stack needs cryptography")
    from emqx_tpu.supervise import Supervisor
    from emqx_tpu.transport.quic import QuicEndpoint

    async def go():
        sup = Supervisor()
        ep = QuicEndpoint(None, b"", b"", None, supervisor=sup)
        ep._ensure_timer()
        child = sup.lookup("quic.timer")
        assert child is not None and child.restart == "transient"
        # by_cid is empty: the loop returns normally, supervision ends
        for _ in range(50):
            if child.done():
                break
            await asyncio.sleep(0.01)
        assert child.done() and child.state == "done"
        # next activity cycle: a fresh child replaces (not accretes)
        ep._timer_task = None
        ep._ensure_timer()
        assert sum(1 for c in sup.children if c.name == "quic.timer") == 1
        await sup.stop()

    run(go())


def test_kafka_poll_registers_as_transient_child():
    from emqx_tpu.bridge.kafka import KafkaConnector, KafkaError
    from emqx_tpu.supervise import Supervisor

    async def go():
        sup = Supervisor()
        conn = KafkaConnector(
            {"server": "127.0.0.1:1", "ingress": {"topic": "t"}},
            name="k", local_publish=lambda *a, **kw: None)
        conn.supervisor = sup

        async def no_meta(topic):
            raise KafkaError("no metadata")

        conn.client.partitions = no_meta  # ingress-only start path
        await conn.start()
        child = sup.lookup("bridge.kafka.k.poll")
        assert child is not None and child.restart == "transient"
        assert conn._poll_task is child
        await conn.stop()
        assert child.done()

    run(go())
