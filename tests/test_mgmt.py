"""Management REST API + CLI tests — live HTTP against a full node,
the reference's emqx_mgmt_api_SUITE style (SURVEY.md §4)."""

import asyncio
import base64
import json

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(extra=""):
    cfg = Config(
        file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\n'
                                'dashboard.auth = false\n'
            'dashboard.listen = "127.0.0.1:0"\n'
            + extra
        )
    )
    node = BrokerNode(cfg)
    await node.start()
    return node


def ports(node):
    return node.listeners.all()[0].port, node.mgmt_server.port


async def api(node, method, path, body=None, auth=None, raw=False):
    """Tiny asyncio HTTP client for the tests."""
    _, mport = ports(node)
    reader, writer = await asyncio.open_connection("127.0.0.1", mport)
    data = json.dumps(body).encode() if body is not None else b""
    hdrs = [
        f"{method} {path} HTTP/1.1",
        "Host: localhost",
        f"Content-Length: {len(data)}",
        "Connection: close",
    ]
    if auth:
        hdrs.append(
            "Authorization: Basic "
            + base64.b64encode(auth.encode()).decode()
        )
    writer.write(("\r\n".join(hdrs) + "\r\n\r\n").encode() + data)
    await writer.drain()
    resp = await reader.read()
    writer.close()
    head, _, payload = resp.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    if raw:
        return status, payload
    return status, json.loads(payload) if payload else None


def test_status_nodes_stats_metrics():
    async def main():
        node = await start_node()
        try:
            st, body = await api(node, "GET", "/api/v5/status", raw=True)
            assert st == 200 and b"running" in body
            st, nodes = await api(node, "GET", "/api/v5/nodes")
            assert st == 200 and nodes[0]["node"]
            st, stats = await api(node, "GET", "/api/v5/stats")
            assert st == 200 and "connections.count" in stats
            st, metrics = await api(node, "GET", "/api/v5/metrics")
            assert st == 200 and "messages.received" in metrics
            st, text = await api(
                node, "GET", "/api/v5/prometheus/stats", raw=True
            )
            assert st == 200 and b"# TYPE emqx_" in text
        finally:
            await node.stop()

    run(main())


def test_clients_subscriptions_kick():
    async def main():
        node = await start_node()
        try:
            mport, _ = ports(node)
            c = Client(clientid="api-c1", port=mport)
            await c.connect()
            await c.subscribe("a/b", qos=1)

            st, page = await api(node, "GET", "/api/v5/clients")
            assert st == 200 and page["meta"]["count"] == 1
            assert page["data"][0]["clientid"] == "api-c1"

            st, one = await api(node, "GET", "/api/v5/clients/api-c1")
            assert st == 200 and one["connected"] is True
            assert one["subscriptions_cnt"] == 1

            st, subs = await api(
                node, "GET", "/api/v5/clients/api-c1/subscriptions"
            )
            assert st == 200 and subs[0]["topic"] == "a/b"

            st, allsubs = await api(node, "GET", "/api/v5/subscriptions")
            assert st == 200 and allsubs["meta"]["count"] == 1

            st, topics = await api(node, "GET", "/api/v5/topics")
            assert st == 200 and topics["data"][0]["topic"] == "a/b"

            st, _ = await api(node, "DELETE", "/api/v5/clients/api-c1")
            assert st == 204
            await c.wait_closed()
            st, _ = await api(node, "GET", "/api/v5/clients/api-c1")
            assert st == 404
        finally:
            await node.stop()

    run(main())


def test_publish_and_retainer_api():
    async def main():
        node = await start_node()
        try:
            mport, _ = ports(node)
            c = Client(clientid="s", port=mport)
            await c.connect()
            await c.subscribe("news/#", qos=1)

            st, out = await api(node, "POST", "/api/v5/publish", {
                "topic": "news/today", "payload": "headline", "qos": 1,
                "retain": True,
            })
            assert st == 200 and out["matched"] == 1
            msg = await c.recv()
            assert msg.payload == b"headline"

            st, page = await api(node, "GET", "/api/v5/retainer/messages")
            assert st == 200 and page["meta"]["count"] == 1

            st, one = await api(
                node, "GET", "/api/v5/retainer/message/news/today"
            )
            assert st == 200
            assert base64.b64decode(one["payload"]) == b"headline"

            st, _ = await api(
                node, "DELETE", "/api/v5/retainer/message/news/today"
            )
            assert st == 204
            st, _ = await api(
                node, "GET", "/api/v5/retainer/message/news/today"
            )
            assert st == 404

            st, outs = await api(node, "POST", "/api/v5/publish/bulk", [
                {"topic": "news/a", "payload": "1"},
                {"topic": "news/b", "payload": "2"},
            ])
            assert st == 200 and len(outs) == 2
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_banned_api_blocks_connect():
    async def main():
        node = await start_node()
        try:
            mport, _ = ports(node)
            st, _ = await api(node, "POST", "/api/v5/banned", {
                "as": "clientid", "who": "evil",
            })
            assert st == 201
            bad = Client(clientid="evil", port=mport, proto_ver=5)
            with pytest.raises(Exception):
                await bad.connect()
            st, page = await api(node, "GET", "/api/v5/banned")
            assert page["meta"]["count"] == 1
            st, _ = await api(
                node, "DELETE", "/api/v5/banned/clientid/evil"
            )
            assert st == 204
            ok = Client(clientid="evil", port=mport)
            await ok.connect()
            await ok.disconnect()
        finally:
            await node.stop()

    run(main())


def test_rules_crud_and_fire():
    async def main():
        node = await start_node()
        try:
            mport, _ = ports(node)
            st, rule = await api(node, "POST", "/api/v5/rules", {
                "id": "r1",
                "sql": 'SELECT payload FROM "ingest/#"',
                "actions": [{"function": "republish",
                             "args": {"topic": "derived/t",
                                      "payload": "${payload}"}}],
            })
            assert st == 201 and rule["id"] == "r1"

            sub = Client(clientid="s", port=mport)
            await sub.connect()
            await sub.subscribe("derived/t", qos=0)
            pub = Client(clientid="p", port=mport)
            await pub.connect()
            await pub.publish("ingest/x", b"42", qos=1)
            msg = await sub.recv()
            assert msg.payload == b"42"

            st, shown = await api(node, "GET", "/api/v5/rules/r1")
            assert shown["metrics"]["matched"] >= 1

            st, _ = await api(node, "PUT", "/api/v5/rules/r1", {
                "enable": False,
            })
            assert st == 200
            st, _ = await api(node, "DELETE", "/api/v5/rules/r1")
            assert st == 204
            st, page = await api(node, "GET", "/api/v5/rules")
            assert page["meta"]["count"] == 0
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_configs_api():
    async def main():
        node = await start_node()
        try:
            st, cfgs = await api(node, "GET", "/api/v5/configs")
            assert st == 200 and "mqtt.max_inflight" in cfgs
            st, out = await api(node, "PUT", "/api/v5/configs", {
                "mqtt.max_inflight": 7,
            })
            assert st == 200 and out["mqtt.max_inflight"] == 7
            assert node.config.get("mqtt.max_inflight") == 7
            st, _ = await api(node, "PUT", "/api/v5/configs", {
                "node.name": "nope",
            })
            assert st == 400
        finally:
            await node.stop()

    run(main())


def test_api_key_auth():
    async def main():
        node = await start_node(
            'api_key.enable = true\n'
            'api_key.key = "k1"\n'
            'api_key.secret = "s1"\n'
        )
        try:
            st, _ = await api(node, "GET", "/api/v5/stats")
            assert st == 401
            st, _ = await api(node, "GET", "/api/v5/stats", auth="k1:s1")
            assert st == 200
            st, _ = await api(node, "GET", "/api/v5/stats", auth="k1:bad")
            assert st == 401
            # status probe stays open (exempt), like the reference
            st, _ = await api(node, "GET", "/api/v5/status", raw=True)
            assert st == 200
        finally:
            await node.stop()

    run(main())


def test_encoded_clientid_routing():
    """Percent-encoded '/' in a clientid must not split the path."""

    async def main():
        node = await start_node()
        try:
            mport, _ = ports(node)
            c = Client(clientid="tenant/dev1", port=mport)
            await c.connect()
            st, one = await api(
                node, "GET", "/api/v5/clients/tenant%2Fdev1"
            )
            assert st == 200 and one["clientid"] == "tenant/dev1"
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_config_put_takes_effect_live():
    """PUT /configs must reach the live components, not just the map."""

    async def main():
        node = await start_node()
        try:
            st, _ = await api(node, "PUT", "/api/v5/configs", {
                "broker.shared_subscription_strategy": "round_robin",
                "limiter.max_conn_rate": 123.0,
            })
            assert st == 200
            assert node.broker.shared.strategy == "round_robin"
            assert node.limiter.conn.rate == 123.0
        finally:
            await node.stop()

    run(main())


def test_stop_with_idle_keepalive_connection_is_fast():
    async def main():
        node = await start_node()
        _, mport = ports(node)
        # park an idle keep-alive connection and never send a request
        reader, writer = await asyncio.open_connection("127.0.0.1", mport)
        t0 = asyncio.get_running_loop().time()
        await node.stop()
        assert asyncio.get_running_loop().time() - t0 < 2.0
        writer.close()

    run(main())


def test_rules_create_missing_sql_is_400():
    async def main():
        node = await start_node()
        try:
            st, body = await api(node, "POST", "/api/v5/rules", {"id": "x"})
            assert st == 400, body
        finally:
            await node.stop()

    run(main())


def test_cli_against_live_node():
    """Drive the ctl CLI (urllib, sync) against a live node from a
    thread so the node's loop keeps running."""

    async def main():
        node = await start_node()
        try:
            mport, aport = ports(node)
            c = Client(clientid="cli-c", port=mport)
            await c.connect()

            from emqx_tpu.mgmt.cli import main as cli_main

            def invoke(*argv):
                import contextlib
                import io

                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    rc = cli_main(
                        ["--url", f"http://127.0.0.1:{aport}", *argv]
                    )
                assert rc == 0
                return buf.getvalue()

            out = await asyncio.to_thread(invoke, "status")
            assert "running" in out
            out = await asyncio.to_thread(invoke, "clients", "list")
            assert "cli-c" in out
            out = await asyncio.to_thread(
                invoke, "publish", "-t", "cli/t", "-m", "hi"
            )
            assert "matched" in out
            out = await asyncio.to_thread(invoke, "stats")
            assert "connections.count" in out
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_cli_round3_commands(capsys):
    """ctl subcommands for the round-3 components drive the REST API."""
    async def main():
        node = await start_node()
        try:
            from emqx_tpu.mgmt.cli import main as ctl_main

            base = f"http://127.0.0.1:{node.mgmt_server.port}"
            await node.bridges.create("webhook", "w1", {
                "url": "http://127.0.0.1:1/x", "enable": False})
            node.tracing.create("t9", "clientid", "c1")

            def run_ctl(*argv):
                rc = ctl_main(["--url", base, *argv])
                out = capsys.readouterr().out
                assert rc == 0
                return out

            assert "w1" in (await asyncio.to_thread(
                run_ctl, "bridges", "list"))
            assert "stomp" not in (await asyncio.to_thread(
                run_ctl, "gateways"))  # none enabled on this node
            assert "t9" in (await asyncio.to_thread(run_ctl, "trace", "list"))
            assert "[]" in (await asyncio.to_thread(
                run_ctl, "slow_subs", "list")) or True
            out = await asyncio.to_thread(
                run_ctl, "trace", "stop", "t9")
            assert "stopped" in out
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# stage-level latency observatory (ISSUE 12): REST + CLI surfaces
# ---------------------------------------------------------------------------

def test_observability_histograms_and_flightrec_rest(tmp_path):
    async def main():
        node = await start_node('slow_subs.enable = true\n')
        try:
            node.flightrec.out_dir = str(tmp_path)  # isolate dumps
            # feed one stage histogram so the merge carries real data
            node.hists.hist("obs.stage.deliver").record(2_500_000)
            st, body = await api(node, "GET",
                                 "/api/v5/observability/histograms")
            assert st == 200 and body["enabled"] is True
            h = body["histograms"]["obs.stage.deliver"]
            assert h["count"] == 1 and h["p50_ms"] > 0
            # every registered stage is present (merged, maybe empty)
            from emqx_tpu.observe.hist import HIST_NAMES
            assert set(body["histograms"]) == set(HIST_NAMES)

            # the manual flight-recorder trigger writes a real dump
            node.flightrec.ring("fanout").push(1, 10, 5, batch=1)
            st, body = await api(node, "POST",
                                 "/api/v5/observability/flightrec")
            assert st == 200 and body["reason"] == "manual"
            import json as _json
            with open(body["path"]) as f:
                assert _json.load(f)["traceEvents"]
            st, info = await api(node, "GET",
                                 "/api/v5/observability/flightrec")
            assert st == 200 and info["dumps"] == 1
            assert node.observed.metrics.get("obs.flightrec.dumps") == 1

            # slow_subs now reports the e2e window histogram alongside
            # the ranking ("how slow is slow" next to who is slow)
            st, body = await api(node, "GET",
                                 "/api/v5/slow_subscriptions")
            assert st == 200
            assert body["data"] == []
            assert body["e2e"]["count"] == 0
        finally:
            await node.stop()

    run(main())


def test_cli_hist_and_flightrec_commands(capsys, tmp_path):
    async def main():
        node = await start_node()
        try:
            node.flightrec.out_dir = str(tmp_path)
            from emqx_tpu.mgmt.cli import main as ctl_main

            base = f"http://127.0.0.1:{node.mgmt_server.port}"

            def run_ctl(*argv):
                rc = ctl_main(["--url", base, *argv])
                out = capsys.readouterr().out
                assert rc == 0
                return out

            node.hists.hist("obs.stage.flush").record(800_000)
            out = await asyncio.to_thread(run_ctl, "hist")
            assert "obs.stage.flush" in out
            out = await asyncio.to_thread(run_ctl, "flightrec", "dump")
            assert "manual" in out
            out = await asyncio.to_thread(run_ctl, "flightrec")
            assert '"dumps": 1' in out
        finally:
            await node.stop()

    run(main())

def test_mesh_api_and_cli(capsys):
    """GET /api/v5/mesh + ``ctl mesh`` (ISSUE 18): 404 when multichip
    is off; with the degraded flag on the snapshot carries the health
    ladder (state, dead shards, rebuild/canary counters)."""

    async def main():
        node = await start_node()
        try:
            status, body = await api(node, "GET", "/api/v5/mesh")
            assert status == 404, body
        finally:
            await node.stop()

        # conftest pins EMQX_TPU__ENABLE=false in the env (which layers
        # above file config) so node starts stay cheap; opt back in via
        # the runtime layer like the chaos suite does.
        cfg = Config(
            file_text=(
                'listeners.tcp.default.bind = "127.0.0.1:0"\n'
                'dashboard.enable = true\n'
                'dashboard.auth = false\n'
                'dashboard.listen = "127.0.0.1:0"\n'
                "tpu.mirror_refresh_interval = 0.01\n"
                "match.multichip.enable = true\n"
                "match.multichip.degraded.enable = true\n"
            )
        )
        cfg.put("tpu.enable", True)
        node = BrokerNode(cfg)
        await node.start()
        try:
            ms = node.match_service
            assert ms is not None and ms.mc is not None
            deadline = asyncio.get_event_loop().time() + 60
            while not (ms.ready and ms.mc.ready) \
                    and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            status, body = await api(node, "GET", "/api/v5/mesh")
            assert status == 200
            assert body["mesh"]["tp"] >= 2
            assert body["mesh_state"] == "healthy"
            assert body["dead_shards"] == []
            assert body["alarmed"] is False and body["rebuilding"] is False
            assert "rebuilds" in body and "readmit_canary_fails" in body
            from emqx_tpu.mgmt.cli import main as ctl_main

            base = f"http://127.0.0.1:{node.mgmt_server.port}"

            def run_ctl(*argv):
                rc = ctl_main(["--url", base, *argv])
                out = capsys.readouterr().out
                assert rc == 0
                return out

            out = await asyncio.to_thread(run_ctl, "mesh")
            assert '"mesh_state": "healthy"' in out
            assert '"dead_shards"' in out
        finally:
            await node.stop()

    run(main())
