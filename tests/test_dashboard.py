"""Dashboard backend: RBAC users, login tokens, role-gated writes
(emqx_dashboard analog)."""

import asyncio
import json

import pytest

from emqx_tpu.bridge import httpc
from emqx_tpu.config import Config
from emqx_tpu.mgmt.dashboard import DashboardUsers
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def test_users_roles_and_tokens(tmp_path):
    d = DashboardUsers(str(tmp_path / "users.json"))
    # bootstrap admin with default password flag
    res = d.login("admin", "public")
    assert res is not None and res["default_password"]
    assert d.check_token(res["token"], write=True)

    assert d.change_password("admin", "public", "newpass1")
    assert d.login("admin", "public") is None
    res2 = d.login("admin", "newpass1")
    assert not res2["default_password"]

    d.add_user("bob", "readonly1", role="viewer")
    t = d.login("bob", "readonly1")["token"]
    assert d.check_token(t, write=False)
    assert not d.check_token(t, write=True)  # viewer can't mutate

    with pytest.raises(ValueError):
        d.add_user("x", "short", role="viewer")   # weak password
    with pytest.raises(ValueError):
        d.add_user("evil\r\nname", "longenough")  # bad charset
    with pytest.raises(ValueError):
        d.delete_user("admin")  # last administrator

    # persistence reload
    d2 = DashboardUsers(str(tmp_path / "users.json"))
    assert d2.login("bob", "readonly1") is not None
    assert d2.login("admin", "newpass1") is not None

    assert d.logout(t)
    assert not d.check_token(t)


def test_dashboard_rest_login_flow():
    async def main():
        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\n'
            'dashboard.listen = "127.0.0.1:0"\n'
            'api_key.enable = true\n'
            'api_key.key = "k"\napi_key.secret = "s"\n')))
        await node.start()
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            # unauthenticated: only /login and /status pass
            r = await httpc.request("GET", f"{base}/stats")
            assert r.status == 401
            r = await httpc.request("POST", f"{base}/login", body=json.dumps(
                {"username": "admin", "password": "public"}).encode())
            assert r.status == 200
            tok = json.loads(r.body)["token"]

            hdr = {"authorization": f"Bearer {tok}"}
            r = await httpc.request("GET", f"{base}/stats", headers=hdr)
            assert r.status == 200

            # admin creates a viewer; viewer token cannot mutate
            r = await httpc.request("POST", f"{base}/users", headers=hdr,
                                    body=json.dumps({
                                        "username": "eve",
                                        "password": "watch1",
                                        "role": "viewer"}).encode())
            assert r.status == 201
            r = await httpc.request("POST", f"{base}/login", body=json.dumps(
                {"username": "eve", "password": "watch1"}).encode())
            vtok = json.loads(r.body)["token"]
            vh = {"authorization": f"Bearer {vtok}"}
            r = await httpc.request("GET", f"{base}/metrics", headers=vh)
            assert r.status == 200
            r = await httpc.request("POST", f"{base}/publish", headers=vh,
                                    body=json.dumps({
                                        "topic": "a", "payload": "x"
                                    }).encode())
            assert r.status == 401  # viewer write denied

            # bad login
            r = await httpc.request("POST", f"{base}/login", body=json.dumps(
                {"username": "admin", "password": "wrong"}).encode())
            assert r.status == 401
        finally:
            await node.stop()

    run(main())


def test_dashboard_auth_enforced_by_default_and_self_service():
    """dashboard.enable alone (no api key) still gates every endpoint
    behind login; viewers can logout and rotate their own password."""
    async def main():
        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\n'
            'dashboard.listen = "127.0.0.1:0"\n')))
        await node.start()
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("GET", f"{base}/stats")
            assert r.status == 401  # no api key needed for enforcement
            r = await httpc.request("POST", f"{base}/users", body=json.dumps(
                {"username": "h4x", "password": "longenough"}).encode())
            assert r.status == 401  # user CRUD gated too

            r = await httpc.request("POST", f"{base}/login", body=json.dumps(
                {"username": "admin", "password": "public"}).encode())
            tok = json.loads(r.body)["token"]
            ah = {"authorization": f"Bearer {tok}"}
            r = await httpc.request("POST", f"{base}/users", headers=ah,
                                    body=json.dumps({
                                        "username": "v", "password": "viewpw1",
                                        "role": "viewer"}).encode())
            assert r.status == 201

            r = await httpc.request("POST", f"{base}/login", body=json.dumps(
                {"username": "v", "password": "viewpw1"}).encode())
            vtok = json.loads(r.body)["token"]
            vh = {"authorization": f"Bearer {vtok}"}
            # viewer self-service: own password change + logout allowed
            r = await httpc.request(
                "PUT", f"{base}/users/v/change_pwd", headers=vh,
                body=json.dumps({"old_pwd": "viewpw1",
                                 "new_pwd": "viewpw2"}).encode())
            assert r.status == 204
            # ...but not someone else's
            r = await httpc.request(
                "PUT", f"{base}/users/admin/change_pwd", headers=vh,
                body=json.dumps({"old_pwd": "public",
                                 "new_pwd": "hacked1"}).encode())
            assert r.status == 401
            r = await httpc.request("POST", f"{base}/logout", headers=vh,
                                    body=b"")
            assert r.status == 204
            r = await httpc.request("GET", f"{base}/stats", headers=vh)
            assert r.status == 401  # token revoked
        finally:
            await node.stop()

    run(main())


def test_dashboard_page_served_unauthenticated():
    """GET / and /dashboard return the SPA without credentials; the data
    endpoints stay behind auth."""
    async def main():
        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\n'
            'dashboard.listen = "127.0.0.1:0"\n'
            'api_key.enable = true\n'
            'api_key.key = "k"\napi_key.secret = "s"\n')))
        await node.start()
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}"
            for path in ("/", "/dashboard"):
                r = await httpc.request("GET", base + path)
                assert r.status == 200
                body = r.body.decode()
                assert r.headers.get("content-type",
                                     "").startswith("text/html")
                assert "/api/v5/login" in body
                assert "emqx_tpu" in body
            # data endpoint still requires auth
            r = await httpc.request("GET", base + "/api/v5/stats")
            assert r.status == 401
        finally:
            await node.stop()

    run(main())
