"""Per-PR e2e tracking: the ``scripts/bench_e2e.py --smoke`` A/B must
run clean on CPU and deliver every fan-out leg on BOTH paths.

Marked ``slow`` (tier-1 runs ``-m 'not slow'``): the smoke A/B is two
~2 s broker runs plus node start/stop.  The speedup itself is NOT
asserted here — a loaded CI box makes ratios noisy; the bench reports
it, the test pins correctness (delivery_ratio) and that the harness
keeps working.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_e2e_smoke_delivers_everything():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_e2e.py"),
         "--smoke", "--chaos"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout)
    for path in ("per_message", "pipeline"):
        sec = out[path]
        assert sec["sent"] > 0, (path, sec)
        assert sec["delivery_ratio"] == 1.0, (path, sec)
    assert out["speedup"] > 0
    # acknowledged-delivery A/Bs: QoS1 windowed subscribers (acks
    # flowing) and QoS2 exactly-once (PUBREC/PUBREL/PUBCOMP flowing) —
    # every fan-out leg delivered, and no DUP redelivery
    # (retry_interval far exceeds the run, so a DUP is a broker bug)
    for section in ("qos1", "qos2"):
        for path in ("per_message", "pipeline"):
            sec = out[section][path]
            assert sec["sent"] > 0, (section, path, sec)
            assert sec["delivery_ratio"] == 1.0, (section, path, sec)
            assert sec["duplicates"] == 0, (section, path, sec)
        assert out[section]["speedup"] > 0
    # connection-plane sections (PR 6): config1 real-client A/B (full
    # protocol clients over the sharded + timer-wheel flag-on node)
    # delivers everything on both sides, and every client-count sweep
    # row completes with ratio 1.0
    for path in ("per_message", "pipeline"):
        sec = out["config1"][path]
        assert sec["sent"] > 0, (path, sec)
        assert sec["delivery_ratio"] == 1.0, (path, sec)
    assert out["config1"]["shards"] >= 1
    for row in out["config1_sweep"]:
        assert row["sent"] > 0, row
        assert row["delivery_ratio"] == 1.0, row
        assert row["e2e_p99_us"] is not None, row
    # deadline serve A/B (ISSUE 7): both sides of the static-vs-deadline
    # A/B served the offered storm and the achieved batch-size histogram
    # is recorded; the p99 ratio itself is bench.py's number, not a CI
    # assertion (kernel-latency ratios are noise on a loaded box)
    sd = out["serve_deadline"]
    assert sd["deadline_ms"] > 0
    assert sd["static"]["served"] > 0, sd
    assert sd["deadline"]["served"] > 0, sd
    assert sd["deadline"]["batch_hist"], sd
    # stage-latency observatory (ISSUE 12): the serve sections report
    # per-stage p50/p99 from the PRODUCT's histograms, parity-checked
    # against the legacy np.percentile extraction over the same
    # post-warmup samples, and the deadline JSON records the split
    # dispatch/readback estimates
    for side in ("static", "deadline"):
        sec = sd[side]
        assert sec["gate_hist_parity"], (side, sec)
        assert sec["stages"]["match_dispatch"]["count"] > 0, sec
        assert sec["hist"]["count"] > 0, sec
    assert sd["deadline"]["est_dispatch_ms"] > 0, sd
    assert sd["deadline"]["est_readback_ms"] > 0, sd
    # overlapped serve pipeline A/B (ISSUE 11): both sides served the
    # offered storm at equal load; the pipelined side's two-phase
    # readback held the 4·(B + sum(counts)) byte contract on EVERY
    # batch (vs the serial 4·FLAT_MULT·B slab), throughput matched
    # serial, and p99 stayed within the host-dependent bound recorded
    # in the JSON (1.1x serial on multi-core; serial + depth pipeline
    # cycles on a 1-core host where the stages cannot overlap)
    sp = out["serve_pipeline"]
    assert sp["serial"]["served"] > 0, sp
    assert sp["pipeline"]["served"] > 0, sp
    assert sp["pipeline"]["readback_bound_ok"], sp
    assert sp["pipeline"]["readback_bytes_per_batch"] \
        < sp["serial"]["readback_bytes_per_batch"], sp
    assert sp["gate_readback_proportional"], sp
    assert sp["gate_throughput_ge_serial"], sp
    assert sp["gate_p99_no_worse"], sp
    want_bound = "1.1x_serial" if (os.cpu_count() or 1) > 1 \
        else "serial_plus_depth_cycles"
    assert sp["p99_bound"] == want_bound, sp
    assert sp["pipeline"]["readback_bytes_hist"], sp
    assert sp["pipeline"]["stage_overlap_ms_hist"], sp
    for side in ("serial", "pipeline"):
        assert sp[side]["gate_hist_parity"], (side, sp[side])
        assert sp[side]["stages"]["match_readback"]["count"] > 0, sp
    # one-round-trip serve A/B (ISSUE 17): chunked vs ragged readback
    # transfer shape at equal load — every ragged batch read back in
    # ≤ 2 d2h round trips with bit-identical rows to the chunked
    # decomposition, the padding stayed under 2x the exact prefix, and
    # the d2h-call histograms rode the JSON for the r06 hardware round
    # (loopback has no RTT, so the latency ratio is a tracking number)
    sr = out["serve_roundtrip"]
    assert sr["gate_ragged_parity"], sr
    assert sr["gate_roundtrips_le_2"], sr
    assert sr["gate_ragged_bytes_bounded"], sr
    assert sr["chunked"]["served"] > 0, sr
    assert sr["ragged"]["served"] > 0, sr
    assert sr["ragged"]["roundtrips_max"] <= 2, sr
    assert sr["ragged"]["d2h_calls_hist"], sr
    assert sr["chunked"]["d2h_calls_hist"], sr
    assert sr["roundtrip_ratio"] >= 1.0, sr
    # kernel backend A/B (ISSUE 13): the join kernel answers every
    # shape bit-for-bit like the hash kernel (matches, counts,
    # row_meta, overflow vectors), the autotuner picked a real backend
    # per shape, and the ratio gates rode the JSON (asserted only for
    # structure — kernel timing ratios on a loaded CI box are noise;
    # the ≥1.3x and auto-within-5% claims belong to bench.py's r06
    # real-hardware round)
    kj = out["kernel_join"]
    assert kj["gate_parity_all"], kj
    assert kj["rows"], kj
    for row in kj["rows"]:
        assert row["parity"], row
        assert row["hash_us"] > 0 and row["join_us"] > 0, row
        assert row["auto_us"] > 0, row
        assert row["auto_backend"] in ("hash", "join"), row
    assert "gate_join_ge_1_3x_any" in kj, kj
    # multichip serve A/B (ISSUE 15): on the virtual 8-device CPU mesh
    # the sharded table reproduces the single-chip rows bit-for-bit,
    # unflagged rows survive an artificially small per-shard match cap
    # complete (truncation psum fail-open), and a killed shard holds
    # delivery 1.0 via the host tables.  The scaling ratio is a
    # tracking number — 8 host threads share one CPU, so the ≥6x
    # claim belongs to bench.py's r06 hardware round
    mcs = out["multichip_serve"]
    assert mcs["gate_hint_parity_all"], mcs
    assert mcs["gate_truncation_failopen"], mcs
    assert mcs["gate_shard_kill_failover"], mcs
    assert mcs["devices"] == 8 and mcs["mesh"]["tp"] > 1, mcs
    assert mcs["single_topics_per_s"] > 0, mcs
    assert mcs["mesh_topics_per_s"] > 0, mcs
    assert "gate_scaling_ge_6x_at_8" in mcs, mcs
    assert mcs["measured_on"] == "cpu", mcs
    # prefix-EP routed vs replicated A/B (ISSUE 16): routed answers
    # are bit-parity with the replicated backend, a root-skewed
    # corpus overflows the bucket grid and fails open complete, the
    # per-shard processed width honors tp*C <= ceil(slack*Bl/tp),
    # and a killed shard raises before routing (delivery 1.0 via the
    # host tables).  Routed speedup is a tracking number off-hardware.
    mce = out["multichip_ep"]
    assert mce["gate_routed_parity_all"], mce
    assert mce["gate_overflow_failopen"], mce
    assert mce["gate_shard_width_le_batch_over_tp"], mce
    assert mce["gate_shard_kill_failover"], mce
    assert mce["devices"] == 8 and mce["mesh"]["tp"] > 1, mce
    assert mce["routed_shard_width"] <= mce["replicated_shard_width"], mce
    assert mce["ici_bytes_per_batch"] > 0, mce
    assert mce["overflow_rows_flagged"] > 0, mce
    assert mce["replicated_topics_per_s"] > 0, mce
    assert mce["routed_topics_per_s"] > 0, mce
    assert "gate_auto_within_5pct" in kj, kj
    assert kj["autotune_picks"], kj
    # load-adaptive plane A/B (ISSUE 20): the overflow EWMA grew the
    # bucket grid at least once with every row complete through the
    # compile window (fail-open, zero breaker strikes), one balance
    # pass cut the worst shard's row share >= 1.5x on the skewed
    # corpus, the post-remap routed rows are bit-parity with the
    # replicated backend, the override map survives a cold start, and
    # an injected ep.rebalance fault stages nothing.  The adaptive
    # speedup is a tracking number (host threads share one CPU).
    mcb = out["multichip_balance"]
    assert mcb["gate_grow_zero_drops"], mcb
    assert mcb["gate_balance_width_ge_1_5x"], mcb
    assert mcb["gate_routed_parity_all"], mcb
    assert mcb["gate_coldstart_placement_restored"], mcb
    assert mcb["gate_rebalance_fault_noop"], mcb
    assert mcb["devices"] == 8 and mcb["mesh"]["tp"] > 1, mcb
    assert mcb["ep_resizes"] >= 1, mcb
    assert mcb["moved_roots"] >= 1, mcb
    assert mcb["worst_width_ratio_x"] >= 1.5, mcb
    assert mcb["adaptive_worst_width"] < mcb["static_worst_width"], mcb
    # streaming table lifecycle A/B (ISSUE 9): segment cold start >=10x
    # the full rebuild at bench scale, arrays byte-identical after the
    # round trip, and the churn soak sustains mutations across >=1 live
    # segment swap with zero waiters stalled toward the prefetch
    # timeout (the acceptance gate booleans ride in the JSON)
    tl = out["table_lifecycle"]
    cold = tl["cold_start"]
    assert cold["arrays_identical"], cold
    assert cold["gate_cold_start_10x"], cold
    churn = tl["churn"]
    assert churn["ops"] > 0 and churn["prefetches"] > 0, churn
    assert churn["segment_swaps"] >= 1, churn
    assert churn["gate_zero_stalls"], churn
    # the host-dependent stall bound is recorded: the tight 2x-budget
    # bound on multi-core hosts (the build thread gets its own core),
    # the prefetch-timeout fallback on the 1-core bench VM
    import os as _os
    want = "2x_budget" if (_os.cpu_count() or 1) > 1 \
        else "prefetch_timeout"
    assert churn["stall_bound"] == want, churn
    # adversarial admission A/B (ISSUE 14): flag-on holds honest
    # delivery 1.0 with no honest client ever flagged while the ladder
    # limits the attackers (throttle/quarantine/ban/refused CONNECTs);
    # the p99-vs-clean ratios are recorded for the bench (latency
    # ratios on a loaded CI box are noise — the 1.5x gate boolean rides
    # the JSON with a 50 ms noise floor and is asserted as present)
    adv = out["adversarial"]
    assert adv["attack_on"]["honest"]["sent"] > 0, adv
    assert adv["gate_honest_delivery"], adv
    assert adv["gate_attackers_limited"], adv
    assert adv["gate_no_honest_flagged"], adv
    assert "gate_honest_p99" in adv and "p99_off_vs_clean" in adv, adv
    assert adv["attack_on"]["bans"] >= 1 \
        or adv["attack_on"]["decisions"], adv
    # staticcheck gate row (ISSUE 19): the cold full-tree scan ran in
    # a subprocess against a throwaway cache, came back clean (exit 0,
    # zero live waivers — staticcheck-waivers.json is empty by policy)
    # and under the bench-box cold budget, with all 13 rules active
    sc = out["staticcheck"]
    assert sc["gate_clean"], sc
    assert sc["exit_code"] == 0, sc
    assert sc["gate_budget"], sc
    assert sc["rules"] == 13, sc
    assert sc["cold_s"] > 0, sc
    assert "0 finding(s)" in sc["summary"], sc
    # chaos smoke: one kill-and-recover cycle per subsystem (including
    # the ISSUE-7 serve plane under "match"), each healing via
    # supervisor restart with delivery intact
    for name, section in out["chaos"].items():
        if section.get("skipped"):
            continue
        assert section["ok"], (name, section)
        assert section["restarts"] >= 1, (name, section)
    match = out["chaos"]["match"]
    assert match["delivery_ratio"] == 1.0, match
    assert match["breaker_tripped"] and match["breaker_recovered"], match
    # serve-pipeline chaos (ISSUE 11): readback child killed mid-storm
    # + 10% injected match.readback faults both hold delivery 1.0 with
    # waiters failing over to the CPU trie, and the two-phase readback
    # shipped real (non-slab) byte counts
    pc = out["chaos"]["pipeline"]
    assert pc["delivery_ratio"] == 1.0, pc
    assert pc["readback_faults"] >= 1, pc
    assert pc["readback_bytes"] > 0, pc
    # table-lifecycle chaos (ISSUE 9): swap fault + compact kill both
    # heal with delivery intact; a corrupt segment checksum-rejects and
    # the full rebuild serves
    seg = out["chaos"]["segments"]
    assert seg["delivery_ratio"] == 1.0, seg
    assert seg["corrupt_segment_rejected"] and seg["rebuild_served"], seg
    assert seg["swap_fault_recovered"] and seg["kill_resumed"], seg
    # admission chaos (ISSUE 14): scorer killed + held down by a
    # persistent injected fault mid-storm → FAIL-OPEN (standing
    # decisions clear, admission_degraded raised, attacker traffic
    # flows — never a new drop path), zero honest drops attributable
    # to admission, supervised restart resumes scoring and clears the
    # alarm; a 10%-fault storm holds delivery 1.0 too
    ac = out["chaos"]["admission"]
    assert ac["delivery_ratio"] == 1.0, ac
    assert ac["quarantined_then_shed"], ac
    assert ac["honest_never_flagged"], ac
    assert ac["failed_open"] and ac["no_new_drop_path"], ac
    assert ac["alarm_raised_and_cleared"], ac
    assert ac["requarantined_after_restart"], ac
    assert ac["score_faults"] >= 1 and ac["fail_opens"] >= 1, ac
    # degraded-mesh chaos (ISSUE 18): shard killed mid-storm with the
    # degraded flag on → scoped failover serves (degraded batches
    # counted), the mesh_degraded alarm + flightrec dump fire, the
    # supervised rebuild survives one injected mesh.rebuild crash
    # (the restart evidence), and the canary re-admits the shard —
    # delivery 1.0 across the whole cycle, ladder back to healthy
    mdc = out["chaos"]["mesh"]
    assert mdc["delivery_ratio"] == 1.0, mdc
    assert mdc["degraded_batches"] >= 1, mdc
    assert mdc["rebuilds"] >= 1, mdc
    assert mdc["alarm_raised_and_cleared"], mdc
    assert mdc["flightrec_dumped"], mdc
    assert mdc["mesh_state"] == 0, mdc
