"""Batched publish→deliver fanout pipeline (broker/fanout.py): delivery
parity with the per-message path, ordering, QoS downgrade, shared-sub
round-robin fidelity, bypass/overflow fallback, and the node-level
opt-in wiring over real TCP."""

import asyncio

import pytest

from emqx_tpu.broker import (
    Broker, FanoutPipeline, Publish, SubOpts, make_message,
)
from emqx_tpu.observe.metrics import Metrics


def msg(topic="t", qos=0, payload=b"x", sender="pub", **kw):
    return make_message(sender, topic, payload, qos=qos, **kw)


def run(coro):
    return asyncio.run(coro)


async def start_pipeline(broker, **kw):
    kw.setdefault("window_s", 0.0)  # tests: flush on next loop tick
    p = FanoutPipeline(broker, **kw)
    await p.start()
    broker.fanout = p
    return p


async def settle(p, timeout=2.0):
    """Wait until the pipeline queue is drained and idle."""
    deadline = asyncio.get_event_loop().time() + timeout
    while (p._q or p._busy) and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.002)
    await asyncio.sleep(0.01)


# ---------------------------------------------------------------------------
# delivery parity + grouping
# ---------------------------------------------------------------------------

def test_fanout_delivery_parity_with_publish():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("s1")
        b.open_session("s2")
        b.subscribe("s1", "sensors/+/temp", SubOpts(qos=1))
        b.subscribe("s2", "sensors/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        assert p.offer(msg(topic="sensors/kitchen/temp", qos=1))
        await settle(p)
        assert got["s1"][0].pid is not None      # QoS1 kept at 1
        assert got["s2"][0].pid is None          # capped to granted 0
        assert got["s1"][0].msg.payload == b"x"
        await p.stop()

    run(main())


def test_fanout_groups_session_deliveries_and_emits_once():
    async def main():
        b = Broker()
        emits = []
        b.on_deliver = lambda cid, pubs: emits.append((cid, list(pubs)))
        b.open_session("sub")
        b.subscribe("sub", "bench/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        for i in range(50):
            assert p.offer(msg(topic=f"bench/{i}", payload=str(i).encode()))
        await settle(p)
        total = sum(len(pubs) for _, pubs in emits)
        assert total == 50
        # bulk flush: far fewer emit calls than messages (one per batch)
        assert len(emits) < 50
        assert p.batches >= 1 and p.msgs == 50
        await p.stop()

    run(main())


def test_fanout_ordering_per_client_topic_preserved():
    async def main():
        b = Broker()
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            int(p.msg.payload) for p in pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        p = await start_pipeline(b)
        for i in range(200):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
            if i % 37 == 0:
                await asyncio.sleep(0)  # interleave with the drain loop
        await settle(p)
        assert got == list(range(200))
        await p.stop()

    run(main())


def test_fanout_zero_copy_shares_message_across_subscribers():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        for c in ("a", "b", "c"):
            b.open_session(c)
            b.subscribe(c, "t/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        m = msg(topic="t/1", qos=0)
        assert p.offer(m)
        await settle(p)
        # no per-subscription transform applies → the SAME object (and
        # payload buffer) is shared across all three fan-out legs
        assert got["a"][0].msg is got["b"][0].msg is got["c"][0].msg is m
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# semantics under batching
# ---------------------------------------------------------------------------

def test_fanout_shared_round_robin_unchanged():
    async def main():
        # per-message reference: round_robin alternates members in offer
        # order — the pipeline must produce the identical pick sequence
        b = Broker(shared_strategy="round_robin")
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            (cid, p.msg.payload) for p in pubs)
        for c in ("c1", "c2"):
            b.open_session(c)
            b.subscribe(c, "$share/g/t/#", SubOpts(qos=1))
        p = await start_pipeline(b)
        for i in range(4):
            assert p.offer(msg(topic="t/x", payload=str(i).encode()))
        await settle(p)
        assert sorted(got) == [
            ("c1", b"0"), ("c1", b"2"), ("c2", b"1"), ("c2", b"3")]
        await p.stop()

    run(main())


def test_fanout_no_local_and_veto_and_no_subscribers():
    async def main():
        b = Broker()
        got = {}
        dropped = []
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.hooks.add("message.dropped", lambda m, r: dropped.append(r))
        b.open_session("c1")
        b.subscribe("c1", "t", SubOpts(nl=True))
        p = await start_pipeline(b)
        assert p.offer(msg(topic="t", sender="c1"))    # No-Local suppressed
        assert p.offer(msg(topic="nobody/listens"))    # no subscribers
        vetoed = msg(topic="t", sender="other")
        vetoed.headers["allow_publish"] = False        # upstream veto
        assert p.offer(vetoed)
        assert p.offer(msg(topic="t", sender="other")) # the one that lands
        await settle(p)
        assert [p_.msg.sender for p_ in got.get("c1", [])] == ["other"]
        assert "no_subscribers" in dropped
        await p.stop()

    run(main())


def test_fanout_qos1_inflight_window_and_queue():
    async def main():
        b = Broker()
        sess, _ = b.open_session("sub", max_inflight=2)
        b.subscribe("sub", "t", SubOpts(qos=1))
        p = await start_pipeline(b)
        for i in range(5):
            assert p.offer(msg(topic="t", qos=1, payload=str(i).encode()))
        await settle(p)
        sends = b.take_outbox("sub")
        assert len(sends) == 2                   # window=2, rest queued
        assert len(sess.mqueue) == 3
        _, more = sess.puback(sends[0].pid)
        assert len(more) == 1                    # queue drains on ack
        await p.stop()

    run(main())


def test_fanout_invalid_topic_raises_at_offer():
    async def main():
        b = Broker()
        p = await start_pipeline(b)
        with pytest.raises(ValueError):
            p.offer(msg(topic="bad/+/wildcard-in-name"))
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# fallback paths
# ---------------------------------------------------------------------------

def test_fanout_refuses_when_not_running():
    b = Broker()
    p = FanoutPipeline(b)  # never started
    assert p.offer(msg()) is False
    # channel-level contract: refusal means the caller publishes sync
    assert b.fanout is None


def test_fanout_low_rate_bypass_refuses_only_when_idle():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = await start_pipeline(b, bypass_rate=1e9, metrics=m)
        assert p.offer(msg(topic="t")) is False  # idle + low rate → sync
        assert m.get("broker.fanout.bypass") == 1
        # with the queue non-empty the bypass must NOT engage (ordering)
        p.bypass_rate = 0.0
        assert p.offer(msg(topic="t"))
        p.bypass_rate = 1e9
        assert p.offer(msg(topic="t"))           # queued behind the first
        await settle(p)
        await p.stop()

    run(main())


def test_fanout_overflow_sheds_to_sync_path():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = FanoutPipeline(b, queue_cap=4, metrics=m)
        p._running = True  # no drain task: queue can only fill
        for _ in range(4):
            assert p.offer(msg(topic="t"))
        assert p.offer(msg(topic="t")) is False
        assert m.get("broker.fanout.overflow") == 1
        p._running = False

    run(main())


def test_fanout_stop_drains_queue_via_sync_publish():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = FanoutPipeline(b, window_s=60.0)  # batch never flushes itself
        await p.start()
        for i in range(3):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
        await p.stop()
        assert [int(x.msg.payload) for x in got["sub"]] == [0, 1, 2]

    run(main())


def test_fanout_drain_loop_survives_raising_hook():
    async def main():
        # a raising message.delivered tap must not kill the drain task:
        # the chunk falls back per message and LATER offers still deliver
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))

        def bomb(cid, m):
            if m.payload == b"boom":
                raise RuntimeError("hook exploded")

        b.hooks.add("message.delivered", bomb)
        m = Metrics()
        p = await start_pipeline(b, metrics=m)
        assert p.offer(msg(topic="t", payload=b"boom"))
        await settle(p)
        assert p.offer(msg(topic="t", payload=b"after"))
        await settle(p)
        assert not p._task.done()                # loop alive
        assert b"after" in [x.msg.payload for x in got["sub"]]
        assert m.get("broker.fanout.fallback") >= 1
        await p.stop()

    run(main())


def test_fanout_plan_failure_falls_back_without_double_fold():
    async def main():
        # route planning blows up once → the chunk re-dispatches via the
        # fold-skipping path: message.publish runs exactly once per msg
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        folds = []
        b.hooks.add("message.publish", lambda m: folds.append(m.payload))
        calls = {"n": 0}

        def flaky_device_match(topic):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("device fell over")
            return None                          # host trie serves

        b.device_match = flaky_device_match
        p = await start_pipeline(b)
        assert p.offer(msg(topic="t", payload=b"0"))
        assert p.offer(msg(topic="t", payload=b"1"))
        await settle(p)
        assert sorted(x.msg.payload for x in got["sub"]) == [b"0", b"1"]
        assert sorted(folds) == [b"0", b"1"]     # one fold per message
        await p.stop()

    run(main())


def test_fanout_batch_prefetches_topics_in_one_call():
    async def main():
        class RecordingMatchService:
            def __init__(self):
                self.calls = []

            async def prefetch_many(self, topics):
                self.calls.append(set(topics))

        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "a/#", SubOpts(qos=0))
        ms = RecordingMatchService()
        p = await start_pipeline(b, match_service=ms)
        for t in ("a/1", "a/2", "a/3"):
            assert p.offer(msg(topic=t))
        await settle(p)
        assert ms.calls                          # pipeline DID prefetch
        seen = set().union(*ms.calls)
        assert seen == {"a/1", "a/2", "a/3"}
        await p.stop()

    run(main())


def test_fanout_stop_requeues_inflight_batch():
    async def main():
        # cancellation lands at the prefetch await point with the whole
        # batch popped off the queue — stop() must still deliver it
        class StalledMatchService:
            async def prefetch_many(self, topics):
                await asyncio.Event().wait()     # never returns

        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        p = await start_pipeline(b, match_service=StalledMatchService())
        for i in range(5):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
        await asyncio.sleep(0.02)                # batch pops, then stalls
        assert p._busy and not p._q              # in flight, queue empty
        await p.stop()
        assert [int(x.msg.payload) for x in got["sub"]] == [0, 1, 2, 3, 4]

    run(main())


def test_fanout_metrics_accounting():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = await start_pipeline(b, metrics=m)
        for _ in range(10):
            p.offer(msg(topic="t"))
        await settle(p)
        assert m.get("broker.fanout.msgs") == 10
        assert m.get("broker.fanout.batches") >= 1
        assert m.get("broker.fanout.batch_size") >= 1
        assert m.get("broker.fanout.flush_us") >= 0
        await p.stop()

    run(main())


def test_fanout_adaptive_batch_bound_tracks_rate():
    b = Broker()
    p = FanoutPipeline(b, max_batch=2048, min_batch=8, adapt_window_s=0.05)
    p._last_rate = 0.0
    assert p._batch_bound() == 8           # idle → floor
    p._last_rate = 10_000.0
    assert p._batch_bound() == 500         # 50 ms of 10k/s arrivals
    p._last_rate = 1e9
    assert p._batch_bound() == 2048        # capped at the sweet spot


# ---------------------------------------------------------------------------
# node-level opt-in over real TCP (pipeline on AND off)
# ---------------------------------------------------------------------------

def _e2e_roundtrip(fanout_on: bool):
    from emqx_tpu.client import Client
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            + ('broker.fanout.enable = true\n' if fanout_on else '')
        ))
        cfg.put("tpu.enable", False)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert (node.fanout_pipeline is not None) is fanout_on
            port = node.listeners.all()[0].port
            sub = Client(clientid="sub", port=port)
            pub = Client(clientid="pub", port=port)
            await sub.connect()
            await pub.connect()
            await sub.subscribe("a/#", qos=1)
            for i in range(20):
                await pub.publish("a/b", str(i).encode(), qos=1)
            got = [await sub.recv(timeout=5) for _ in range(20)]
            assert [int(g.payload) for g in got] == list(range(20))
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_node_e2e_pipeline_off_default():
    _e2e_roundtrip(False)


def test_node_e2e_pipeline_on():
    _e2e_roundtrip(True)


# ---------------------------------------------------------------------------
# batched shared-sub picker
# ---------------------------------------------------------------------------

def test_shared_pick_batch_matches_pick_sequence():
    from emqx_tpu.broker import SharedSub

    for strategy in ("round_robin", "sticky", "random", "hash_topic",
                     "hash_clientid"):
        a = SharedSub(strategy, seed=7)
        b = SharedSub(strategy, seed=7)
        for s in (a, b):
            for cid in ("c1", "c2", "c3"):
                s.subscribe("g", "t/#", cid)
        keys = [(f"t/{i}", "sender") for i in range(10)]
        serial = [a.pick("g", "t/#", t, snd) for t, snd in keys]
        assert b.pick_batch("g", "t/#", keys) == serial
        # strategy state advanced identically: the NEXT per-message
        # pick continues the same sequence on both
        assert a.pick("g", "t/#", "t/x", "sender") == \
            b.pick("g", "t/#", "t/x", "sender")


def test_fanout_shared_sticky_unchanged():
    async def main():
        b = Broker(shared_strategy="sticky")
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            (cid, p.msg.payload) for p in pubs)
        for c in ("c1", "c2"):
            b.open_session(c)
            b.subscribe(c, "$share/g/t/#", SubOpts(qos=1))
        p = await start_pipeline(b)
        for i in range(6):
            assert p.offer(msg(topic="t/x", payload=str(i).encode()))
        await settle(p)
        # sticky: ONE member takes the whole batch, in order
        assert len({cid for cid, _ in got}) == 1
        assert [int(pl) for _, pl in got] == list(range(6))
        await p.stop()

    run(main())


def test_shared_batch_nack_redispatches_to_other_member():
    async def main():
        # round_robin picks alternate c1/c2, but c2's session is gone:
        # its picks must redispatch to c1 (ack-aware), dropping nothing
        b = Broker(shared_strategy="round_robin")
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            (cid, p.msg.payload) for p in pubs)
        for c in ("c1", "c2"):
            b.open_session(c)
            b.subscribe(c, "$share/g/t/#", SubOpts(qos=0))
        del b.sessions["c2"]          # member gone without unsubscribe
        p = await start_pipeline(b)
        for i in range(4):
            assert p.offer(msg(topic="t/x", payload=str(i).encode()))
        await settle(p)
        assert [cid for cid, _ in got] == ["c1"] * 4
        assert sorted(int(pl) for _, pl in got) == [0, 1, 2, 3]
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# shape-aware gate
# ---------------------------------------------------------------------------

def test_fanout_shape_gate_bypasses_1to1_and_releases_on_fanout():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = await start_pipeline(
            b, shape_routes=1.25, shape_probe_s=60.0, metrics=m)
        assert p.offer(msg(topic="t"))          # no estimate yet: accept
        await settle(p)
        assert p._avg_routes == 1.0             # measured 1 leg/msg
        assert p.offer(msg(topic="t"))          # first gated offer: probe
        await settle(p)
        assert p.offer(msg(topic="t")) is False  # within probe window
        assert m.get("broker.fanout.shape_bypass") == 1
        # fan-out grows: a probe batch re-measures and the gate releases
        for c in ("s2", "s3", "s4"):
            b.open_session(c)
            b.subscribe(c, "t", SubOpts())
        p._shape_probe_at = 0.0                  # due for a probe
        assert p.offer(msg(topic="t"))           # probe batch
        await settle(p)
        assert p._avg_routes > 1.25              # EWMA pulled up by 4 legs
        assert p.offer(msg(topic="t"))           # gate released
        await settle(p)
        await p.stop()

    run(main())


def test_fanout_shape_gate_disabled_by_default_in_direct_use():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = await start_pipeline(b)              # shape_routes=0 default
        for i in range(20):
            assert p.offer(msg(topic="t"))       # never shape-bypassed
            if i % 5 == 0:
                await settle(p)
        await settle(p)
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# ack/write coalescing: byte-identical packet stream, fewer writes
# ---------------------------------------------------------------------------

class _FakeTransport:
    def __init__(self):
        self.writes = []
        self.closed = False

    def write(self, data):
        self.writes.append(bytes(data))

    def close(self):
        self.closed = True

    def get_extra_info(self, key):
        return None

    def pause_reading(self):
        pass

    def resume_reading(self):
        pass


def _qos1_echo_session(coalesce: bool):
    """One client subscribes (QoS1) and publishes to itself over a
    MqttProtocol with a fake transport; returns (transport, metrics).
    Window 2 forces queueing, the PUBACK bursts drive batched refills."""
    from emqx_tpu.broker import Channel, ConnectionManager
    from emqx_tpu.mqtt import frame as F
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.transport.proto_conn import MqttProtocol

    async def main():
        b = Broker()
        cm = ConnectionManager(b)
        chan = Channel(b, cm, max_inflight=2)
        m = Metrics()
        b.metrics = m   # sessions inherit → batch_admitted counts
        conn = MqttProtocol(chan, metrics=m, coalesce=coalesce)
        b.on_deliver = lambda cid, pubs: conn.deliver(pubs)
        t = _FakeTransport()
        conn.connection_made(t)
        conn.data_received(F.serialize(P.Connect(
            proto_ver=4, clientid="c", clean_start=True, keepalive=0)))
        conn.data_received(F.serialize(P.Subscribe(
            packet_id=1, topic_filters=[("t", {"qos": 1})])))
        # 6 QoS1 publishes in ONE TCP read: echoes 2 (window), queues 4
        conn.data_received(b"".join(
            F.serialize(P.Publish(qos=1, topic="t", packet_id=10 + i,
                                  payload=b"m%d" % i))
            for i in range(6)))
        # ack the echoed publishes in bursts → window refills in batches
        for pids in ((1, 2), (3, 4), (5, 6)):
            conn.data_received(b"".join(
                F.serialize(P.PubAck(P.PUBACK, pid)) for pid in pids))
        return t, m

    return run(main())


def test_coalesced_ack_stream_byte_identical_to_unbatched():
    t_batched, m = _qos1_echo_session(coalesce=True)
    t_plain, _ = _qos1_echo_session(coalesce=False)
    # identical packet bytes on the wire...
    assert b"".join(t_batched.writes) == b"".join(t_plain.writes)
    # ...in strictly fewer transport writes, with coalesced flushes and
    # batched window admissions counted
    assert len(t_batched.writes) < len(t_plain.writes)
    assert m.get("broker.ack.coalesced_writes") >= 1
    assert m.get("broker.inflight.batch_admitted") >= 2
