"""Batched publish→deliver fanout pipeline (broker/fanout.py): delivery
parity with the per-message path, ordering, QoS downgrade, shared-sub
round-robin fidelity, bypass/overflow fallback, and the node-level
opt-in wiring over real TCP."""

import asyncio

import pytest

from emqx_tpu.broker import (
    Broker, FanoutPipeline, Publish, SubOpts, make_message,
)
from emqx_tpu.observe.metrics import Metrics


def msg(topic="t", qos=0, payload=b"x", sender="pub", **kw):
    return make_message(sender, topic, payload, qos=qos, **kw)


def run(coro):
    return asyncio.run(coro)


async def start_pipeline(broker, **kw):
    kw.setdefault("window_s", 0.0)  # tests: flush on next loop tick
    p = FanoutPipeline(broker, **kw)
    await p.start()
    broker.fanout = p
    return p


async def settle(p, timeout=2.0):
    """Wait until the pipeline queue is drained and idle."""
    deadline = asyncio.get_event_loop().time() + timeout
    while (p._q or p._busy) and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.002)
    await asyncio.sleep(0.01)


# ---------------------------------------------------------------------------
# delivery parity + grouping
# ---------------------------------------------------------------------------

def test_fanout_delivery_parity_with_publish():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("s1")
        b.open_session("s2")
        b.subscribe("s1", "sensors/+/temp", SubOpts(qos=1))
        b.subscribe("s2", "sensors/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        assert p.offer(msg(topic="sensors/kitchen/temp", qos=1))
        await settle(p)
        assert got["s1"][0].pid is not None      # QoS1 kept at 1
        assert got["s2"][0].pid is None          # capped to granted 0
        assert got["s1"][0].msg.payload == b"x"
        await p.stop()

    run(main())


def test_fanout_groups_session_deliveries_and_emits_once():
    async def main():
        b = Broker()
        emits = []
        b.on_deliver = lambda cid, pubs: emits.append((cid, list(pubs)))
        b.open_session("sub")
        b.subscribe("sub", "bench/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        for i in range(50):
            assert p.offer(msg(topic=f"bench/{i}", payload=str(i).encode()))
        await settle(p)
        total = sum(len(pubs) for _, pubs in emits)
        assert total == 50
        # bulk flush: far fewer emit calls than messages (one per batch)
        assert len(emits) < 50
        assert p.batches >= 1 and p.msgs == 50
        await p.stop()

    run(main())


def test_fanout_ordering_per_client_topic_preserved():
    async def main():
        b = Broker()
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            int(p.msg.payload) for p in pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        p = await start_pipeline(b)
        for i in range(200):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
            if i % 37 == 0:
                await asyncio.sleep(0)  # interleave with the drain loop
        await settle(p)
        assert got == list(range(200))
        await p.stop()

    run(main())


def test_fanout_zero_copy_shares_message_across_subscribers():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        for c in ("a", "b", "c"):
            b.open_session(c)
            b.subscribe(c, "t/#", SubOpts(qos=0))
        p = await start_pipeline(b)
        m = msg(topic="t/1", qos=0)
        assert p.offer(m)
        await settle(p)
        # no per-subscription transform applies → the SAME object (and
        # payload buffer) is shared across all three fan-out legs
        assert got["a"][0].msg is got["b"][0].msg is got["c"][0].msg is m
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# semantics under batching
# ---------------------------------------------------------------------------

def test_fanout_shared_round_robin_unchanged():
    async def main():
        # per-message reference: round_robin alternates members in offer
        # order — the pipeline must produce the identical pick sequence
        b = Broker(shared_strategy="round_robin")
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            (cid, p.msg.payload) for p in pubs)
        for c in ("c1", "c2"):
            b.open_session(c)
            b.subscribe(c, "$share/g/t/#", SubOpts(qos=1))
        p = await start_pipeline(b)
        for i in range(4):
            assert p.offer(msg(topic="t/x", payload=str(i).encode()))
        await settle(p)
        assert sorted(got) == [
            ("c1", b"0"), ("c1", b"2"), ("c2", b"1"), ("c2", b"3")]
        await p.stop()

    run(main())


def test_fanout_no_local_and_veto_and_no_subscribers():
    async def main():
        b = Broker()
        got = {}
        dropped = []
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.hooks.add("message.dropped", lambda m, r: dropped.append(r))
        b.open_session("c1")
        b.subscribe("c1", "t", SubOpts(nl=True))
        p = await start_pipeline(b)
        assert p.offer(msg(topic="t", sender="c1"))    # No-Local suppressed
        assert p.offer(msg(topic="nobody/listens"))    # no subscribers
        vetoed = msg(topic="t", sender="other")
        vetoed.headers["allow_publish"] = False        # upstream veto
        assert p.offer(vetoed)
        assert p.offer(msg(topic="t", sender="other")) # the one that lands
        await settle(p)
        assert [p_.msg.sender for p_ in got.get("c1", [])] == ["other"]
        assert "no_subscribers" in dropped
        await p.stop()

    run(main())


def test_fanout_qos1_inflight_window_and_queue():
    async def main():
        b = Broker()
        sess, _ = b.open_session("sub", max_inflight=2)
        b.subscribe("sub", "t", SubOpts(qos=1))
        p = await start_pipeline(b)
        for i in range(5):
            assert p.offer(msg(topic="t", qos=1, payload=str(i).encode()))
        await settle(p)
        sends = b.take_outbox("sub")
        assert len(sends) == 2                   # window=2, rest queued
        assert len(sess.mqueue) == 3
        _, more = sess.puback(sends[0].pid)
        assert len(more) == 1                    # queue drains on ack
        await p.stop()

    run(main())


def test_fanout_invalid_topic_raises_at_offer():
    async def main():
        b = Broker()
        p = await start_pipeline(b)
        with pytest.raises(ValueError):
            p.offer(msg(topic="bad/+/wildcard-in-name"))
        await p.stop()

    run(main())


# ---------------------------------------------------------------------------
# fallback paths
# ---------------------------------------------------------------------------

def test_fanout_refuses_when_not_running():
    b = Broker()
    p = FanoutPipeline(b)  # never started
    assert p.offer(msg()) is False
    # channel-level contract: refusal means the caller publishes sync
    assert b.fanout is None


def test_fanout_low_rate_bypass_refuses_only_when_idle():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = await start_pipeline(b, bypass_rate=1e9, metrics=m)
        assert p.offer(msg(topic="t")) is False  # idle + low rate → sync
        assert m.get("broker.fanout.bypass") == 1
        # with the queue non-empty the bypass must NOT engage (ordering)
        p.bypass_rate = 0.0
        assert p.offer(msg(topic="t"))
        p.bypass_rate = 1e9
        assert p.offer(msg(topic="t"))           # queued behind the first
        await settle(p)
        await p.stop()

    run(main())


def test_fanout_overflow_sheds_to_sync_path():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = FanoutPipeline(b, queue_cap=4, metrics=m)
        p._running = True  # no drain task: queue can only fill
        for _ in range(4):
            assert p.offer(msg(topic="t"))
        assert p.offer(msg(topic="t")) is False
        assert m.get("broker.fanout.overflow") == 1
        p._running = False

    run(main())


def test_fanout_stop_drains_queue_via_sync_publish():
    async def main():
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = FanoutPipeline(b, window_s=60.0)  # batch never flushes itself
        await p.start()
        for i in range(3):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
        await p.stop()
        assert [int(x.msg.payload) for x in got["sub"]] == [0, 1, 2]

    run(main())


def test_fanout_drain_loop_survives_raising_hook():
    async def main():
        # a raising message.delivered tap must not kill the drain task:
        # the chunk falls back per message and LATER offers still deliver
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))

        def bomb(cid, m):
            if m.payload == b"boom":
                raise RuntimeError("hook exploded")

        b.hooks.add("message.delivered", bomb)
        m = Metrics()
        p = await start_pipeline(b, metrics=m)
        assert p.offer(msg(topic="t", payload=b"boom"))
        await settle(p)
        assert p.offer(msg(topic="t", payload=b"after"))
        await settle(p)
        assert not p._task.done()                # loop alive
        assert b"after" in [x.msg.payload for x in got["sub"]]
        assert m.get("broker.fanout.fallback") >= 1
        await p.stop()

    run(main())


def test_fanout_plan_failure_falls_back_without_double_fold():
    async def main():
        # route planning blows up once → the chunk re-dispatches via the
        # fold-skipping path: message.publish runs exactly once per msg
        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        folds = []
        b.hooks.add("message.publish", lambda m: folds.append(m.payload))
        calls = {"n": 0}

        def flaky_device_match(topic):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("device fell over")
            return None                          # host trie serves

        b.device_match = flaky_device_match
        p = await start_pipeline(b)
        assert p.offer(msg(topic="t", payload=b"0"))
        assert p.offer(msg(topic="t", payload=b"1"))
        await settle(p)
        assert sorted(x.msg.payload for x in got["sub"]) == [b"0", b"1"]
        assert sorted(folds) == [b"0", b"1"]     # one fold per message
        await p.stop()

    run(main())


def test_fanout_batch_prefetches_topics_in_one_call():
    async def main():
        class RecordingMatchService:
            def __init__(self):
                self.calls = []

            async def prefetch_many(self, topics):
                self.calls.append(set(topics))

        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "a/#", SubOpts(qos=0))
        ms = RecordingMatchService()
        p = await start_pipeline(b, match_service=ms)
        for t in ("a/1", "a/2", "a/3"):
            assert p.offer(msg(topic=t))
        await settle(p)
        assert ms.calls                          # pipeline DID prefetch
        seen = set().union(*ms.calls)
        assert seen == {"a/1", "a/2", "a/3"}
        await p.stop()

    run(main())


def test_fanout_stop_requeues_inflight_batch():
    async def main():
        # cancellation lands at the prefetch await point with the whole
        # batch popped off the queue — stop() must still deliver it
        class StalledMatchService:
            async def prefetch_many(self, topics):
                await asyncio.Event().wait()     # never returns

        b = Broker()
        got = {}
        b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts(qos=0))
        p = await start_pipeline(b, match_service=StalledMatchService())
        for i in range(5):
            assert p.offer(msg(topic="t", payload=str(i).encode()))
        await asyncio.sleep(0.02)                # batch pops, then stalls
        assert p._busy and not p._q              # in flight, queue empty
        await p.stop()
        assert [int(x.msg.payload) for x in got["sub"]] == [0, 1, 2, 3, 4]

    run(main())


def test_fanout_metrics_accounting():
    async def main():
        b = Broker()
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        m = Metrics()
        p = await start_pipeline(b, metrics=m)
        for _ in range(10):
            p.offer(msg(topic="t"))
        await settle(p)
        assert m.get("broker.fanout.msgs") == 10
        assert m.get("broker.fanout.batches") >= 1
        assert m.get("broker.fanout.batch_size") >= 1
        assert m.get("broker.fanout.flush_us") >= 0
        await p.stop()

    run(main())


def test_fanout_adaptive_batch_bound_tracks_rate():
    b = Broker()
    p = FanoutPipeline(b, max_batch=2048, min_batch=8, adapt_window_s=0.05)
    p._last_rate = 0.0
    assert p._batch_bound() == 8           # idle → floor
    p._last_rate = 10_000.0
    assert p._batch_bound() == 500         # 50 ms of 10k/s arrivals
    p._last_rate = 1e9
    assert p._batch_bound() == 2048        # capped at the sweet spot


# ---------------------------------------------------------------------------
# node-level opt-in over real TCP (pipeline on AND off)
# ---------------------------------------------------------------------------

def _e2e_roundtrip(fanout_on: bool):
    from emqx_tpu.client import Client
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            + ('broker.fanout.enable = true\n' if fanout_on else '')
        ))
        cfg.put("tpu.enable", False)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert (node.fanout_pipeline is not None) is fanout_on
            port = node.listeners.all()[0].port
            sub = Client(clientid="sub", port=port)
            pub = Client(clientid="pub", port=port)
            await sub.connect()
            await pub.connect()
            await sub.subscribe("a/#", qos=1)
            for i in range(20):
                await pub.publish("a/b", str(i).encode(), qos=1)
            got = [await sub.recv(timeout=5) for _ in range(20)]
            assert [int(g.payload) for g in got] == list(range(20))
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_node_e2e_pipeline_off_default():
    _e2e_roundtrip(False)


def test_node_e2e_pipeline_on():
    _e2e_roundtrip(True)
