"""Grab-bag services: slow subs, statsd, telemetry, PSK store, plugins,
jq subset — the remaining §2.3 inventory rows."""

import asyncio
import json
import socket

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(extra=""):
    cfg = Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n' + extra))
    node = BrokerNode(cfg)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


# ---------------------------------------------------------------------------
# slow subs
# ---------------------------------------------------------------------------

def test_slow_subs_ranks_late_deliveries():
    async def main():
        node = await start_node("slow_subs.enable = true\n"
                                "slow_subs.threshold = 50ms\n")
        try:
            c = Client(clientid="slowpoke", port=port_of(node))
            await c.connect()
            await c.subscribe("lag/#")
            # a message whose publish timestamp is in the past simulates
            # queueing delay (the tracked latency is publish->deliver)
            from emqx_tpu.broker.message import make_message
            import time

            msg = make_message("p", "lag/x", b"old")
            msg.timestamp = time.time() - 0.4
            node.broker.publish(msg)
            await c.recv()
            rank = node.slow_subs.ranking()
            assert rank and rank[0]["clientid"] == "slowpoke"
            assert rank[0]["topic"] == "lag/x"
            assert rank[0]["timespan_ms"] >= 300
            node.slow_subs.clear()
            assert node.slow_subs.ranking() == []
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# statsd
# ---------------------------------------------------------------------------

def test_statsd_pushes_counters_and_gauges():
    async def main():
        sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sink.bind(("127.0.0.1", 0))
        sink.settimeout(5.0)
        sport = sink.getsockname()[1]
        node = await start_node(
            "statsd.enable = true\n"
            f'statsd.server = "127.0.0.1:{sport}"\n'
            "statsd.flush_interval = 600s\n")
        try:
            c = Client(clientid="s1", port=port_of(node))
            await c.connect()
            await c.publish("a/b", b"x")
            await c.disconnect()
            node.statsd.push()  # deterministic flush for the test
            data = await asyncio.to_thread(sink.recvfrom, 65535)
            lines = data[0].decode().splitlines()
            kinds = {ln.rsplit("|", 1)[1] for ln in lines}
            # counters + gauges always; |ms histogram timing lines ride
            # later datagrams when the payload chunks (test_observe.py
            # covers the timing lines and the chunk boundaries)
            assert {"c", "g"} <= kinds <= {"c", "g", "ms"}
            names = {ln.split(":", 1)[0] for ln in lines}
            assert "emqx.messages.received" in names
            assert "emqx.connections.count" in names
        finally:
            await node.stop()
            sink.close()

    run(main())


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_telemetry_report_and_post():
    async def main():
        hits = []

        async def handle(reader, writer):
            head = await reader.readuntil(b"\r\n\r\n")
            n = int(next((ln.split(":")[1] for ln in
                          head.decode().split("\r\n")
                          if ln.lower().startswith("content-length")), "0"))
            hits.append(json.loads(await reader.readexactly(n)))
            writer.write(b"HTTP/1.1 200 OK\r\ncontent-length: 0\r\n\r\n")
            await writer.drain()
            writer.close()

        srv = await asyncio.start_server(handle, "127.0.0.1", 0)
        tport = srv.sockets[0].getsockname()[1]
        node = await start_node(
            "telemetry.enable = true\n"
            f'telemetry.url = "http://127.0.0.1:{tport}/t"\n'
            "telemetry.interval = 600s\n")
        try:
            for _ in range(100):
                if hits:
                    break
                await asyncio.sleep(0.02)
            assert hits, "no telemetry report arrived"
            rep = hits[0]
            assert rep["emqx_version"]
            assert rep["features"]["retainer"] is True
            assert "payload" not in json.dumps(rep)  # no message data
        finally:
            await node.stop()
            srv.close()
            await srv.wait_closed()

    run(main())


# ---------------------------------------------------------------------------
# PSK store
# ---------------------------------------------------------------------------

def test_psk_store_load_and_crud():
    from emqx_tpu.auth.psk import PskStore

    s = PskStore("dev1:aabbcc\n# comment\ndev2:00ff\n")
    assert s.get("dev1") == bytes.fromhex("aabbcc")
    assert s.get("dev2") == b"\x00\xff"
    assert s.get("nope") is None
    s.put("dev3", b"\x01\x02")
    assert sorted(s.identities()) == ["dev1", "dev2", "dev3"]
    assert s.delete("dev1") and not s.delete("dev1")
    with pytest.raises(ValueError):
        PskStore("malformed-line\n")

    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    wired = s.wire_into(ctx)
    # 3.13+ wires for real; older Pythons degrade with a warning
    assert wired is hasattr(ctx, "set_psk_server_callback")


def test_psk_rest_crud():
    async def main():
        from emqx_tpu.bridge import httpc

        node = await start_node(
            "psk.enable = true\n"
            'psk.entries = "a:0a0b,b:0c0d"\n'
            "dashboard.enable = true\n"
            "dashboard.auth = false\n"
            'dashboard.listen = "127.0.0.1:0"\n')
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("GET", f"{base}/psk")
            assert sorted(json.loads(r.body)["identities"]) == ["a", "b"]
            r = await httpc.request("POST", f"{base}/psk", body=json.dumps(
                {"identity": "c", "psk": "ff"}).encode())
            assert r.status == 201
            assert node.psk.get("c") == b"\xff"
            r = await httpc.request("DELETE", f"{base}/psk/a")
            assert r.status == 204
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# plugins
# ---------------------------------------------------------------------------

def test_plugin_install_start_stop(tmp_path):
    async def main():
        pdir = tmp_path / "audit_plugin"
        pdir.mkdir()
        (pdir / "plugin.json").write_text(json.dumps({
            "name": "audit", "version": "1.2.3", "module": "audit",
            "description": "counts publishes",
        }))
        (pdir / "audit.py").write_text(
            "def start(node):\n"
            "    seen = []\n"
            "    def tap(msg):\n"
            "        seen.append(msg.topic)\n"
            "        return msg\n"
            "    node.broker.hooks.add('message.publish', tap,\n"
            "                          priority=-10, name='audit.tap')\n"
            "    return seen\n"
            "def stop(node, handle):\n"
            "    node.broker.hooks.delete('message.publish', 'audit.tap')\n"
        )

        node = await start_node()
        try:
            pl = node.plugins.install(str(pdir))
            assert pl.info()["rel_vsn"] == "1.2.3"
            node.plugins.start("audit")
            c = Client(clientid="p", port=port_of(node))
            await c.connect()
            await c.publish("seen/1", b"x")
            await asyncio.sleep(0.05)
            assert pl.handle == ["seen/1"]
            node.plugins.stop("audit")
            await c.publish("seen/2", b"x")
            await asyncio.sleep(0.05)
            assert pl.handle is None
            assert node.plugins.uninstall("audit")
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# jq subset
# ---------------------------------------------------------------------------

def test_jq_subset():
    from emqx_tpu.rule_engine.funcs import call_func

    doc = {"a": {"b": [{"c": 1}, {"c": 2}]}, "k.x": 5}
    assert call_func("jq", [".", doc]) == [doc]
    assert call_func("jq", [".a.b[0].c", doc]) == [1]
    assert call_func("jq", [".a.b[-1].c", doc]) == [2]
    assert call_func("jq", [".a.b[].c", doc]) == [1, 2]
    assert call_func("jq", ['.["k.x"]', doc]) == [5]
    assert call_func("jq", [".a | .b | .[0]", doc]) == [{"c": 1}]
    assert call_func("jq", [".a.b[0].c, .a.b[1].c", doc]) == [1, 2]
    assert call_func("jq", [".missing.deep", doc]) == [None]
    # string input parses as JSON (reference jq/2 takes JSON strings)
    assert call_func("jq", [".x", '{"x": 42}']) == [42]
    with pytest.raises(ValueError):
        call_func("jq", ["garbage(", doc])
    with pytest.raises(ValueError):
        call_func("jq", [".[]", 42])


def test_jq_quoted_keys_with_separator_chars():
    from emqx_tpu.rule_engine.funcs import call_func

    doc = {"a|b": 1, "x,y": {"z": 2}}
    assert call_func("jq", ['.["a|b"]', doc]) == [1]
    assert call_func("jq", ['.["x,y"].z', doc]) == [2]
    assert call_func("jq", ['.["a|b"], .["x,y"].z', doc]) == [1, 2]


def test_ssl_listener_tls_roundtrip(tmp_path):
    """Real TLS handshake against the ssl listener (cert generated with
    the system openssl; skipped where unavailable)."""
    import shutil
    import ssl
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("no openssl binary")
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        check=True, capture_output=True)

    async def main():
        node = await start_node(
            "listeners.ssl.default.enable = true\n"
            'listeners.ssl.default.bind = "127.0.0.1:0"\n'
            f'listeners.ssl.default.certfile = "{cert}"\n'
            f'listeners.ssl.default.keyfile = "{key}"\n')
        try:
            ssl_l = [l for l in node.listeners.all() if l.name == "ssl-default"]
            assert ssl_l, "ssl listener missing"
            sport = ssl_l[0].port
            cctx = ssl.create_default_context()
            cctx.check_hostname = False
            cctx.verify_mode = ssl.CERT_NONE
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", sport, ssl=cctx)
            # minimal MQTT CONNECT over TLS -> CONNACK
            from emqx_tpu.mqtt import frame as F, packet as P

            writer.write(F.serialize(P.Connect(proto_ver=4, clientid="tlsc")))
            await writer.drain()
            data = await asyncio.wait_for(reader.read(64), 5)
            assert data[0] >> 4 == 2  # CONNACK
            assert data[3] == 0       # rc accepted
            writer.close()
        finally:
            await node.stop()

    run(main())


def test_slow_subs_ignores_by_design_delays():
    """Retained replay / delayed publishes carry old publish timestamps
    by design — they must not register as slow consumers."""
    async def main():
        node = await start_node("slow_subs.enable = true\n"
                                "slow_subs.threshold = 50ms\n")
        try:
            c = Client(clientid="fresh", port=port_of(node))
            await c.connect()
            # retained message published "an hour ago"
            from emqx_tpu.broker.message import make_message
            import time

            old = make_message("p", "old/news", b"r", retain=True)
            old.timestamp = time.time() - 3600
            node.broker.publish(old)
            await c.subscribe("old/#")
            msg = await c.recv()
            assert msg.payload == b"r"
            assert node.slow_subs.ranking() == []  # not a slow consumer
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_ssl_sni_selects_per_hostname_cert(tmp_path):
    """SNI: the served chain depends on the requested server name; the
    client proves it by pinning the matching self-signed cert as CA."""
    import shutil
    import ssl
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("no openssl binary")

    def gen(cn):
        cert, key = tmp_path / f"{cn}.pem", tmp_path / f"{cn}.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", f"/CN={cn}", "-addext", f"subjectAltName=DNS:{cn}"],
            check=True, capture_output=True)
        return cert, key

    dflt_c, dflt_k = gen("default.example")
    a_c, a_k = gen("a.example")

    async def main():
        node = await start_node(
            "listeners.ssl.default.enable = true\n"
            'listeners.ssl.default.bind = "127.0.0.1:0"\n'
            f'listeners.ssl.default.certfile = "{dflt_c}"\n'
            f'listeners.ssl.default.keyfile = "{dflt_k}"\n'
            f'listeners.ssl.default.sni = "a.example={a_c};{a_k}"\n')
        try:
            sport = [l for l in node.listeners.all()
                     if l.name == "ssl-default"][0].port

            async def connect_with(expect_cert, server_name):
                cctx = ssl.create_default_context(cafile=str(expect_cert))
                cctx.check_hostname = True
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", sport, ssl=cctx,
                    server_hostname=server_name)
                writer.close()

            await connect_with(a_c, "a.example")          # SNI match
            await connect_with(dflt_c, "default.example")  # fallback chain
            with pytest.raises(ssl.SSLError):
                # wrong pin proves different chains were served
                await connect_with(dflt_c, "a.example")
        finally:
            await node.stop()

    run(main())


def test_ssl_listener_crl_rejects_revoked_client(tmp_path):
    pytest.importorskip("cryptography")
    """Client-cert verification with a CRL: a revoked client cert fails
    the handshake, a valid one connects (emqx_tls_lib CRL-check analog).
    Certs/CRL built with the cryptography package."""
    import datetime
    import ssl

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)
    day = datetime.timedelta(days=1)

    def keypair():
        return rsa.generate_private_key(public_exponent=65537,
                                        key_size=2048)

    def name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    ca_key = keypair()
    ca_cert = (x509.CertificateBuilder()
               .subject_name(name("test-ca")).issuer_name(name("test-ca"))
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - day).not_valid_after(now + day)
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    def issue(cn):
        k = keypair()
        c = (x509.CertificateBuilder()
             .subject_name(name(cn)).issuer_name(name("test-ca"))
             .public_key(k.public_key())
             .serial_number(x509.random_serial_number())
             .not_valid_before(now - day).not_valid_after(now + day)
             .sign(ca_key, hashes.SHA256()))
        return k, c

    srv_key, srv_cert = issue("127.0.0.1")
    ok_key, ok_cert = issue("good-client")
    bad_key, bad_cert = issue("revoked-client")

    crl = (x509.CertificateRevocationListBuilder()
           .issuer_name(name("test-ca"))
           .last_update(now - day).next_update(now + day)
           .add_revoked_certificate(
               x509.RevokedCertificateBuilder()
               .serial_number(bad_cert.serial_number)
               .revocation_date(now - day).build())
           .sign(ca_key, hashes.SHA256()))

    def pem(path, *objs):
        data = b""
        for o in objs:
            if hasattr(o, "private_bytes"):
                data += o.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.TraditionalOpenSSL,
                    serialization.NoEncryption())
            else:
                data += o.public_bytes(serialization.Encoding.PEM)
        p = tmp_path / path
        p.write_bytes(data)
        return p

    ca_pem = pem("ca.pem", ca_cert)
    crl_pem = pem("crl.pem", crl)
    srv_c, srv_k = pem("srv.pem", srv_cert), pem("srv.key", srv_key)
    ok_c, ok_k = pem("ok.pem", ok_cert), pem("ok.key", ok_key)
    bad_c, bad_k = pem("bad.pem", bad_cert), pem("bad.key", bad_key)

    async def main():
        node = await start_node(
            "listeners.ssl.default.enable = true\n"
            'listeners.ssl.default.bind = "127.0.0.1:0"\n'
            f'listeners.ssl.default.certfile = "{srv_c}"\n'
            f'listeners.ssl.default.keyfile = "{srv_k}"\n'
            f'listeners.ssl.default.cacertfile = "{ca_pem}"\n'
            "listeners.ssl.default.verify = true\n"
            f'listeners.ssl.default.crlfile = "{crl_pem}"\n')
        try:
            sport = [l for l in node.listeners.all()
                     if l.name == "ssl-default"][0].port

            def cctx(certfile, keyfile):
                c = ssl.create_default_context()
                c.check_hostname = False
                c.verify_mode = ssl.CERT_NONE
                c.load_cert_chain(certfile, keyfile)
                return c

            # valid client: full MQTT CONNECT/CONNACK over TLS
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", sport, ssl=cctx(ok_c, ok_k))
            from emqx_tpu.mqtt import frame as F, packet as P

            writer.write(F.serialize(P.Connect(proto_ver=4,
                                               clientid="crl-ok")))
            await writer.drain()
            data = await asyncio.wait_for(reader.read(64), 5)
            assert data[0] >> 4 == 2 and data[3] == 0
            writer.close()

            # revoked client: rejected at (or right after) the
            # handshake — under TLS 1.3 the client "finishes" before
            # the server's cert verdict, so the alert may surface as an
            # error/EOF on the first read instead of in open_connection
            try:
                r2, w2 = await asyncio.wait_for(asyncio.open_connection(
                    "127.0.0.1", sport, ssl=cctx(bad_c, bad_k)), 5)
            except (ssl.SSLError, ConnectionError, OSError):
                pass
            else:
                w2.write(F.serialize(P.Connect(proto_ver=4,
                                               clientid="crl-bad")))
                with pytest.raises((ssl.SSLError, ConnectionError,
                                    OSError, asyncio.IncompleteReadError)):
                    await w2.drain()
                    got = await asyncio.wait_for(r2.read(64), 5)
                    assert got == b"", got  # server alert -> EOF
                    raise ConnectionResetError("rejected via EOF")
                w2.close()
        finally:
            await node.stop()

    run(main())
