"""Native topic encoder ≡ pure-Python fallback, byte for byte.

The encoder is the serving-path front (VERDICT.md weak item 3); parity
here is what lets the native path replace the Python loop safely.
"""

import numpy as np
from _optional import given, settings, st

from emqx_tpu.ops import TopicEncoder, compile_filters, encode_batch
from emqx_tpu.ops import encode as E


def _python_encode(enc, names, depth, batch=None):
    h, enc._h = enc._h, None
    try:
        return enc.encode(names, depth, batch=batch)
    finally:
        enc._h = h


def test_native_available():
    """The image ships g++; the native path must actually build."""
    assert E._native() is not None


def test_parity_basic():
    tbl = compile_filters(["a/+/c", "a/b/#", "x/y", "$SYS/#", "a//c"])
    names = [
        "a/b/c", "x/y", "$SYS/broker/x", "a//c", "", "unseen/words/here",
        "a", "very/deep/topic/a/b/c/d/e/f/g/h",
    ]
    enc = TopicEncoder(tbl.vocab)
    w1, l1, s1 = enc.encode(names, tbl.depth, batch=16)
    w2, l2, s2 = _python_encode(enc, names, tbl.depth, batch=16)
    np.testing.assert_array_equal(w1, w2)
    np.testing.assert_array_equal(l1, l2)
    np.testing.assert_array_equal(s1, s2)


topic_st = st.lists(
    st.text(
        alphabet=st.characters(
            blacklist_characters="\x00",
            blacklist_categories=("Cs",),
        ),
        max_size=6,
    ).map(lambda s: s.replace("/", "_")),
    min_size=1,
    max_size=10,
).map("/".join)


@settings(max_examples=50, deadline=None)
@given(st.lists(topic_st, min_size=0, max_size=20))
def test_parity_property(names):
    vocab = {}
    for n in names[: len(names) // 2]:  # half the words are known
        for w in n.split("/"):
            vocab.setdefault(w, len(vocab) + 1)
    enc = TopicEncoder(vocab)
    w1, l1, s1 = enc.encode(names, 8)
    w2, l2, s2 = _python_encode(enc, names, 8)
    np.testing.assert_array_equal(w1, w2)
    np.testing.assert_array_equal(l1, l2)
    np.testing.assert_array_equal(s1, s2)


def test_incremental_vocab_push():
    vocab = {"a": 1}
    enc = TopicEncoder(vocab)
    w, _, _ = enc.encode(["a/b"], 4)
    assert w[0, 0] == 1 and w[0, 1] == 0
    vocab["b"] = 2  # interned later, as IncrementalNfa does
    w, _, _ = enc.encode(["a/b"], 4)
    assert w[0, 1] == 2


def test_nul_topic_falls_back():
    tbl = compile_filters(["a/b"])
    names = ["a/b", "bad\x00topic"]
    w, l, s = encode_batch(tbl, names, batch=4)
    # fallback still encodes row 0 correctly
    assert l[0] == 2 and bool(s[0]) is False


def test_nul_topic_must_not_row_shift_neighbors():
    """A NUL-smuggling topic in the MIDDLE of a batch must not shift the
    encodings of the innocent topics after it (native path rejects the
    whole batch; Python fallback encodes per-topic)."""
    tbl = compile_filters(["a/b", "x/y/z"])
    names = ["ok/first", "bad\x00topic", "x/y/z"]
    w, l, s = encode_batch(tbl, names, batch=4)
    enc = TopicEncoder(tbl.vocab)
    w2, l2, s2 = _python_encode(enc, names, tbl.depth, batch=4)
    np.testing.assert_array_equal(w, w2)
    np.testing.assert_array_equal(l, l2)
    # the innocent last topic keeps its true encoding
    assert l[2] == 3
    assert w[2, 0] == tbl.vocab["x"] and w[2, 2] == tbl.vocab["z"]


def test_padding_rows_inert():
    tbl = compile_filters(["a/b"])
    w, l, s = encode_batch(tbl, ["a/b"], batch=8)
    assert (l[1:] == tbl.depth + 2).all()
    assert s[1:].all()
    assert (w[1:] == 0).all()
