"""NFA compiler + match kernel parity vs the host oracle/trie.

The contract (SURVEY.md §7 stage 4): for any wildcard filter set and any
topic batch, kernel matches ≡ FilterTrie.match ≡ {f | topic.match(n, f)}.
"""

import numpy as np
import pytest
from _optional import given, settings, st

from emqx_tpu import topic as T
from emqx_tpu.broker import FilterTrie
from emqx_tpu.ops import compile_filters, encode_topics, match_topics, nfa_match

import jax.numpy as jnp


FILTERS = [
    "a/b/c", "a/+/c", "a/#", "#", "+", "+/b", "a/b", "b",
    "$SYS/#", "$SYS/+/x", "x//y", "+/+/+", "a/+/+", "deep/1/2/3/4/5/6/#",
]
TOPICS = [
    "a/b/c", "a/b", "a", "b", "x//y", "x/y", "$SYS/broker", "$SYS/a/x",
    "deep/1/2/3/4/5/6/7/8/9", "nomatch/zzz", "a/q/c", "/", "a/b/c/d",
]


def oracle(name, filters):
    return {f for f in filters if T.match(name, f)}


def test_compile_basic_shapes():
    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    assert t.n_states <= t.S
    assert t.n_accepts == len(set(FILTERS))
    # host-side probe agrees with trie structure: root literal 'a'
    aid = t.vocab["a"]
    assert t.lookup_literal(0, aid) > 0
    assert t.lookup_literal(0, 0) == -1  # UNKNOWN has no edges


def test_compile_rejects_too_deep():
    with pytest.raises(ValueError):
        compile_filters(["a/b/c"], depth=2)


def test_match_kernel_explicit():
    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    got = match_topics(t, TOPICS)
    for name, matched in zip(TOPICS, got):
        assert set(matched) == oracle(name, FILTERS), name


def test_match_kernel_against_trie():
    tr = FilterTrie()
    for f in FILTERS:
        tr.insert(f)
    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    got = match_topics(t, TOPICS)
    for name, matched in zip(TOPICS, got):
        assert set(matched) == set(tr.match(name)), name


def test_batch_padding_rows_inert():
    t = compile_filters(["#", "+", "a/#"], depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, ["a/b"], batch=4)
    res = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
    )
    n = np.asarray(res.n_matches)
    assert n[0] == 2  # '#', 'a/#'
    assert (n[1:] == 0).all()  # padding matches nothing


def test_empty_filter_set():
    t = compile_filters([], depth=8, state_bucket=8)
    assert match_topics(t, ["a/b", "x"]) == [[], []]


def test_unknown_words_still_match_wildcards():
    t = compile_filters(["+/+", "a/#"], depth=8, state_bucket=8)
    got = match_topics(t, ["zz/ww", "a/zz"])
    assert set(got[0]) == {"+/+"}
    assert set(got[1]) == {"a/#", "+/+"}


def test_match_overflow_reported():
    # 100 filters all matching one topic, K=16 → overflow
    filters = [f"a/{i}/#" for i in range(100)] + ["a/+/+"]
    t = compile_filters(filters, depth=8, state_bucket=8)
    names = [f"a/{i}/x" for i in range(8)]
    words, lens, is_sys = encode_topics(t, names)
    res = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
        max_matches=2,
    )
    # each topic matches a/<i>/# and a/+/+ = 2 matches → no overflow at K=2
    assert int(np.sum(res.match_overflow)) == 0
    res2 = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
        max_matches=1,
    )
    # per-row overflow: every one of the 8 rows spilled, flagged exactly
    assert np.asarray(res2.match_overflow)[:8].tolist() == [1] * 8
    assert np.asarray(res2.spilled_rows())[:8].all()
    assert (np.asarray(res2.n_matches)[:8] == 2).all()  # exact beyond K


def test_active_overflow_reported():
    # force active-set spill with tiny A: filters +/+/.../+ at all depths
    filters = []
    for d in range(1, 7):
        for combo in range(2 ** d):
            ws = [("+" if (combo >> i) & 1 else "w") for i in range(d)]
            filters.append(T.join(ws))
    filters = list(set(filters))
    t = compile_filters(filters, depth=8, state_bucket=8)
    words, lens, is_sys = encode_topics(t, ["w/w/w/w/w/w"])
    res = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
        active_slots=4,
    )
    # the overloaded row is flagged; per-row so the host can fail open
    assert int(np.asarray(res.active_overflow)[0]) > 0
    assert bool(np.asarray(res.spilled_rows())[0])
    with pytest.raises(OverflowError):
        match_topics(t, ["w/w/w/w/w/w"], active_slots=4)


# ---------------------------------------------------------------------------
# property: kernel ≡ oracle on random tables/batches
# ---------------------------------------------------------------------------

word_st = st.sampled_from(["a", "b", "c", "", "d1"])
name_st = st.lists(
    st.one_of(word_st, st.just("$s")), min_size=1, max_size=6
).map(T.join)
filter_st = st.lists(
    st.one_of(word_st, st.just("+")), min_size=1, max_size=6
).flatmap(lambda ws: st.sampled_from([ws, ws + ["#"], ["#"]])).map(T.join)


@settings(max_examples=30, deadline=None)
@given(
    st.lists(filter_st, min_size=0, max_size=25),
    st.lists(name_st, min_size=1, max_size=12),
)
def test_kernel_equals_oracle_random(filters, names):
    t = compile_filters(filters, depth=8, state_bucket=8)
    got = match_topics(t, names, active_slots=64, max_matches=64)
    for name, matched in zip(names, got):
        assert set(matched) == oracle(name, set(filters)), (name, filters)


def test_flat_output_parity_and_truncation():
    """Flat mode: globally compacted ids decode to the same per-row sets
    as compact mode; rows truncated by K or the global cap are flagged."""
    from emqx_tpu.ops.match_kernel import decode_flat

    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS)
    K = 8
    cap = 128
    r = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
        active_slots=16, max_matches=K, flat_cap=cap,
    )
    flat = np.asarray(r.matches)
    assert flat.shape == (cap,)
    n = np.asarray(r.n_matches)
    spilled = np.asarray(r.spilled_rows())
    rows = decode_flat(flat, n, K)
    for i, name in enumerate(TOPICS):
        want = oracle(name, FILTERS)
        got = {t.accept_filters[a] for a in rows[i]}
        if not spilled[i]:
            assert got == want, (name, got, want)
        else:
            assert got <= want

    # tiny global cap: every row past the cap must be flagged
    r2 = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in t.device_arrays()],
        active_slots=16, max_matches=K, flat_cap=4,
    )
    n2 = np.asarray(r2.n_matches)
    sp2 = np.asarray(r2.spilled_rows())
    nk = np.minimum(n2, K)
    offs = np.cumsum(nk) - nk
    for i in range(len(TOPICS)):
        if offs[i] + nk[i] > 4:
            assert sp2[i], i
    # un-truncated prefix rows still decode correctly
    rows2 = decode_flat(np.asarray(r2.matches), n2, K)
    for i in range(len(TOPICS)):
        if not sp2[i]:
            got = {t.accept_filters[a] for a in rows2[i]}
            assert got == oracle(TOPICS[i], FILTERS)


def test_row_meta_packs_counts_and_spill_flags():
    """Flat mode's packed (B,) row_meta vector (ISSUE 11): low 16 bits
    = min(n, K), bit 16 = the fail-open flag — ONE tiny d2h carries
    everything a two-phase readback needs; non-flat modes carry None."""
    from emqx_tpu.ops.match_kernel import decode_row_meta

    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS)
    K = 8
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in t.device_arrays()])
    r = nfa_match(*args, active_slots=16, max_matches=K, flat_cap=128)
    meta = np.asarray(r.row_meta)
    nk, sp = decode_row_meta(meta)
    np.testing.assert_array_equal(
        nk, np.minimum(np.asarray(r.n_matches), K))
    np.testing.assert_array_equal(sp, np.asarray(r.spilled_rows()))
    # truncation by a tiny global cap lands in the packed flag too
    r2 = nfa_match(*args, active_slots=16, max_matches=K, flat_cap=4)
    _, sp2 = decode_row_meta(np.asarray(r2.row_meta))
    np.testing.assert_array_equal(sp2, np.asarray(r2.spilled_rows()))
    # non-flat modes: no meta output
    assert nfa_match(*args, active_slots=16, max_matches=K
                     ).row_meta is None


def test_fetch_flat_prefix_exact_and_bounded_executables():
    """Phase 2 of the two-phase readback ships EXACTLY total ids via
    pow2 binary decomposition — parity with a host slice for arbitrary
    totals, including 0 and the full buffer."""
    from emqx_tpu.ops.match_kernel import fetch_flat_prefix

    buf = jnp.asarray(np.arange(937, dtype=np.int32))
    for total in (0, 1, 2, 3, 7, 64, 100, 511, 937):
        got = fetch_flat_prefix(buf, total)
        np.testing.assert_array_equal(
            got, np.arange(total, dtype=np.int32))


def test_donated_kernel_variant_matches_and_consumes_inputs():
    """nfa_match_donated: identical results, operand buffers donated
    (the pipelined serve chain's contract — nothing may reuse them)."""
    import jax

    from emqx_tpu.ops.match_kernel import nfa_match_donated

    t = compile_filters(FILTERS, depth=16, state_bucket=8)
    words, lens, is_sys = encode_topics(t, TOPICS)
    tabs = [jnp.asarray(a) for a in t.device_arrays()]
    K = 8
    ref = nfa_match(jnp.asarray(words), jnp.asarray(lens),
                    jnp.asarray(is_sys), *tabs,
                    active_slots=16, max_matches=K, flat_cap=128)
    jw, jl, js = (jnp.asarray(words), jnp.asarray(lens),
                  jnp.asarray(is_sys))
    got = nfa_match_donated(jw, jl, js, *tabs,
                            active_slots=16, max_matches=K,
                            flat_cap=128)
    np.testing.assert_array_equal(np.asarray(ref.matches),
                                  np.asarray(got.matches))
    np.testing.assert_array_equal(np.asarray(ref.row_meta),
                                  np.asarray(got.row_meta))
    # at least one operand buffer was really donated (deleted)
    def deleted(a):
        try:
            jax.device_get(a)
            return False
        except RuntimeError:
            return True
    assert any(deleted(a) for a in (jw, jl, js))
    # table arrays are NOT donated: they serve every in-flight batch
    assert not any(deleted(a) for a in tabs)
