"""Bridges/connectors: buffered worker semantics, MQTT bridge (two live
in-process nodes), webhook bridge against an in-test HTTP server, rule
wiring, REST CRUD.  Mirrors the reference's bridge suites
(`apps/emqx_bridge*/test` [U]): real connections, no protocol mocks."""

import asyncio
import json

import pytest

from emqx_tpu.bridge import BridgeManager, BufferedWorker, Connector, SendError
from emqx_tpu.bridge import httpc
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_node(extra_cfg: str = "", **node_kw):
    cfg = Config(
        file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n' + extra_cfg
    )
    node = BrokerNode(cfg, **node_kw)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


# ---------------------------------------------------------------------------
# BufferedWorker semantics
# ---------------------------------------------------------------------------

class FlakyConnector(Connector):
    """Fails the first `fail_n` send calls, then succeeds."""

    def __init__(self, fail_n=0, retryable=True):
        self.fail_n = fail_n
        self.retryable = retryable
        self.sent = []
        self.calls = 0

    async def send(self, items):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise SendError("boom", retryable=self.retryable)
        self.sent.extend(items)


def test_worker_delivers_and_batches():
    async def main():
        conn = FlakyConnector()
        w = BufferedWorker(conn, batch_size=8)
        await w.start()
        for i in range(20):
            w.enqueue(i)
        for _ in range(100):
            if len(conn.sent) == 20:
                break
            await asyncio.sleep(0.01)
        assert conn.sent == list(range(20))  # order preserved
        assert w.metrics["success"] == 20
        assert w.status == "connected"
        await w.stop()

    run(main())


def test_worker_retries_with_backoff_until_success():
    async def main():
        conn = FlakyConnector(fail_n=3)
        w = BufferedWorker(conn, batch_size=4, retry_base=0.01)
        await w.start()
        for i in range(4):
            w.enqueue(i)
        for _ in range(200):
            if len(conn.sent) == 4:
                break
            await asyncio.sleep(0.01)
        assert conn.sent == [0, 1, 2, 3]
        assert w.metrics["retried"] >= 4 * 3  # 3 failed attempts requeued
        assert w.metrics["success"] == 4
        await w.stop()

    run(main())


def test_worker_nonretryable_drops_batch():
    async def main():
        conn = FlakyConnector(fail_n=1, retryable=False)
        w = BufferedWorker(conn, batch_size=2, retry_base=0.01)
        await w.start()
        w.enqueue("a")
        w.enqueue("b")
        w.enqueue("c")
        for _ in range(100):
            if conn.sent:
                break
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.05)
        # first batch (a, b) dropped as failed; c delivered
        assert conn.sent == ["c"]
        assert w.metrics["failed"] == 2
        await w.stop()

    run(main())


def test_worker_overflow_drops_oldest():
    async def main():
        conn = FlakyConnector(fail_n=10**9)  # never succeeds
        w = BufferedWorker(conn, max_queue=5, batch_size=2, retry_base=5.0)
        await w.start()
        await asyncio.sleep(0)
        for i in range(12):
            w.enqueue(i)
        assert w.queuing <= 5 + 2  # queue cap (+ a possibly inflight batch)
        assert w.metrics["dropped.queue_full"] >= 5
        await w.stop()

    run(main())


# ---------------------------------------------------------------------------
# MQTT bridge: egress + ingress between two live nodes
# ---------------------------------------------------------------------------

def test_mqtt_bridge_egress_via_rule():
    async def main():
        remote = await start_node()
        local = await start_node()
        try:
            watcher = Client(clientid="w", port=port_of(remote))
            await watcher.connect()
            await watcher.subscribe("remote/#", qos=0)

            await local.bridges.create("mqtt", "r1", {
                "server": f"127.0.0.1:{port_of(remote)}",
                "remote_topic": "remote/${topic}",
                "payload": "${payload}",
                "resource_opts": {"retry_base": 0.01},
            })
            local.rule_engine.create_rule(
                "fwd", 'SELECT * FROM "up/#"', actions=["mqtt:r1"]
            )

            pub = Client(clientid="p", port=port_of(local))
            await pub.connect()
            await pub.publish("up/x", b"data1")
            msg = await watcher.recv(timeout=5)
            assert msg.topic == "remote/up/x"
            assert msg.payload == b"data1"

            br = local.bridges.get("mqtt:r1")
            assert br.worker.metrics["success"] == 1
            await pub.disconnect()
            await watcher.disconnect()
        finally:
            await local.stop()
            await remote.stop()

    run(main())


def test_mqtt_bridge_buffers_while_remote_down_then_flushes():
    async def main():
        remote = await start_node()
        rport = port_of(remote)
        local = await start_node()
        try:
            await local.bridges.create("mqtt", "r1", {
                "server": f"127.0.0.1:{rport}",
                "remote_topic": "remote/${topic}",
                "resource_opts": {"retry_base": 0.02, "health_interval": 0.1},
            })
            local.rule_engine.create_rule(
                "fwd", 'SELECT * FROM "up/#"', actions=["mqtt:r1"]
            )
            await remote.stop()  # remote goes down

            pub = Client(clientid="p", port=port_of(local))
            await pub.connect()
            for i in range(5):
                await pub.publish("up/x", f"m{i}".encode())
            await asyncio.sleep(0.1)
            br = local.bridges.get("mqtt:r1")
            assert br.worker.queuing >= 1  # buffering, not dropping

            # remote comes back on the same port
            remote2 = BrokerNode(Config(
                file_text=f'listeners.tcp.default.bind = "127.0.0.1:{rport}"'
            ))
            await remote2.start()
            watcher = Client(clientid="w", port=rport)
            await watcher.connect()
            await watcher.subscribe("remote/#", qos=1)
            got = set()
            # bridge redelivers the buffered window after reconnect
            for _ in range(5):
                m = await watcher.recv(timeout=10)
                got.add(m.payload)
            assert got == {b"m0", b"m1", b"m2", b"m3", b"m4"}
            await watcher.disconnect()
            await pub.disconnect()
            await remote2.stop()
        finally:
            await local.stop()

    run(main())


def test_mqtt_bridge_ingress_republishes_locally():
    async def main():
        remote = await start_node()
        local = await start_node()
        try:
            sub = Client(clientid="s", port=port_of(local))
            await sub.connect()
            await sub.subscribe("from_remote/#", qos=0)

            await local.bridges.create("mqtt", "in1", {
                "server": f"127.0.0.1:{port_of(remote)}",
                "ingress": {
                    "remote_topic": "cloud/#",
                    "local_topic": "from_remote/${topic}",
                },
            })
            pub = Client(clientid="p", port=port_of(remote))
            await pub.connect()
            await pub.publish("cloud/t1", b"down")
            msg = await sub.recv(timeout=5)
            assert msg.topic == "from_remote/cloud/t1"
            assert msg.payload == b"down"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await local.stop()
            await remote.stop()

    run(main())


# ---------------------------------------------------------------------------
# webhook bridge against an in-test HTTP server
# ---------------------------------------------------------------------------

class TinyHttp:
    """Captures requests; scripted status codes per call."""

    def __init__(self, statuses=None):
        self.requests = []
        self.statuses = list(statuses or [])
        self.server = None
        self.port = 0

    async def start(self):
        async def handle(reader, writer):
            try:
                head = await reader.readuntil(b"\r\n\r\n")
                lines = head.decode("latin-1").split("\r\n")
                method, path, _ = lines[0].split(" ", 2)
                headers = {}
                for ln in lines[1:]:
                    if ":" in ln:
                        k, _, v = ln.partition(":")
                        headers[k.strip().lower()] = v.strip()
                body = b""
                n = int(headers.get("content-length", "0"))
                if n:
                    body = await reader.readexactly(n)
                status = self.statuses.pop(0) if self.statuses else 200
                self.requests.append((method, path, headers, body))
                payload = b'{"ok":true}'
                writer.write(
                    b"HTTP/1.1 %d X\r\ncontent-length: %d\r\n"
                    b"content-type: application/json\r\n\r\n%s"
                    % (status, len(payload), payload)
                )
                await writer.drain()
            except Exception:
                pass
            finally:
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()


def test_httpc_roundtrip_and_chunked():
    async def main():
        srv = TinyHttp()
        await srv.start()
        resp = await httpc.request(
            "POST", f"http://127.0.0.1:{srv.port}/hook",
            headers={"x-k": "v"}, body=b"hello",
        )
        assert resp.status == 200
        assert json.loads(resp.body) == {"ok": True}
        method, path, headers, body = srv.requests[0]
        assert (method, path, body) == ("POST", "/hook", b"hello")
        assert headers["x-k"] == "v"
        await srv.stop()

    run(main())


def test_webhook_bridge_posts_rule_output_and_retries_5xx():
    async def main():
        srv = TinyHttp(statuses=[500, 200])  # first attempt fails
        await srv.start()
        node = await start_node()
        try:
            await node.bridges.create("webhook", "wh", {
                "url": f"http://127.0.0.1:{srv.port}/hook",
                "headers": {"x-rule": "t"},
                "resource_opts": {"retry_base": 0.01, "batch_size": 1},
            })
            node.rule_engine.create_rule(
                "wh", 'SELECT topic, payload FROM "ev/#"',
                actions=["webhook:wh"],
            )
            pub = Client(clientid="p", port=port_of(node))
            await pub.connect()
            await pub.publish("ev/1", b"x42")
            br = node.bridges.get("webhook:wh")
            for _ in range(600):  # generous: suite runs on one busy core
                if br.worker.metrics["success"] >= 1:
                    break
                await asyncio.sleep(0.01)
            assert len(srv.requests) >= 2  # retried after the scripted 500
            body = json.loads(srv.requests[-1][3])
            assert body["topic"] == "ev/1"
            assert body["payload"] == "x42"
            assert br.worker.metrics["success"] == 1
            assert br.worker.metrics["retried"] >= 1
            await pub.disconnect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


def test_webhook_4xx_drops_without_retry():
    async def main():
        srv = TinyHttp(statuses=[404])
        await srv.start()
        node = await start_node()
        try:
            await node.bridges.create("webhook", "wh", {
                "url": f"http://127.0.0.1:{srv.port}/nope",
                "resource_opts": {"retry_base": 0.01, "batch_size": 1},
            })
            node.rule_engine.create_rule(
                "wh", 'SELECT * FROM "ev/#"', actions=["webhook:wh"]
            )
            pub = Client(clientid="p", port=port_of(node))
            await pub.connect()
            await pub.publish("ev/1", b"x")
            br = node.bridges.get("webhook:wh")
            for _ in range(100):
                if br.worker.metrics["failed"]:
                    break
                await asyncio.sleep(0.01)
            assert br.worker.metrics["failed"] == 1
            assert len(srv.requests) == 1  # no retry on 404
            await pub.disconnect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


# ---------------------------------------------------------------------------
# REST CRUD
# ---------------------------------------------------------------------------

def test_bridge_rest_crud():
    async def main():
        node = await start_node('dashboard.enable = true\n'
                                'dashboard.auth = false\n'
                                'dashboard.listen = "127.0.0.1:0"\n')
        try:
            mport = node.mgmt_server.port
            base = f"http://127.0.0.1:{mport}/api/v5"

            r = await httpc.request("POST", f"{base}/bridges", body=json.dumps({
                "type": "webhook", "name": "wh1",
                "conf": {"url": "http://127.0.0.1:1/x", "enable": False},
            }).encode())
            assert r.status == 201

            r = await httpc.request("GET", f"{base}/bridges")
            data = json.loads(r.body)["data"]
            assert data[0]["name"] == "wh1"
            assert data[0]["status"] == "stopped"

            r = await httpc.request("GET", f"{base}/bridges/webhook:wh1")
            assert r.status == 200

            r = await httpc.request(
                "POST", f"{base}/bridges/webhook:wh1/enable/true", body=b"")
            assert r.status == 204
            assert node.bridges.get("webhook:wh1").worker.status != "stopped"

            r = await httpc.request("DELETE", f"{base}/bridges/webhook:wh1")
            assert r.status == 204
            assert node.bridges.list() == []
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# backup round-trip: bridges + string actions survive export/import
# ---------------------------------------------------------------------------

def test_backup_roundtrip_restores_bridges_and_string_actions():
    async def main():
        from emqx_tpu.storage import export_data, import_data

        node = await start_node()
        try:
            await node.bridges.create("webhook", "wh", {
                "url": "http://127.0.0.1:1/x", "enable": False,
            })
            node.rule_engine.create_rule(
                "r1", 'SELECT * FROM "t/#"', actions=["webhook:wh"]
            )
            blob = export_data(node)
        finally:
            await node.stop()

        node2 = await start_node()
        try:
            counts = import_data(node2, blob)
            assert counts["bridges"] == 1
            assert counts["rules"] == 1
            assert node2.bridges.get("webhook:wh") is not None
            assert node2.rule_engine.rules["r1"].actions == ["webhook:wh"]
            # restored action resolves (no 'unknown bridge action')
            assert node2.rule_engine.bridge_resolver("webhook:wh") is not None
        finally:
            await node2.stop()

    run(main())


def test_webhook_mid_batch_resume_and_per_item_reject():
    """SendError.done: a 5xx mid-batch resumes from the failed item
    (delivered prefix not re-sent); a 4xx rejects only that item."""
    async def main():
        from emqx_tpu.bridge.webhook import WebhookConnector
        from emqx_tpu.bridge.resource import BufferedWorker

        srv = TinyHttp(statuses=[200, 500, 200, 404, 200])
        await srv.start()
        conn = WebhookConnector({"url": ""}, "wh")
        w = BufferedWorker(conn, batch_size=4, retry_base=0.01)
        await w.start()
        for i in range(4):
            w.enqueue({"url": f"http://127.0.0.1:{srv.port}/i{i}",
                       "method": "POST", "body": b""})
        for _ in range(300):
            if w.metrics["success"] + w.metrics["failed"] >= 4:
                break
            await asyncio.sleep(0.01)
        paths = [p for _, p, _, _ in srv.requests]
        # i0 ok; i1 500 then retried; i2 ok; i3 404 (once, rejected)
        assert paths == ["/i0", "/i1", "/i1", "/i2", "/i3"]
        assert w.metrics["success"] == 3
        assert w.metrics["failed"] == 1
        await w.stop()
        await srv.stop()

    run(main())
