"""External authn/authz (HTTP + JWKS) against in-test mock servers,
through full CONNECT/SUBSCRIBE round trips — chain ordering, deny
policy, timeout fail-ignore (emqx_authn/http, jwks, emqx_authz/http
analogs)."""

import asyncio
import base64
import hashlib
import json
import math
import secrets

import pytest

from emqx_tpu.auth import (
    AuthChain, Authz, BuiltinDbAuthenticator, HttpAuthenticator,
    HttpAuthzSource, JwksJwtAuthenticator,
)
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


class MockHttp:
    """Scripted HTTP server: handler(method, path, body) -> (status, doc)."""

    def __init__(self, handler):
        self.handler = handler
        self.requests = []
        self.port = 0

    async def start(self):
        async def handle(reader, writer):
            try:
                while True:
                    head = await reader.readuntil(b"\r\n\r\n")
                    lines = head.decode("latin-1").split("\r\n")
                    method, path, _ = lines[0].split(" ", 2)
                    headers = {}
                    for ln in lines[1:]:
                        if ":" in ln:
                            k, _, v = ln.partition(":")
                            headers[k.strip().lower()] = v.strip()
                    n = int(headers.get("content-length", "0"))
                    body = await reader.readexactly(n) if n else b""
                    self.requests.append((method, path, body))
                    status, doc = self.handler(method, path, body)
                    payload = json.dumps(doc).encode() if doc is not None else b""
                    writer.write(
                        b"HTTP/1.1 %d X\r\ncontent-length: %d\r\n"
                        b"content-type: application/json\r\n"
                        b"connection: close\r\n\r\n%s"
                        % (status, len(payload), payload))
                    await writer.drain()
                    return
            except Exception:
                pass
            finally:
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()


async def start_node(auth_chain=None, authz=None):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    node = BrokerNode(cfg, auth_chain=auth_chain, authz=authz)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


def test_http_authn_allow_deny_superuser():
    async def main():
        def handler(method, path, body):
            doc = json.loads(body)
            if doc["username"] == "alice" and doc["password"] == "pw1":
                return 200, {"result": "allow", "is_superuser": True}
            if doc["username"] == "mallory":
                return 200, {"result": "deny"}
            return 200, {"result": "ignore"}

        srv = await MockHttp(handler).start()
        chain = AuthChain(allow_anonymous=False).add(
            HttpAuthenticator(f"http://127.0.0.1:{srv.port}/auth"))
        node = await start_node(auth_chain=chain)
        try:
            ok = Client(clientid="c1", port=port_of(node),
                        username="alice", password=b"pw1")
            await ok.connect()
            # superuser attr propagated: denied-by-nothing, can pub $SYS-ish
            await ok.disconnect()

            bad = Client(clientid="c2", port=port_of(node),
                         username="mallory", password=b"x")
            with pytest.raises(MqttError):
                await bad.connect()

            # ignore + allow_anonymous=False => refused
            anon = Client(clientid="c3", port=port_of(node),
                          username="nobody", password=b"x")
            with pytest.raises(MqttError):
                await anon.connect()
            # each connect hit the backend exactly once (async intercept
            # parked the verdict; the sync fold did NOT re-request)
            assert len(srv.requests) == 3
        finally:
            await node.stop()
            await srv.stop()

    run(main())


def test_http_authn_unreachable_is_ignore_and_chain_order():
    async def main():
        # chain: builtin-db FIRST, dead http SECOND — db users never
        # touch the network; unknown users fall through to http =>
        # unreachable => ignore => anonymous policy decides
        db = BuiltinDbAuthenticator()
        db.add_user("dbuser", b"s3cret")
        chain = AuthChain(allow_anonymous=False)
        chain.add(db).add(HttpAuthenticator("http://127.0.0.1:1/auth",
                                            timeout=0.3))
        node = await start_node(auth_chain=chain)
        try:
            ok = Client(clientid="c1", port=port_of(node),
                        username="dbuser", password=b"s3cret")
            await ok.connect()
            await ok.disconnect()

            bad = Client(clientid="c2", port=port_of(node),
                         username="webuser", password=b"x")
            with pytest.raises(MqttError):
                await bad.connect()
        finally:
            await node.stop()

    run(main())


def test_http_authz_per_topic_with_cache():
    async def main():
        def handler(method, path, body):
            doc = json.loads(body)
            if doc["topic"].startswith("open/"):
                return 200, {"result": "allow"}
            if doc["topic"].startswith("secret/"):
                return 200, {"result": "deny"}
            return 200, {"result": "ignore"}

        srv = await MockHttp(handler).start()
        authz = Authz(
            sources=[HttpAuthzSource(f"http://127.0.0.1:{srv.port}/acl")],
            no_match="deny", cache_enable=False,
        )
        node = await start_node(auth_chain=AuthChain(), authz=authz)
        try:
            c = Client(clientid="c1", port=port_of(node))
            await c.connect()
            assert await c.subscribe("open/news") == [0]
            assert (await c.subscribe("secret/x"))[0] >= 0x80  # denied
            assert (await c.subscribe("other/x"))[0] >= 0x80   # nomatch→deny
            n_before = len(srv.requests)
            assert await c.subscribe("open/news") == [0]  # cached verdict
            assert len(srv.requests) == n_before
            await c.disconnect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


# ---------------------------------------------------------------------------
# JWKS / RS256 (pure-python RSA test keypair)
# ---------------------------------------------------------------------------

def _miller_rabin(n, rounds=24):
    if n % 2 == 0:
        return n == 2
    r, d = 0, n - 1
    while d % 2 == 0:
        r += 1
        d //= 2
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = pow(x, 2, n)
            if x == n - 1:
                break
        else:
            return False
    return True


def _gen_prime(bits):
    while True:
        p = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _miller_rabin(p):
            return p


def make_rsa():
    p, q = _gen_prime(512), _gen_prime(512)
    n, e = p * q, 65537
    d = pow(e, -1, math.lcm(p - 1, q - 1))
    return n, e, d


def b64u(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


_SHA256_PREFIX = bytes.fromhex("3031300d060960864801650304020105000420")


def rs256_sign(n, d, header: dict, claims: dict) -> str:
    h64 = b64u(json.dumps(header).encode())
    b64 = b64u(json.dumps(claims).encode())
    msg = f"{h64}.{b64}".encode()
    k = (n.bit_length() + 7) // 8
    t = _SHA256_PREFIX + hashlib.sha256(msg).digest()
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    sig = pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")
    return f"{h64}.{b64}.{b64u(sig)}"


def test_jwks_rs256_roundtrip():
    async def main():
        n, e, d = make_rsa()
        jwks = {"keys": [{
            "kty": "RSA", "kid": "k1", "use": "sig", "alg": "RS256",
            "n": b64u(n.to_bytes((n.bit_length() + 7) // 8, "big")),
            "e": b64u(e.to_bytes(3, "big")),
        }]}
        srv = await MockHttp(lambda m, p, b: (200, jwks)).start()
        chain = AuthChain(allow_anonymous=False).add(
            JwksJwtAuthenticator(
                f"http://127.0.0.1:{srv.port}/jwks",
                verify_claims={"sub": "%u"},
            ))
        node = await start_node(auth_chain=chain)
        try:
            import time as _t

            token = rs256_sign(n, d, {"alg": "RS256", "kid": "k1"},
                               {"sub": "alice", "exp": _t.time() + 60})
            ok = Client(clientid="c1", port=port_of(node),
                        username="alice", password=token.encode())
            await ok.connect()
            await ok.disconnect()

            # tampered signature -> deny
            bad_token = token[:-6] + ("AAAAAA" if not token.endswith("AAAAAA")
                                      else "BBBBBB")
            bad = Client(clientid="c2", port=port_of(node),
                         username="alice", password=bad_token.encode())
            with pytest.raises(MqttError):
                await bad.connect()

            # wrong claim (sub != username) -> deny
            tok2 = rs256_sign(n, d, {"alg": "RS256", "kid": "k1"},
                              {"sub": "bob", "exp": _t.time() + 60})
            bad2 = Client(clientid="c3", port=port_of(node),
                          username="alice", password=tok2.encode())
            with pytest.raises(MqttError):
                await bad2.connect()

            # expired -> deny
            tok3 = rs256_sign(n, d, {"alg": "RS256", "kid": "k1"},
                              {"sub": "alice", "exp": _t.time() - 5})
            bad3 = Client(clientid="c4", port=port_of(node),
                          username="alice", password=tok3.encode())
            with pytest.raises(MqttError):
                await bad3.connect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


def test_rsa_verify_unit():
    from emqx_tpu.auth.external import _rsa_verify_sha256

    n, e, d = make_rsa()
    msg = b"hello world"
    k = (n.bit_length() + 7) // 8
    t = _SHA256_PREFIX + hashlib.sha256(msg).digest()
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    sig = pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")
    assert _rsa_verify_sha256(n, e, msg, sig)
    assert not _rsa_verify_sha256(n, e, b"other", sig)
    assert not _rsa_verify_sha256(n, e, msg, b"\x00" * k)


# ---------------------------------------------------------------------------
# Redis authn/authz against an in-test RESP server
# ---------------------------------------------------------------------------

class MockRedis:
    """Tiny RESP2 server: serves HMGET/HGETALL from a dict-of-dicts."""

    def __init__(self, data, password=None):
        self.data = data
        self.password = password
        self.commands = []
        self.port = 0
        self._conns = set()

    async def start(self):
        async def handle(reader, writer):
            authed = self.password is None
            self._conns.add(writer)
            try:
                while True:
                    line = await reader.readline()
                    if not line:
                        return
                    assert line[:1] == b"*"
                    n = int(line[1:-2])
                    parts = []
                    for _ in range(n):
                        hdr = await reader.readline()
                        assert hdr[:1] == b"$"
                        ln = int(hdr[1:-2])
                        parts.append((await reader.readexactly(ln + 2))[:-2])
                    cmd = parts[0].upper().decode()
                    self.commands.append((cmd, *[p.decode() for p in parts[1:]]))
                    if cmd == "AUTH":
                        authed = parts[1].decode() == self.password
                        writer.write(b"+OK\r\n" if authed else b"-ERR auth\r\n")
                    elif not authed:
                        writer.write(b"-NOAUTH\r\n")
                    elif cmd == "HMGET":
                        h = self.data.get(parts[1].decode(), {})
                        out = [b"*%d\r\n" % (len(parts) - 2)]
                        for f in parts[2:]:
                            v = h.get(f.decode())
                            out.append(b"$-1\r\n" if v is None else
                                       b"$%d\r\n%s\r\n" % (len(v), v.encode()))
                        writer.write(b"".join(out))
                    elif cmd == "HGETALL":
                        h = self.data.get(parts[1].decode(), {})
                        out = [b"*%d\r\n" % (len(h) * 2)]
                        for k, v in h.items():
                            out.append(b"$%d\r\n%s\r\n" % (len(k), k.encode()))
                            out.append(b"$%d\r\n%s\r\n" % (len(v), v.encode()))
                        writer.write(b"".join(out))
                    else:
                        writer.write(b"-ERR unknown\r\n")
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        # clients (the node's auth backends) hold persistent conns;
        # wait_closed() would block on them forever
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


def test_redis_authn_and_authz_roundtrip():
    async def main():
        from emqx_tpu.auth.authn import hash_password
        from emqx_tpu.auth.redis import RedisAuthenticator, RedisAuthzSource

        salt = "abcd1234"
        redis = await MockRedis({
            "mqtt_user:rita": {
                "password_hash": hash_password(b"rpw", "sha256",
                                               salt.encode()),
                "salt": salt,
                "is_superuser": "0",
            },
            "mqtt_acl:rita": {"open/#": "all", "wr/%u/own": "publish"},
        }).start()

        chain = AuthChain(allow_anonymous=False).add(
            RedisAuthenticator(f"127.0.0.1:{redis.port}"))
        authz = Authz(
            sources=[RedisAuthzSource(f"127.0.0.1:{redis.port}")],
            no_match="deny", cache_enable=False,
        )
        node = await start_node(auth_chain=chain, authz=authz)
        try:
            ok = Client(clientid="c1", port=port_of(node),
                        username="rita", password=b"rpw")
            await ok.connect()
            assert await ok.subscribe("open/news") == [0]
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            # %u placeholder rule: publish-only on wr/rita/own
            assert (await ok.subscribe("wr/rita/own"))[0] >= 0x80
            await ok.disconnect()

            bad = Client(clientid="c2", port=port_of(node),
                         username="rita", password=b"wrong")
            with pytest.raises(MqttError):
                await bad.connect()
            # unknown user -> ignore -> anonymous policy (deny)
            unk = Client(clientid="c3", port=port_of(node),
                         username="ghost", password=b"x")
            with pytest.raises(MqttError):
                await unk.connect()
        finally:
            await node.stop()
            await redis.stop()

    run(main())


def test_redis_auth_with_password_and_down_server():
    async def main():
        from emqx_tpu.auth.authn import hash_password
        from emqx_tpu.auth.redis import RedisAuthenticator
        from emqx_tpu.auth.authn import Credentials

        redis = await MockRedis({
            "mqtt_user:u1": {
                "password_hash": hash_password(b"p", "sha256", b"s"),
                "salt": "s",
            },
        }, password="redispass").start()
        a = RedisAuthenticator(f"127.0.0.1:{redis.port}",
                               password="redispass")
        res = await a.authenticate_async(
            Credentials("c", "u1", b"p"))
        assert res.outcome == "ok"
        assert ("AUTH", "redispass") in redis.commands
        await redis.stop()

        # server down => ignore (never deny on infra failure)
        dead = RedisAuthenticator("127.0.0.1:1", timeout=0.3)
        res = await dead.authenticate_async(Credentials("c", "u1", b"p"))
        assert res.outcome == "ignore"

    run(main())


# ---------------------------------------------------------------------------
# SCRAM-SHA-256 over MQTT 5 enhanced auth (AUTH exchange)
# ---------------------------------------------------------------------------

def test_scram_unit_roundtrip_and_tamper():
    from emqx_tpu.auth.scram import (
        ScramAuthenticator, scram_client_final, scram_client_first,
    )

    a = ScramAuthenticator()
    a.add_user("sue", b"pw-sue", is_superuser=True)

    first, ctx = scram_client_first("sue")
    verdict = a.start("c1", "sue", first)
    assert verdict[0] == "continue"
    final, ctx = scram_client_final(ctx, b"pw-sue", verdict[1])
    ok = a.continue_auth(verdict[2], final)
    assert ok[0] == "ok" and ok[1] == "sue" and ok[2] is True
    # mutual auth: the client can verify the server signature
    assert ok[3] == ctx["expect_server_final"]

    # wrong password -> bad proof
    first, ctx = scram_client_first("sue")
    verdict = a.start("c1", "sue", first)
    final, _ = scram_client_final(ctx, b"WRONG", verdict[1])
    assert a.continue_auth(verdict[2], final)[0] == "deny"

    # unknown user / malformed first
    assert a.start("c1", "ghost", scram_client_first("ghost")[0])[0] == "deny"
    assert a.start("c1", "sue", b"\xff\xfe")[0] == "deny"


def test_scram_mqtt5_auth_exchange_end_to_end():
    async def main():
        from emqx_tpu.auth.scram import (
            ScramAuthenticator, scram_client_final, scram_client_first,
        )

        scram = ScramAuthenticator()
        scram.add_user("dev9", b"sekret9")
        node = await start_node(auth_chain=AuthChain(allow_anonymous=False))
        node.broker.enhanced_auth["SCRAM-SHA-256"] = scram
        try:
            first, ctx = scram_client_first("dev9")
            holder = {"ctx": ctx}

            def on_auth(server_first: bytes) -> bytes:
                final, holder["ctx"] = scram_client_final(
                    holder["ctx"], b"sekret9", server_first)
                return final

            c = Client(clientid="c9", port=port_of(node), proto_ver=5,
                       properties={
                           "Authentication-Method": "SCRAM-SHA-256",
                           "Authentication-Data": first,
                       }, on_auth=on_auth)
            ack = await c.connect()
            assert ack.reason_code == 0
            # CONNACK carries server-final: mutual authentication
            assert ack.properties.get("Authentication-Data") == \
                holder["ctx"]["expect_server_final"]
            await c.subscribe("sc/t")
            await c.publish("sc/t", b"hello-scram", qos=1)
            msg = await c.recv(timeout=5)
            assert msg.payload == b"hello-scram"
            await c.disconnect()

            # wrong password: server denies at the proof step
            first2, ctx2 = scram_client_first("dev9")
            h2 = {"ctx": ctx2}

            def on_auth_bad(server_first: bytes) -> bytes:
                final, h2["ctx"] = scram_client_final(
                    h2["ctx"], b"nope", server_first)
                return final

            bad = Client(clientid="c10", port=port_of(node), proto_ver=5,
                         properties={
                             "Authentication-Method": "SCRAM-SHA-256",
                             "Authentication-Data": first2,
                         }, on_auth=on_auth_bad)
            with pytest.raises(MqttError):
                await bad.connect()

            # unknown method -> 0x8C
            unk = Client(clientid="c11", port=port_of(node), proto_ver=5,
                         properties={
                             "Authentication-Method": "GSSAPI",
                             "Authentication-Data": b"x",
                         })
            with pytest.raises(MqttError) as ei:
                await unk.connect()
            assert "8c" in str(ei.value).lower() or "140" in str(ei.value)
        finally:
            await node.stop()

    run(main())


def test_scram_reauthentication_mid_session():
    """MQTT 5 §4.12.1: a connected enhanced-auth client re-authenticates
    with AUTH rc 0x19 without dropping the connection."""
    async def main():
        from emqx_tpu.auth.scram import (
            ScramAuthenticator, scram_client_final, scram_client_first,
        )
        from emqx_tpu.mqtt import packet as P

        scram = ScramAuthenticator()
        scram.add_user("ra", b"pw-ra")
        node = await start_node(auth_chain=AuthChain(allow_anonymous=False))
        node.broker.enhanced_auth["SCRAM-SHA-256"] = scram
        try:
            first, ctx = scram_client_first("ra")
            holder = {"ctx": ctx}

            def on_auth(server_first: bytes) -> bytes:
                final, holder["ctx"] = scram_client_final(
                    holder["ctx"], b"pw-ra", server_first)
                return final

            c = Client(clientid="cr", port=port_of(node), proto_ver=5,
                       properties={
                           "Authentication-Method": "SCRAM-SHA-256",
                           "Authentication-Data": first,
                       }, on_auth=on_auth)
            await c.connect()
            await c.subscribe("ra/t")

            # re-auth: new client-first with rc 0x19
            first2, ctx2 = scram_client_first("ra")
            holder["ctx"] = ctx2
            c._send(P.Auth(
                reason_code=P.RC.REAUTHENTICATE,
                properties={"Authentication-Method": "SCRAM-SHA-256",
                            "Authentication-Data": first2}))
            # on_auth answers the challenge; server finishes with AUTH 0x00
            await asyncio.sleep(0.2)
            assert c.connected
            # session still works after re-auth
            await c.publish("ra/t", b"post-reauth")
            msg = await c.recv(timeout=5)
            assert msg.payload == b"post-reauth"
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_scram_banned_client_rejected():
    """The ban check must hold on the enhanced-auth path too (it rides a
    dedicated pre-auth fold since the chain fold never runs there)."""
    async def main():
        from emqx_tpu.auth.scram import (
            ScramAuthenticator, scram_client_final, scram_client_first,
        )

        scram = ScramAuthenticator()
        scram.add_user("evil", b"pw")
        node = await start_node(auth_chain=AuthChain(allow_anonymous=False))
        node.broker.enhanced_auth["SCRAM-SHA-256"] = scram
        node.banned.add("clientid", "banned-c")
        try:
            first, ctx = scram_client_first("evil")
            h = {"ctx": ctx}

            def on_auth(sf):
                final, h["ctx"] = scram_client_final(h["ctx"], b"pw", sf)
                return final

            bad = Client(clientid="banned-c", port=port_of(node),
                         proto_ver=5, properties={
                             "Authentication-Method": "SCRAM-SHA-256",
                             "Authentication-Data": first,
                         }, on_auth=on_auth)
            with pytest.raises(MqttError) as ei:
                await bad.connect()
            assert "138" in str(ei.value)  # 0x8A BANNED
        finally:
            await node.stop()

    run(main())
