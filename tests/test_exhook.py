"""ExHook boundary tests: gRPC HookProvider round trips against a live
broker, and the TPU match sidecar's mirror/batch paths.

Mirrors the reference's exhook suite shape (SURVEY.md §4: fake gRPC
HookProvider servers inside the suite — ``apps/emqx_exhook/test/`` runs
a demo provider the same way [U])."""

import asyncio

import grpc
import grpc.aio
import pytest

from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.exhook.rpc import (
    HookProviderStub,
    MirrorSyncStub,
    add_hook_provider_to_server,
    add_mirror_sync_to_server,
    pb,
)
from emqx_tpu.exhook.server import TpuMatchSidecar
from emqx_tpu.mqtt import packet as P
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


class DemoProvider:
    """Scriptable HookProvider: deny-lists + message rewrite + event log."""

    def __init__(
        self,
        hooks=("client.authenticate", "client.authorize", "message.publish",
               "session.subscribed", "session.unsubscribed",
               "client.connected", "client.disconnected"),
        deny_clientids=(),
        deny_topics=(),
        rewrite=None,  # (from_topic, to_topic)
        fail_methods=(),
    ):
        self.hooks = list(hooks)
        self.deny_clientids = set(deny_clientids)
        self.deny_topics = set(deny_topics)
        self.rewrite = rewrite
        self.fail_methods = set(fail_methods)
        self.events = []

    async def OnProviderLoaded(self, request, context):
        self.events.append(("loaded", request.meta.node))
        return pb.LoadedResponse(hooks=[pb.HookSpec(name=h) for h in self.hooks])

    async def OnProviderUnloaded(self, request, context):
        self.events.append(("unloaded",))
        return pb.EmptySuccess()

    async def OnClientAuthenticate(self, request, context):
        if "OnClientAuthenticate" in self.fail_methods:
            raise RuntimeError("scripted failure")
        deny = request.clientinfo.clientid in self.deny_clientids
        self.events.append(("auth", request.clientinfo.clientid, not deny))
        if deny:
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN, bool_result=False
            )
        return pb.ValuedResponse(type=pb.ValuedResponse.CONTINUE)

    async def OnClientAuthorize(self, request, context):
        deny = request.topic in self.deny_topics
        self.events.append(
            ("authz", request.clientinfo.clientid, request.type,
             request.topic, not deny)
        )
        if deny:
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN, bool_result=False
            )
        return pb.ValuedResponse(type=pb.ValuedResponse.CONTINUE)

    async def OnMessagePublish(self, request, context):
        self.events.append(("publish", request.message.topic))
        if self.rewrite and request.message.topic == self.rewrite[0]:
            m = pb.Message()
            m.CopyFrom(request.message)
            m.topic = self.rewrite[1]
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN, message=m
            )
        return pb.ValuedResponse(type=pb.ValuedResponse.CONTINUE)

    async def OnClientConnected(self, request, context):
        self.events.append(("connected", request.clientinfo.clientid))
        return pb.EmptySuccess()

    async def OnClientDisconnected(self, request, context):
        self.events.append(("disconnected", request.clientinfo.clientid))
        return pb.EmptySuccess()

    async def OnSessionSubscribed(self, request, context):
        self.events.append(("subscribed", request.clientinfo.clientid,
                            request.topic))
        return pb.EmptySuccess()

    async def OnSessionUnsubscribed(self, request, context):
        self.events.append(("unsubscribed", request.clientinfo.clientid,
                            request.topic))
        return pb.EmptySuccess()


async def start_provider(servicer):
    server = grpc.aio.server()
    add_hook_provider_to_server(servicer, server)
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    return server, port


async def start_node_with_exhook(port, failure_action="ignore"):
    cfg = Config(
        file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            f'exhook.servers = "default=127.0.0.1:{port}"\n'
            'exhook.request_timeout = 2s\n'
            f'exhook.failure_action = {failure_action}\n'
        )
    )
    node = BrokerNode(cfg)
    await node.start()
    return node


def node_port(node):
    return node.listeners.all()[0].port


async def settle(pred, timeout=5.0, interval=0.02):
    """Await an eventually-true condition (async notify queues drain)."""
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def synced(sidecar):
    """Sidecar device mirror is serving AND caught up with the host
    table (answers reflect every mutation so far, not a stale prefix)."""
    return (
        sidecar._engine is not None
        and not sidecar._dirty.is_set()
        and sidecar._eng.dev.epoch == sidecar._eng.inc.epoch
    )


# ---------------------------------------------------------------------------
# broker-side manager: advisory verdicts
# ---------------------------------------------------------------------------


def test_authenticate_deny_refuses_connect():
    async def main():
        provider = DemoProvider(deny_clientids={"evil"})
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            ok = Client(clientid="good", port=node_port(node))
            await ok.connect()
            await ok.disconnect()

            bad = Client(clientid="evil", port=node_port(node))
            with pytest.raises(Exception):
                await bad.connect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_authorize_deny_publish_and_subscribe():
    async def main():
        provider = DemoProvider(deny_topics={"forbidden/t"})
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            sub = Client(clientid="s1", port=node_port(node), proto_ver=5)
            await sub.connect()
            # subscribe deny → SUBACK 0x87 for that filter only
            codes = await sub.subscribe("forbidden/t", qos=1)
            assert codes == [P.RC.NOT_AUTHORIZED]
            codes = await sub.subscribe("allowed/t", qos=1)
            assert codes == [1]

            pub = Client(clientid="p1", port=node_port(node), proto_ver=5)
            await pub.connect()
            # publish deny → PUBACK 0x87, message not routed
            rc = await pub.publish("forbidden/t", b"x", qos=1)
            assert rc == P.RC.NOT_AUTHORIZED
            await pub.publish("allowed/t", b"y", qos=1)
            msg = await sub.recv()
            assert (msg.topic, msg.payload) == ("allowed/t", b"y")
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_message_publish_rewrite():
    async def main():
        provider = DemoProvider(rewrite=("in/t", "out/t"))
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            sub = Client(clientid="s1", port=node_port(node))
            await sub.connect()
            await sub.subscribe("out/#", qos=0)
            pub = Client(clientid="p1", port=node_port(node))
            await pub.connect()
            await pub.publish("in/t", b"m", qos=1)
            msg = await sub.recv()
            assert msg.topic == "out/t"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_notification_events_stream():
    async def main():
        provider = DemoProvider()
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            c = Client(clientid="c1", port=node_port(node))
            await c.connect()
            await c.subscribe("a/b", qos=0)
            await c.unsubscribe("a/b")
            await c.disconnect()
            assert await settle(
                lambda: ("connected", "c1") in provider.events
                and ("subscribed", "c1", "a/b") in provider.events
                and ("unsubscribed", "c1", "a/b") in provider.events
                and ("disconnected", "c1") in provider.events
            ), provider.events
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_failure_action_deny_vs_ignore():
    async def main():
        provider = DemoProvider(fail_methods={"OnClientAuthenticate"})
        server, port = await start_provider(provider)
        # ignore → fail-open, clients still connect
        node = await start_node_with_exhook(port, failure_action="ignore")
        try:
            c = Client(clientid="c1", port=node_port(node))
            await c.connect()
            await c.disconnect()
        finally:
            await node.stop()
        # deny → fail-closed, connect refused
        node = await start_node_with_exhook(port, failure_action="deny")
        try:
            c = Client(clientid="c2", port=node_port(node))
            with pytest.raises(Exception):
                await c.connect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_server_down_fails_open():
    async def main():
        # nothing listening on the port: load fails, broker runs normally
        node = await start_node_with_exhook(1)  # port 1: connection refused
        try:
            c = Client(clientid="c1", port=node_port(node))
            await c.connect()
            await c.subscribe("x", qos=0)
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


def test_server_down_deny_policy_fails_closed_then_recovers():
    """failure_action=deny + unreachable server: advisory ops refused
    until the reconnect loop restores the server."""
    from emqx_tpu.exhook.manager import ExHookManager

    async def main():
        old = ExHookManager.RECONNECT_INTERVAL
        ExHookManager.RECONNECT_INTERVAL = 0.1
        provider = DemoProvider()
        # reserve a port, then kill the server so load fails
        server, port = await start_provider(provider)
        await server.stop(None)
        node = await start_node_with_exhook(port, failure_action="deny")
        try:
            c = Client(clientid="c1", port=node_port(node))
            with pytest.raises(Exception):
                await c.connect()  # fail-closed while server is down
            # bring a provider back on the same port; reconnect loop heals
            server2 = grpc.aio.server()
            add_hook_provider_to_server(provider, server2)
            assert server2.add_insecure_port(f"127.0.0.1:{port}") == port
            await server2.start()
            assert await settle(
                lambda: node.exhook.servers[0].stub is not None
            )
            c2 = Client(clientid="c2", port=node_port(node))
            await c2.connect()
            await c2.disconnect()
            await server2.stop(None)
        finally:
            ExHookManager.RECONNECT_INTERVAL = old
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# TPU sidecar: mirror + batched device match
# ---------------------------------------------------------------------------


async def start_sidecar(**kw):
    sidecar = TpuMatchSidecar(**kw)
    server = grpc.aio.server()
    add_hook_provider_to_server(sidecar, server)
    add_mirror_sync_to_server(sidecar, server)
    port = server.add_insecure_port("127.0.0.1:0")
    await sidecar.start()
    await server.start()
    return server, sidecar, port


FILTERS = ["s/+/t", "s/#", "a/b", "+/b", "$SYS/x", "deep/+/x/#"]
TOPICS = ["s/1/t", "s/9/zz", "a/b", "$SYS/x", "nomatch/q", "deep/k/x/y/z"]


def test_sidecar_delta_feed_and_match_batch():
    async def main():
        server, sidecar, port = await start_sidecar(
            rebuild_debounce_s=0.01, batch_window_ms=1.0
        )
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        mirror = MirrorSyncStub(chan)
        try:
            resp = await hooks.OnProviderLoaded(
                pb.ProviderLoadedRequest(meta=pb.RequestMeta(node="n1"))
            )
            names = [h.name for h in resp.hooks]
            assert "session.subscribed" in names and "message.publish" in names

            for flt in FILTERS:
                await hooks.OnSessionSubscribed(
                    pb.SessionSubscribedRequest(
                        clientinfo=pb.ClientInfo(clientid="c1"), topic=flt
                    )
                )
            assert await settle(lambda: synced(sidecar))

            resp = await mirror.MatchBatch(
                pb.MatchBatchRequest(topics=TOPICS)
            )
            # id resolution over the wire, as an external broker would
            ft = await mirror.FilterTable(pb.FilterTableRequest())
            assert ft.table_version == resp.table_version
            assert list(ft.filters) == sidecar.filter_table()
            table = list(ft.filters)
            for topic, row in zip(TOPICS, resp.results):
                got = sorted(table[i] for i in row.filter_ids)
                want = sorted(f for f in FILTERS if T.match(topic, f))
                assert got == want, (topic, got, want)

            # unsubscribe drops the filter from the mirror
            await hooks.OnSessionUnsubscribed(
                pb.SessionUnsubscribedRequest(
                    clientinfo=pb.ClientInfo(clientid="c1"), topic="a/b"
                )
            )
            assert await settle(
                lambda: "a/b" not in sidecar.filter_table()
            )

            stats = await mirror.Stats(pb.StatsRequest())
            assert stats.n_filters == len(FILTERS) - 1
            assert stats.batches >= 1
        finally:
            await chan.close()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_sidecar_snapshot_install_and_publish_hook():
    async def main():
        server, sidecar, port = await start_sidecar(
            rebuild_debounce_s=0.01, annotate=True
        )
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        mirror = MirrorSyncStub(chan)
        try:
            async def chunks():
                yield pb.SnapshotChunk(
                    epoch=7, filters=FILTERS[:3], refcounts=[1, 2, 1]
                )
                yield pb.SnapshotChunk(
                    epoch=7, filters=FILTERS[3:], refcounts=[1] * 3, last=True
                )

            ack = await mirror.InstallSnapshot(chunks())
            assert ack.epoch == 7 and ack.n_filters == len(FILTERS)
            assert await settle(lambda: synced(sidecar))

            resp = await hooks.OnMessagePublish(
                pb.MessagePublishRequest(
                    message=pb.Message(topic="s/1/t", payload=b"x")
                )
            )
            assert resp.type == pb.ValuedResponse.STOP_AND_RETURN
            want = len([f for f in FILTERS if T.match("s/1/t", f)])
            assert resp.message.headers["matched_filters"] == str(want)
        finally:
            await chan.close()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_v311_suback_deny_uses_0x80():
    """3.1.1 only knows granted-QoS and 0x80 failure (spec §3.9.3)."""

    async def main():
        provider = DemoProvider(deny_topics={"forbidden/t"})
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            c = Client(clientid="v3", port=node_port(node), proto_ver=4)
            await c.connect()
            codes = await c.subscribe("forbidden/t", qos=1)
            assert codes == [0x80]
            await c.disconnect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_topic_alias_cannot_bypass_authorize():
    """A denied aliased publish must not leak through via alias-only
    retries (the alias never registers because the packet never reaches
    the channel)."""

    async def main():
        provider = DemoProvider(deny_topics={"forbidden/t"})
        server, port = await start_provider(provider)
        node = await start_node_with_exhook(port)
        try:
            spy = Client(clientid="spy", port=node_port(node), proto_ver=5)
            await spy.connect()
            await spy.subscribe("#", qos=0)

            pub = Client(clientid="p1", port=node_port(node), proto_ver=5)
            await pub.connect()
            rc = await pub.publish(
                "forbidden/t", b"x", qos=1,
                properties={"Topic-Alias": 1},
            )
            assert rc == P.RC.NOT_AUTHORIZED
            # alias-only retry: unknown alias → channel drops the conn,
            # and nothing ever reaches the subscriber
            try:
                await pub.publish(
                    "", b"y", qos=1, properties={"Topic-Alias": 1},
                    timeout=2.0,
                )
            except Exception:
                pass
            with pytest.raises(asyncio.TimeoutError):
                await spy.recv(timeout=0.5)
            await spy.disconnect()
        finally:
            await node.stop()
            await server.stop(None)

    run(main())


def test_shared_sub_filter_stripped_for_mirror():
    """session.subscribed events carry the routing filter — $share/<g>/
    stripped — so the sidecar mirror can actually match topics."""

    async def main():
        server, sidecar, port = await start_sidecar(rebuild_debounce_s=0.01)
        node = await start_node_with_exhook(port)
        try:
            c = Client(clientid="c1", port=node_port(node), proto_ver=5)
            await c.connect()
            await c.subscribe("$share/g1/room/+/temp", qos=0)
            assert await settle(
                lambda: "room/+/temp" in sidecar.filter_table()
            ), sidecar.filter_table()
            await c.unsubscribe("$share/g1/room/+/temp")
            assert await settle(
                lambda: "room/+/temp" not in sidecar.filter_table()
            )
            await c.disconnect()
        finally:
            await node.stop()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_sidecar_deep_filters_merge_host_side():
    """Filters deeper than the device table depth still match (served
    from the host trie and merged into device results)."""

    async def main():
        server, sidecar, port = await start_sidecar(
            rebuild_debounce_s=0.01, depth=4
        )
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        mirror = MirrorSyncStub(chan)
        try:
            deep = "a/b/c/d/e/+/g"          # 7 levels > depth 4
            shallow = "a/#"
            for flt in (deep, shallow):
                await hooks.OnSessionSubscribed(
                    pb.SessionSubscribedRequest(
                        clientinfo=pb.ClientInfo(clientid="c1"), topic=flt
                    )
                )
            assert await settle(lambda: synced(sidecar))
            topics = ["a/b/c/d/e/f/g", "a/x"]
            resp = await mirror.MatchBatch(pb.MatchBatchRequest(topics=topics))
            table = sidecar.filter_table()
            got = [sorted(table[i] for i in r.filter_ids)
                   for r in resp.results]
            assert got[0] == sorted([deep, shallow]), got
            assert got[1] == [shallow], got
        finally:
            await chan.close()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_broker_feeds_sidecar_mirror_end_to_end():
    """BrokerNode → exhook → sidecar: real subscribe events populate the
    mirror; OnMessagePublish rides the micro-batch loop."""

    async def main():
        server, sidecar, port = await start_sidecar(
            rebuild_debounce_s=0.01, batch_window_ms=0.5
        )
        node = await start_node_with_exhook(port)
        try:
            c = Client(clientid="c1", port=node_port(node))
            await c.connect()
            await c.subscribe("room/+/temp", qos=0)
            assert await settle(
                lambda: "room/+/temp" in sidecar.filter_table()
            )
            # wait for the device engine so the publish rides the counted
            # micro-batch path, not the host fail-open fallback
            assert await settle(lambda: synced(sidecar))
            await c.publish("room/7/temp", b"21.5")
            msg = await c.recv()
            assert msg.payload == b"21.5"
            assert await settle(lambda: sidecar.topics_matched >= 1)
            await c.disconnect()
        finally:
            await node.stop()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_sidecar_overflow_fails_open_to_host_trie():
    """Force active-set overflow (A=2, heavy '+' fan-in) and match-count
    overflow (K=4): spilled rows must be re-run on the host trie so the
    combined answer is exactly the oracle's (VERDICT.md weak item 1)."""

    async def main():
        server, sidecar, port = await start_sidecar(
            rebuild_debounce_s=0.01, active_slots=2, max_matches=4
        )
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        mirror = MirrorSyncStub(chan)
        try:
            # 8 filters all matching a/b/c with distinct prefixes ⇒ the
            # active set needs >2 slots and the row matches >4 filters
            flts = (
                ["a/b/c", "+/b/c", "a/+/c", "a/b/+", "+/+/c", "a/+/+",
                 "+/b/+", "+/+/+", "a/#", "#"]
            )
            for flt in flts:
                await hooks.OnSessionSubscribed(
                    pb.SessionSubscribedRequest(
                        clientinfo=pb.ClientInfo(clientid="c1"), topic=flt
                    )
                )
            assert await settle(lambda: synced(sidecar))
            topics = ["a/b/c", "z/b/c", "none"]
            resp = await mirror.MatchBatch(pb.MatchBatchRequest(topics=topics))
            table = sidecar.filter_table()
            for topic, row in zip(topics, resp.results):
                got = sorted(table[i] for i in row.filter_ids)
                want = sorted(f for f in flts if T.match(topic, f))
                assert got == want, (topic, got, want)
            assert sidecar.spill_fallbacks >= 1  # the fail-open path ran
            stats = await mirror.Stats(pb.StatsRequest())
            assert int(stats.extra["spill_fallbacks"]) >= 1
        finally:
            await chan.close()
            await sidecar.stop()
            await server.stop(None)

    run(main())


def test_sidecar_incremental_no_reupload_under_churn():
    """Steady-state filter churn must ride the delta path: no device
    re-uploads, no table rebuilds (VERDICT.md round-1 item 1)."""

    async def main():
        server, sidecar, port = await start_sidecar(rebuild_debounce_s=0.005)
        chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        hooks = HookProviderStub(chan)
        mirror = MirrorSyncStub(chan)
        try:
            for i in range(64):
                await hooks.OnSessionSubscribed(
                    pb.SessionSubscribedRequest(
                        clientinfo=pb.ClientInfo(clientid="c"),
                        topic=f"base/{i}/+",
                    )
                )
            assert await settle(lambda: synced(sidecar))
            uploads0 = sidecar._eng.dev.uploads
            for i in range(40):
                await hooks.OnSessionSubscribed(
                    pb.SessionSubscribedRequest(
                        clientinfo=pb.ClientInfo(clientid="c"),
                        topic=f"churn/{i}",
                    )
                )
                if i % 2:
                    await hooks.OnSessionUnsubscribed(
                        pb.SessionUnsubscribedRequest(
                            clientinfo=pb.ClientInfo(clientid="c"),
                            topic=f"churn/{i}",
                        )
                    )
            assert await settle(
                lambda: not sidecar._dirty.is_set()
                and sidecar._eng.dev.epoch == sidecar._eng.inc.epoch
            )
            assert sidecar._eng.dev.uploads == uploads0
            assert sidecar._eng.dev.delta_applies >= 1
            resp = await mirror.MatchBatch(
                pb.MatchBatchRequest(topics=["churn/2", "base/3/x"])
            )
            table = sidecar.filter_table()
            assert sorted(
                table[i] for i in resp.results[0].filter_ids
            ) == ["churn/2"]
            assert sorted(
                table[i] for i in resp.results[1].filter_ids
            ) == ["base/3/+"]
        finally:
            await chan.close()
            await sidecar.stop()
            await server.stop(None)

    run(main())
