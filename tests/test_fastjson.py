"""Native single-field JSON extractor: exact parity with json.loads on
everything it claims to handle, and bail-to-fallback on everything else
(the jiffy-analog, SURVEY.md §2.4)."""

import json

import pytest

from emqx_tpu.native import fastjson

pytestmark = pytest.mark.skipif(
    not fastjson.available(), reason="native toolchain unavailable")


def oracle(doc: bytes, path):
    """What the fallback would produce, or BAIL-equivalent None info."""
    try:
        val = json.loads(doc)
    except ValueError:
        return None
    for p in path:
        if not isinstance(val, dict) or p not in val:
            return None
        val = val[p]
    return val


CASES = [
    (b'{"a": 1}', ("a",), True, 1),
    (b'{"a": -17}', ("a",), True, -17),
    (b'{"a": 1.5e3}', ("a",), True, 1500.0),
    (b'{"a": "x y z"}', ("a",), True, "x y z"),
    (b'{"a": true, "b": false, "c": null}', ("b",), True, False),
    (b'{"a": true, "b": false, "c": null}', ("c",), True, None),
    (b'{"a": {"b": {"c": 42}}}', ("a", "b", "c"), True, 42),
    (b'  {  "a" :\t{"b": 7}\n}  ', ("a", "b"), True, 7),
    # skipping siblings of every type
    (b'{"x": [1, {"y": "]"}, "}"], "a": {"n": [""]}, "t": 9}',
     ("t",), True, 9),
    # duplicate keys: json.loads keeps the LAST one
    (b'{"a": 1, "a": 2}', ("a",), True, 2),
    (b'{"a": {"k": 1}, "a": {"k": 9}}', ("a", "k"), True, 9),
    # unicode (no escapes) round-trips
    ('{"ключ": "значение"}'.encode(), ("ключ",), True, "значение"),
    # bails: escaped string value
    (b'{"a": "x\\ny"}', ("a",), False, None),
    # bails: escaped key anywhere in the object
    (b'{"\\u0061": 1}', ("a",), False, None),
    # bails: result is a container
    (b'{"a": {"b": 1}}', ("a",), False, None),
    (b'{"a": [1, 2]}', ("a",), False, None),
    # bails: int beyond long long
    (b'{"a": 99999999999999999999999999}', ("a",), False, None),
    # bails: missing key / wrong shape / malformed
    (b'{"a": 1}', ("zz",), False, None),
    (b'{"a": "str"}', ("a", "deeper"), False, None),
    (b'[1, 2, 3]', ("a",), False, None),
    (b'not json at all', ("a",), False, None),
    (b'{"a": ', ("a",), False, None),
    # strictness: everything json.loads rejects must BAIL even when the
    # requested key parsed fine (the whole document is invalid)
    (b'{"a": 25}garbage', ("a",), False, None),
    (b'{"a": 25,}', ("a",), False, None),
    (b'{"a": 025}', ("a",), False, None),
    (b'{"a": +5}', ("a",), False, None),
    (b'{"a": .5}', ("a",), False, None),
    (b'{"a": 5.}', ("a",), False, None),
    (b'{"a": 1, "b": tru}', ("a",), False, None),
    (b'{"a": 1, "b": "unterminated}', ("a",), False, None),
    (b'{"a": 1, "b": "ctrl\nchar"}', ("a",), False, None),
    (b'{"a": 1, "b": "bad \\x esc"}', ("a",), False, None),
    (b'{"a": 1, "b": "\xff"}', ("a",), False, None),     # invalid utf-8
    (b'{"a": 1, "b": [1, 2,]}', ("a",), False, None),
    (b'{"a": 1 "b": 2}', ("a",), False, None),
    (b'{"a": 1, 5: 2}', ("a",), False, None),
    (b'{"a": NaN, "b": 2}', ("b",), False, None),  # loads accepts; we bail
]


@pytest.mark.parametrize("doc,path,want_found,want", CASES)
def test_cases(doc, path, want_found, want):
    found, val = fastjson.get_path(doc, path)
    assert found == want_found, (doc, path, found, val)
    if want_found:
        assert val == want and type(val) is type(want)
        assert val == oracle(doc, path)


def test_randomized_parity():
    """Fuzz parity: whenever the native path claims found, the value
    must equal the json.loads walk byte-for-byte."""
    import random

    rng = random.Random(7)
    scalars = [1, -5, 0, 2.5, -0.125, True, False, None, "s", "longer str",
               "unié", 10**12]

    def gen(depth=0):
        r = rng.random()
        if depth >= 3 or r < 0.5:
            return rng.choice(scalars)
        if r < 0.8:
            return {f"k{rng.randrange(6)}": gen(depth + 1)
                    for _ in range(rng.randrange(1, 5))}
        return [gen(depth + 1) for _ in range(rng.randrange(3))]

    checked_found = 0
    for _ in range(400):
        doc_obj = {f"k{i}": gen() for i in range(rng.randrange(1, 6))}
        doc = json.dumps(doc_obj).encode()
        path = tuple(f"k{rng.randrange(6)}"
                     for _ in range(rng.randrange(1, 4)))
        found, val = fastjson.get_path(doc, path)
        if found:
            checked_found += 1
            want = oracle(doc, path)
            assert val == want and type(val) is type(want), (doc, path)
    assert checked_found > 20   # the fast path actually fires


def test_mutation_fuzz_never_diverges():
    """Corrupt valid documents byte-by-byte: wherever the native path
    still claims found, json.loads must agree (parse AND value)."""
    import random

    rng = random.Random(11)
    base = json.dumps({"temp": 25, "tag": "ok", "m": {"x": 1.5, "y": None},
                       "arr": [1, "two", {"z": True}]}).encode()
    paths = [("temp",), ("tag",), ("m", "x"), ("m", "y"), ("nope",)]
    for _ in range(3000):
        doc = bytearray(base)
        for _ in range(rng.randrange(1, 3)):
            doc[rng.randrange(len(doc))] = rng.randrange(256)
        doc = bytes(doc)
        for path in paths:
            found, val = fastjson.get_path(doc, path)
            if found:
                want = oracle(doc, path)  # None if loads rejects the doc
                assert val == want and type(val) is type(want), (doc, path)


def test_rule_engine_uses_fast_path_with_identical_results():
    """End-to-end: rules over JSON payloads produce identical outputs
    with the native extractor available (it is, in this env) — and the
    memoized-decode fallback still serves multi-field/odd shapes."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.message import make_message
    from emqx_tpu.rule_engine.engine import RuleEngine

    broker = Broker(node="n@test")
    engine = RuleEngine(broker)
    out = []
    engine.create_rule(
        "r1", 'SELECT payload.temp as t, payload.meta.site as s, clientid '
              'FROM "sens/+" WHERE payload.temp > 20',
        actions=[lambda o, c: out.append(o)])
    broker.publish(make_message(
        "c1", "sens/a",
        json.dumps({"temp": 25, "meta": {"site": "x"}}).encode()))
    broker.publish(make_message(
        "c1", "sens/b",
        json.dumps({"temp": 5, "meta": {"site": "y"}}).encode()))
    # escaped content forces the fallback mid-stream: same answers
    broker.publish(make_message(
        "c1", "sens/c",
        json.dumps({"temp": 30, "meta": {"site": "a\"b"}}).encode()))
    assert out == [
        {"t": 25, "s": "x", "clientid": "c1"},
        {"t": 30, "s": 'a"b', "clientid": "c1"},
    ]


def test_mixed_payload_and_bare_key_access():
    """Review finding: a native payload.x hit must not starve LATER
    bare-key lookups that rely on the decoded payload."""
    import json as _json

    from emqx_tpu.rule_engine.runtime import EvalContext

    ctx = EvalContext({"payload": _json.dumps(
        {"temp": 25, "humidity": 60}).encode(), "clientid": "c1"})
    assert ctx.resolve(["payload", "temp"]) == 25       # fast path
    assert ctx.resolve(["humidity"]) == 60              # bare key works
    assert ctx.resolve(["clientid"]) == "c1"


def test_empty_path_segment_bails():
    assert fastjson.get_path(b'42 garbage', ("",)) == (False, None)
    assert fastjson.get_path(b'{"a": 7}', ("a", "")) == (False, None)
    assert fastjson.get_path(b'{"a": 7}', ()) == (False, None)
