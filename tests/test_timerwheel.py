"""Hashed timer wheel (transport/timerwheel.py): bucket rounding,
O(1) cancel, the one-scheduled-callback-per-tick contract (spy on the
loop's ``call_later``), mass-expiry parity against per-connection
``loop.call_later``, periodic re-insertion and the awaitable sleep."""

import asyncio

from emqx_tpu.transport.timerwheel import TimerWheel


def run(coro):
    return asyncio.run(coro)


def test_bucket_rounding_never_fires_early():
    # injectable clock: delays round UP to the next bucket boundary
    now = [100.0]
    w = TimerWheel(tick_s=1.0, clock=lambda: now[0])
    t = w.call_later(0.01, lambda: None)
    assert t.slot == 101          # not the current bucket (100)
    t2 = w.call_later(1.0, lambda: None)
    assert t2.slot == 101         # exactly on a boundary: fires there
    t3 = w.call_later(1.5, lambda: None)
    assert t3.slot == 102         # ceil: 1.5 waits for boundary 102
    t4 = w.call_later(2.0, lambda: None)
    assert t4.slot == 102
    now[0] = 100.9
    assert w.call_later(0.0, lambda: None).slot == 101
    w.close()


def test_cancel_is_o1_and_skipped_at_expiry():
    async def main():
        w = TimerWheel(tick_s=0.05)
        fired = []
        timers = [w.call_later(0.05, lambda i=i: fired.append(i))
                  for i in range(10)]
        for t in timers[::2]:
            t.cancel()
        await asyncio.sleep(0.2)
        assert sorted(fired) == [1, 3, 5, 7, 9]
        assert len(w) == 0        # cancelled entries reaped at advance
        w.close()

    run(main())


def test_one_scheduled_callback_per_tick_regardless_of_timers():
    """The wheel keeps exactly ONE loop.call_later outstanding: a
    1000-connection keepalive storm costs one scheduled callback whose
    body walks the bucket — spy-asserted on the loop."""
    async def main():
        loop = asyncio.get_running_loop()
        orig = loop.call_later
        sched = []

        def spy(delay, cb, *args):
            sched.append(cb)
            return orig(delay, cb, *args)

        loop.call_later = spy
        try:
            w = TimerWheel(tick_s=0.05)
            fired = []
            for i in range(1000):
                w.call_later(0.05, lambda i=i: fired.append(i))
            wheel_scheds = [cb for cb in sched if cb == w._advance]
            assert len(wheel_scheds) == 1   # ONE timer for 1000 entries
            await asyncio.sleep(0.15)
            assert len(fired) == 1000       # all ran from that callback
            # each advance re-arms at most once
            assert len([cb for cb in sched if cb == w._advance]) \
                <= w.ticks + 1
            w.close()
        finally:
            loop.call_later = orig

    run(main())


def test_mass_expiry_parity_with_per_conn_call_later():
    """Same observable effects as N per-connection loop.call_later
    timers: every callback fires exactly once, late-not-early."""
    async def main():
        loop = asyncio.get_running_loop()
        w = TimerWheel(tick_s=0.05)
        wheel_fired = []
        loop_fired = []
        t0 = loop.time()
        for i in range(50):
            w.call_later(0.08, lambda i=i: wheel_fired.append(
                (i, loop.time() - t0)))
            loop.call_later(0.08, lambda i=i: loop_fired.append(i))
        await asyncio.sleep(0.3)
        assert sorted(i for i, _ in wheel_fired) == sorted(loop_fired)
        # late, never early (bucket rounding)
        assert all(dt >= 0.08 - 1e-3 for _, dt in wheel_fired)
        w.close()

    run(main())


def test_call_repeat_reinserts_and_cancels():
    async def main():
        w = TimerWheel(tick_s=0.03)
        ticks = []
        t = w.call_repeat(0.03, lambda: ticks.append(1))
        await asyncio.sleep(0.2)
        assert len(ticks) >= 3
        t.cancel()
        n = len(ticks)
        await asyncio.sleep(0.1)
        assert len(ticks) == n
        assert len(w) == 0
        w.close()

    run(main())


def test_sleep_awaitable_and_cancellation_cleanup():
    async def main():
        w = TimerWheel(tick_s=0.03)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await w.sleep(0.05)
        assert loop.time() - t0 >= 0.05 - 1e-3
        # a cancelled sleeper reaps its wheel entry
        task = asyncio.ensure_future(w.sleep(5.0))
        await asyncio.sleep(0.01)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await asyncio.sleep(0.07)   # let an advance reap it
        assert len(w) == 0
        w.close()

    run(main())


def test_close_drops_everything_and_new_inserts_are_dead():
    async def main():
        w = TimerWheel(tick_s=0.03)
        fired = []
        w.call_later(0.03, lambda: fired.append(1))
        w.close()
        t = w.call_later(0.03, lambda: fired.append(2))
        assert t.cancelled
        await asyncio.sleep(0.1)
        assert fired == []

    run(main())


def test_callback_exception_does_not_stop_the_wheel():
    async def main():
        w = TimerWheel(tick_s=0.03)
        fired = []

        def boom():
            raise RuntimeError("x")

        w.call_later(0.03, boom)
        w.call_later(0.03, lambda: fired.append(1))
        w.call_later(0.09, lambda: fired.append(2))
        await asyncio.sleep(0.2)
        assert fired == [1, 2]
        w.close()

    run(main())
