"""Database egress bridges (Redis / PostgreSQL / MongoDB / InfluxDB)
against the SAME in-test wire-protocol mocks the auth backends use —
rule → bridge delivery through live nodes (emqx_bridge_redis/pgsql/
mongodb/influxdb analogs)."""

import asyncio
import json

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode

from test_mongo_ldap_auth import MockMongo
from test_sql_auth import MockPg


def run(coro):
    return asyncio.run(coro)


async def start_node():
    node = BrokerNode(Config(
        file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n'))
    await node.start()
    return node


async def settle_success(br, want=1, tries=600):
    for _ in range(tries):
        if br.worker.metrics["success"] >= want:
            return True
        await asyncio.sleep(0.01)
    return False


class MockRedisStore:
    """RESP2 server recording LPUSH/PING (bridge-side command subset)."""

    def __init__(self):
        self.lists = {}
        self.port = 0
        self._conns = set()

    async def start(self):
        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                while True:
                    line = await reader.readline()
                    if not line.startswith(b"*"):
                        return
                    n = int(line[1:-2])
                    parts = []
                    for _ in range(n):
                        ln = int((await reader.readline())[1:-2])
                        parts.append(await reader.readexactly(ln + 2))
                    cmd = parts[0][:-2].decode().upper()
                    if cmd == "PING":
                        writer.write(b"+PONG\r\n")
                    elif cmd == "LPUSH":
                        key = parts[1][:-2].decode()
                        self.lists.setdefault(key, []).insert(
                            0, parts[2][:-2])
                        writer.write(b":%d\r\n" % len(self.lists[key]))
                    else:
                        writer.write(b"-ERR unknown\r\n")
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


def test_redis_bridge_lpush_via_rule():
    async def main():
        rs = await MockRedisStore().start()
        node = await start_node()
        try:
            await node.bridges.create("redis", "rq", {
                "server": f"127.0.0.1:{rs.port}",
                "command": ["LPUSH", "q:${topic}", "${payload}"],
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rr", 'SELECT topic, payload FROM "ev/#"',
                actions=["redis:rq"])
            pub = Client(clientid="p", port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/1", b"r-payload")
            br = node.bridges.get("redis:rq")
            assert await settle_success(br)
            assert rs.lists["q:ev/1"] == [b"r-payload"]
            await pub.disconnect()
        finally:
            await node.stop()
            await rs.stop()

    run(main())


def test_pgsql_bridge_insert_with_bind_params():
    async def main():
        inserts = []

        def insert_log(params):
            inserts.append(tuple(params))
            return [], []

        pg = await MockPg({"mqtt_messages": insert_log}).start()
        node = await start_node()
        try:
            await node.bridges.create("pgsql", "pgb", {
                "server": f"127.0.0.1:{pg.port}",
                "user": "broker", "password": "dbpw",
                "sql": "INSERT INTO mqtt_messages (c, t, p) "
                       "VALUES (${1}, ${2}, ${3})",
                "parameters": ["${clientid}", "${topic}", "${payload}"],
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rp", 'SELECT clientid, topic, payload FROM "ev/#"',
                actions=["pgsql:pgb"])
            pub = Client(clientid="pgpub",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            # payload with SQL metacharacters must ride bind params
            await pub.publish("ev/2", b"x'); DROP TABLE users;--")
            br = node.bridges.get("pgsql:pgb")
            assert await settle_success(br)
            assert inserts == [("pgpub", "ev/2",
                                "x'); DROP TABLE users;--")]
            # the SQL text itself never contained the payload
            assert all("DROP TABLE" not in q for q, _ in pg.queries)
        finally:
            await node.stop()
            await pg.stop()

    run(main())


def test_mongodb_bridge_insert_documents():
    async def main():
        mongo = await MockMongo({}).start()
        node = await start_node()
        try:
            await node.bridges.create("mongodb", "mgb", {
                "server": f"127.0.0.1:{mongo.port}",
                "collection": "mqtt_messages",
                "payload_template": {"client": "${clientid}",
                                     "t": "${topic}",
                                     "body": "${payload}"},
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "rm", 'SELECT clientid, topic, payload FROM "ev/#"',
                actions=["mongodb:mgb"])
            pub = Client(clientid="mgpub",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/3", b"doc-body")
            br = node.bridges.get("mongodb:mgb")
            assert await settle_success(br)
            assert mongo.collections["mqtt_messages"] == [
                {"client": "mgpub", "t": "ev/3", "body": "doc-body"}]
        finally:
            await node.stop()
            await mongo.stop()

    run(main())


def test_influxdb_bridge_line_protocol():
    async def main():
        writes = []

        async def handle(reader, writer):
            try:
                head = await reader.readuntil(b"\r\n\r\n")
                lines = head.decode().split("\r\n")
                n = 0
                for ln in lines:
                    if ln.lower().startswith("content-length:"):
                        n = int(ln.split(":")[1])
                body = await reader.readexactly(n) if n else b""
                writes.append((lines[0], body))
                writer.write(b"HTTP/1.1 204 No Content\r\n"
                             b"content-length: 0\r\n"
                             b"connection: close\r\n\r\n")
                await writer.drain()
            except Exception:
                pass
            finally:
                writer.close()

        srv = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        node = await start_node()
        try:
            await node.bridges.create("influxdb", "ifx", {
                "server": f"http://127.0.0.1:{port}",
                "bucket": "iot", "org": "acme", "token": "tkn",
                "measurement": "mqtt",
                "tags": {"topic": "${topic}"},
                "fields": {"val": "${payload}", "who": "${clientid}"},
                "resource_opts": {"batch_size": 4, "retry_base": 0.01},
            })
            node.rule_engine.create_rule(
                "ri", 'SELECT clientid, topic, payload FROM "ev/#"',
                actions=["influxdb:ifx"])
            pub = Client(clientid="ipub",
                         port=node.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("ev/t 1", b"42.5")  # space needs escaping
            br = node.bridges.get("influxdb:ifx")
            assert await settle_success(br)
            reqline, body = writes[0]
            assert "bucket=iot" in reqline and "org=acme" in reqline
            assert body == b'mqtt,topic=ev/t\\ 1 val=42.5,who="ipub"'
        finally:
            await node.stop()
            await pub.disconnect()
            srv.close()

    run(main())


def test_render_influx_field_typing_and_escaping():
    from emqx_tpu.bridge.db import render_influx

    out = {"payload": b"not-a-number", "topic": "a,b c", "clientid": "q\"x"}
    item = render_influx({"fields": {"v": "${payload}"},
                          "tags": {"t": "${topic}"}}, out, out)
    assert item["line"] == 'mqtt,t=a\\,b\\ c v="not-a-number"'
    item = render_influx({"fields": {"v": "3.5"}}, out, out)
    assert item["line"].endswith(" v=3.5")
