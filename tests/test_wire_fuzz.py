"""Malformed-input robustness for the hand-rolled wire parsers (Kafka
record batches, BSON, BER/LDAP, MySQL lenenc) — a buggy or hostile
server must produce a clean Python exception, never a hang, wrong-type
crash deep in a loop, or silent corruption.  Mirrors the gateway codec
fuzz suite's posture."""

import random
import struct

import pytest

from emqx_tpu.auth.ldap import ber, ber_parse
from emqx_tpu.auth.mongo import bson_decode, bson_encode
from emqx_tpu.auth.mysql import _lenenc
from emqx_tpu.bridge.kafka import (
    parse_batches, parse_record_batch, record_batch,
)

# NOTE: MemoryError is deliberately NOT acceptable — a parser trusting
# an attacker-controlled length into a giant allocation is exactly the
# DoS this suite exists to reject
OK_ERRORS = (ValueError, KeyError, IndexError, struct.error,
             UnicodeDecodeError, OverflowError)


def _mutations(blob: bytes, rng: random.Random, n: int = 200):
    for _ in range(n):
        b = bytearray(blob)
        op = rng.randrange(3)
        if op == 0 and b:                      # flip a byte
            i = rng.randrange(len(b))
            b[i] ^= rng.randrange(1, 256)
        elif op == 1:                          # truncate
            b = b[: rng.randrange(len(b) + 1)]
        else:                                  # splice garbage
            i = rng.randrange(len(b) + 1)
            b[i:i] = bytes(rng.randrange(256)
                           for _ in range(rng.randrange(1, 9)))
        yield bytes(b)


def test_kafka_batch_parser_survives_mutation():
    from emqx_tpu.bridge.kafka import KafkaError

    rng = random.Random(7)
    base = record_batch([(b"k", b"v1"), (None, b"v2"), (b"", b"")],
                        base_offset=5)
    for blob in _mutations(base, rng, 300):
        try:
            parse_batches(blob)
        except (KafkaError, *OK_ERRORS):
            pass
        try:
            parse_record_batch(blob)
        except (KafkaError, *OK_ERRORS):
            pass


def test_bson_decoder_survives_mutation():
    from emqx_tpu.auth.mongo import MongoError

    rng = random.Random(11)
    base = bson_encode({"a": 1, "s": "xx", "n": None, "d": {"k": True},
                        "arr": [1, "two", 3.5], "big": 2 ** 40})
    for blob in _mutations(base, rng, 300):
        try:
            bson_decode(blob)
        except (MongoError, *OK_ERRORS):
            pass


def test_ber_parser_survives_mutation():
    rng = random.Random(13)
    base = ber(0x30, ber(0x02, b"\x01") + ber(0x04, b"hello")
               + ber(0x61, ber(0x0A, b"\x00")))
    for blob in _mutations(base, rng, 300):
        try:
            tag, payload, off = ber_parse(blob)
            # walk children like the LDAP client does
            o = 0
            while o < len(payload):
                _, _, o2 = ber_parse(payload, o)
                if o2 <= o:          # must always advance
                    break
                o = o2
        except OK_ERRORS:
            pass


def test_bson_negative_length_rejected_not_looped():
    """Regression: a negative string length moved the cursor BACKWARD,
    spinning _dec_doc forever (hostile-server one-packet DoS)."""
    from emqx_tpu.auth.mongo import MongoError

    doc = bytearray(bson_encode({"a": "x"}))
    # element 'a' (0x02): overwrite its int32 length with -7
    i = doc.index(b"\x02a\x00") + 3
    doc[i:i + 4] = (-7).to_bytes(4, "little", signed=True)
    with pytest.raises(MongoError):
        bson_decode(bytes(doc))
    with pytest.raises(MongoError):
        bson_decode(b"\x00\x00\x00\x00")   # doc length < 5


def test_mysql_lenenc_survives_mutation():
    rng = random.Random(17)
    for blob in _mutations(bytes([0xFC, 0x10, 0x00]) + b"x" * 16,
                           rng, 200):
        if not blob:
            continue
        try:
            v, off = _lenenc(blob, 0)
            assert off > 0
        except OK_ERRORS:
            pass


def test_ber_zero_length_and_giant_lengths():
    # zero-length element
    tag, payload, off = ber_parse(bytes([0x04, 0x00]))
    assert (tag, payload, off) == (0x04, b"", 2)
    # declared length far past the buffer: the slice clamps to the
    # actual remaining byte — concrete expectations, not a tautology
    blob = bytes([0x30, 0x84, 0x7F, 0xFF, 0xFF, 0xFF]) + b"x"
    tag, payload, off = ber_parse(blob)
    assert tag == 0x30 and payload == b"x"
    assert off == 6 + 0x7FFFFFFF      # callers bound reads themselves


def test_kafka_batch_crc_guard_catches_flips():
    from emqx_tpu.bridge.kafka import KafkaError

    base = bytearray(record_batch([(b"k", b"payload")] * 3))
    flipped = 0
    rng = random.Random(23)
    for _ in range(50):
        b = bytearray(base)
        i = rng.randrange(21, len(b))   # flip inside the CRC'd region
        b[i] ^= 0x01
        try:
            parse_record_batch(bytes(b))
        except KafkaError:
            flipped += 1
        except OK_ERRORS:
            flipped += 1
    assert flipped == 50                # every corruption detected
