"""MongoDB and LDAP auth backends against in-test mock servers speaking
the real wire protocols (OP_MSG/BSON; LDAPv3 BER bind+search) —
including full CONNECT round trips (emqx_authn mongodb/ldap analogs)."""

import asyncio
import struct

import pytest

from emqx_tpu.auth import AuthChain, Authz
from emqx_tpu.auth.authn import Credentials, hash_password
from emqx_tpu.auth.ldap import (
    LdapAuthenticator, ber, ber_parse, RES_INVALID_CREDENTIALS,
    RES_SUCCESS,
)
from emqx_tpu.auth.mongo import (
    MongoAuthenticator, MongoAuthzSource, bson_decode, bson_encode,
)
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def test_bson_roundtrip():
    doc = {
        "find": "mqtt_user",
        "filter": {"username": "m1", "n": 3, "big": 2 ** 40,
                   "pi": 3.5, "ok": True, "none": None},
        "tags": ["a", "b", {"x": 1}],
    }
    assert bson_decode(bson_encode(doc)) == doc


class MockMongo:
    """OP_MSG server over in-memory collections with equality filters.

    ``first_batch_size`` forces cursor paging so the client's getMore
    follow-up is exercised."""

    def __init__(self, collections, first_batch_size=0, auth_users=None):
        self.collections = collections
        self.first_batch_size = first_batch_size
        self.finds = []
        self._cursors = {}
        self._next_cursor = 7
        self._conns = set()
        self.port = 0
        # SCRAM-SHA-256 mode: like a mongod with auth enabled — every
        # command except the SASL conversation requires a login
        self.scram = None
        if auth_users:
            from emqx_tpu.auth.scram import ScramAuthenticator

            self.scram = ScramAuthenticator(iterations=512)
            for u, p in auth_users.items():
                self.scram.add_user(u, p.encode())

    async def start(self):
        from emqx_tpu.auth.mongo import Binary

        async def handle(reader, writer):
            self._conns.add(writer)
            sasl = {"state": None, "authed": False}
            try:
                while True:
                    head = await reader.readexactly(16)
                    ln, reqid, _, opcode = struct.unpack("<iiii", head)
                    payload = await reader.readexactly(ln - 16)
                    assert opcode == 2013 and payload[4] == 0
                    cmd = bson_decode(payload[5:])

                    def send(reply):
                        body = struct.pack("<i", 0) + b"\x00" \
                            + bson_encode(reply)
                        writer.write(struct.pack(
                            "<iiii", 16 + len(body), 1, reqid, 2013)
                            + body)

                    if self.scram is not None:
                        if "saslStart" in cmd:
                            assert cmd["mechanism"] == "SCRAM-SHA-256"
                            assert cmd["$db"] == "admin"
                            r = self.scram.start(
                                "", None, bytes(cmd["payload"]))
                            if r[0] != "continue":
                                send({"ok": 0.0, "errmsg": r[1]})
                            else:
                                sasl["state"] = r[2]
                                send({"conversationId": 1, "done": False,
                                      "payload": Binary(r[1]),
                                      "ok": 1.0})
                            await writer.drain()
                            continue
                        if "saslContinue" in cmd:
                            if sasl["authed"]:   # empty final round trip
                                send({"conversationId": 1, "done": True,
                                      "payload": Binary(b""), "ok": 1.0})
                                await writer.drain()
                                continue
                            r = self.scram.continue_auth(
                                sasl["state"], bytes(cmd["payload"]))
                            if r[0] != "ok":
                                send({"ok": 0.0, "errmsg": r[1]})
                            else:
                                sasl["authed"] = True
                                send({"conversationId": 1, "done": False,
                                      "payload": Binary(r[3]),
                                      "ok": 1.0})
                            await writer.drain()
                            continue
                        if not sasl["authed"]:
                            send({"ok": 0.0, "code": 13,
                                  "errmsg": "command requires "
                                            "authentication"})
                            await writer.drain()
                            continue
                    if "insert" in cmd:
                        coll = cmd["insert"]
                        docs = cmd.get("documents", [])
                        self.collections.setdefault(coll, []).extend(docs)
                        reply = {"n": len(docs), "ok": 1.0}
                        body = struct.pack("<i", 0) + b"\x00" \
                            + bson_encode(reply)
                        writer.write(struct.pack(
                            "<iiii", 16 + len(body), 1, reqid, 2013)
                            + body)
                        await writer.drain()
                        continue
                    if "ping" in cmd:
                        body = struct.pack("<i", 0) + b"\x00" \
                            + bson_encode({"ok": 1.0})
                        writer.write(struct.pack(
                            "<iiii", 16 + len(body), 1, reqid, 2013)
                            + body)
                        await writer.drain()
                        continue
                    if "getMore" in cmd:
                        rest = self._cursors.pop(cmd["getMore"], [])
                        reply = {"cursor": {"nextBatch": rest, "id": 0,
                                            "ns": "mqtt.x"},
                                 "ok": 1.0}
                        body = struct.pack("<i", 0) + b"\x00" \
                            + bson_encode(reply)
                        writer.write(struct.pack(
                            "<iiii", 16 + len(body), 1, reqid, 2013)
                            + body)
                        await writer.drain()
                        continue
                    coll = cmd.get("find")
                    filt = cmd.get("filter", {})
                    self.finds.append((coll, filt))
                    docs = [d for d in self.collections.get(coll, [])
                            if all(d.get(k) == v for k, v in filt.items())]
                    if cmd.get("limit"):
                        docs = docs[:cmd["limit"]]
                    cursor_id = 0
                    if (self.first_batch_size
                            and len(docs) > self.first_batch_size):
                        cursor_id = self._next_cursor
                        self._next_cursor += 1
                        self._cursors[cursor_id] = \
                            docs[self.first_batch_size:]
                        docs = docs[:self.first_batch_size]
                    reply = {"cursor": {"firstBatch": docs,
                                        "id": cursor_id,
                                        "ns": f"mqtt.{coll}"},
                             "ok": 1.0}
                    body = struct.pack("<i", 0) + b"\x00" \
                        + bson_encode(reply)
                    writer.write(struct.pack(
                        "<iiii", 16 + len(body), 1, reqid, 2013) + body)
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


SALT = "msalt"


def mongo_fixture():
    return {
        "mqtt_user": [
            {"username": "mia",
             "password_hash": hash_password(b"mpw", "sha256",
                                            SALT.encode()),
             "salt": SALT, "is_superuser": False},
        ],
        "mqtt_acl": [
            {"username": "mia", "permission": "allow", "action": "all",
             "topics": ["open/#", "wr/%u/own"]},
            {"username": "mia", "permission": "deny",
             "action": "subscribe", "topics": "secret/#"},
        ],
    }


def test_mongo_authn_authz_roundtrip():
    async def main():
        mongo = await MockMongo(mongo_fixture()).start()
        server = f"127.0.0.1:{mongo.port}"
        chain = AuthChain(allow_anonymous=False).add(
            MongoAuthenticator(server))
        authz = Authz(sources=[MongoAuthzSource(server)],
                      no_match="deny", cache_enable=False)
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg, auth_chain=chain, authz=authz)
        await node.start()
        port = node.listeners.all()[0].port
        try:
            ok = Client(clientid="c1", port=port,
                        username="mia", password=b"mpw")
            await ok.connect()
            assert await ok.subscribe("open/news") == [0]
            assert await ok.subscribe("wr/mia/own") == [0]
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            await ok.disconnect()

            bad = Client(clientid="c2", port=port,
                         username="mia", password=b"wrong")
            with pytest.raises(MqttError):
                await bad.connect()
            unk = Client(clientid="c3", port=port,
                         username="ghost", password=b"x")
            with pytest.raises(MqttError):
                await unk.connect()
            assert ("mqtt_user", {"username": "mia"}) in mongo.finds
        finally:
            await node.stop()
            await mongo.stop()

    run(main())


def test_mongo_cursor_paging_fetches_all_rules():
    async def main():
        fixture = mongo_fixture()
        fixture["mqtt_acl"] = [
            {"username": "mia", "permission": "allow", "action": "all",
             "topics": [f"bulk/{i}"]} for i in range(5)
        ] + [{"username": "mia", "permission": "deny",
              "action": "subscribe", "topics": "secret/#"}]
        mongo = await MockMongo(fixture, first_batch_size=2).start()
        z = MongoAuthzSource(f"127.0.0.1:{mongo.port}")
        # the deciding deny rule lives beyond the first batch
        assert await z.prefetch_async(
            "c", "mia", None, "subscribe", "secret/x") == "deny"
        assert await z.prefetch_async(
            "c", "mia", None, "publish", "bulk/4") == "allow"
        await mongo.stop()

    run(main())


def test_mongo_down_server_ignores():
    async def main():
        a = MongoAuthenticator("127.0.0.1:1", timeout=0.3)
        res = await a.authenticate_async(Credentials("c", "mia", b"mpw"))
        assert res.outcome == "ignore"
        z = MongoAuthzSource("127.0.0.1:1", timeout=0.3)
        assert await z.prefetch_async(
            "c", "mia", None, "publish", "t") == "nomatch"

    run(main())


# ---------------------------------------------------------------------------
# LDAP
# ---------------------------------------------------------------------------

class MockLdap:
    """BER server: simple bind + equality search over a DN->entry dict.

    ``entries``: dn -> {"password": bytes, attrs...}.
    """

    def __init__(self, entries):
        self.entries = entries
        self.binds = []
        self._conns = set()
        self.port = 0

    @staticmethod
    def _children(payload):
        out, off = [], 0
        while off < len(payload):
            tag, body, off = ber_parse(payload, off)
            out.append((tag, body))
        return out

    async def start(self):
        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                while True:
                    head = await reader.readexactly(2)
                    ln = head[1]
                    if ln & 0x80:
                        more = await reader.readexactly(ln & 0x7F)
                        ln = int.from_bytes(more, "big")
                    payload = await reader.readexactly(ln)
                    _, body, _ = ber_parse(bytes(head) + payload)
                    children = self._children(body)
                    msgid = int.from_bytes(children[0][1], "big")
                    op_tag, op_body = children[1]
                    if op_tag == 0x60:           # BindRequest
                        parts = self._children(op_body)
                        dn = parts[1][1].decode()
                        pw = parts[2][1]
                        self.binds.append((dn, pw))
                        entry = self.entries.get(dn)
                        if dn == "" or (
                                entry is not None
                                and entry.get("password") == pw):
                            code = RES_SUCCESS
                        else:
                            code = RES_INVALID_CREDENTIALS
                        resp = ber(0x61, ber(0x0A, bytes([code]))
                                   + ber(0x04, b"") + ber(0x04, b""))
                    elif op_tag == 0x63:         # SearchRequest
                        parts = self._children(op_body)
                        filt_tag, filt_body = next(
                            (t, b) for t, b in parts if t == 0xA3)
                        fparts = self._children(filt_body)
                        attr = fparts[0][1].decode()
                        value = fparts[1][1].decode()
                        msgs = []
                        for dn, entry in self.entries.items():
                            if str(entry.get(attr)) == value:
                                attrs = b"".join(
                                    ber(0x30, ber(0x04, k.encode())
                                        + ber(0x31, ber(0x04,
                                                        str(v).encode())))
                                    for k, v in entry.items()
                                    if k not in ("password", attr))
                                msgs.append(ber(
                                    0x64, ber(0x04, dn.encode())
                                    + ber(0x30, attrs)))
                                break
                        for m in msgs:
                            writer.write(ber(
                                0x30, ber(0x02, bytes([msgid])) + m))
                        resp = ber(0x65, ber(0x0A, bytes([RES_SUCCESS]))
                                   + ber(0x04, b"") + ber(0x04, b""))
                    else:
                        return
                    writer.write(ber(0x30, ber(0x02, bytes([msgid]))
                                     + resp))
                    await writer.drain()
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


def ldap_fixture():
    return {
        "uid=lena,ou=users,dc=example,dc=com": {
            "password": b"lpw", "uid": "lena", "isSuperuser": "true",
        },
    }


def test_ldap_bind_mode():
    async def main():
        srv = await MockLdap(ldap_fixture()).start()
        a = LdapAuthenticator(f"127.0.0.1:{srv.port}")
        assert (await a.authenticate_async(
            Credentials("c", "lena", b"lpw"))).outcome == "ok"
        assert (await a.authenticate_async(
            Credentials("c", "lena", b"bad"))).outcome == "deny"
        # empty password must NOT ride the anonymous-bind loophole
        assert (await a.authenticate_async(
            Credentials("c", "lena", b""))).outcome == "deny"
        await srv.stop()

        dead = LdapAuthenticator("127.0.0.1:1", timeout=0.3)
        assert (await dead.authenticate_async(
            Credentials("c", "lena", b"lpw"))).outcome == "ignore"

    run(main())


def test_ldap_search_bind_mode():
    async def main():
        srv = await MockLdap(ldap_fixture()).start()
        a = LdapAuthenticator(
            f"127.0.0.1:{srv.port}", method="search_bind",
            base_dn="dc=example,dc=com")
        res = await a.authenticate_async(Credentials("c", "lena", b"lpw"))
        assert res.outcome == "ok" and res.is_superuser
        assert (await a.authenticate_async(
            Credentials("c", "ghost", b"x"))).outcome == "ignore"
        await srv.stop()

    run(main())


def test_ldap_connect_through_broker():
    async def main():
        srv = await MockLdap(ldap_fixture()).start()
        chain = AuthChain(allow_anonymous=False).add(
            LdapAuthenticator(f"127.0.0.1:{srv.port}"))
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        node = BrokerNode(cfg, auth_chain=chain)
        await node.start()
        port = node.listeners.all()[0].port
        try:
            ok = Client(clientid="c1", port=port,
                        username="lena", password=b"lpw")
            await ok.connect()
            await ok.disconnect()
            bad = Client(clientid="c2", port=port,
                         username="lena", password=b"nope")
            with pytest.raises(MqttError):
                await bad.connect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


def test_wildcard_injection_guard_in_backend_acls():
    """A clientid/username of '#', '+', or containing '/' must never
    widen a %c/%u rule (the authz.py guard, shared via _backend)."""
    from emqx_tpu.auth.mongo import MongoAuthzSource
    from emqx_tpu.auth.postgres import PostgresAuthzSource

    rules = [("allow", "all", "devices/%c")]
    docs = [{"permission": "allow", "action": "all",
             "topics": ["devices/%c"]}]
    for cid in ("#", "+", "a/b"):
        assert PostgresAuthzSource._match(
            rules, "subscribe", "devices/other", cid, "u") == "nomatch"
        assert MongoAuthzSource._match(
            docs, "subscribe", "devices/other", cid, "u") == "nomatch"
    # benign clientid still substitutes
    assert PostgresAuthzSource._match(
        rules, "subscribe", "devices/c9", "c9", "u") == "allow"
    # topics: null document is skipped, not a crash
    assert MongoAuthzSource._match(
        [{"permission": "allow", "action": "all", "topics": None}],
        "publish", "t", "c", "u") == "nomatch"


def test_ldap_dn_escaping_blocks_injection():
    from emqx_tpu.auth.ldap import LdapAuthenticator

    a = LdapAuthenticator()
    from emqx_tpu.auth.authn import Credentials

    dn = a._dn(Credentials("c", "svc,ou=services"))
    # the comma (and '=', conservatively) must be escaped so the DN
    # stays inside ou=users
    assert dn == "uid=svc\\,ou\\=services,ou=users,dc=example,dc=com"
    assert a._dn_escape(" lead") == "\\ lead"
    assert a._dn_escape("trail ") == "trail\\ "
    assert a._dn_escape("#tag") == "\\#tag"
    assert a._dn_escape("a=b+c") == "a\\=b\\+c"


def test_mongo_scram_sha256_client_auth():
    """mongod-with-auth analog: the broker's Mongo client performs the
    SCRAM-SHA-256 SASL conversation (shared RFC 5802 core with the
    PostgreSQL backend) and verifies the server signature; without
    credentials every command is rejected (round-5: flips the 'Mongo
    assumes localhost trust' limitation)."""
    from emqx_tpu.auth.mongo import (
        MongoAuthenticator, MongoClient, MongoError,
    )
    from emqx_tpu.auth.authn import Credentials

    users = [{"username": "ada",
              "password_hash": hash_password(b"pw", "sha256", b"s1",
                                             "prefix"),
              "salt": "s1", "is_superuser": True}]

    async def scenario():
        mock = MockMongo({"mqtt_user": users},
                         auth_users={"broker": "sekret"})
        await mock.start()
        try:
            # authenticated client: full authn round trip works
            auth = MongoAuthenticator(
                f"127.0.0.1:{mock.port}", username="broker",
                password="sekret")
            r = await auth.authenticate_async(
                Credentials(clientid="c1", username="ada", password=b"pw"))
            assert r.outcome == "ok" and r.is_superuser
            await auth.client.close()

            # wrong password: SASL fails loudly
            bad = MongoClient(f"127.0.0.1:{mock.port}",
                              username="broker", password="wrong")
            try:
                await bad.command({"ping": 1})
                raise AssertionError("bad credentials accepted")
            except MongoError:
                pass
            finally:
                await bad.close()

            # no credentials: commands are rejected by the server
            anon = MongoClient(f"127.0.0.1:{mock.port}")
            try:
                await anon.command({"ping": 1})
                raise AssertionError("unauthenticated command accepted")
            except MongoError:
                pass
            finally:
                await anon.close()
        finally:
            await mock.stop()

    run(scenario())
