"""ExProto gateway: a user-defined line protocol implemented in an
in-test gRPC ConnectionHandler drives the broker through the hosted
ConnectionAdapter — the reference's bring-your-own-protocol flow."""

import asyncio

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.gateway import exproto_pb2 as pb
from emqx_tpu.gateway.exproto import (
    ConnectionAdapterStub, add_connection_handler_to_server,
)
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


class LineProtocolHandler:
    """A trivial text protocol, one command per line:

    AUTH <clientid> [password]   -> 'OK AUTH' / 'ERR ...'
    SUB <topic>                  -> 'OK SUB'
    PUB <topic> <payload>        -> 'OK PUB'
    deliveries push 'MSG <topic> <payload>' lines to the socket.
    """

    def __init__(self):
        self.adapter = None  # ConnectionAdapterStub, set after gw start

    async def _send_line(self, conn, text):
        await self.adapter.Send(pb.SendBytesRequest(
            conn=conn, bytes=(text + "\n").encode()))

    async def OnSocketCreated(self, req, ctx):
        await self._send_line(req.conn, "WELCOME")
        return pb.EmptySuccess()

    async def OnSocketClosed(self, req, ctx):
        return pb.EmptySuccess()

    async def OnReceivedBytes(self, req, ctx):
        for line in req.bytes.decode().splitlines():
            parts = line.strip().split(" ")
            if not parts or not parts[0]:
                continue
            cmd = parts[0].upper()
            if cmd == "AUTH":
                r = await self.adapter.Authenticate(pb.AuthenticateRequest(
                    conn=req.conn,
                    clientinfo=pb.ClientInfo(clientid=parts[1]),
                    password=parts[2] if len(parts) > 2 else "",
                ))
                await self._send_line(
                    req.conn,
                    "OK AUTH" if r.code == pb.SUCCESS else f"ERR {r.code}")
            elif cmd == "SUB":
                r = await self.adapter.Subscribe(pb.SubscribeRequest(
                    conn=req.conn, topic=parts[1], qos=0))
                await self._send_line(
                    req.conn,
                    "OK SUB" if r.code == pb.SUCCESS else f"ERR {r.code}")
            elif cmd == "PUB":
                r = await self.adapter.Publish(pb.PublishRequest(
                    conn=req.conn, topic=parts[1],
                    payload=" ".join(parts[2:]).encode()))
                await self._send_line(
                    req.conn,
                    "OK PUB" if r.code == pb.SUCCESS else f"ERR {r.code}")
            elif cmd == "QUIT":
                await self.adapter.Close(pb.CloseSocketRequest(conn=req.conn))
        return pb.EmptySuccess()

    async def OnReceivedMessages(self, req, ctx):
        for m in req.messages:
            await self._send_line(
                req.conn, f"MSG {m.topic} {m.payload.decode()}")
        return pb.EmptySuccess()


def test_exproto_line_protocol_roundtrip():
    async def main():
        import grpc.aio

        handler = LineProtocolHandler()
        hserver = grpc.aio.server()
        add_connection_handler_to_server(handler, hserver)
        hport = hserver.add_insecure_port("127.0.0.1:0")
        await hserver.start()

        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'gateway.exproto.enable = true\n'
            'gateway.exproto.bind = "127.0.0.1:0"\n'
            f'gateway.exproto.handler = "127.0.0.1:{hport}"\n')))
        await node.start()
        try:
            gw = node.gateways.gateways["exproto"]
            ch = grpc.aio.insecure_channel(f"127.0.0.1:{gw.adapter_port}")
            handler.adapter = ConnectionAdapterStub(ch)

            mq = Client(clientid="m1",
                        port=node.listeners.all()[0].port)
            await mq.connect()
            await mq.subscribe("from_ex/#")

            reader, writer = await asyncio.open_connection(
                "127.0.0.1", gw.port)

            async def line():
                return (await asyncio.wait_for(
                    reader.readline(), 5)).decode().strip()

            assert await line() == "WELCOME"
            writer.write(b"AUTH dev-ex\n")
            assert await line() == "OK AUTH"
            writer.write(b"SUB cmds/#\n")
            assert await line() == "OK SUB"
            writer.write(b"PUB from_ex/t hello-bridge\n")
            assert await line() == "OK PUB"

            # custom-protocol publish reached the MQTT subscriber
            got = await mq.recv(timeout=5)
            assert (got.topic, got.payload) == ("from_ex/t", b"hello-bridge")

            # MQTT publish reaches the custom-protocol socket as MSG line
            await mq.publish("cmds/go", b"run42")
            assert await line() == "MSG cmds/go run42"

            # adapter op on an unauthenticated/unknown conn errors cleanly
            r = await handler.adapter.Publish(pb.PublishRequest(
                conn="nope", topic="x", payload=b""))
            assert r.code == pb.CONN_PROCESS_NOT_ALIVE

            writer.write(b"QUIT\n")
            await asyncio.sleep(0.1)
            data = await reader.read(64)
            assert data == b""  # handler-initiated close
            writer.close()
            await mq.disconnect()
            await ch.close()
        finally:
            await node.stop()
            await hserver.stop(grace=0.2)

    run(main())


def test_exproto_requires_auth_before_ops():
    async def main():
        import grpc.aio

        handler = LineProtocolHandler()
        hserver = grpc.aio.server()
        add_connection_handler_to_server(handler, hserver)
        hport = hserver.add_insecure_port("127.0.0.1:0")
        await hserver.start()
        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'gateway.exproto.enable = true\n'
            'gateway.exproto.bind = "127.0.0.1:0"\n'
            f'gateway.exproto.handler = "127.0.0.1:{hport}"\n')))
        await node.start()
        try:
            gw = node.gateways.gateways["exproto"]
            ch = grpc.aio.insecure_channel(f"127.0.0.1:{gw.adapter_port}")
            handler.adapter = ConnectionAdapterStub(ch)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", gw.port)
            assert (await asyncio.wait_for(reader.readline(), 5)) \
                .decode().strip() == "WELCOME"
            # SUB before AUTH -> CONN_PROCESS_NOT_ALIVE surfaced as ERR
            writer.write(b"SUB x/#\n")
            line = (await asyncio.wait_for(reader.readline(), 5)) \
                .decode().strip()
            assert line.startswith("ERR")
            writer.close()
            await ch.close()
        finally:
            await node.stop()
            await hserver.stop(grace=0.2)

    run(main())
