"""Banned / flapping / limiter / overload protection — emqx_banned,
emqx_flapping, emqx_limiter, emqx_olp parity (SURVEY.md §2.1)."""

from emqx_tpu.broker import Banned, Broker, Flapping, LimiterGroup, Olp, TokenBucket
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.mqtt import packet as P
from emqx_tpu.observe import Alarms


def test_banned_dimensions_and_expiry():
    b = Banned()
    b.add("clientid", "evil", duration=100)
    b.add("username", "mallory")
    b.add("peerhost", "10.0.0.1", duration=0.0)  # already expired
    assert b.check(clientid="evil")
    assert b.check(username="mallory")
    assert not b.check(peerhost="10.0.0.1")
    assert not b.check(clientid="good")
    assert b.delete("clientid", "evil")
    assert not b.check(clientid="evil")


def test_banned_blocks_connect_with_banned_rc():
    broker = Broker()
    cm = ConnectionManager(broker)
    banned = Banned().attach(broker)
    banned.add("clientid", "evil")
    ch = Channel(broker, cm)
    acts = ch.handle_in(P.Connect(proto_ver=5, clientid="evil"))
    connacks = [a[1] for a in acts if a[0] == "send" and a[1].type == P.CONNACK]
    assert connacks[0].reason_code == P.RC.BANNED
    assert any(a[0] == "close" for a in acts)


def test_flapping_bans_after_threshold():
    # clock-injectable (the supervise.py discipline): the hook-driven
    # detector, the ban it issues and the expiry all ride ONE fake
    # clock — no wall-clock reads anywhere in the assertion chain
    now = [1000.0]
    broker = Broker()
    banned = Banned().attach(broker)
    f = Flapping(banned, max_count=3, window_time=10, ban_time=60,
                 clock=lambda: now[0]).attach(broker)
    for _ in range(2):
        broker.hooks.run("client.disconnected", ("c1", "x"))
        now[0] += 1.0
    assert not banned.check(clientid="c1", now=now[0])
    broker.hooks.run("client.disconnected", ("c1", "x"))
    assert banned.check(clientid="c1", now=now[0])
    assert f.detected == 1
    # the ban carries the injected clock: expiry is deterministic
    assert banned.check(clientid="c1", now=now[0] + 59.0)
    assert not banned.check(clientid="c1", now=now[0] + 61.0)


def test_flapping_window_slides():
    banned = Banned()
    f = Flapping(banned, max_count=3, window_time=10)
    f.record_disconnect("c", now=0)
    f.record_disconnect("c", now=1)
    f.record_disconnect("c", now=12)  # first two aged out
    assert not banned.check(clientid="c")


def test_flapping_sweep_bounds_table_under_churn():
    # the churn-audit satellite: a burst of one-shot clientids followed
    # by SILENCE must not pin the events table (the amortized in-line
    # sweep only runs while disconnects keep arriving — housekeeping
    # calls sweep() explicitly)
    now = [0.0]
    f = Flapping(Banned(), max_count=5, window_time=10,
                 clock=lambda: now[0])
    for i in range(300):
        f.record_disconnect(f"churn{i}")
        now[0] += 0.01
    tracked = f.tracked()
    assert tracked > 0
    now[0] += 11.0            # whole window elapsed for everyone
    assert f.sweep() == tracked
    assert f.tracked() == 0
    assert f.sweep() == 0     # idempotent


def test_token_bucket():
    tb = TokenBucket(rate=10, burst=10)
    ok, wait = tb.consume(10, now=0)
    assert ok and wait == 0
    ok, wait = tb.consume(5, now=0)
    assert not ok and abs(wait - 0.5) < 1e-9
    ok, wait = tb.consume(5, now=0.5)  # refilled 5
    assert ok
    assert TokenBucket(0).consume(1000)[0]  # unlimited


def test_limiter_group_per_connection():
    lg = LimiterGroup(max_conn_rate=1, max_messages_rate=2, max_bytes_rate=100)
    assert lg.allow_connect(now=0)[0]
    assert not lg.allow_connect(now=0)[0]
    ok, _ = lg.allow_publish("c1", 50, now=0)
    assert ok
    ok, _ = lg.allow_publish("c1", 50, now=0)
    assert ok
    ok, wait = lg.allow_publish("c1", 1, now=0)  # msg tokens exhausted
    assert not ok and wait > 0
    lg.drop_conn("c1")
    assert lg.allow_publish("c1", 1, now=10)[0]


def test_olp_shedding_and_alarm():
    alarms = Alarms()
    olp = Olp(alarms, max_loop_lag=0.1, max_queue_depth=10, cooloff=5)
    olp.report(loop_lag=0.05, queue_depth=1, now=0)
    assert not olp.should_shed_connect(now=0)
    olp.report(loop_lag=0.5, queue_depth=1, now=1)
    assert olp.should_shed_connect(now=1)
    assert alarms.is_active("overload")
    # cools off after quiet period
    olp.report(loop_lag=0.0, queue_depth=0, now=10)
    assert not olp.should_shed_connect(now=10)
    assert not alarms.is_active("overload")
