"""Runtime authn/authz management over REST (emqx_authn/emqx_authz API
analog): factory-built backends, ordered chain/source mutation on a
LIVE node, user store CRUD — verified by real CONNECT round trips."""

import asyncio
import json

import pytest

from emqx_tpu.auth.factory import (
    AUTHN_TYPES, AUTHZ_TYPES, describe, make_authenticator,
    make_authz_source,
)
from emqx_tpu.bridge import httpc
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def test_factory_builds_each_type():
    a, _ = make_authenticator({"type": "built_in_database",
                               "users": [{"user_id": "u",
                                          "password": "pw12345"}]})
    assert a.authenticate.__self__ is a
    a, _ = make_authenticator({"type": "jwt", "secret": "k" * 16})
    assert a.secret == b"k" * 16
    a, _ = make_authenticator({"type": "postgresql",
                               "server": "127.0.0.1:5",
                               "user": "u", "password": "p"})
    assert a.client.port == 5
    a, _ = make_authenticator({"type": "ldap",
                               "server": "127.0.0.1:3",
                               "method": "bind"})
    assert a.method == "bind"
    s, _ = make_authz_source({"type": "file", "rules": [
        {"permission": "allow", "action": "all", "topics": ["t/#"]}]})
    assert s.authorize("c", "u", None, "publish", "t/x") == "allow"

    with pytest.raises(ValueError):
        make_authenticator({"type": "nope"})
    with pytest.raises(ValueError):
        # typo'd key must error, not silently default
        make_authenticator({"type": "postgresql", "serverr": "x"})
    with pytest.raises(ValueError):
        make_authz_source({"type": "nope"})


def test_describe_redacts_secrets():
    d = describe({"type": "postgresql", "password": "hunter2",
                  "server": "s", "users": [{"user_id": "u",
                                            "password": "pw"}]})
    assert d["password"] == "******"
    assert d["users"][0]["password"] == "******"
    assert d["server"] == "s"


async def start_node():
    node = BrokerNode(Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n'
        'dashboard.enable = true\n'
        'dashboard.listen = "127.0.0.1:0"\n'
        'api_key.enable = true\n'
        'api_key.key = "k"\napi_key.secret = "s"\n')))
    await node.start()
    return node


async def api(node, method, path, body=None, token=None):
    headers = {}
    if token:
        headers["authorization"] = f"Bearer {token}"
    r = await httpc.request(
        method, f"http://127.0.0.1:{node.mgmt_server.port}/api/v5{path}",
        headers=headers,
        body=json.dumps(body).encode() if body is not None else b"")
    return r.status, (json.loads(r.body) if r.body else None)


async def login(node):
    st, doc = await api(node, "POST", "/login",
                        {"username": "admin", "password": "public"})
    assert st == 200
    return doc["token"]


def test_rest_authn_lifecycle_enforced_on_live_connects():
    async def main():
        node = await start_node()
        try:
            tok = await login(node)
            port = node.listeners.all()[0].port

            # no auth configured: anonymous connects fine
            c = Client(clientid="anon", port=port)
            await c.connect()
            await c.disconnect()

            # create a built-in-db authenticator that denies anonymous
            st, doc = await api(node, "POST", "/authentication", {
                "type": "built_in_database",
                "allow_anonymous": False,
                "users": [{"user_id": "alice", "password": "secret99"}],
            }, tok)
            assert st == 201, doc
            idx = doc["index"]

            ok = Client(clientid="a1", port=port, username="alice",
                        password=b"secret99")
            await ok.connect()
            await ok.disconnect()
            with pytest.raises(MqttError):
                await Client(clientid="a2", port=port).connect()

            # add a user over REST
            st, doc = await api(
                node, "POST", f"/authentication/{idx}/users",
                {"user_id": "bob", "password": "bobpass1"}, tok)
            assert st == 201
            ok2 = Client(clientid="b1", port=port, username="bob",
                         password=b"bobpass1")
            await ok2.connect()
            await ok2.disconnect()

            # list shows redacted conf
            st, doc = await api(node, "GET", "/authentication",
                                token=tok)
            assert st == 200
            assert doc["data"][0]["type"] == "built_in_database"
            assert doc["data"][0]["users"][0]["password"] == "******"

            # bad type -> 400
            st, _ = await api(node, "POST", "/authentication",
                              {"type": "wat"}, tok)
            assert st == 400

            # delete -> back to allow (chain empty, allow_anonymous
            # stays as configured False -> still denied)
            st, _ = await api(node, "DELETE",
                              f"/authentication/{idx}", token=tok)
            assert st == 204
            with pytest.raises(MqttError):
                await Client(clientid="a3", port=port).connect()
        finally:
            await node.stop()

    run(main())


def test_rest_authz_sources_lifecycle():
    async def main():
        node = await start_node()
        try:
            tok = await login(node)
            port = node.listeners.all()[0].port

            st, doc = await api(node, "POST", "/authorization/sources", {
                "type": "file",
                "rules": [
                    {"permission": "deny", "action": "subscribe",
                     "topics": ["secret/#"]},
                    {"permission": "allow", "action": "all",
                     "topics": ["#"]},
                ],
            }, tok)
            assert st == 201, doc

            c = Client(clientid="z1", port=port)
            await c.connect()
            assert (await c.subscribe("secret/x"))[0] >= 0x80
            assert await c.subscribe("open/x") == [0]

            # delete the source; cache cleared -> subscribe allowed by
            # the default no_match policy (allow)
            st, _ = await api(node, "DELETE", "/authorization/sources/0",
                              token=tok)
            assert st == 204
            assert await c.subscribe("secret/y") == [0]
            await c.disconnect()

            st, doc = await api(node, "GET", "/authorization/sources",
                                token=tok)
            assert st == 200 and doc["data"] == []
        finally:
            await node.stop()

    run(main())


def test_auth_configs_roundtrip_through_backup():
    async def main():
        from emqx_tpu.storage import export_data, import_data

        node = await start_node()
        try:
            tok = await login(node)
            await api(node, "POST", "/authentication", {
                "type": "built_in_database", "allow_anonymous": False,
                "users": [{"user_id": "alice", "password": "secret99"}],
            }, tok)
            await api(node, "POST", "/authorization/sources", {
                "type": "file",
                "rules": [{"permission": "deny", "action": "subscribe",
                           "topics": ["secret/#"]}],
            }, tok)
            blob = export_data(node)
        finally:
            await node.stop()

        node2 = await start_node()
        try:
            counts = import_data(node2, blob)
            assert counts["auth"] == 2
            port = node2.listeners.all()[0].port
            ok = Client(clientid="r1", port=port, username="alice",
                        password=b"secret99")
            await ok.connect()
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            await ok.disconnect()
            with pytest.raises(MqttError):
                await Client(clientid="r2", port=port).connect()
        finally:
            await node2.stop()

    run(main())


def test_rest_created_async_backend_is_consulted():
    """Regression: needs_async() is cached; runtime chain mutations must
    invalidate it or a REST-created network backend (http/redis/...)
    is never consulted by the connect path."""
    async def main():
        import sys
        sys.path.insert(0, "tests")
        from test_external_auth import MockHttp

        def handler(method, path, body):
            doc = json.loads(body)
            if doc.get("username") == "carol" and \
                    doc.get("password") == "cpw":
                return 200, {"result": "allow"}
            return 200, {"result": "deny"}

        srv = await MockHttp(handler).start()
        node = await start_node()
        try:
            tok = await login(node)
            port = node.listeners.all()[0].port
            # an anonymous connect first caches needs_async=False
            c0 = Client(clientid="warm", port=port)
            await c0.connect()
            await c0.disconnect()

            st, doc = await api(node, "POST", "/authentication", {
                "type": "http",
                "url": f"http://127.0.0.1:{srv.port}/auth",
                "allow_anonymous": False,
            }, tok)
            assert st == 201, doc

            ok = Client(clientid="h1", port=port, username="carol",
                        password=b"cpw")
            await ok.connect()     # would hang/deny with a stale cache
            await ok.disconnect()
            with pytest.raises(MqttError):
                await Client(clientid="h2", port=port, username="carol",
                             password=b"wrong").connect()
        finally:
            await node.stop()
            await srv.stop()

    run(main())


def test_rest_added_users_export_and_duplicates_409():
    async def main():
        from emqx_tpu.storage import export_data, import_data

        node = await start_node()
        try:
            tok = await login(node)
            st, doc = await api(node, "POST", "/authentication", {
                "type": "built_in_database", "allow_anonymous": False,
            }, tok)
            idx = doc["index"]
            st, _ = await api(node, "POST",
                              f"/authentication/{idx}/users",
                              {"user_id": "dana", "password": "dpw9999"},
                              tok)
            assert st == 201
            # duplicate -> 409, password NOT rotated
            st, _ = await api(node, "POST",
                              f"/authentication/{idx}/users",
                              {"user_id": "dana", "password": "other99"},
                              tok)
            assert st == 409
            blob = export_data(node)
        finally:
            await node.stop()

        node2 = await start_node()
        try:
            import_data(node2, blob)
            port = node2.listeners.all()[0].port
            ok = Client(clientid="d1", port=port, username="dana",
                        password=b"dpw9999")
            await ok.connect()      # REST-added user survives restore
            await ok.disconnect()
        finally:
            await node2.stop()

    run(main())


def test_factory_validation_hardening():
    # typo'd file-source key must error, not install an empty source
    with pytest.raises(ValueError):
        make_authz_source({"type": "file", "rule": []})
    with pytest.raises(ValueError):
        make_authz_source({"type": "file",
                           "rules": [{"permision": "deny"}]})
    # reference-shaped scram config resolves to ScramAuthenticator
    a, _ = make_authenticator({"mechanism": "scram",
                               "backend": "built_in_database"})
    from emqx_tpu.auth.scram import ScramAuthenticator
    assert isinstance(a, ScramAuthenticator)
