"""Two-tier hot/cold match table (VERDICT r4 item 2): routing
correctness and merged-answer parity vs the host oracle, with the
pallas tier in interpret mode on the CPU mesh."""

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops.tiered import (
    TieredMatcher, build_tiered, pick_hot_roots, route, split_filters,
)


def oracle(topics, filters):
    return [sorted(f for f in set(filters) if T.match(t, f))
            for t in topics]


FILTERS = [
    "hot1/+", "hot1/a/#", "hot1/x/y", "hot1/+/z",
    "hot2/devices/+/temp", "hot2/#",
    "cold1/a", "cold1/+/b", "cold2/#", "cold3/deep/+/x",
    "+/status", "#",                       # root wildcards: both tiers
    "$SYS/broker/uptime",
]
TOPICS = [
    "hot1/a", "hot1/a/b/c", "hot1/x/y", "hot1/q/z",
    "hot2/devices/d9/temp", "hot2/anything",
    "cold1/a", "cold1/x/b", "cold2/what/ever", "cold3/deep/k/x",
    "misc/status", "unrelated/topic",
    "$SYS/broker/uptime",
]


def test_split_filters_replicates_root_wildcards():
    hot, cold = split_filters(FILTERS, {"hot1", "hot2"})
    assert "+/status" in hot and "+/status" in cold
    assert "#" in hot and "#" in cold
    assert "hot1/+" in hot and "hot1/+" not in cold
    assert "cold1/a" in cold and "cold1/a" not in hot


def test_route_by_root():
    hot_idx, cold_idx = route(TOPICS, frozenset({"hot1", "hot2"}))
    assert sorted(hot_idx + cold_idx) == list(range(len(TOPICS)))
    assert all(TOPICS[i].split("/")[0] in ("hot1", "hot2")
               for i in hot_idx)


def test_pick_hot_roots_traffic_driven():
    counts = {"hot1": 100_000, "hot2": 50_000, "cold1": 3}
    picked = pick_hot_roots(FILTERS, counts)
    assert picked[:2] == ["hot1", "hot2"]
    # zero-traffic roots are not admitted
    assert "cold2" not in picked and "cold3" not in picked


def test_pick_hot_roots_respects_budget():
    counts = {"hot1": 100, "hot2": 50}
    picked = pick_hot_roots(FILTERS, counts, vmem_budget_bytes=16 * 10)
    # tiny budget: at most one root fits
    assert len(picked) <= 1


def test_tiered_matches_oracle_interpret():
    tiered = build_tiered(FILTERS, {"hot1", "hot2"}, depth=8)
    assert tiered.hot is not None
    tm = TieredMatcher(tiered, depth=8, interpret=True)
    got = tm.match(TOPICS)
    want = oracle(TOPICS, FILTERS)
    for t, g, w in zip(TOPICS, got, want):
        assert sorted(g) == w, (t, sorted(g), w)
    # routing actually split the work
    assert tm.hot_topics > 0 and tm.cold_topics > 0


def test_tiered_randomized_parity():
    rng = np.random.default_rng(9)
    roots = [f"r{i}" for i in range(12)]
    filters = sorted({
        rng.choice(roots + ["+"]) + "/"
        + "/".join(("+" if rng.random() < 0.3 else f"w{rng.integers(6)}")
                   for _ in range(rng.integers(1, 4)))
        + ("/#" if rng.random() < 0.25 else "")
        for _ in range(160)
    })
    counts = {r: (1000 if i < 4 else 0) for i, r in enumerate(roots)}
    hot_roots = pick_hot_roots(filters, counts, depth=8)
    assert hot_roots, "expected some hot roots"
    tiered = build_tiered(filters, hot_roots, depth=8)
    tm = TieredMatcher(tiered, depth=8, interpret=True)
    topics = [
        f"{rng.choice(roots)}/" + "/".join(
            f"w{rng.integers(6)}" for _ in range(rng.integers(1, 5)))
        for _ in range(64)
    ]
    got = tm.match(topics)
    want = oracle(topics, filters)
    for t, g, w in zip(topics, got, want):
        assert sorted(g) == w, (t, sorted(g), w)


def test_no_hot_roots_degenerates_to_cold_only():
    tiered = build_tiered(FILTERS, (), depth=8)
    assert tiered.hot is None
    tm = TieredMatcher(tiered, depth=8)
    got = tm.match(TOPICS)
    want = oracle(TOPICS, FILTERS)
    for g, w in zip(got, want):
        assert sorted(g) == w
    assert tm.hot_topics == 0


def test_build_demotes_until_vmem_fits(monkeypatch):
    """If the compiled hot tier exceeds the VMEM budget, roots demote
    until it fits (the pick estimate is advisory, the compile decides)."""
    import emqx_tpu.ops.pallas_match as pm

    calls = []
    real = pm.supports_table

    def tight(node_tab, edge_tab):
        calls.append(node_tab.shape[0])
        # reject anything holding both hot roots' filters
        return (node_tab.nbytes + edge_tab.nbytes) < 10_000 \
            and len(calls) > 1

    monkeypatch.setattr(pm, "supports_table", tight)
    tiered = build_tiered(FILTERS, ["hot1", "hot2"], depth=8)
    assert len(tiered.hot_roots) < 2
    # every filter is still matchable somewhere
    all_placed = set()
    if tiered.hot is not None:
        all_placed |= {f for f in tiered.hot.accept_filters if f}
    all_placed |= {f for f in tiered.cold.accept_filters if f}
    assert set(FILTERS) <= all_placed
