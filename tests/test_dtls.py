"""DTLS 1.2 PSK transport: sans-IO handshake/record tests plus the
endpoint's stateless-cookie and sweep behavior (the esockd-dtls analog
for the UDP gateways, VERDICT r4 item 7)."""

import pytest

pytest.importorskip("cryptography")

from emqx_tpu.transport.dtls import (
    DtlsConnection, DtlsEndpoint, PskStore,
)

KEY = b"sixteen-byte-key"
STORE = PskStore({"dev1": KEY}, hint="emqx")


def pump(a, b, limit=20):
    """Shuttle datagrams between two sans-IO connections; returns all
    plaintext chunks surfaced on each side."""
    got_a, got_b = [], []
    for _ in range(limit):
        moved = False
        for src, dst, sink in ((a, b, got_b), (b, a, got_a)):
            for dg in src.take_outgoing():
                moved = True
                sink.extend(dst.receive(dg))
        if not moved:
            return got_a, got_b
    raise AssertionError("handshake did not settle")


def new_pair(identity="dev1", key=KEY):
    client = DtlsConnection("client", psk_identity=identity, psk=key)
    server = DtlsConnection("server", psk_store=STORE, peer=("1.2.3.4", 5))
    return client, server


def test_handshake_and_bidirectional_data():
    client, server = new_pair()
    pump(client, server)
    assert client.complete and server.complete
    assert server.psk_identity == b"dev1"
    client.send(b"up " * 100)
    server.send(b"down")
    got_client, got_server = pump(client, server)
    assert got_server == [b"up " * 100]
    assert got_client == [b"down"]


def test_wrong_psk_fails_finished():
    client, server = new_pair(key=b"the-wrong-key-!!")
    pump(client, server)
    # server drops the bad Finished; neither side completes
    assert not server.complete and not client.complete


def test_unknown_identity_rejected():
    client, server = new_pair(identity="who-dis")
    pump(client, server)
    assert not server.complete
    with pytest.raises(Exception):
        client.send(b"x")


def test_tampered_record_dropped():
    client, server = new_pair()
    pump(client, server)
    client.send(b"genuine")
    (dg,) = client.take_outgoing()
    bad = dg[:-1] + bytes([dg[-1] ^ 0xFF])
    assert server.receive(bad) == []        # auth tag fails: dropped
    # the channel stays usable for intact records
    client.send(b"second")
    (dg2,) = client.take_outgoing()
    assert server.receive(dg2) == [b"second"]


def test_application_data_needs_handshake():
    client, _ = new_pair()
    with pytest.raises(Exception):
        client.send(b"too-early")


class _FakeTransport:
    def __init__(self):
        self.sent = []

    def sendto(self, data, addr):
        self.sent.append((data, addr))

    def close(self):
        pass

    def get_extra_info(self, name, default=None):
        return default


def test_endpoint_stateless_before_cookie():
    """The pre-cookie first flight must not allocate per-address state
    (RFC 6347 §4.2.1 DoS posture): only a cookie'd ClientHello earns a
    session slot."""
    plain = []
    ep = DtlsEndpoint(_FakeTransport(), lambda d, a: plain.append((d, a)),
                      STORE)
    client = DtlsConnection("client", psk_identity="dev1", psk=KEY)
    addr = ("9.9.9.9", 1234)
    (ch0,) = client.take_outgoing()
    ep.datagram_received(ch0, addr)
    assert ep.sessions == {}               # HVR sent, nothing retained
    assert len(ep.transport.sent) == 1
    # replay the HVR into the client, complete the handshake
    for dg, _ in list(ep.transport.sent):
        client.receive(dg)
    for dg in client.take_outgoing():      # cookie'd CH
        ep.datagram_received(dg, addr)
    assert addr in ep.sessions             # address verified: retained
    for _round in range(4):
        for dg, _ in ep.transport.sent[1:]:
            client.receive(dg)
        ep.transport.sent[1:] = []
        for dg in client.take_outgoing():
            ep.datagram_received(dg, addr)
        if client.complete and ep.handshakes:
            break
    assert client.complete and ep.handshakes == 1
    client.send(b"app")
    for dg in client.take_outgoing():
        ep.datagram_received(dg, addr)
    assert plain == [(b"app", addr)]


def test_endpoint_sweep_drops_idle_sessions():
    ep = DtlsEndpoint(_FakeTransport(), lambda d, a: None, STORE,
                      idle_timeout=0.5)
    client = DtlsConnection("client", psk_identity="dev1", psk=KEY)
    addr = ("8.8.8.8", 42)
    for dg in client.take_outgoing():
        ep.datagram_received(dg, addr)
    for dg, _ in list(ep.transport.sent):
        client.receive(dg)
    for dg in client.take_outgoing():
        ep.datagram_received(dg, addr)
    assert addr in ep.sessions
    now = ep.sessions[addr].last_seen
    assert ep.sweep(now + 0.4) == 0
    assert ep.sweep(now + 1.0) == 1
    assert ep.sessions == {}
