"""Metrics/stats/alarms/$SYS — emqx_metrics/emqx_stats/emqx_alarm/emqx_sys
parity surface (SURVEY.md §5.5)."""

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.message import make_message
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.observe import Alarms, Metrics, Stats, SysBroker
from emqx_tpu.observe.metrics import METRIC_NAMES
from emqx_tpu.observe.wiring import observe


def test_metrics_fixed_names_and_inc():
    m = Metrics()
    assert "messages.received" in METRIC_NAMES
    m.inc("messages.received")
    m.inc("messages.received", 5)
    assert m.get("messages.received") == 6
    with pytest.raises(KeyError):
        m.inc("not.a.metric")


def test_metrics_packet_and_qos_families():
    m = Metrics()
    m.inc_recv_packet("connect", nbytes=12)
    m.inc_sent_packet("connack", nbytes=4)
    m.inc_msg_received(2)
    m.inc_msg_dropped("queue_full")
    assert m.get("packets.connect.received") == 1
    assert m.get("packets.connack.sent") == 1
    assert m.get("bytes.received") == 12 and m.get("bytes.sent") == 4
    assert m.get("messages.qos2.received") == 1
    assert m.get("messages.dropped") == 1
    assert m.get("messages.dropped.queue_full") == 1


def test_stats_watermarks():
    s = Stats()
    s.setstat("connections.count", 5)
    s.setstat("connections.count", 3)
    assert s.get("connections.count") == 3
    assert s.get("connections.max") == 5


def test_stats_pull_provider():
    s = Stats()
    n = {"v": 7}
    s.provide("topics.count", lambda: n["v"])
    assert s.get("topics.count") == 7
    n["v"] = 9
    assert s.all()["topics.count"] == 9


def test_alarms_lifecycle_and_events():
    events = []
    a = Alarms(history_size=2)
    a.on_change = lambda kind, alarm: events.append((kind, alarm.name))
    assert a.activate("high_cpu", {"usage": 0.93})
    assert not a.activate("high_cpu")  # idempotent
    assert a.is_active("high_cpu")
    assert a.deactivate("high_cpu")
    assert not a.deactivate("high_cpu")
    assert events == [("activate", "high_cpu"), ("deactivate", "high_cpu")]
    for i in range(4):
        a.activate(f"x{i}")
        a.deactivate(f"x{i}")
    assert len(a.history) == 2  # bounded


def test_sys_broker_tick_publishes_under_prefix():
    out = []
    sys = SysBroker("node1", lambda t, p: out.append((t, p)), interval=60)
    sys.attach(stats=lambda: {"connections.count": 2}, metrics=lambda: {"messages.received": 3})
    assert sys.tick(now=sys.start_time + 61)
    topics = [t for t, _ in out]
    assert "$SYS/brokers/node1/uptime" in topics
    assert "$SYS/brokers/node1/stats/connections.count" in topics
    assert "$SYS/brokers/node1/metrics/messages.received" in topics
    out.clear()
    assert not sys.tick(now=sys.start_time + 90)  # within interval


def test_observe_wires_broker_hooks():
    b = Broker()
    obs = observe(b)
    b.open_session("sub1")
    b.subscribe("sub1", "t/+")
    res = b.publish(make_message("pub", "t/1", b"x", qos=1))
    assert res.matched == 1
    m = obs.metrics
    assert m.get("messages.received") == 1
    assert m.get("messages.qos1.received") == 1
    assert m.get("messages.delivered") == 1
    assert m.get("session.created") == 1
    assert obs.stats.get("topics.count") == 1
    assert obs.stats.get("sessions.count") == 1
    assert obs.stats.get("subscriptions.count") == 1
    # no-subscriber drop accounted
    b.publish(make_message("pub", "none/here", b"x"))
    assert m.get("messages.dropped.no_subscribers") == 1


def test_sys_messages_do_not_count_as_received():
    b = Broker()
    obs = observe(b, sys_interval=0)
    b.open_session("s")
    b.subscribe("s", "$SYS/brokers/#", SubOpts())
    obs.sys.tick()
    assert obs.metrics.get("messages.received") == 0
    # but the subscriber saw the $SYS publishes
    sess = b.sessions["s"]
    assert sess is not None


def test_connections_count_tracks_live_channels():
    import asyncio

    """connections.count / live_connections.count come from the CM —
    regression: they were never wired and stayed 0 (found driving the
    dashboard against a live node)."""
    async def main():
        from emqx_tpu.client import Client
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        node = BrokerNode(Config(
            file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n'))
        await node.start()
        try:
            port = node.listeners.all()[0].port
            cs = []
            for i in range(3):
                c = Client(clientid=f"cc{i}", port=port)
                await c.connect()
                cs.append(c)
            stats = node.observed.stats.all()
            assert stats["connections.count"] == 3
            assert stats["live_connections.count"] == 3
            assert stats["connections.max"] >= 3
            await cs[0].disconnect()
            await asyncio.sleep(0.05)
            assert node.observed.stats.all()["connections.count"] == 2
            for c in cs[1:]:
                await c.disconnect()
        finally:
            await node.stop()

    asyncio.run(main())


def test_topic_metrics_counts_and_rest():
    """emqx_topic_metrics analog: exact-topic counters over the publish
    path + REST lifecycle."""
    import asyncio

    async def main():
        import json as _json

        from emqx_tpu.bridge import httpc
        from emqx_tpu.client import Client
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\ndashboard.listen = "127.0.0.1:0"\n'
            'api_key.enable = true\napi_key.key = "k"\n'
            'api_key.secret = "s"\n')))
        await node.start()
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("POST", f"{base}/login", body=_json.dumps(
                {"username": "admin", "password": "public"}).encode())
            tok = _json.loads(r.body)["token"]
            hdr = {"authorization": f"Bearer {tok}"}

            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/1"}')
            assert r.status == 201
            # wildcards rejected; duplicates 409
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/+"}')
            assert r.status == 400
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/1"}')
            assert r.status == 409

            port = node.listeners.all()[0].port
            sub = Client(clientid="tm-s", port=port)
            await sub.connect()
            await sub.subscribe("m/1")
            pub = Client(clientid="tm-p", port=port)
            await pub.connect()
            for i in range(3):
                await pub.publish("m/1", b"x", qos=1)
            await pub.publish("m/other", b"x")  # unregistered: no count
            await asyncio.wait_for(sub.messages.get(), 5)

            r = await httpc.request("GET", f"{base}/mqtt/topic_metrics",
                                    headers=hdr)
            data = _json.loads(r.body)["data"]
            assert len(data) == 1
            rec = data[0]
            assert rec["topic"] == "m/1"
            assert rec["messages.in"] == 3
            assert rec["messages.qos1.in"] == 3
            assert rec["messages.out"] >= 1

            # reset zeroes counters and rate
            r = await httpc.request(
                "PUT", f"{base}/mqtt/topic_metrics/m/1/reset",
                headers=hdr)
            assert r.status == 204
            r = await httpc.request("GET", f"{base}/mqtt/topic_metrics",
                                    headers=hdr)
            rec = _json.loads(r.body)["data"][0]
            assert rec["messages.in"] == 0 and rec["rate.in"] == 0.0
            # invalid names: embedded wildcard chars and non-strings
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "a/x+y"}')
            assert r.status == 400
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr, body=b'{"topic": 123}')
            assert r.status == 400
            r = await httpc.request(
                "DELETE", f"{base}/mqtt/topic_metrics/m/1", headers=hdr)
            assert r.status == 204
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# stage-level latency observatory (ISSUE 12): histograms + flight recorder
# ---------------------------------------------------------------------------

def test_hist_percentiles_track_np_percentile():
    import numpy as np

    from emqx_tpu.observe.hist import LatencyHistogram

    rng = np.random.default_rng(3)
    # lognormal ns around ~5 ms — the shape real stage latencies have
    vals = rng.lognormal(mean=np.log(5e6), sigma=0.9, size=30000)
    h = LatencyHistogram()
    for v in vals:
        h.record(int(v))
    for q in (50, 95, 99):
        hp = h.percentile_ns(q)
        npp = float(np.percentile(vals, q))
        # the bench parity gate's tolerance: 1/16-octave sub-buckets
        assert abs(hp - npp) <= 0.12 * npp, (q, hp, npp)
    assert h.count == len(vals)
    assert h.to_dict()["p50_ms"] > 0


def test_hist_record_many_matches_scalar_records():
    import numpy as np

    from emqx_tpu.observe.hist import LatencyHistogram

    rng = np.random.default_rng(4)
    secs = rng.lognormal(mean=np.log(3e-3), sigma=1.2, size=5000)
    a, b = LatencyHistogram(), LatencyHistogram()
    for s in secs:
        a.record(int(s * 1e9))
    b.record_many_s(secs)
    assert a.counts == b.counts


def test_hist_merge_sums_planes_and_registry_is_fixed():
    import pytest as _pytest

    from emqx_tpu.observe.hist import (
        HIST_NAMES, HistSet, LatencyHistogram,
    )

    main, shard = HistSet("main"), HistSet("shard0")
    main.hist("obs.stage.deliver").record(1_000_000)
    shard.hist("obs.stage.deliver").record(2_000_000)
    shard.hist("obs.stage.ingest_parse").record(5_000)
    merged = HistSet.merge_all([main, shard])
    assert merged["obs.stage.deliver"].count == 2
    assert merged["obs.stage.ingest_parse"].count == 1
    pct = HistSet.percentiles([main, shard])
    assert set(pct) == set(HIST_NAMES)
    assert pct["obs.stage.deliver"]["count"] == 2
    # the fixed-table discipline: a typo'd name raises at the lookup
    with _pytest.raises(KeyError):
        main.hist("obs.stage.not_a_stage")
    # single-writer merge is a read-time sum, sources keep counting
    main.hist("obs.stage.deliver").record(1_000_000)
    assert merged["obs.stage.deliver"].count == 2  # snapshot, not live
    assert LatencyHistogram.merged(
        [main.hist("obs.stage.deliver")]).count == 2


def test_hist_recording_sites_zero_call_when_disabled(monkeypatch):
    """The overhead-gate spy (the faultinject idiom): with hists=None
    every recording site is an attribute check, never a call."""
    import asyncio as aio

    from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, \
        make_message
    from emqx_tpu.observe.hist import LatencyHistogram

    calls = []
    monkeypatch.setattr(
        LatencyHistogram, "record",
        lambda self, ns: calls.append(ns))
    monkeypatch.setattr(
        LatencyHistogram, "record_s",
        lambda self, s: calls.append(s))

    async def main():
        b = Broker()
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(pubs)
        b.open_session("s")
        b.subscribe("s", "t/#", SubOpts())
        p = FanoutPipeline(b, window_s=0.0)   # hists defaults to None
        await p.start()
        for i in range(20):
            assert p.offer(make_message("pub", f"t/{i}", b"x"))
        deadline = aio.get_event_loop().time() + 2.0
        while (p._q or p._busy) and \
                aio.get_event_loop().time() < deadline:
            await aio.sleep(0.002)
        await p.stop()
        assert len(got) == 20
        assert calls == []          # not one record() anywhere

    aio.run(main())


def test_hist_recording_sites_record_when_enabled():
    import asyncio as aio

    from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, \
        make_message
    from emqx_tpu.observe.hist import HistSet

    async def main():
        b = Broker()
        b.on_deliver = lambda cid, pubs: None
        b.open_session("s")
        b.subscribe("s", "t/#", SubOpts())
        hs = HistSet("main")
        p = FanoutPipeline(b, window_s=0.0, hists=hs)
        await p.start()
        for i in range(20):
            assert p.offer(make_message("pub", f"t/{i}", b"x"))
        deadline = aio.get_event_loop().time() + 2.0
        while (p._q or p._busy) and \
                aio.get_event_loop().time() < deadline:
            await aio.sleep(0.002)
        await p.stop()
        assert hs.hist("obs.stage.fanout_queue").count >= 1
        assert hs.hist("obs.stage.deliver").count >= 1
        assert hs.hist("obs.stage.flush").count >= 1
        assert hs.hist("obs.e2e.publish_deliver").count >= 1

    aio.run(main())


def test_sync_publish_path_records_spans_on_fanout_bypass():
    """ISSUE 13 observability follow-on (b): traffic the fanout gate
    BYPASSES to the per-message ``Broker.publish`` path must still land
    deliver/flush/e2e spans — bypass rates climbing no longer hollow
    out the histograms."""
    import asyncio as aio

    from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, \
        make_message
    from emqx_tpu.observe.hist import HistSet

    async def main():
        b = Broker()
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(pubs)
        b.open_session("s")
        b.subscribe("s", "t/#", SubOpts())
        hs = HistSet("main")
        b.attach_hists(hs)
        # huge bypass threshold: the low-rate gate refuses every offer,
        # exactly the path a quiet publisher rides in production
        p = FanoutPipeline(b, window_s=0.0, hists=hs, bypass_rate=1e9)
        await p.start()
        b.fanout = p
        for i in range(10):
            m = make_message("pub", f"t/{i}", b"x")
            if not p.offer(m):        # the caller contract: bypass →
                b.publish(m)          # per-message sync path
        await p.stop()
        assert len(got) == 10
        assert b.metrics is None     # bypass metric needs observe();
        assert hs.hist("obs.stage.deliver").count >= 10
        assert hs.hist("obs.stage.flush").count >= 10
        assert hs.hist("obs.e2e.publish_deliver").count >= 10

    aio.run(main())


def test_sync_publish_spans_zero_call_when_unattached(monkeypatch):
    """Without attach_hists the sync path stays an attribute check —
    the zero-cost-when-off discipline every recording site follows."""
    from emqx_tpu.observe.hist import LatencyHistogram

    calls = []
    monkeypatch.setattr(LatencyHistogram, "record",
                        lambda self, ns: calls.append(ns))
    monkeypatch.setattr(LatencyHistogram, "record_s",
                        lambda self, s: calls.append(s))
    b = Broker()
    b.open_session("s")
    b.subscribe("s", "t/#", SubOpts())
    res = b.publish(make_message("pub", "t/1", b"x"))
    assert res.matched == 1
    assert calls == []


def test_flightrec_ring_wraps_and_snapshots_in_order():
    from emqx_tpu.observe.flightrec import Ring

    r = Ring("main", depth=64)
    for i in range(100):
        r.push(1, i, 10, batch=i)
    snap = r.snapshot()
    assert len(snap) == 64
    starts = [e[1] for e in snap]
    assert starts == list(range(36, 100))   # oldest→newest, wrapped
    # depth rounds up to a power of two
    assert len(Ring("x", depth=100).buf) == 128


def test_flightrec_dump_writes_valid_perfetto_trace(tmp_path):
    import json as _json

    from emqx_tpu.observe.flightrec import (
        DUMP_REASONS, FlightRecorder,
    )
    from emqx_tpu.observe.metrics import Metrics

    m = Metrics()
    fr = FlightRecorder(str(tmp_path), depth=128, metrics=m)
    ring = fr.ring("match.encode")
    for i in range(10):
        ring.push(3, 1000 + i * 100, 50, batch=8, gen=i)
    fr.ring("fanout").push(1, 500, 20, batch=4)
    path = fr.dump("manual", note="test")
    assert path is not None and path.endswith(".json")
    with open(path) as f:
        payload = _json.load(f)
    assert payload["reason"] == "manual"
    evs = payload["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(slices) == 11
    assert len(metas) == 2           # one thread_name per plane
    # events ordered by ts (the chaos-test contract)
    ts = [e["ts"] for e in slices]
    assert ts == sorted(ts)
    assert slices[0]["name"] == "fanout_queue"
    assert {e["args"]["name"] for e in metas} == {
        "match.encode", "fanout"}
    assert m.get("obs.flightrec.dumps") == 1
    assert fr.dumps == 1 and fr.last_reason == "manual"
    # reasons are a fixed vocabulary
    assert "breaker_trip" in DUMP_REASONS
    with pytest.raises(ValueError):
        fr.dump("no_such_reason")


def test_flightrec_dump_failure_leaves_no_torn_file(tmp_path, monkeypatch):
    import json as _json
    import os as _os

    from emqx_tpu.observe.flightrec import FlightRecorder

    fr = FlightRecorder(str(tmp_path), depth=64)
    fr.ring("main").push(0, 1, 2)

    def boom(*a, **kw):
        raise OSError("disk died mid-write")

    monkeypatch.setattr(_json, "dump", boom)
    assert fr.dump("manual") is None          # contained, not raised
    assert fr.dumps == 0
    # no torn JSON, no leftover temp file
    assert [p for p in _os.listdir(tmp_path)] == []
    monkeypatch.undo()
    # and the recorder still works afterwards
    assert fr.dump("manual") is not None


def test_slow_subs_e2e_histogram_one_clock_read(monkeypatch):
    import time as _time

    from emqx_tpu.observe.slow_subs import SlowSubs

    ss = SlowSubs(threshold_ms=100.0, window_s=10.0)
    reads = [0]
    real = _time.time

    def counting_time():
        reads[0] += 1
        return real()

    class Msg:
        retain = False
        topic = "a/b"

        def __init__(self, age_s):
            self.timestamp = real() - age_s

    monkeypatch.setattr(
        "emqx_tpu.observe.slow_subs.time.time", counting_time)
    reads[0] = 0
    ss._on_delivered("c1", Msg(0.5))      # past threshold: ranked
    assert reads[0] == 1                  # ONE wall-clock read
    reads[0] = 0
    ss._on_delivered("c1", Msg(0.01))     # fast: histogram only
    assert reads[0] == 1
    monkeypatch.undo()
    assert len(ss.ranking()) == 1         # only the slow one ranked
    e2e = ss.e2e()
    assert e2e["count"] == 2              # but BOTH deliveries measured
    assert e2e["p50_ms"] > 0
    ss.clear()
    assert ss.e2e()["count"] == 0


def test_sys_broker_publishes_hist_payloads():
    import json as _json

    got = []
    sysb = SysBroker("n1", lambda t, p: got.append((t, p)), interval=0)
    sysb.attach_hists(lambda: {
        "obs.stage.deliver": {"count": 3, "p50_ms": 1.5, "p95_ms": 2.0,
                              "p99_ms": 2.5, "max_ms": 3.0},
        "obs.stage.flush": {"count": 0},     # empty: skipped
    })
    assert sysb.tick(now=1e9)
    hist_topics = {t: p for t, p in got if "/hist/" in t}
    assert list(hist_topics) == ["$SYS/brokers/n1/hist/obs.stage.deliver"]
    body = _json.loads(next(iter(hist_topics.values())))
    assert body["p99_ms"] == 2.5


def test_statsd_hist_timing_lines_and_line_boundary_chunking():
    from emqx_tpu.observe.statsd import StatsdPusher

    class FakeMetrics:
        def __init__(self, n):
            self._d = {f"fake.counter.{i:04d}": i for i in range(n)}

        def all(self):
            return dict(self._d)

    class FakeStats(FakeMetrics):
        pass

    class Observed:
        metrics = FakeMetrics(400)      # ~10 KB of counter lines
        stats = FakeStats(50)

    pusher = StatsdPusher(
        Observed(), server="127.0.0.1:1",
        hist_source=lambda: {
            "obs.stage.deliver": {"count": 7, "p50_ms": 1.25,
                                  "p95_ms": 2.5, "p99_ms": 4.75,
                                  "max_ms": 9.0},
            "obs.stage.flush": {"count": 0},
        })
    payload = pusher.render()
    text = payload.decode()
    assert "emqx.obs.stage.deliver.p99:4.75|ms" in text
    assert "emqx.obs.stage.deliver.p50:1.25|ms" in text
    assert "emqx.obs.stage.deliver.count:7|g" in text
    assert "obs.stage.flush" not in text     # empty hists are skipped
    assert len(payload) > 8000               # forces the chunk path

    sent = []

    class FakeSock:
        def sendto(self, data, addr):
            sent.append(bytes(data))

        def close(self):
            pass

    pusher._sock = FakeSock()
    pusher.push()
    assert len(sent) >= 2                    # multi-datagram flush
    for chunk in sent:
        assert len(chunk) <= 8000
        for line in chunk.decode().splitlines():
            # every line in every datagram is whole: name:value|type
            name, rest = line.split(":", 1)
            assert name and rest.rsplit("|", 1)[1] in ("c", "g", "ms")
    # recombining the datagrams yields exactly the rendered payload
    assert b"\n".join(sent) == payload
    assert pusher.pushes == 1


def test_obs_hist_disable_wires_none_everywhere():
    """obs.hist.enable = false: every plane's histogram handle is None,
    so (with the spy test above proving None ⇒ no call) the whole
    recording surface is zero-call."""
    import asyncio as aio

    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            "obs.hist.enable = false\n"))
        cfg.put("tpu.enable", True)
        cfg.put("broker.fanout.enable", True)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.hists is None
            assert node.hist_sets() == []
            assert node.hist_percentiles() == {}
            fp = node.fanout_pipeline
            assert fp._h_queue is None and fp._h_e2e is None
            ms = node.match_service
            if ms is not None:   # device may be absent on CI
                assert ms._h_wait is None and ms._h_encode is None
            # the flight recorder stays ALWAYS on regardless
            assert node.flightrec is not None
            assert node.supervisor.flightrec is node.flightrec
        finally:
            await node.stop()

    aio.run(main())


def test_obs_hist_enabled_by_default_and_wired():
    import asyncio as aio

    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    async def main():
        cfg = Config(
            file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("broker.fanout.enable", True)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.hists is not None
            assert node.fanout_pipeline._h_queue is not None
            pct = node.hist_percentiles()
            from emqx_tpu.observe.hist import HIST_NAMES
            assert set(pct) == set(HIST_NAMES)
        finally:
            await node.stop()

    aio.run(main())


def test_per_leg_e2e_hist_sampled_when_enabled():
    """``obs.hist.e2e_per_leg_sample = N`` records every Nth delivery
    leg into the per-leg e2e histogram — the per-subscriber skew
    signal the batch-level e2e span can't see."""
    import asyncio as aio

    from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, \
        make_message
    from emqx_tpu.observe.hist import HistSet

    async def main():
        b = Broker()
        b.on_deliver = lambda cid, pubs: None
        for i in range(4):
            b.open_session(f"s{i}")
            b.subscribe(f"s{i}", "t/#", SubOpts())
        hs = HistSet("main")
        p = FanoutPipeline(b, window_s=0.0, hists=hs,
                           e2e_per_leg_sample=2)
        await p.start()
        for i in range(10):
            assert p.offer(make_message("pub", f"t/{i}", b"x"))
        deadline = aio.get_event_loop().time() + 2.0
        while (p._q or p._busy) and \
                aio.get_event_loop().time() < deadline:
            await aio.sleep(0.002)
        await p.stop()
        # 10 msgs × 4 subscribers = 40 legs, sampled every 2nd
        leg = hs.hist("obs.e2e.publish_deliver_leg")
        assert leg.count == 20, leg.count
        # the batch-level span keeps recording alongside
        assert hs.hist("obs.e2e.publish_deliver").count >= 1

    aio.run(main())


def test_per_leg_e2e_hist_zero_call_when_off(monkeypatch):
    """Default off: the per-leg histogram is never looked up and
    record_s is never called for it — the recording site stays an
    attribute check (spy-asserted)."""
    import asyncio as aio

    from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, \
        make_message
    from emqx_tpu.observe.hist import HistSet, LatencyHistogram

    async def main():
        b = Broker()
        b.on_deliver = lambda cid, pubs: None
        b.open_session("s")
        b.subscribe("s", "t/#", SubOpts())
        hs = HistSet("main")
        leg_calls = []
        orig = LatencyHistogram.record_s
        leg_hist = hs.hist("obs.e2e.publish_deliver_leg")

        def spy(self, s):
            if self is leg_hist:
                leg_calls.append(s)
            return orig(self, s)

        monkeypatch.setattr(LatencyHistogram, "record_s", spy)
        p = FanoutPipeline(b, window_s=0.0, hists=hs)  # sample=0 (off)
        assert p._h_e2e_leg is None
        await p.start()
        for i in range(10):
            assert p.offer(make_message("pub", f"t/{i}", b"x"))
        deadline = aio.get_event_loop().time() + 2.0
        while (p._q or p._busy) and \
                aio.get_event_loop().time() < deadline:
            await aio.sleep(0.002)
        await p.stop()
        assert leg_calls == []       # not one record for the leg hist
        assert hs.hist("obs.e2e.publish_deliver").count >= 1

    aio.run(main())
